"""Host-side page-pool allocator + prefix-sharing index (ISSUE 19).

The device side of the paged KV cache is dumb on purpose: per layer, one
``[num_pages + 1, page_size, d_model]`` K and V pool (the last row is
the TRASH page — inactive-slot decode writes, prefill pad pages and
unmapped page-table entries all land there, and the exact ``-inf``
validity bias guarantees its garbage never reaches an output bit).  ALL
allocation policy lives here, in plain host data structures the engine
mutates under its dispatch lock:

 - **free list**: pages allocate on admit (``(plen - 1) // page_size +
   1`` pages — the last is always slot-private, decode growth adds more
   one at a time) and return on retire/expiry.  ``admit`` returns None
   when the pool cannot cover a request (admission backpressure: the
   engine re-queues, never crashes) and ``ensure`` returns False when
   growth finds the pool dry (the slot stalls one tick, bitwise-invisibly
   — the discarded tick re-derives the same token later).
 - **prefix sharing**: every FULL prompt page (all of its positions <
   plen - 1, so decode writes can never touch it) is published in an
   exact-match index keyed by ``(bucket, prompt-prefix-tokens)`` and
   refcount-shared read-only across slots.  Keys are the full token
   tuple — no hashing, no collisions — and carry the prefill bucket so a
   hit's resident K/V is guaranteed BITWISE identical to what this
   request's own prefill would write (same program, same causal window).
   When every shareable page hits and the private page would start
   empty, the engine skips the prefill dispatch entirely.
 - **accounting**: every mutation republishes the always-on gauges
   (``kvpool.pages_free/pages_live/hbm_bytes``), feeds the PR 11
   live-buffer ledger (scope ``kvpool`` — a page leak breaches the SLO
   watchdog like any other live-bytes growth), and the page-free path
   consults the ``PADDLE_FAULT_KV_PAGE_LEAK`` oracle (fluid.fault),
   which makes the ledger/watchdog leak story deterministically
   testable.

Thread-safety: one internal lock; the arrays ``table()`` returns are
rebuilt copies, safe to hand to the executor.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PageGrant", "PagePool"]


@dataclass
class PageGrant:
    """One admission's page set.  ``pages[:hits]`` came refcount-shared
    from the prefix index; the rest are freshly allocated (the last one
    is always slot-private).  ``full_hit`` means every prompt position
    the prefill would write below ``plen - 1`` is already resident, so
    the engine may skip the prefill dispatch (the first decode tick
    writes position ``plen - 1`` itself)."""
    slot: int
    pages: List[int]
    hits: int
    full_hit: bool


class PagePool:
    """Allocator + prefix index over ``num_pages`` device pages.

    ``page_bytes`` is the HBM cost of ONE page across K+V and all layers
    (``page_size * d_model * 4 bytes * 2 * n_layer``) — only used for
    gauges.  ``metrics`` (a :class:`..metrics.ServingMetrics`) receives
    the ``prefix_hits`` counter and ``kvpool_*`` gauge mirrors so bench
    snapshots carry them without reaching into the process registry."""

    def __init__(self, num_pages: int, page_size: int, pages_per_slot: int,
                 max_slots: int, page_bytes: int = 0,
                 prefix_share: bool = True, metrics=None):
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        self.max_slots = int(max_slots)
        self.page_bytes = int(page_bytes)
        self.prefix_share = bool(prefix_share)
        self.trash_page = self.num_pages
        self._metrics = metrics
        self._lock = threading.Lock()
        # LIFO free list: pop() from the end => low page ids stay hot,
        # allocation order is deterministic for the churn oracles
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        self._slot_pages: Dict[int, List[int]] = {}
        self._ref: Dict[int, int] = {}
        self._index: Dict[tuple, int] = {}   # (bucket, prefix) -> page
        self._page_key: Dict[int, tuple] = {}
        self._leaked = 0
        self._publish_locked()

    # -- queries -----------------------------------------------------------

    @property
    def pages_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def pages_live(self) -> int:
        with self._lock:
            return self.num_pages - len(self._free)

    @property
    def pages_leaked(self) -> int:
        return self._leaked

    def pages_needed(self, prompt_len: int) -> int:
        """Pages one admission allocates up front: every full prompt page
        plus the always-private page the first decode tick writes into."""
        return (int(prompt_len) - 1) // self.page_size + 1

    def slot_pages(self, slot: int) -> List[int]:
        with self._lock:
            return list(self._slot_pages.get(slot, ()))

    def table(self) -> np.ndarray:
        """The per-tick ``[max_slots, pages_per_slot]`` page-table feed:
        each slot's owned pages in position order, trash elsewhere."""
        with self._lock:
            t = np.full((self.max_slots, self.pages_per_slot),
                        self.trash_page, np.int64)
            for slot, pages in self._slot_pages.items():
                t[slot, :len(pages)] = pages
            return t

    def write_loc(self, slot: int, pos: int) -> Tuple[int, int]:
        """(page, offset) for this slot's decode write at ``pos`` — call
        only after :meth:`ensure` returned True for the position."""
        with self._lock:
            pages = self._slot_pages[slot]
            return pages[pos // self.page_size], pos % self.page_size

    # -- allocate ----------------------------------------------------------

    def _prefix_key(self, bucket: int, prompt, j: int) -> tuple:
        return (int(bucket), tuple(prompt[:(j + 1) * self.page_size]))

    def admit(self, slot: int, prompt, bucket: int) -> Optional[PageGrant]:
        """Allocate the admission page set for ``prompt`` into ``slot``;
        None = insufficient free pages (the engine re-queues the request
        — backpressure, not failure).  Shared full-prompt pages already
        in the index are attached by refcount instead of allocated."""
        ps = self.page_size
        plen = len(prompt)
        f_share = (plen - 1) // ps  # full pages, all positions < plen-1
        with self._lock:
            hits: List[int] = []
            if self.prefix_share:
                # keys are full-prefix tuples, so hits always form a
                # prefix chain: page j+1 in the index implies some live
                # holder also pins page j's entry
                for j in range(f_share):
                    page = self._index.get(
                        self._prefix_key(bucket, prompt, j))
                    if page is None:
                        break
                    hits.append(page)
            fresh = (f_share + 1) - len(hits)
            if fresh > len(self._free):
                return None
            for page in hits:
                self._ref[page] += 1
            pages = list(hits)
            for j in range(len(hits), f_share + 1):
                page = self._free.pop()
                self._ref[page] = 1
                pages.append(page)
                if self.prefix_share and j < f_share:
                    key = self._prefix_key(bucket, prompt, j)
                    self._index[key] = page
                    self._page_key[page] = key
            self._slot_pages[slot] = pages
            full_hit = bool(self.prefix_share and f_share > 0
                            and len(hits) == f_share
                            and (plen - 1) % ps == 0)
            self._publish_locked()
        if hits:
            self._count("kvpool.prefix_hits", len(hits))
            if self._metrics is not None:
                self._metrics.inc("prefix_hits", len(hits))
        return PageGrant(slot=int(slot), pages=pages, hits=len(hits),
                         full_hit=full_hit)

    def ensure(self, slot: int, pos: int) -> bool:
        """Grow the slot's page list to cover a decode write at ``pos``;
        False = pool dry (the slot stalls this tick: the engine feeds the
        trash page, masks the output token, and retries next tick)."""
        with self._lock:
            pages = self._slot_pages.get(slot)
            if pages is None:
                return False
            need = pos // self.page_size
            if need < len(pages):
                return True
            if not self._free:  # need == len(pages): grow by exactly one
                return False
            page = self._free.pop()
            self._ref[page] = 1
            pages.append(page)
            self._publish_locked()
            return True

    def prefill_pages(self, slot: int, bucket: int) -> np.ndarray:
        """The ``[bucket // page_size]`` int64 PF_PAGES feed: the slot's
        owned pages, then trash for bucket pad pages beyond them (their
        pad-token K/V must land nowhere real)."""
        n = int(bucket) // self.page_size
        out = np.full((n,), self.trash_page, np.int64)
        with self._lock:
            pages = self._slot_pages.get(slot, ())
            k = min(n, len(pages))
            out[:k] = pages[:k]
        return out

    # -- release -----------------------------------------------------------

    def _drop_page_locked(self, page: int) -> int:
        """THE single page-release path: refcount decrement, prefix-index
        eviction at zero, the ``PADDLE_FAULT_KV_PAGE_LEAK`` oracle, then
        the actual free.  Every way a page leaves a slot — retire,
        expiry, reap, teardown, speculative rewind — funnels through
        here, so the leak oracle and the gauges see them all.  Returns
        pages actually freed (0 on shared or leaked pages)."""
        from ...fluid import fault as _fault

        self._ref[page] -= 1
        if self._ref[page] > 0:
            return 0
        del self._ref[page]
        key = self._page_key.pop(page, None)
        # evict the prefix entry only if it still names this page
        # (flush_index may have dropped or re-bound the key)
        if key is not None and self._index.get(key) == page:
            del self._index[key]
        if _fault.kv_page_leak():
            self._leaked += 1
            return 0  # the skipped free: page never returns
        self._free.append(page)
        return 1

    def release(self, slot: int) -> int:
        """Return the slot's pages (retire, deadline expiry, reap, static
        teardown).  Shared pages only reach the free list at refcount
        zero — a sharer's expiry never tears pages out from under the
        other holders.  Returns the number of pages actually freed."""
        freed = 0
        with self._lock:
            pages = self._slot_pages.pop(slot, None)
            if pages is None:
                return 0
            for page in pages:
                freed += self._drop_page_locked(page)
            self._publish_locked()
        return freed

    def rewind(self, slot: int, keep_pos: int) -> int:
        """Shrink the slot's page list to exactly cover positions
        ``<= keep_pos`` (speculative rollback, ISSUE 20): pages grown
        for rejected draft positions return through the single release
        path.  The page holding ``keep_pos`` itself is always kept —
        rewinding never tears a slot's committed frontier.  Returns the
        number of pages actually freed."""
        freed = 0
        with self._lock:
            pages = self._slot_pages.get(slot)
            if pages is None:
                return 0
            keep = int(keep_pos) // self.page_size + 1
            while len(pages) > keep:
                freed += self._drop_page_locked(pages.pop())
            if freed:
                self._publish_locked()
        return freed

    def flush_index(self) -> None:
        """Drop every prefix entry (weight rebind / cache scrub: resident
        page content no longer matches what a NEW admission's prefill
        would write).  Holders keep their refcounts; pages just stop
        being discoverable."""
        with self._lock:
            self._index.clear()
            self._page_key.clear()

    # -- accounting --------------------------------------------------------

    def _publish_locked(self) -> None:
        free = len(self._free)
        live = self.num_pages - free
        gauges = {
            "kvpool.pages_free": free,
            "kvpool.pages_live": live,
            "kvpool.pages_leaked": self._leaked,
            "kvpool.hbm_bytes": live * self.page_bytes,
            "kvpool.pool_bytes": (self.num_pages + 1) * self.page_bytes,
        }
        try:
            from ... import observe
            from ...observe.memory import ledger

            reg = observe.registry()
            for name, val in gauges.items():
                reg.set_gauge(name, val)
            # live-buffer ledger: paged-KV residency breaches the SLO
            # watchdog like any other leak (PR 11 wiring)
            ledger().update("kvpool", live * self.page_bytes)
        except Exception:
            pass  # accounting must never fail the allocator
        if self._metrics is not None:
            for name, val in gauges.items():
                self._metrics.set_gauge(name.replace(".", "_"), val)

    def _count(self, name: str, n: int) -> None:
        try:
            from ... import observe

            observe.registry().inc(name, n)
        except Exception:
            pass
