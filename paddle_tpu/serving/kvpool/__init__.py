"""Paged KV-cache subsystem for the decode engine (ISSUE 19 tentpole).

Host-side page-pool allocator + prefix-sharing index over the device
page pools the paged :class:`~paddle_tpu.models.transformer.DecodeModel`
declares (``[num_pages + 1, page_size, d_model]`` per layer; the last
row is the trash page).  See :class:`PagePool`.
"""

from .pool import PageGrant, PagePool

__all__ = ["PageGrant", "PagePool"]
