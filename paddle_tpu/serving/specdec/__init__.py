"""paddle_tpu.serving.specdec: speculative decoding for DecodeEngine
(ISSUE 20 tentpole).

With ``PADDLE_SERVE_SPEC=k > 0`` the engine's one-token tick becomes a
draft + verify tick:

 - a :class:`~.draft.DraftSource` — a cheap self-draft model built from
   the target's first ``PADDLE_SERVE_SPEC_DRAFT_LAYERS`` decoder layers
   (weights shared BY NAME), or any registry serial loaded through the
   PR 16 ``load_serial_weights`` path — runs k sequential one-token
   steps over its own slot-parallel dense KV cache;
 - ONE wider fixed-shape target verify step
   (``DecodeModel.spec_program(k)``) scores all k + 1 positions per
   slot, and the device-side ``spec_accept`` op takes the longest
   prefix where draft token == target argmax plus the first correction
   token — so accepted output is bitwise identical to sequential greedy
   decode by construction;
 - rejected speculative positions roll back through the PR 19
   :class:`~..kvpool.PagePool`: the slot's write frontier rewinds,
   stale writes steer to the trash page, and speculatively-grown pages
   return through the pool's single release path
   (``kvpool.pages_leaked`` stays 0 under churn).

The executable set stays closed — one draft step + one verify step +
the draft prefill buckets join the warmed set, and ``bucket_compiles``
stays flat after warmup.  A :class:`~.controller.SpecController` watches
rolling acceptance: below ``PADDLE_SERVE_SPEC_MIN_ACCEPT`` over a
``PADDLE_SERVE_SPEC_WINDOW`` of spec ticks the engine falls back to
plain one-token ticks (``specdec.fallback`` event), re-arming after a
cooldown.  ``PADDLE_SERVE_SPEC=0`` is the kill switch: the PR 15/19
tick runs verbatim.  See docs/SERVING.md "Speculative decoding".
"""

from .controller import SpecController
from .decoder import SpecDecoder
from .draft import DraftSource

__all__ = ["SpecDecoder", "DraftSource", "SpecController"]
