"""The draft side of speculative decoding: a cheap DecodeModel that
proposes k tokens per tick for the target to verify.

Two ways to get one (ISSUE 20):

 - **self-draft** (default): a truncated clone of the target — the
   first ``PADDLE_SERVE_SPEC_DRAFT_LAYERS`` decoder layers, sharing
   embeddings and weights BY NAME.  The truncated model's parameter
   names (``dlm_emb``, ``dlm_out_w``, ``dlm{i}_*`` for ``i < depth``)
   are exactly a prefix of the target's, so :meth:`sync` is a plain
   name-for-name copy from the target scope — no surgery, and a weight
   hot-swap re-syncs the same way.  ``draft_layers=0`` means full
   depth: the draft IS the target (acceptance 1.0 — the throughput
   ceiling probe ``tools/bench_serving.py`` uses).
 - **registry serial**: any PR 16 serial directory whose weights match
   the draft architecture, loaded through
   :func:`..registry.load_serial_weights` (same manifest/digest checks
   as a hot swap).  Serial-backed drafts keep their own weights across
   target swaps.

The draft always runs a DENSE slot cache regardless of the target's
paged mode: draft K/V is private scratch (never shared, never read by
the target), rollback is free — the validity bias masks everything past
the committed frontier, so rejected draft positions are simply
overwritten next tick — and the page pool stays dedicated to target
state the bitwise contract actually depends on.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...fluid.executor import Scope
from ...models.transformer import Config, DecodeModel

__all__ = ["DraftSource"]


class DraftSource:
    """The draft model plus its private scope and per-slot chain state.

    ``exe`` is the ENGINE's executor — draft programs dispatch through
    it (so ``bucket_compiles`` accounting sees them) but against
    ``self.scope``, keeping draft weights and caches fully separate
    from the target's."""

    def __init__(self, target: DecodeModel, exe, draft_layers: int,
                 serial: Optional[str] = None):
        depth = int(draft_layers)
        if depth < 0 or depth > target.cfg.n_layer:
            raise ValueError(
                f"draft_layers ({depth}) must be in [0, "
                f"{target.cfg.n_layer}] (0 = full-depth self-draft)")
        if depth == 0:
            depth = target.cfg.n_layer
        c = target.cfg
        dcfg = Config(f"{c.name}_draft{depth}", src_vocab_size=c.src_vocab_size,
                      tgt_vocab_size=c.tgt_vocab_size, d_model=c.d_model,
                      d_inner=c.d_inner, n_head=c.n_head, n_layer=depth,
                      dropout=0.0, label_smooth=0.0)
        self.depth = depth
        self.serial = serial
        self.model = DecodeModel(
            cfg=dcfg, max_slots=target.max_slots, max_len=target.max_len,
            prefill_buckets=target.prefill_buckets, end_id=target.end_id,
            seed=target.seed, paged=False)
        self._exe = exe
        self.scope = Scope()
        exe.run(self.model.startup, scope=self.scope)
        if serial is not None:
            self._load_serial(serial)

    # -- weights -----------------------------------------------------------

    def _load_serial(self, serial: str) -> None:
        from ..registry import load_serial_weights

        names = self.model.weight_names()
        shapes = {n: tuple(np.asarray(self.scope.get(n)).shape)
                  for n in names}
        weights, _meta = load_serial_weights(serial, names, shapes=shapes)
        for name, arr in weights.items():
            self.scope.set(name, np.asarray(arr, np.float32))

    def sync(self, target_scope) -> None:
        """Copy the shared-by-name weight set target -> draft.  Called
        once after engine startup and again after every weight swap;
        a no-op for serial-backed drafts (their weights are pinned)."""
        if self.serial is not None:
            return
        for name in self.model.weight_names():
            val = target_scope.get(name)
            if val is not None:
                self.scope.set(name, np.array(val, np.float32, copy=True))

    def scrub(self) -> None:
        """Zero the draft slot caches (engine ``_scrub_caches`` hook)."""
        for name in (v.name for v in self.model.startup.list_vars()
                     if v.persistable and "_cache_" in v.name):
            arr = self.scope.get(name)
            if arr is not None:
                self.scope.set(name, np.zeros_like(np.asarray(arr)))
