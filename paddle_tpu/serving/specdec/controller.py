"""Adaptive speculation controller: rolling acceptance-rate gauges and
the fallback / re-arm state machine (ISSUE 20).

Speculation only pays when the draft mostly agrees with the target — a
spec tick costs k draft dispatches plus one (k + 1)-wide verify, so at
low acceptance it is strictly worse than the plain one-token tick it
replaced.  The controller watches a rolling window of spec ticks:

 - every spec tick reports ``(accepted, drafted)`` per participating
   slot; per-slot rolling rates and the aggregate feed the
   ``spec_accept_rate`` gauge;
 - once the window is FULL and the aggregate rate sits below
   ``PADDLE_SERVE_SPEC_MIN_ACCEPT``, the controller trips: the engine
   runs plain one-token ticks (bitwise the PR 15/19 path), a
   ``specdec.fallback`` event fires and ``spec_fallbacks`` counts it;
 - after ``PADDLE_SERVE_SPEC_WINDOW`` plain ticks of cooldown it
   re-arms with a cleared window (``specdec.rearm``) — a transient
   collapse (e.g. the ``PADDLE_FAULT_SPEC_DRAFT_POISON`` drill ending)
   recovers without a restart.

Tripping never affects output bits — acceptance already guarantees spec
output == sequential greedy — it only stops burning draft compute.
Callers hold the engine dispatch lock; no internal locking."""

from __future__ import annotations

import collections
from typing import Deque, Dict, Optional, Tuple

__all__ = ["SpecController"]


class SpecController:

    def __init__(self, min_accept: float, window: int, metrics=None):
        self.min_accept = float(min_accept)
        self.window = max(1, int(window))
        self._metrics = metrics
        self._samples: Deque[Tuple[int, int]] = \
            collections.deque(maxlen=self.window)
        self._slot_samples: Dict[int, Deque[Tuple[int, int]]] = {}
        self._fallen = False
        self._cooldown = 0
        self.fallbacks = 0

    # -- state -------------------------------------------------------------

    @property
    def armed(self) -> bool:
        """True = the next tick may speculate."""
        return not self._fallen

    def rate(self) -> Optional[float]:
        """Aggregate accepted/drafted over the rolling window (None
        until the first spec tick lands)."""
        drafted = sum(d for _a, d in self._samples)
        if not drafted:
            return None
        return sum(a for a, _d in self._samples) / drafted

    def slot_rate(self, slot: int) -> Optional[float]:
        """One slot's rolling acceptance rate (None = never speculated)."""
        q = self._slot_samples.get(slot)
        if not q:
            return None
        drafted = sum(d for _a, d in q)
        return (sum(a for a, _d in q) / drafted) if drafted else None

    # -- transitions -------------------------------------------------------

    def observe(self, per_slot: Dict[int, Tuple[int, int]]) -> None:
        """Record one spec tick's ``{slot: (accepted, drafted)}`` and
        trip to fallback if the full window runs below the floor."""
        acc = sum(a for a, _d in per_slot.values())
        drafted = sum(d for _a, d in per_slot.values())
        self._samples.append((acc, drafted))
        for slot, sample in per_slot.items():
            q = self._slot_samples.get(slot)
            if q is None:
                q = self._slot_samples[slot] = \
                    collections.deque(maxlen=self.window)
            q.append(sample)
        rate = self.rate()
        if rate is not None:
            self._gauge(rate)
        if (rate is not None and rate < self.min_accept
                and len(self._samples) == self.window):
            self._fallen = True
            self._cooldown = self.window
            self.fallbacks += 1
            if self._metrics is not None:
                self._metrics.inc("spec_fallbacks")
            self._emit("specdec.fallback", rate=round(rate, 4),
                       floor=self.min_accept, window=self.window,
                       cooldown_ticks=self.window)

    def note_plain_tick(self) -> None:
        """One plain tick elapsed while fallen; re-arm at cooldown 0.
        The window clears so stale pre-fallback samples cannot trip the
        very next spec tick."""
        if not self._fallen:
            return
        self._cooldown -= 1
        if self._cooldown <= 0:
            self._fallen = False
            self._samples.clear()
            for q in self._slot_samples.values():
                q.clear()
            self._emit("specdec.rearm", window=self.window)

    def retire_slot(self, slot: int) -> None:
        """Drop a retired slot's rolling state — the next resident of
        the slot id starts with a fresh rate."""
        self._slot_samples.pop(slot, None)

    # -- plumbing ----------------------------------------------------------

    def _gauge(self, rate: float) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge("spec_accept_rate", round(rate, 6))
        try:
            from ... import observe

            observe.registry().set_gauge("specdec.accept_rate", rate)
        except Exception:
            pass

    def _emit(self, event: str, **fields) -> None:
        try:
            from ... import observe

            observe.emit(event, **fields)
        except Exception:
            pass
