"""The draft + verify tick (ISSUE 20 tentpole).

One spec tick replaces one plain engine tick:

::

    draft  x k+1 [S,1]-shaped draft-model steps over the draft's own
                 dense cache — k cheap dispatches proposing d_1 .. d_k
                 per slot, plus one cache-fill step (proposal
                 discarded) so a full accept leaves no stale draft row
    verify x 1   ONE (k+1)-position target dispatch
                 (``DecodeModel.spec_program``): position j re-derives
                 exactly what sequential step j would, writes its K/V,
                 and ``spec_accept`` takes the longest draft == argmax
                 prefix plus the first correction token on device
    commit       the engine consumes ``n + 1`` tokens per slot
                 (n = accepted drafts), then rewinds the page pool to
                 the committed frontier — speculatively grown pages
                 return through the pool's single release path

Acceptance is greedy-bitwise BY CONSTRUCTION: every committed token is
a target argmax over a cache prefix identical to sequential decode's
(see ``spec_program``'s shape-clone rationale), so churn, stalls and
fallback can reorder WHEN tokens appear but never WHICH tokens.

Slots too close to ``max_len`` to score k + 1 positions (and any tick
where speculation is off) ride a plain step dispatch instead — the
same warmed executable, so the executable set stays closed:

    1 step + 1 prefill/bucket            (the PR 15/19 set)
  + 1 draft step + 1 draft prefill/bucket + 1 verify

``bucket_compiles`` stays flat after :meth:`SpecDecoder.warmup`.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .controller import SpecController
from .draft import DraftSource

__all__ = ["SpecDecoder"]


class SpecDecoder:
    """Speculative tick orchestration for one DecodeEngine.

    Lives entirely inside the engine's dispatch lock — the worker calls
    :meth:`run_tick` from ``_tick``, admission calls :meth:`prefill`,
    the swap surface calls ``draft.sync`` / ``draft.scrub``.  No
    internal locking."""

    def __init__(self, engine, k: int, draft_layers: int,
                 min_accept: float, window: int,
                 serial: Optional[str] = None):
        if int(k) < 1:
            raise ValueError(f"speculation depth must be >= 1, got {k}")
        self.engine = engine
        self.k = int(k)
        self.draft = DraftSource(engine.model, engine._exe,
                                 draft_layers, serial=serial)
        self.draft.sync(engine._scope)
        self.controller = SpecController(min_accept, window,
                                         metrics=engine.metrics)
        (self._verify_prog, self._tok_fetch, self._nacc_fetch,
         self._logits_fetch) = engine.model.spec_program(self.k)
        # cumulative dispatch wall-time, split draft vs verify — the
        # bench's draft_ms / verify_ms columns read these
        self.draft_s = 0.0
        self.verify_s = 0.0

    # ------------------------------------------------------------------
    # admission + warmup + the draft phase
    # ------------------------------------------------------------------

    def prefill(self, slot: int, tokens: np.ndarray, bucket: int) -> None:
        """Write the prompt's K/V prefix into the DRAFT cache (engine
        ``_prefill`` hook).  Always dispatched — even when the target
        prefill was a prefix-share full hit, the draft's private dense
        cache has no sharing to hit."""
        dm = self.draft.model
        self.engine._run(dm.prefill_program(bucket),
                         {dm.PF_TOKENS: tokens,
                          dm.PF_SLOT: np.asarray([slot], np.int64)},
                         [], scope=self.draft.scope)

    def warmup(self) -> None:
        """Precompile the spec additions to the executable set: every
        draft prefill bucket, the draft step, and the verify program.
        Caller (engine ``warmup``) holds the dispatch lock."""
        eng, dm = self.engine, self.draft.model
        for b in dm.prefill_buckets:
            self.prefill(0, np.zeros((1, b), np.int64), b)
            eng.metrics.inc("warmup_dispatches")
        self._draft_step(np.zeros((eng.model.max_slots, 1), np.int64),
                         np.zeros((eng.model.max_slots,), np.int64),
                         np.zeros((eng.model.max_slots,), np.float32))
        eng.metrics.inc("warmup_dispatches")
        self._dispatch_verify(self._idle_verify_feeds())
        eng.metrics.inc("warmup_dispatches")

    def _draft_step(self, tokens, pos, active) -> np.ndarray:
        dm = self.draft.model
        feeds = {dm.DC_TOKENS: tokens, dm.DC_POS: pos,
                 dm.DC_ACTIVE: active,
                 dm.DC_POSENC: dm.posenc_rows(pos).astype(np.float32),
                 dm.DC_BIAS: dm.validity_bias(pos)}
        (nxt,) = self.engine._run(dm.step_program, feeds,
                                  [dm.step_fetch],
                                  scope=self.draft.scope)
        # writable host copy: the poison hook mutates drafted tokens
        return np.array(nxt, np.int64).reshape(-1)

    def _dispatch_verify(self, feeds):
        outs = self.engine._run(
            self._verify_prog, feeds,
            [self._tok_fetch, self._nacc_fetch, self._logits_fetch])
        return (np.asarray(outs[0]), np.asarray(outs[1]),
                np.asarray(outs[2]))

    def _idle_verify_feeds(self) -> dict:
        """All-inactive verify feeds (warmup): every write aims at the
        trash destination, every row is masked."""
        model = self.engine.model
        s, w = model.max_slots, self.k + 1
        trash = (self.engine._pool.trash_page
                 if self.engine._pool is not None else model.max_slots)
        feeds = {model.SP_DRAFT: np.zeros((s, self.k), np.int64),
                 model.SP_ACTIVE: np.zeros((s,), np.float32)}
        if self.engine._pool is not None:
            feeds[model.SP_PTABLE] = self.engine._pool.table()
        zero_pos = np.zeros((s,), np.int64)
        for j in range(w):
            feeds[model.SP_TOK.format(j)] = np.zeros((s, 1), np.int64)
            feeds[model.SP_PE.format(j)] = \
                model.posenc_rows(zero_pos).astype(np.float32)
            feeds[model.SP_BIAS_J.format(j)] = model.validity_bias(zero_pos)
            feeds[model.SP_WROW.format(j)] = np.full((s,), trash, np.int64)
            feeds[model.SP_WOFF.format(j)] = np.zeros((s,), np.int64)
        return feeds

    # ------------------------------------------------------------------
    # the spec tick
    # ------------------------------------------------------------------

    def run_tick(self) -> bool:
        """One draft + verify tick over the engine's slot table; returns
        False when this tick should run the plain path instead (fallback
        cooldown, or no slot has room to score k + 1 positions)."""
        from ...fluid import fault as _fault

        eng = self.engine
        if not self.controller.armed:
            # a plain tick is about to run; count it toward cooldown
            self.controller.note_plain_tick()
            return False
        model, k, w = eng.model, self.k, self.k + 1
        s = model.max_slots
        slots = list(eng._slots)
        # a slot speculates only when positions pos .. pos+k all fit the
        # cache; tail slots ride a plain step dispatch this same tick
        eligible = [i for i, r in enumerate(slots)
                    if r is not None and int(r.pos) + k <= model.max_len - 1]
        if not eligible:
            return False
        tail = [i for i, r in enumerate(slots)
                if r is not None and i not in eligible]

        # -- draft: k sequential cheap steps over the draft cache ------
        t0 = time.perf_counter()
        tok0 = np.zeros((s, 1), np.int64)
        base = np.zeros((s,), np.int64)
        act = np.zeros((s,), np.float32)
        for i in eligible:
            r = slots[i]
            tok0[i, 0] = (r.out_tokens[-1] if r.out_tokens
                          else r.prompt[-1])
            base[i] = int(r.pos)
            act[i] = 1.0
        poison_from = _fault.spec_draft_poison()
        poisoned = poison_from is not None and eng._ticks >= poison_from
        drafted = np.zeros((s, k), np.int64)
        cur = tok0.copy()
        for j in range(k):
            nxt = self._draft_step(cur, base + j, act)
            if poisoned:
                # deterministic garbage, valid vocab ids: acceptance
                # collapses, the controller trips, and every committed
                # token is still a target argmax — zero wrong bits out
                for i in eligible:
                    nxt[i] = (int(base[i]) + 31 * j + 7 * i) \
                        % model.vocab_size
            drafted[:, j] = nxt
            cur = nxt.reshape(s, 1).astype(np.int64)
        # one extra step, proposal discarded: a FULL accept commits
        # k + 1 tokens, so the draft cache needs row base+k (token d_k)
        # before the next tick's attention reads it — without this
        # write every full accept leaves one stale row behind and the
        # draft diverges from the committed stream until a partial
        # accept happens to overwrite it
        self._draft_step(cur, base + k, act)
        self.draft_s += time.perf_counter() - t0

        # -- verify: one (k+1)-position target dispatch ----------------
        t1 = time.perf_counter()
        pool = eng._pool
        trash = pool.trash_page if pool is not None else model.max_slots
        wrow = [np.full((s,), trash, np.int64) for _ in range(w)]
        woff = [np.zeros((s,), np.int64) for _ in range(w)]
        n_cap: Dict[int, int] = {}
        stalled = set()
        if pool is not None:
            for i in eligible:
                p = int(base[i])
                covered = 0
                for j in range(w):
                    if not pool.ensure(i, p + j):
                        break  # pool dry: rows >= j write trash, and
                    covered += 1  # acceptance caps below them
                if covered == 0:
                    stalled.add(i)  # not even the mandatory write fits:
                    continue        # stall whole-slot like a plain tick
                n_cap[i] = covered - 1
                for j in range(covered):
                    wrow[j][i], woff[j][i] = pool.write_loc(i, p + j)
        else:
            for i in eligible:
                p = int(base[i])
                n_cap[i] = k
                for j in range(w):
                    wrow[j][i], woff[j][i] = i, p + j
        act2 = act.copy()
        for i in stalled:
            act2[i] = 0.0
        feeds = {model.SP_DRAFT: drafted, model.SP_ACTIVE: act2}
        if pool is not None:
            feeds[model.SP_PTABLE] = pool.table()
        for j in range(w):
            tok_j = np.zeros((s, 1), np.int64)
            for i in eligible:
                if i in stalled:
                    continue
                tok_j[i, 0] = tok0[i, 0] if j == 0 else drafted[i, j - 1]
            pos_j = np.where(act2 > 0, base + j, 0)
            feeds[model.SP_TOK.format(j)] = tok_j
            feeds[model.SP_PE.format(j)] = \
                model.posenc_rows(pos_j).astype(np.float32)
            feeds[model.SP_BIAS_J.format(j)] = model.validity_bias(pos_j)
            feeds[model.SP_WROW.format(j)] = wrow[j]
            feeds[model.SP_WOFF.format(j)] = woff[j]
        toks, nacc, logits0 = self._dispatch_verify(feeds)
        self.verify_s += time.perf_counter() - t1

        # -- tail: plain step over the slots that couldn't speculate --
        merged_logits = np.array(logits0)
        tail_nxt, tail_stalled = None, set()
        if tail:
            tail_slots: List = [slots[i] if i in tail else None
                                for i in range(s)]
            tail_nxt, tail_stalled, tail_logits = \
                eng._step_dispatch(tail_slots, count_tick=False)
            for i in tail:
                merged_logits[i] = tail_logits[i]
        t2 = time.perf_counter()

        # -- commit: consume accepted prefix + correction per slot -----
        eng._ticks += 1
        eng.metrics.inc("decode_ticks")
        eng.metrics.inc("spec_ticks")
        eng._last_logits = merged_logits
        sample: Dict[int, Tuple[int, int]] = {}
        for i in eligible:
            req = slots[i]
            if i in stalled:
                eng._stall_expire(i, req, t2)
                continue
            n = min(int(nacc[i]), n_cap[i])
            sample[i] = (n, k)
            eng.metrics.inc("spec_draft_tokens", k)
            eng.metrics.inc("spec_accepted_tokens", n)
            for j in range(n + 1):
                if eng._consume(i, req, int(toks[i, j]), t1, t2):
                    break  # retired (end_id / budget / expiry):
                           # _retire released every page
            else:
                if pool is not None:
                    # rejected speculative growth rewinds to the
                    # committed frontier (req.pos = the next write)
                    pool.rewind(i, int(req.pos))
        for i in tail:
            req = slots[i]
            if i in tail_stalled:
                eng._stall_expire(i, req, t2)
                continue
            eng._consume(i, req, int(tail_nxt[i]), t1, t2)
        if sample:
            self.controller.observe(sample)
        eng._run_monitor(merged_logits, slots)
        return True
