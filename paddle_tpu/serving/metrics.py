"""Serving metrics: a lock-protected registry for the engine's counters,
gauges and latency distribution.

The reference stack exported serving health through each server's
`/metrics`-style counters; here one in-process registry covers the single
engine.  Everything is O(1) per observation: counters are plain ints,
latencies go into a fixed-size ring buffer (percentiles are computed only
at ``snapshot()`` time), and batch occupancy is tracked as two running
sums (real rows / padded bucket rows).

``snapshot()`` returns a plain dict so callers can json.dump it (the bench
tool's BENCH-line format) or diff two snapshots.  Per-event wiring into
``fluid.profiler.record_event`` means a ``fluid.profiler.profiler()``
context around serving traffic gets ``serving_request`` /
``serving_dispatch[...]`` rows in the standard aggregate table for free.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Thread-safe counters + latency reservoir for one ServingEngine."""

    #: counters every snapshot reports even when still zero
    COUNTERS = ("submitted", "completed", "failed", "shed", "expired",
                "dispatches", "bucket_compiles", "warmup_dispatches",
                "warmup_cached")

    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {k: 0 for k in self.COUNTERS}
        self._gauges: Dict[str, float] = {"queue_depth": 0}
        # latency ring buffer, seconds; percentile accuracy degrades
        # gracefully under sustained load instead of growing unboundedly
        self._window = int(latency_window)
        self._lat = [0.0] * self._window
        self._lat_n = 0  # total observations ever (ring index = n % window)
        self._rows_real = 0
        self._rows_padded = 0
        self._t0 = time.perf_counter()

    # -- recording --
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name: str, value) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe_latency(self, seconds: float) -> None:
        """One completed request's queue+execute latency."""
        with self._lock:
            self._lat[self._lat_n % self._window] = float(seconds)
            self._lat_n += 1
        # profiler hook: no-op unless a profiler session is active
        from ..fluid import profiler as _prof

        _prof.record_event("serving_request", seconds)

    def observe_batch(self, real_rows: int, bucket_rows: int,
                      seconds: Optional[float] = None) -> None:
        """One executor dispatch: ``real_rows`` request rows padded into a
        ``bucket_rows`` executable."""
        with self._lock:
            self._rows_real += int(real_rows)
            self._rows_padded += int(bucket_rows)
        if seconds is not None:
            from ..fluid import profiler as _prof

            _prof.record_event(f"serving_dispatch[bs={bucket_rows}]", seconds)

    # -- reading --
    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def _percentiles(self, lat, qs):
        if not lat:
            return {f"p{int(q * 100)}_ms": None for q in qs}
        s = sorted(lat)
        out = {}
        for q in qs:
            idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
            out[f"p{int(q * 100)}_ms"] = round(s[idx] * 1e3, 3)
        return out

    def snapshot(self) -> dict:
        """Point-in-time dict of every metric (safe to json.dump)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            n = min(self._lat_n, self._window)
            lat = list(self._lat[:n])
            rows_real, rows_padded = self._rows_real, self._rows_padded
            elapsed = time.perf_counter() - self._t0
        snap = dict(counters)
        snap.update(gauges)
        snap["elapsed_s"] = round(elapsed, 3)
        snap["qps"] = round(counters.get("completed", 0) / elapsed, 3) \
            if elapsed > 0 else 0.0
        snap.update(self._percentiles(lat, (0.50, 0.95, 0.99)))
        snap["latency_samples"] = n
        snap["mean_batch_occupancy"] = (
            round(rows_real / rows_padded, 4) if rows_padded else None)
        return snap
