"""Serving metrics: the engine's counters, gauges and latency distribution,
backed by the unified observability registry (``paddle_tpu.observe``).

Each engine owns a private :class:`~paddle_tpu.observe.MetricsRegistry`
(so two engines in one process never mix counts) and MIRRORS every counter
and gauge into the process-wide registry under the ``serving.`` prefix —
that is what the ``/metrics`` endpoint, the background flusher and the
fleet aggregator read, so one engine's traffic is visible fleet-wide
without any extra wiring.  Latencies additionally land in the global
``serving.latency_s`` histogram (Prometheus-bucket form) while the private
ring buffer keeps exact-ish percentiles for ``snapshot()``.

Windowed rates (ISSUE 5 satellite): ``snapshot()``'s cumulative ``qps``
decays toward the lifetime mean and is meaningless after hours of uptime.
``window(prev, cur)`` computes interval rates from ANY two snapshots, and
``interval()`` maintains the previous-snapshot state for you — each call
returns the rates since the last call (exactly Prometheus ``rate()``
semantics, computed client-side).  ``tools/bench_serving.py`` and the
``/metrics`` endpoint report these, not the lifetime average.

``snapshot()`` returns a plain dict so callers can json.dump it (the bench
tool's BENCH-line format) or diff two snapshots.  Per-event wiring into
``fluid.profiler.record_event`` means a ``fluid.profiler.profiler()``
context around serving traffic gets ``serving_request`` /
``serving_dispatch[...]`` rows in the standard aggregate table for free.

Fleet label dimension (ISSUE 17 satellite): ``ServingMetrics(labels=
{"model": ..., "replica": ...})`` stamps every GLOBAL-registry mirror
with those labels — ``serving.completed{model="chat",replica="chat-r0"}``
— so the fleet aggregator (``observe.fleet.label_sums``) sums per-model
/ per-replica through the registry's structured label support instead of
string-parsing metric names.  The PRIVATE registry stays unlabeled (it
is per-engine by construction; ``snapshot()`` keys stay flat), and the
SLO-watchdog feeds stay on the unlabeled series names (breach policy is
fleet-wide).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..observe import MetricsRegistry
from ..observe import registry as _global_registry

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Thread-safe counters + latency reservoir for one ServingEngine."""

    #: counters every snapshot reports even when still zero
    COUNTERS = ("submitted", "completed", "failed", "shed", "expired",
                "dispatches", "bucket_compiles", "warmup_dispatches",
                "warmup_cached", "rows_real", "rows_padded",
                # continuous-batching decode (ISSUE 15): iteration-level
                # scheduling counters, zero-reported on batch engines too
                # so snapshot consumers never branch on engine kind
                "prefills", "decode_ticks", "tokens_generated",
                # hot model swap (ISSUE 16): registry swap/rollback counts;
                # the serving.model_serial gauge rides set_gauge
                "model_swaps", "model_rollbacks",
                # paged KV cache (ISSUE 19): prefix-cache hits (one per
                # shared full-prompt page attached at admit) and whole
                # prefill dispatches skipped because every prompt page was
                # already resident — plus admissions bounced back to the
                # queue because the page pool ran dry (backpressure, the
                # paged twin of "shed" — except nothing is lost).  All
                # zero-reported on dense engines.
                "prefix_hits", "prefill_skips", "page_requeues",
                # speculative decoding (ISSUE 20): spec ticks taken, draft
                # tokens proposed vs accepted (their ratio is the rolling
                # acceptance rate the adaptive controller watches, also
                # published as the spec_accept_rate gauge), and controller
                # fallbacks to plain one-token ticks.  Zero-reported with
                # speculation off.
                "spec_ticks", "spec_draft_tokens", "spec_accepted_tokens",
                "spec_fallbacks")

    def __init__(self, latency_window: int = 4096,
                 registry: Optional[MetricsRegistry] = None,
                 labels: Optional[Dict[str, str]] = None):
        self._reg = registry or MetricsRegistry()
        #: labels stamped on every process-registry mirror (model=/replica=
        #: in a fleet); None keeps the single-engine flat names
        self._labels = {str(k): str(v) for k, v in labels.items()} \
            if labels else None
        self._lock = self._reg.lock  # one lock for registry + ring state
        for k in self.COUNTERS:
            self._reg.inc(k, 0)
        self._reg.set_gauge("queue_depth", 0)
        # latency ring buffers, seconds; percentile accuracy degrades
        # gracefully under sustained load instead of growing unboundedly.
        # Decode engines additionally track time-to-first-token and
        # inter-token gaps — the two latencies request-level percentiles
        # cannot decompose (a long generation with healthy per-token
        # pacing vs a short one stuck behind a convoy look identical in
        # completion latency).
        self._window = int(latency_window)
        self._lat = [0.0] * self._window
        self._lat_n = 0  # total observations ever (ring index = n % window)
        self._ttft = [0.0] * self._window
        self._ttft_n = 0
        self._itl = [0.0] * self._window
        self._itl_n = 0
        self._t0 = time.perf_counter()
        self._last_interval: Optional[dict] = None

    # -- recording --
    def inc(self, name: str, n: int = 1) -> None:
        self._reg.inc(name, n)
        _global_registry().inc(f"serving.{name}", n, labels=self._labels)

    def set_gauge(self, name: str, value) -> None:
        self._reg.set_gauge(name, value)
        _global_registry().set_gauge(f"serving.{name}", value,
                                     labels=self._labels)
        if name == "queue_depth":
            from ..observe import watchdog as _watchdog

            # SLO watchdog on admission-queue depth (no-op unless armed)
            _watchdog.observe_value("serving.queue_depth", value)

    def note_bucket_bytes(self, bucket: int, peak_bytes: float) -> None:
        """Per-bucket compiled HBM footprint (``ServingEngine.warmup``):
        the ``serving.bucket_bytes{bucket=...}`` gauge in BOTH registries
        — capacity planning reads it to answer 'how many replicas fit on
        one device pool' without re-lowering anything."""
        self._reg.set_gauge("bucket_bytes", float(peak_bytes),
                            labels={"bucket": int(bucket)})
        _global_registry().set_gauge("serving.bucket_bytes",
                                     float(peak_bytes),
                                     labels=dict(self._labels or {},
                                                 bucket=int(bucket)))

    def observe_latency(self, seconds: float) -> None:
        """One completed request's queue+execute latency."""
        with self._lock:
            self._lat[self._lat_n % self._window] = float(seconds)
            self._lat_n += 1
        _global_registry().observe("serving.latency_s", seconds,
                                   labels=self._labels)
        from ..observe import watchdog as _watchdog

        # per-request latency feeds the SLO watchdog: a p99 regression IS
        # individual requests regressing past the rolling baseline
        _watchdog.observe_value("serving.latency_s", seconds)
        # profiler hook: no-op unless a profiler session is active
        from ..fluid import profiler as _prof

        _prof.record_event("serving_request", seconds)

    def observe_ttft(self, seconds: float) -> None:
        """Time-to-first-token of one decode request (submit -> first
        generated token): prefill queueing + prefill dispatch + the first
        decode tick.  Feeds the SLO watchdog as ``serving.ttft_s``."""
        with self._lock:
            self._ttft[self._ttft_n % self._window] = float(seconds)
            self._ttft_n += 1
        _global_registry().observe("serving.ttft_s", seconds,
                                   labels=self._labels)
        from ..observe import watchdog as _watchdog

        _watchdog.observe_value("serving.ttft_s", seconds)
        from ..fluid import profiler as _prof

        _prof.record_event("serving_ttft", seconds)

    def observe_intertoken(self, seconds: float) -> None:
        """Gap between two consecutive generated tokens of one stream —
        the per-tick pacing metric iteration-level scheduling exists to
        protect.  Feeds the SLO watchdog as ``serving.intertoken_s`` (the
        PADDLE_FAULT_DECODE_STALL_MS breach oracle)."""
        with self._lock:
            self._itl[self._itl_n % self._window] = float(seconds)
            self._itl_n += 1
        _global_registry().observe("serving.intertoken_s", seconds,
                                   labels=self._labels)
        from ..observe import watchdog as _watchdog

        _watchdog.observe_value("serving.intertoken_s", seconds)

    def note_slots(self, active: int, free: int) -> None:
        """Decode slot occupancy: mirrored into BOTH registries (so the
        process ``/metrics`` endpoint and the fleet aggregator see
        ``serving.slots_active`` / ``serving.slots_free`` without extra
        wiring — the ISSUE 15 observability satellite)."""
        self.set_gauge("slots_active", int(active))
        self.set_gauge("slots_free", int(free))

    def observe_batch(self, real_rows: int, bucket_rows: int,
                      seconds: Optional[float] = None) -> None:
        """One executor dispatch: ``real_rows`` request rows padded into a
        ``bucket_rows`` executable."""
        self.inc("rows_real", int(real_rows))
        self.inc("rows_padded", int(bucket_rows))
        if seconds is not None:
            from ..fluid import profiler as _prof

            _prof.record_event(f"serving_dispatch[bs={bucket_rows}]",
                               seconds)

    # -- reading --
    def counter(self, name: str) -> int:
        return self._reg.flat().get(name, 0)

    def _percentiles(self, lat, qs, prefix=""):
        if not lat:
            return {f"{prefix}p{int(q * 100)}_ms": None for q in qs}
        s = sorted(lat)
        out = {}
        for q in qs:
            idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
            out[f"{prefix}p{int(q * 100)}_ms"] = round(s[idx] * 1e3, 3)
        return out

    def snapshot(self) -> dict:
        """Point-in-time dict of every metric (safe to json.dump)."""
        with self._lock:
            flat = self._reg.flat()
            n = min(self._lat_n, self._window)
            lat = list(self._lat[:n])
            n_ttft = min(self._ttft_n, self._window)
            ttft = list(self._ttft[:n_ttft])
            n_itl = min(self._itl_n, self._window)
            itl = list(self._itl[:n_itl])
            elapsed = time.perf_counter() - self._t0
        snap = dict(flat)
        snap["elapsed_s"] = round(elapsed, 3)
        snap["qps"] = round(flat.get("completed", 0) / elapsed, 3) \
            if elapsed > 0 else 0.0
        snap.update(self._percentiles(lat, (0.50, 0.95, 0.99)))
        snap.update(self._percentiles(ttft, (0.50, 0.99), prefix="ttft_"))
        snap.update(self._percentiles(itl, (0.50, 0.99),
                                      prefix="intertoken_"))
        snap["latency_samples"] = n
        snap["ttft_samples"] = n_ttft
        snap["intertoken_samples"] = n_itl
        rows_real = flat.get("rows_real", 0)
        rows_padded = flat.get("rows_padded", 0)
        snap["mean_batch_occupancy"] = (
            round(rows_real / rows_padded, 4) if rows_padded else None)
        return snap

    def export_snapshot(self) -> dict:
        """This engine's metrics in ``MetricsRegistry.snapshot()`` shape
        with the ``serving.`` prefix — the ``/metrics`` endpoint's provider
        view.  Counters/gauges are the SAME values ``snapshot()`` reports
        (one consistent source, the private registry), plus per-scrape
        interval rates as gauges (``serving.interval_qps`` ...) so the
        endpoint shows current throughput, not the decayed lifetime mean."""
        snap = self._reg.snapshot()
        out = {fam: {f"serving.{k}": v for k, v in snap.get(fam, {}).items()}
               for fam in ("counters", "gauges", "histograms")}
        rates = self.interval()
        for src, dst in (("qps", "serving.interval_qps"),
                         ("dispatch_rate", "serving.interval_dispatch_rate"),
                         ("interval_s", "serving.interval_s"),
                         ("tokens_per_s", "serving.interval_tokens_per_s"),
                         ("mean_batch_occupancy",
                          "serving.interval_batch_occupancy")):
            v = rates.get(src)
            if isinstance(v, (int, float)):
                out["gauges"][dst] = v
        return out

    # -- windowed rates --
    @staticmethod
    def window(prev: dict, cur: dict) -> dict:
        """Interval rates between two ``snapshot()`` dicts (cur - prev):
        current throughput/shed-rate/occupancy, immune to uptime decay.

        An EMPTY interval (identical snapshots, zero elapsed time, no
        padded rows) is well-defined zeros across the board — never
        None/NaN/ZeroDivision — so the ``/metrics`` endpoint and the
        bench tool can emit every field unconditionally (ISSUE 9
        satellite; ISSUE 15 extends the same contract to the decode
        series: ``tokens_per_s`` / ``tick_rate`` are finite zeros on an
        idle decode engine)."""
        dt = max(0.0, cur.get("elapsed_s", 0) - prev.get("elapsed_s", 0))
        delta: Dict[str, float] = {
            k: cur.get(k, 0) - prev.get(k, 0)
            for k in ("completed", "submitted", "failed", "shed", "expired",
                      "dispatches", "rows_real", "rows_padded",
                      "prefills", "decode_ticks", "tokens_generated")}
        out = {"interval_s": round(dt, 3)}
        out.update({k: v for k, v in delta.items()})
        out["qps"] = round(delta["completed"] / dt, 3) if dt > 0 else 0.0
        out["dispatch_rate"] = (round(delta["dispatches"] / dt, 3)
                                if dt > 0 else 0.0)
        out["tokens_per_s"] = (round(delta["tokens_generated"] / dt, 3)
                               if dt > 0 else 0.0)
        out["tick_rate"] = (round(delta["decode_ticks"] / dt, 3)
                            if dt > 0 else 0.0)
        out["mean_batch_occupancy"] = (
            round(delta["rows_real"] / delta["rows_padded"], 4)
            if delta["rows_padded"] else 0.0)
        return out

    def interval(self) -> dict:
        """Rates since the previous ``interval()`` call (or construction).
        Each caller tick defines the window — a /metrics scrape loop gets
        per-scrape rates for free."""
        cur = self.snapshot()
        with self._lock:
            prev = self._last_interval
            self._last_interval = cur
        if prev is None:
            prev = {"elapsed_s": 0.0}
        return self.window(prev, cur)
