"""Continuous batching for autoregressive decode (ISSUE 15 tentpole).

The PR 2 :class:`~paddle_tpu.serving.engine.ServingEngine` batches at
REQUEST granularity: a batch runs to completion before its members
resolve, so one long generation convoys every short request behind it,
and each distinct live-batch shape risks a fresh XLA executable.  This
module is the canonical fix (the Orca/vLLM iteration-level design,
shaped TPU-first):

 - **Slot-based KV cache**: the decode state is a persistable
   ``[max_slots, max_len, d_model]`` pytree of per-layer K/V caches that
   lives DEVICE-RESIDENT across dispatches (executor scope state, donated
   buffers aliasing window-over-window — the PR 6 machinery, opted in
   via ``program._donate_state``).  A request owns one slot from
   admission to retirement.
 - **Iteration-level scheduling**: every engine tick runs ONE compiled
   decode step over ALL slots — fixed ``[max_slots, ...]`` shapes mean
   exactly one decode executable plus a small bucketed-prefill set, so
   the compile counter stays flat in steady state no matter how requests
   arrive (the shape discipline the bucket manifest and compile cache
   were built for).  New requests enter free slots mid-flight via a
   bucketed prefill that writes their K/V prefix in place; finished
   slots retire IMMEDIATELY, so a short request's latency is
   O(own length), not O(longest cohabitant).
 - **Worker loop**: ``admit -> step -> retire``, one thread owning every
   dispatch (single jit-cache writer, donation-safe).

Correctness contract: the decode-step program is row-independent over
the slot dim and masks stale cache positions with EXACT ``-inf`` bias
(zero attention weight in IEEE), so generated tokens are bitwise
identical to per-request sequential decode — continuous batching is
purely a scheduling change.  :meth:`DecodeEngine.decode_static` keeps
the request-granularity baseline alive as the convoy oracle's
comparator.

Observability: ``serving.request`` spans gain ``serving.prefill`` and
``serving.decode_step`` × N children (iteration-level preemption is
visible in the span tree); :class:`ServingMetrics` gains TTFT and
inter-token latency series plus ``slots_active``/``slots_free`` gauges
mirrored into the process registry; the SLO watchdog watches
``serving.ttft_s``/``serving.intertoken_s`` (deterministic breach
oracle: ``PADDLE_FAULT_DECODE_STALL_MS``).

Hot model swap (ISSUE 16): weights are shared BY NAME across the
startup/prefill/step programs through the engine's one scope, and the
executor re-gathers state from the scope on every dispatch — so
:meth:`DecodeEngine.swap_weights` is a scope rebind between ticks under
``_dispatch_lock``, never a recompile, and the fixed-executable-set
invariant holds across arbitrarily many checkpoint swaps.  The
per-tick monitor hook (:meth:`DecodeEngine.set_tick_monitor`) hands the
step's logits to ``serving.registry``'s canary sentinel.

Paged KV cache (ISSUE 19): with ``PADDLE_SERVE_PAGED=1`` the model's
per-layer caches become ``[num_pages + 1, page_size, d_model]`` page
pools and the engine drives a host-side :class:`~.kvpool.PagePool` —
admission allocates pages (or re-queues on exhaustion: backpressure,
never a crash), decode growth allocates one page per ``page_size``
ticks (a dry pool stalls the slot one bitwise-invisible tick), retire
and deadline expiry return pages EXPLICITLY, and full prompt pages are
refcount-shared across requests with a common prefix (``full_hit``
admissions skip the prefill dispatch outright).  Decode output stays
bitwise identical to the dense engine — the page indirection only moves
where K/V rows live, never what they contain or how they reduce.

Knobs (``fluid.envcontract``): ``PADDLE_SERVE_DECODE`` (kill switch),
``PADDLE_SERVE_SLOTS``, ``PADDLE_SERVE_MAX_LEN``,
``PADDLE_SERVE_PREFILL_BUCKETS``; paged mode adds
``PADDLE_SERVE_PAGED``, ``PADDLE_SERVE_PAGE_SIZE``,
``PADDLE_SERVE_NUM_PAGES``, ``PADDLE_SERVE_PREFIX_SHARE``; speculative
decoding (ISSUE 20, ``serving.specdec``) adds ``PADDLE_SERVE_SPEC``,
``PADDLE_SERVE_SPEC_DRAFT_LAYERS``, ``PADDLE_SERVE_SPEC_MIN_ACCEPT``,
``PADDLE_SERVE_SPEC_WINDOW``.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .engine import (DrainTimeout, EngineClosed, EngineOverloaded,
                     RequestTimeout, _Request)
from .metrics import ServingMetrics

__all__ = ["DecodeConfig", "DecodeEngine", "create_decode_engine"]


@dataclass
class DecodeConfig:
    """Scheduling policy for a :class:`DecodeEngine`.  The SHAPE knobs
    (slots, max_len, prefill buckets) live on the model — they define
    the executable set — while this carries pure policy:

    ``max_queue_depth``    pending requests beyond this shed with
                           :class:`EngineOverloaded` (same fast-fail
                           backpressure as the batch engine);
    ``default_timeout_ms`` per-request deadline when submit() gets none.
                           Decode deadlines are checked PER TOKEN: a
                           request can expire mid-generation and free
                           its slot for the queue;
    ``idle_wait_s``        worker-condition wait while fully idle;
    ``spec``               speculation depth k (draft+verify ticks,
                           ISSUE 20).  None = use ``PADDLE_SERVE_SPEC``
                           (config beats env; 0 is the kill switch);
    ``spec_draft_layers``  self-draft depth override for
                           ``PADDLE_SERVE_SPEC_DRAFT_LAYERS`` (0 =
                           full-depth self-draft);
    ``spec_draft_serial``  registry serial directory to load the draft
                           model's weights from instead of sharing the
                           target's (serving.registry
                           ``load_serial_weights`` path).
    """
    max_queue_depth: int = 256
    default_timeout_ms: Optional[float] = None
    idle_wait_s: float = 0.05
    spec: Optional[int] = None
    spec_draft_layers: Optional[int] = None
    spec_draft_serial: Optional[str] = None


class DecodeEngine:
    """Iteration-level-scheduled generation over one step-form decode
    model (:class:`paddle_tpu.models.transformer.DecodeModel`).

    ``submit(prompt_ids, max_new_tokens)`` returns a Future of the
    generated token-id list (greedy decode; ends at the model's
    ``end_id``, the token budget, or cache capacity).  Use as a context
    manager or call ``shutdown()``."""

    def __init__(self, model=None, config: Optional[DecodeConfig] = None,
                 place=None, metrics_labels: Optional[Dict[str, str]] = None):
        from ..fluid import envcontract as _ec

        if not _ec.get("PADDLE_SERVE_DECODE"):
            raise EngineClosed(
                "continuous-batching decode is disabled "
                "(PADDLE_SERVE_DECODE=0)")
        if model is None:
            from ..models.transformer import DecodeModel

            model = DecodeModel()
        self.model = model
        self.config = config or DecodeConfig()
        # metrics_labels (e.g. {"model": ..., "replica": ...}) dimension
        # this engine's process-registry mirrors so a fleet of engines
        # stays separable in one registry (serving/fleet.py sets them)
        self.metrics = ServingMetrics(labels=metrics_labels)
        from ..fluid import core as _core
        from ..fluid.executor import Executor, Scope

        self._scope = Scope()
        self._exe = Executor(place if place is not None
                             else _core.CPUPlace())
        self._exe.run(model.startup, scope=self._scope)
        # paged KV cache (ISSUE 19): when the model was built paged, all
        # page policy lives in this host-side pool — the worker consults
        # it under _dispatch_lock for admissions (backpressure), growth
        # (per-tick stalls) and frees (retire/expiry/reap)
        self._pool = None
        if getattr(model, "paged", False):
            from .kvpool import PagePool

            page_bytes = (model.page_size * model.cfg.d_model * 4
                          * 2 * model.cfg.n_layer)
            self._pool = PagePool(
                model.num_pages, model.page_size, model.pages_per_slot,
                model.max_slots, page_bytes=page_bytes,
                prefix_share=bool(_ec.get("PADDLE_SERVE_PREFIX_SHARE")),
                metrics=self.metrics)
        # speculative decoding (ISSUE 20): PADDLE_SERVE_SPEC=k>0 arms
        # draft+verify ticks; DecodeConfig fields beat the env knobs.
        # k=0 is the kill switch — the plain tick runs verbatim and no
        # draft model is even built.
        self._spec = None
        spec_k = (self.config.spec if self.config.spec is not None
                  else int(_ec.get("PADDLE_SERVE_SPEC") or 0))
        if spec_k > 0:
            from .specdec import SpecDecoder

            draft_layers = (
                self.config.spec_draft_layers
                if self.config.spec_draft_layers is not None
                else int(_ec.get("PADDLE_SERVE_SPEC_DRAFT_LAYERS")))
            self._spec = SpecDecoder(
                self, spec_k, draft_layers,
                min_accept=float(_ec.get("PADDLE_SERVE_SPEC_MIN_ACCEPT")),
                window=int(_ec.get("PADDLE_SERVE_SPEC_WINDOW")),
                serial=self.config.spec_draft_serial)
        self._cond = threading.Condition(threading.Lock())
        self._queue: collections.deque = collections.deque()
        self._slots: List[Optional[_Request]] = [None] * model.max_slots
        self._n_active = 0
        self._ticks = 0
        self._draining = False
        self._paused = False  # hot-swap drain: hold admissions, keep queue
        self._stopped = False
        self._rid = itertools.count()
        self._tick_monitor = None  # registry canary sentinel (or None)
        self._last_logits = None
        # serializes every dispatch: the worker holds it per iteration,
        # warmup()/decode_static() grab it between iterations
        self._dispatch_lock = threading.Lock()
        self.metrics.note_slots(0, model.max_slots)
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-worker")
        self._worker.start()
        # piggyback on the process observe endpoint when one is up, like
        # the batch engine's port-less mode
        from .. import observe

        srv = observe.http_server()
        if srv is not None:
            srv.add_provider(self.metrics.export_snapshot)
            srv.add_health(self._health)

    @property
    def alive(self) -> bool:
        """False once the engine stopped (shutdown, kill, worker death) —
        the fleet census's liveness probe."""
        return not self._stopped and self._worker.is_alive()

    def _health(self) -> dict:
        with self._cond:
            return {"ok": not self._stopped and not self._draining,
                    "queue_depth": len(self._queue),
                    "slots_active": self._n_active,
                    "slots_free": self.model.max_slots - self._n_active}

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int,
               timeout_ms: Optional[float] = None) -> Future:
        """Enqueue one generation request; returns a Future of the
        generated token ids (list of int, excluding the prompt)."""
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        if any(not 0 <= t < self.model.vocab_size for t in prompt):
            raise ValueError(f"prompt token out of vocab range "
                             f"[0, {self.model.vocab_size})")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.model.bucket_for(len(prompt)) is None:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest "
                f"prefill bucket ({self.model.prefill_buckets[-1]})")
        if len(prompt) + max_new > self.model.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceed the KV-cache capacity "
                f"(max_len {self.model.max_len})")
        if timeout_ms is None:
            timeout_ms = self.config.default_timeout_ms
        now = time.perf_counter()
        fut: Future = Future()
        req = _Request(None, 1, None, fut, now + timeout_ms / 1000.0
                       if timeout_ms else None, now)
        req.prompt, req.max_new, req.out_tokens = prompt, max_new, []
        req.rid = f"d{next(self._rid)}"
        with self._cond:
            if self._stopped or self._draining:
                raise EngineClosed("decode engine is draining/stopped")
            if len(self._queue) >= self.config.max_queue_depth:
                self.metrics.inc("shed")
                from .. import observe

                observe.emit("serving.shed", kind="decode",
                             queue_depth=self.config.max_queue_depth)
                raise EngineOverloaded(
                    f"decode queue full ({self.config.max_queue_depth} "
                    f"pending); request shed")
            from ..observe import trace as _trace

            req.span = _trace.start_span("serving.request", kind="decode",
                                         prompt_len=len(prompt),
                                         max_new=max_new)
            self._queue.append(req)
            self.metrics.inc("submitted")
            self.metrics.set_gauge("queue_depth", len(self._queue))
            self._cond.notify()
        return fut

    def generate(self, prompt_ids: Sequence[int], max_new_tokens: int,
                 timeout_ms: Optional[float] = None) -> List[int]:
        """Blocking submit."""
        return self.submit(prompt_ids, max_new_tokens,
                           timeout_ms=timeout_ms).result()

    # ------------------------------------------------------------------
    # the worker loop: admit -> step -> retire
    # ------------------------------------------------------------------

    def _loop(self):
        from ..fluid import fault as _fault

        while True:
            with self._cond:
                # a paused engine (mid hot-swap drain) must not spin on
                # its queue: only admissible work or live slots wake it
                while not self._n_active and not self._stopped \
                        and not (self._queue and not self._paused):
                    self._cond.wait(self.config.idle_wait_s)
                if self._stopped:
                    break
            with self._dispatch_lock:
                # robustness-harness hook: per-tick injected stall (the
                # deterministic inter-token-latency breach oracle)
                _fault.decode_stall()
                self._reap_abandoned()
                self._admit()
                if self._n_active:
                    self._tick()
            with self._cond:
                self._cond.notify_all()  # drain() watches progress
        self._fail_leftovers()

    def _reap_abandoned(self):
        """Free slots whose futures were already resolved from outside
        the worker (the bounded-drain timeout fails stuck futures with
        DrainTimeout; their slots must not keep decoding dead work)."""
        for i, r in enumerate(self._slots):
            if r is not None and r.future.done():
                self._slots[i] = None
                self._n_active -= 1
                if self._pool is not None:
                    self._pool.release(i)
        self.metrics.note_slots(self._n_active,
                                self.model.max_slots - self._n_active)

    def _fail_leftovers(self):
        """Worker exit with work still resident (drain timeout path):
        nothing will ever resolve these futures — fail them loudly."""
        leftovers = [r for r in self._slots if r is not None]
        if self._pool is not None:
            for i, r in enumerate(self._slots):
                if r is not None:
                    self._pool.release(i)
        self._slots = [None] * self.model.max_slots
        self._n_active = 0
        with self._cond:
            leftovers += list(self._queue)
            self._queue.clear()
        for r in leftovers:
            if r.future.done():
                continue  # already failed by the bounded-drain path
            self.metrics.inc("failed")
            if r.span is not None:
                r.span.end(status="engine_stopped")
            r.future.set_exception(
                EngineClosed("decode engine stopped"))

    def _admit(self):
        """Fill free slots from the queue: one bucketed prefill dispatch
        per admitted request writes its K/V prefix in place."""
        if self._paused:
            return  # hot-swap drain: queue keeps building, nothing sheds
        while True:
            free = next((i for i, r in enumerate(self._slots)
                         if r is None), None)
            if free is None:
                return
            req = None
            with self._cond:
                while self._queue:
                    cand = self._queue.popleft()
                    now = time.perf_counter()
                    if cand.deadline is not None and now > cand.deadline:
                        self.metrics.inc("expired")
                        if cand.span is not None:
                            cand.span.end(status="expired")
                        cand.future.set_exception(RequestTimeout(
                            f"deadline expired after "
                            f"{(now - cand.t_submit) * 1e3:.1f} ms in "
                            f"queue"))
                        continue
                    req = cand
                    break
                self.metrics.set_gauge("queue_depth", len(self._queue))
                if req is not None:
                    # reserve the slot HERE, still under _cond: between
                    # the queue pop and the end of the prefill dispatch
                    # the request must stay visible to the bounded-drain
                    # abort (which scans queue + slots under _cond) — a
                    # drain expiry in that window would otherwise miss
                    # it and the request would decode to completion
                    # unaborted
                    self._slots[free] = req
                    self._n_active += 1
            if req is None:
                return
            if self._pool is not None:
                grant = self._pool.admit(
                    free, req.prompt,
                    self.model.bucket_for(len(req.prompt)))
                if grant is None:
                    # admission backpressure: not enough free pages —
                    # put the request BACK at the head of the queue and
                    # give the slot up.  Resident streams retire pages
                    # over the next ticks; the request re-admits then.
                    with self._cond:
                        self._slots[free] = None
                        self._n_active -= 1
                        self._queue.appendleft(req)
                        self.metrics.inc("page_requeues")
                        self.metrics.set_gauge("queue_depth",
                                               len(self._queue))
                        idle = self._n_active == 0
                    if idle:
                        # nothing is retiring pages: don't busy-spin the
                        # worker against a dry pool (release() notifies
                        # nobody; the idle wait is the retry cadence)
                        time.sleep(self.config.idle_wait_s)
                    return
                req.grant = grant
            self._prefill(req, free)

    def _prefill(self, req: _Request, slot: int):
        from ..observe import trace as _trace

        model = self.model
        plen = len(req.prompt)
        bucket = model.bucket_for(plen)
        tokens = np.zeros((1, bucket), np.int64)
        tokens[0, :plen] = req.prompt
        t0 = time.perf_counter()
        # prefix sharing: when every page the prefill would write below
        # plen-1 is already resident (full_hit), the dispatch is pure
        # re-derivation of bit-identical K/V — skip it entirely.  On a
        # PARTIAL hit the prefill still runs: rewriting a shared page
        # with the same (bucket, prefix) content is bitwise idempotent.
        grant = getattr(req, "grant", None)
        skip = (self._pool is not None and grant is not None
                and grant.full_hit)
        if not skip:
            feeds = {model.PF_TOKENS: tokens}
            if self._pool is not None:
                feeds[model.PF_PAGES] = self._pool.prefill_pages(slot,
                                                                 bucket)
            else:
                feeds[model.PF_SLOT] = np.asarray([slot], np.int64)
            self._run(model.prefill_program(bucket), feeds, [])
            self.metrics.inc("prefills")
        else:
            self.metrics.inc("prefill_skips")
            from .. import observe

            observe.registry().inc("kvpool.prefill_skips")
        if self._spec is not None:
            # the draft cache is private and unshared: its prefill runs
            # even when the target's was a full-hit skip
            self._spec.prefill(slot, tokens, bucket)
        t1 = time.perf_counter()
        req.t_taken = t0
        req.slot = slot
        # the first decode tick re-derives position plen-1 (same token,
        # same weights => bit-identical K/V) and emits the first token
        req.pos = plen - 1
        self.metrics.note_slots(self._n_active,
                                model.max_slots - self._n_active)
        if req.span is not None:
            _trace.emit_span("serving.queue", req.t_submit, t0,
                             parent=req.span)
            if not skip:
                _trace.emit_span("serving.prefill", t0, t1,
                                 parent=req.span, bucket=bucket,
                                 slot=slot, prompt_len=plen)

    def _tick_feeds(self, slots):
        """Fixed-shape decode-step feeds off the current slot table.
        Returns ``(feeds, stalled)``: in paged mode a slot whose cache
        growth found the pool dry STALLS this tick — its active flag
        drops, its write aims at the trash page and the caller discards
        its token (the next tick re-derives the same bits, so a stall is
        invisible in the output stream)."""
        model = self.model
        s = model.max_slots
        tokens = np.zeros((s, 1), np.int64)
        pos = np.zeros((s,), np.int64)
        active = np.zeros((s,), np.float32)
        stalled = set()
        if self._pool is not None:
            wpage = np.full((s,), self._pool.trash_page, np.int64)
            woff = np.zeros((s,), np.int64)
        for i, r in enumerate(slots):
            if r is None:
                continue
            if self._pool is not None:
                if not self._pool.ensure(i, int(r.pos)):
                    stalled.add(i)
                    continue  # active stays 0: masked like a free slot
                wpage[i], woff[i] = self._pool.write_loc(i, int(r.pos))
            active[i] = 1.0
            tokens[i, 0] = (r.out_tokens[-1] if r.out_tokens
                            else r.prompt[-1])
            pos[i] = r.pos
        feeds = {model.DC_TOKENS: tokens, model.DC_POS: pos,
                 model.DC_ACTIVE: active,
                 model.DC_POSENC:
                     model.posenc_rows(pos).astype(np.float32),
                 model.DC_BIAS: model.validity_bias(pos)}
        if self._pool is not None:
            feeds[model.DC_PTABLE] = self._pool.table()
            feeds[model.DC_WPAGE] = wpage
            feeds[model.DC_WOFF] = woff
        return feeds, stalled

    def _step_dispatch(self, slots, count_tick=True):
        """ONE compiled decode step over all slots; returns the [S] next
        tokens (host ints), the set of paged slots that stalled this
        tick, and the [S, V] logits.  The logits ride along as a second
        fetch of the SAME executable (a fixed fetch set from warmup on,
        so the canary sentinel never perturbs the compile counter) and
        land in ``_last_logits`` for the tick monitor.

        ``count_tick=False`` runs the dispatch without advancing the
        engine tick (the spec tick's tail dispatch: slots too close to
        max_len to speculate ride the plain step INSIDE the one spec
        tick, so one scheduling iteration still counts once)."""
        feeds, stalled = self._tick_feeds(slots)
        nxt, logits = self._run(self.model.step_program, feeds,
                                [self.model.step_fetch,
                                 self.model.logits_fetch])
        logits = np.asarray(logits)
        if count_tick:
            self._ticks += 1
            self.metrics.inc("decode_ticks")
            self._last_logits = logits
        return np.asarray(nxt).reshape(-1), stalled, logits

    def _consume(self, i: int, req: _Request, tok: int, t0: float,
                 t1: float) -> bool:
        """Commit ONE generated token to slot ``i`` with all the stream
        bookkeeping (latency observations, span, retirement on end_id /
        token budget / cache capacity, the per-token deadline).  Shared
        by the plain tick and the spec tick's accepted-prefix commit so
        the two paths cannot drift.  Returns True when the request
        retired (caller must stop feeding it tokens)."""
        from ..observe import trace as _trace

        model = self.model
        req.out_tokens.append(tok)
        req.pos += 1
        self.metrics.inc("tokens_generated")
        if len(req.out_tokens) == 1:
            self.metrics.observe_ttft(t1 - req.t_submit)
        else:
            self.metrics.observe_intertoken(t1 - req.t_prev_token)
        req.t_prev_token = t1
        if req.span is not None:
            _trace.emit_span("serving.decode_step", t0, t1,
                             parent=req.span, slot=i,
                             token_index=len(req.out_tokens) - 1,
                             tick=self._ticks)
        done = (tok == model.end_id
                or len(req.out_tokens) >= req.max_new
                or req.pos >= model.max_len)
        if done:
            self._retire(i)
            return True
        if req.deadline is not None and t1 > req.deadline:
            # per-token deadline: expire MID-GENERATION and free the
            # slot for the queue instead of decoding a dead request
            self._retire(i, error=RequestTimeout(
                f"deadline expired after {len(req.out_tokens)} "
                f"generated tokens"))
            return True
        return False

    def _stall_expire(self, i: int, req: _Request, t1: float) -> None:
        """Pool-dry stall: the row ran masked (trash write, active=0) —
        its token is discarded, pos keeps, and it retries next tick once
        a retirement frees pages.  Deadlines still apply: an expired
        staller must retire and return its pages, or mutual stalls could
        live-lock the pool."""
        if req.deadline is not None and t1 > req.deadline:
            self._retire(i, error=RequestTimeout(
                f"deadline expired after {len(req.out_tokens)} "
                f"generated tokens (pool-stalled)"))

    def _run_monitor(self, logits, dispatched) -> None:
        """Canary sentinel invocation: this tick's logits + the slot
        table they were computed for (pre-retire copy, so completions
        are visible to the probation counter).  A sentinel fault must
        never take down the worker it watches."""
        mon = self._tick_monitor
        if mon is None:
            return
        try:
            mon(logits, dispatched)
        except Exception:
            import traceback

            from .. import observe

            observe.emit("model.monitor_error",
                         error=traceback.format_exc(limit=3))

    def _tick(self):
        if self._spec is not None and self._spec.run_tick():
            return  # draft+verify tick ran (specdec.SpecDecoder)
        t0 = time.perf_counter()
        dispatched = list(self._slots)  # rows the logits correspond to
        nxt, stalled, _ = self._step_dispatch(self._slots)
        t1 = time.perf_counter()
        for i, req in enumerate(list(self._slots)):
            if req is None:
                continue
            if i in stalled:
                self._stall_expire(i, req, t1)
                continue
            self._consume(i, req, int(nxt[i]), t0, t1)
        self._run_monitor(self._last_logits, dispatched)

    def _retire(self, slot: int, error: Optional[Exception] = None):
        req = self._slots[slot]
        self._slots[slot] = None
        self._n_active -= 1
        if self._pool is not None:
            # explicit page return on EVERY retirement path — completion
            # AND deadline expiry (the lazy-reclaim bug: an expired
            # stream's rows used to stay resident until slot reuse).
            # Refcounted prefix pages survive until their last sharer.
            self._pool.release(slot)
        if self._spec is not None:
            # the next resident of this slot id starts with a fresh
            # rolling acceptance rate
            self._spec.controller.retire_slot(slot)
        self.metrics.note_slots(self._n_active,
                                self.model.max_slots - self._n_active)
        if req.future.done():
            return  # failed externally (bounded-drain timeout)
        if error is not None:
            self.metrics.inc("expired" if isinstance(error, RequestTimeout)
                             else "failed")
            if req.span is not None:
                req.span.end(status="expired"
                             if isinstance(error, RequestTimeout)
                             else "error")
            req.future.set_exception(error)
            return
        now = time.perf_counter()
        self.metrics.inc("completed")
        self.metrics.observe_latency(now - req.t_submit)
        if req.span is not None:
            req.span.end(status="ok", slot=slot,
                         tokens=len(req.out_tokens))
        req.future.set_result(list(req.out_tokens))

    # ------------------------------------------------------------------
    # dispatch plumbing + warmup
    # ------------------------------------------------------------------

    def _run(self, program, feed, fetch_list, scope=None):
        """Executor dispatch with compile-counter accounting: any jit-
        cache growth under traffic shows up on ``bucket_compiles`` — the
        fixed-executable-set invariant's counter (must stay flat after
        warmup).  ``scope`` overrides the engine scope (the spec draft
        model dispatches against its own scope through the SAME executor
        so its compiles land on the same counter)."""
        before = len(self._exe._cache)
        outs = self._exe.run(program, feed=feed, fetch_list=fetch_list,
                             scope=scope if scope is not None
                             else self._scope)
        grown = len(self._exe._cache) - before
        if grown > 0:
            self.metrics.inc("bucket_compiles", grown)
        return outs

    def executables(self) -> int:
        """Compiled executables resident in the engine's jit cache (the
        fixed set: one decode step + one per warmed prefill bucket)."""
        return len(self._exe._cache)

    def _warm_fingerprints(self) -> Dict[str, str]:
        """Content fingerprints of the fixed executable set, keyed
        ``prefill:<bucket>`` / ``step`` — the decode twin of the batch
        engine's bucket fingerprints.  The model builds its programs
        rename-invariantly from a deterministic seed, so two separately
        constructed engines over the same config (fleet replicas) hash
        identically and share store entries.  Empty dict on any
        fingerprint failure (caller falls back to full dispatch)."""
        from .. import compile_cache as _cc

        model = self.model
        paged = self._pool is not None
        fps: Dict[str, str] = {}
        try:
            for b in model.prefill_buckets:
                if paged:
                    pf_feeds = [(model.PF_PAGES,
                                 (int(b) // model.page_size,), "int64"),
                                (model.PF_TOKENS, (1, int(b)), "int64")]
                else:
                    pf_feeds = [(model.PF_SLOT, (1,), "int64"),
                                (model.PF_TOKENS, (1, int(b)), "int64")]
                fps[f"prefill:{int(b)}"] = _cc.program_fingerprint(
                    model.prefill_program(b),
                    feeds=pf_feeds,
                    fetches=[],
                    extra={"kind": "decode_prefill", "bucket": int(b),
                           "paged": paged})
            step_feed = self._tick_feeds([None] * model.max_slots)[0]
            fps["step"] = _cc.program_fingerprint(
                model.step_program,
                feeds=sorted((k, tuple(v.shape), str(v.dtype))
                             for k, v in step_feed.items()),
                fetches=[model.step_fetch, model.logits_fetch],
                extra={"kind": "decode_step", "paged": paged})
        except Exception:
            return {}
        return fps

    def _write_warm_manifest(self, fps: Dict[str, str]) -> None:
        """Atomic (tmp + rename) decode warmup manifest next to the batch
        engine's bucket manifests under ``<store>/serving/``; never fails
        warmup.  A re-spawned replica's cold start is driven by the SAME
        store entries, the manifest records what the set was."""
        import json
        import os

        from .. import compile_cache as _cc

        store = _cc.get_store()
        if store is None or "step" not in fps:
            return
        model = self.model
        manifest = {
            "version": 1,
            "created": time.time(),
            "kind": "decode",
            "max_slots": int(model.max_slots),
            "max_len": int(model.max_len),
            "prefill_buckets": [int(b) for b in model.prefill_buckets],
            "paged": self._pool is not None,
            "page_size": (int(model.page_size) if self._pool is not None
                          else None),
            "fingerprints": dict(fps),
        }
        try:
            path = store.serving_manifest_path(f"decode-{fps['step']}")
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1)
            os.replace(tmp, path)
        except Exception:
            pass

    def warmup(self, only_missing: Optional[bool] = None) -> int:
        """Precompile the ENTIRE fixed executable set — the one decode
        step plus every prefill bucket — before traffic, so steady state
        never compiles (any later ``bucket_compiles`` growth is a bug:
        an unplanned shape reached the executor).

        With the persistent compile cache enabled (``only_missing`` left
        at its default), programs whose fingerprints are already in the
        store are NOT dispatched: a prior process — or another replica of
        the same model — compiled them into the shared backend cache, so
        a scale-out/re-spawned replica's warm is cache-hit-only
        (``warmup_cached`` counts up, ``warmup_dispatches`` stays 0; the
        executable loads from the store on first use).
        ``only_missing=False`` forces full dispatch.

        Safe to call again; returns the executable count."""
        from .. import compile_cache as _cc

        store = _cc.get_store()
        if only_missing is None:
            only_missing = store is not None
        model = self.model
        fps = self._warm_fingerprints() if store is not None else {}

        def _cached(key: str) -> bool:
            fp = fps.get(key)
            return bool(only_missing and store is not None
                        and fp is not None and store.get(fp) is not None)

        def _record(key: str, program, meta: dict) -> None:
            fp = fps.get(key)
            if store is None or fp is None:
                return
            try:  # cache bookkeeping never fails warmup
                store.put(fp, program.serialize_to_string(), meta)
            except Exception:
                pass

        with self._dispatch_lock:
            for b in model.prefill_buckets:
                key = f"prefill:{int(b)}"
                if _cached(key):
                    self.metrics.inc("warmup_cached")
                    continue
                feeds = {model.PF_TOKENS: np.zeros((1, b), np.int64)}
                if self._pool is not None:
                    # warm against the trash page: zero-token K/V lands
                    # nowhere a real stream will ever read
                    feeds[model.PF_PAGES] = np.full(
                        (b // model.page_size,), self._pool.trash_page,
                        np.int64)
                else:
                    feeds[model.PF_SLOT] = np.zeros((1,), np.int64)
                self._run(model.prefill_program(b), feeds, [])
                self.metrics.inc("warmup_dispatches")
                _record(key, model.prefill_program(b),
                        {"kind": "decode_prefill", "bucket": int(b)})
            if _cached("step"):
                self.metrics.inc("warmup_cached")
            else:
                self._step_dispatch([None] * model.max_slots)
                self.metrics.inc("warmup_dispatches")
                _record("step", model.step_program,
                        {"kind": "decode_step"})
            if self._spec is not None:
                # the spec additions to the executable set (draft
                # prefills, draft step, verify) precompile here too —
                # spec traffic must not grow bucket_compiles either
                self._spec.warmup()
        self._write_warm_manifest(fps)
        from .. import observe

        observe.emit("serving.warmup", kind="decode",
                     prefill_buckets=model.prefill_buckets,
                     max_slots=model.max_slots, max_len=model.max_len,
                     dispatched=self.metrics.counter("warmup_dispatches"),
                     cached=self.metrics.counter("warmup_cached"),
                     executables=self.executables())
        return self.executables()

    # ------------------------------------------------------------------
    # static-batching baseline (the convoy oracle's comparator)
    # ------------------------------------------------------------------

    def decode_static(self, batch: Sequence[Tuple[Sequence[int], int]]
                      ) -> List[Tuple[List[int], float]]:
        """Request-granularity batching over the SAME model/executables:
        admit the whole batch, tick until EVERY member finishes, and
        resolve all of them at batch end — exactly the convoy the
        iteration-level scheduler removes.  A one-request batch is the
        per-request sequential baseline (the bitwise-identity oracle).
        Returns ``[(tokens, latency_s), ...]``; only callable while the
        engine is otherwise idle (test/bench comparator, not a serving
        path)."""
        if len(batch) > self.model.max_slots:
            raise ValueError(f"static batch ({len(batch)}) exceeds "
                             f"max_slots ({self.model.max_slots})")
        with self._dispatch_lock:
            if self._n_active or self._queue:
                raise RuntimeError("decode_static requires an idle engine")
            slots: List[Optional[_Request]] = [None] * self.model.max_slots
            t_start = []
            admitted: List[int] = []
            try:
                for i, (prompt, max_new) in enumerate(batch):
                    fut: Future = Future()
                    t0 = time.perf_counter()
                    req = _Request(None, 1, None, fut, None, t0)
                    req.prompt = [int(t) for t in prompt]
                    req.max_new = int(max_new)
                    req.out_tokens = []
                    plen = len(req.prompt)
                    bucket = self.model.bucket_for(plen)
                    tokens = np.zeros((1, bucket), np.int64)
                    tokens[0, :plen] = req.prompt
                    feeds = {self.model.PF_TOKENS: tokens}
                    skip = False
                    if self._pool is not None:
                        grant = self._pool.admit(i, req.prompt, bucket)
                        if grant is None:
                            raise RuntimeError(
                                f"page pool cannot admit static batch "
                                f"member {i} "
                                f"({self._pool.pages_free} pages free)")
                        admitted.append(i)
                        skip = grant.full_hit
                        feeds[self.model.PF_PAGES] = \
                            self._pool.prefill_pages(i, bucket)
                    else:
                        feeds[self.model.PF_SLOT] = \
                            np.asarray([i], np.int64)
                    if not skip:
                        self._run(self.model.prefill_program(bucket),
                                  feeds, [])
                    req.pos = plen - 1
                    slots[i] = req
                    t_start.append(t0)
                finished = [False] * len(batch)
                while not all(finished):
                    live = [r if r is not None and not finished[j]
                            else None
                            for j, r in enumerate(slots[:len(batch)])]
                    live += [None] * (self.model.max_slots - len(live))
                    nxt, stalled, _ = self._step_dispatch(live)
                    progressed = False
                    for j, req in enumerate(slots[:len(batch)]):
                        if finished[j] or j in stalled:
                            continue
                        progressed = True
                        tok = int(nxt[j])
                        req.out_tokens.append(tok)
                        req.pos += 1
                        finished[j] = (tok == self.model.end_id
                                       or len(req.out_tokens)
                                       >= req.max_new
                                       or req.pos >= self.model.max_len)
                        if finished[j] and self._pool is not None:
                            self._pool.release(j)
                            if j in admitted:
                                admitted.remove(j)
                    if not progressed:
                        # every live slot stalled and none can retire:
                        # a static batch has no churn to free pages
                        raise RuntimeError(
                            "page pool exhausted with the whole static "
                            "batch resident — no retirement can free "
                            "pages; use a smaller batch or more pages")
                t_end = time.perf_counter()
                return [(list(slots[j].out_tokens), t_end - t_start[j])
                        for j in range(len(batch))]
            finally:
                if self._pool is not None:
                    for j in list(admitted):
                        self._pool.release(j)

    # ------------------------------------------------------------------
    # hot model swap surface (serving.registry drives these)
    # ------------------------------------------------------------------

    def snapshot_weights(self, names: Sequence[str]) -> Dict[str, np.ndarray]:
        """Host copies of the named scope vars, taken between dispatches
        — the registry's rollback set (the old serial stays resident as
        plain host arrays until the new one is promoted)."""
        with self._dispatch_lock:
            out = {}
            for name in names:
                val = self._scope.get(name)
                if val is None:
                    raise KeyError(f"no scope var named {name!r}")
                out[name] = np.array(val, copy=True)
            return out

    def _rebind_weights(self, weights: Dict[str, np.ndarray]) -> None:
        """Scope rebind — caller MUST hold ``_dispatch_lock`` (or be the
        worker inside a tick).  The executor re-gathers state from the
        scope on every dispatch and the jit cache key carries no state
        values, so the next tick runs the SAME executables over the new
        weights: a swap is never a recompile."""
        for name, arr in weights.items():
            self._scope.set(name, np.asarray(arr))
        if self._pool is not None:
            # resident prefix pages were written by the OLD weights: a
            # new admission's prefill would produce different bits, so
            # the share index must forget them (holders keep decoding —
            # their whole cache is old-weight-consistent until retire)
            self._pool.flush_index()
        if self._spec is not None:
            # a self-draft shares weights BY NAME: re-copy so draft and
            # target keep agreeing (serial-backed drafts are pinned and
            # sync() is a no-op for them)
            self._spec.draft.sync(self._scope)

    def swap_weights(self, weights: Dict[str, np.ndarray]) -> None:
        """Atomically rebind the named weights between decode ticks."""
        with self._dispatch_lock:
            self._rebind_weights(weights)

    def _scrub_caches(self) -> None:
        """Zero every slot K/V cache — caller holds ``_dispatch_lock``
        (or is the worker inside a tick).  The rollback path needs this:
        a poisoned canary serial writes NaN into resident caches, and
        NaN rides THROUGH the -inf validity mask (NaN + -inf = NaN), so
        rebinding healthy weights alone would leave every future request
        in that slot poisoned.  Zeros restore the engine-start state:
        fresh admissions prefill over them and are bitwise-clean."""
        for v in self.model.startup.list_vars():
            if not v.persistable or "_cache_" not in v.name:
                continue
            cur = self._scope.get(v.name)
            if cur is not None:
                self._scope.set(v.name, np.zeros(np.shape(cur),
                                                 np.asarray(cur).dtype))
        if self._pool is not None:
            self._pool.flush_index()  # scrubbed pages share nothing
        if self._spec is not None:
            self._spec.draft.scrub()  # draft caches are poisonable too

    def pause_admissions(self) -> None:
        """Hold admissions (the drain swap policy): submits still land in
        the queue — nothing sheds — but no slot is filled until
        :meth:`resume_admissions`.  Resident slots keep ticking."""
        with self._cond:
            self._paused = True
            self._cond.notify_all()

    def resume_admissions(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def wait_idle(self, timeout_s: float = 60.0) -> bool:
        """Wait until no slot is resident (queued work may remain when
        admissions are paused).  Returns False on timeout."""
        deadline = time.perf_counter() + timeout_s
        with self._cond:
            while self._n_active:
                left = deadline - time.perf_counter()
                if left <= 0:
                    return False
                self._cond.wait(min(left, 0.05))
        return True

    def abort_resident(self, what: str = "swap drain") -> List[str]:
        """Fail every resident request's future with :class:`DrainTimeout`
        (the bounded-drain expiry path, reused by the drain swap policy
        when old-version slots refuse to retire).  Returns the stuck
        request ids; the worker reaps the dead slots on its next pass."""
        stuck = [r for r in self._slots
                 if r is not None and not r.future.done()]
        ids = [r.rid for r in stuck]
        if stuck:
            exc = DrainTimeout(
                f"{what} timed out with {len(ids)} resident "
                f"request(s) still generating: {', '.join(ids)}", ids)
            for r in stuck:
                self.metrics.inc("failed")
                if r.span is not None:
                    r.span.end(status="drain_timeout")
                if not r.future.done():
                    r.future.set_exception(exc)
        with self._cond:
            self._cond.notify_all()
        return ids

    def set_tick_monitor(self, fn) -> None:
        """Install/remove (None) the per-tick monitor: called on the
        worker thread after each decode tick with ``(logits, slots)`` —
        the [S, V] logits of the dispatch and the slot table it ran
        over.  The registry's canary output-sanity sentinel lives here."""
        self._tick_monitor = fn

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Stop admitting; wait until every queued and resident request
        has resolved.  Returns True when fully drained.  On expiry every
        outstanding future fails with :class:`DrainTimeout` naming the
        stuck request ids — callers never block forever on a wedged
        generation."""
        deadline = time.perf_counter() + timeout_s
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._queue or self._n_active:
                left = deadline - time.perf_counter()
                if left <= 0:
                    self._abort_outstanding_locked("drain")
                    return False
                self._cond.wait(min(left, 0.05))
        return True

    def _abort_outstanding_locked(self, what: str) -> None:
        """Fail every queued + resident future with DrainTimeout (caller
        holds ``_cond``).  Resident slots are left for the worker's
        reap pass — the worker may be mid-tick holding the dispatch
        lock, so they cannot be cleared from here."""
        stuck = list(self._queue) + [r for r in self._slots
                                     if r is not None
                                     and not r.future.done()]
        self._queue.clear()
        self.metrics.set_gauge("queue_depth", 0)
        if not stuck:
            return
        ids = [r.rid for r in stuck]
        exc = DrainTimeout(
            f"{what} timed out after {len(ids)} outstanding decode "
            f"request(s): {', '.join(ids)}", ids)
        for r in stuck:
            self.metrics.inc("failed")
            if r.span is not None:
                r.span.end(status="drain_timeout")
            if not r.future.done():
                r.future.set_exception(exc)

    def shutdown(self, timeout_s: float = 60.0) -> bool:
        ok = self.drain(timeout_s=timeout_s)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._worker.join(timeout=timeout_s)
        return ok

    def kill(self, join_timeout_s: float = 10.0) -> List[str]:
        """Hard stop WITHOUT drain — the replica-death path (crash
        simulation: ``PADDLE_FAULT_REPLICA_KILL_AFTER``, exercised by
        ``serving/fleet.py``).  Every queued and resident request fails
        with :class:`EngineClosed` when the worker exits; the fleet's
        router re-enqueues those, so a killed replica never sheds.
        Returns the request ids that were in flight."""
        with self._cond:
            in_flight = [r.rid for r in
                         list(self._queue) + [s for s in self._slots
                                              if s is not None]
                         if not r.future.done()]
            self._stopped = True
            self._cond.notify_all()
        if threading.current_thread() is not self._worker:
            self._worker.join(timeout=join_timeout_s)
        from .. import observe

        observe.emit("serving.engine_killed", kind="decode",
                     in_flight=len(in_flight))
        return in_flight

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


def create_decode_engine(cfg=None, config: Optional[DecodeConfig] = None,
                         metrics_labels: Optional[Dict[str, str]] = None,
                         **model_kwargs) -> DecodeEngine:
    """Build a DecodeEngine over a fresh step-form decode model.  ``cfg``
    is a transformer Config (default: CPU-test-scale decode LM);
    ``model_kwargs`` forward to DecodeModel (max_slots / max_len /
    prefill_buckets default from the env contract)."""
    from ..models.transformer import DecodeModel

    return DecodeEngine(DecodeModel(cfg=cfg, **model_kwargs), config,
                        metrics_labels=metrics_labels)
