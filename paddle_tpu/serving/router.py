"""Multi-model request router over N engine replicas (ISSUE 17).

One :class:`Router` fronts every replica of every model a
:class:`~paddle_tpu.serving.fleet.ServingFleet` runs.  It owns exactly
three things:

 - **Per-model bounded queues**: ``submit(model_id, ...)`` lands in the
   model's own deque — one slow model can never convoy another model's
   traffic behind it.  The bound is ``PADDLE_ROUTER_QUEUE_HARD``; an
   overflowing submit is shed (:class:`EngineOverloaded`) ONLY after the
   fleet's last-chance hook has had its say — the hook is the scale
   policy's emergency path, so a load spike always produces a
   ``fleet.scale_out`` before the first ``fleet.shed`` (the fleet
   oracle).
 - **Least-loaded dispatch**: one dispatcher thread drains the queues
   onto live replicas, picking the READY replica with the smallest
   (resident slots + engine queue depth) — gauges the engines already
   keep, no probing dispatches.  Which replicas are candidates for a
   given request is the fleet's call (``selector(model_id, seq)``):
   that's where the canary traffic slice and draining-replica exclusion
   live, so the router itself stays policy-free.
 - **End-to-end deadlines + zero-shed failover**: a request's deadline
   is fixed at submit and rides through requeues — the remaining budget
   (never the original) is what the chosen engine gets.  When a replica
   dies mid-request (``EngineClosed``/``DrainTimeout`` out of its
   future), the router puts the request back at the FRONT of its queue
   and redispatches to a survivor: a killed replica costs latency, not
   requests.  Only ``retry_limit`` consecutive engine losses fail a
   request — a fleet with zero live replicas must not loop forever.

The router never constructs replicas and holds no model state; it
duck-types against the :class:`~paddle_tpu.serving.fleet.Replica`
surface (``engine``, ``name``, ``load()``, ``note_dead()``).  Tests
drive it with bare engines wrapped in stubs.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence

from .engine import EngineClosed, EngineOverloaded, RequestTimeout
from .engine import DrainTimeout  # re-raised by dead-replica futures

__all__ = ["Router", "RouterConfig"]


class RouterConfig:
    """Queue/shed policy knobs, defaulted from the env contract
    (``PADDLE_ROUTER_*``); constructor args override for tests."""

    def __init__(self, queue_hard: Optional[int] = None,
                 default_timeout_ms: Optional[float] = None,
                 retry_limit: int = 5,
                 idle_wait_s: float = 0.02):
        from ..fluid import envcontract as _ec

        self.queue_hard = int(queue_hard if queue_hard is not None
                              else _ec.get("PADDLE_ROUTER_QUEUE_HARD"))
        self.default_timeout_ms = default_timeout_ms
        self.retry_limit = int(retry_limit)
        self.idle_wait_s = float(idle_wait_s)


class _RoutedRequest:
    __slots__ = ("model_id", "prompt", "max_new", "future", "deadline",
                 "t_submit", "rid", "retries", "replica")

    def __init__(self, model_id, prompt, max_new, future, deadline,
                 t_submit, rid):
        self.model_id = model_id
        self.prompt = prompt
        self.max_new = max_new
        self.future = future
        self.deadline = deadline
        self.t_submit = t_submit
        self.rid = rid
        self.retries = 0
        self.replica = None  # the replica currently generating it


class Router:
    """See module docstring.  ``selector(model_id, seq)`` must return
    the replicas eligible for that model's ``seq``-th dispatch (the
    fleet's routing policy); ``last_chance(model_id)`` is consulted on
    queue overflow — return True to accept the request anyway (scale-out
    under way), False to shed."""

    def __init__(self, selector: Callable[[str, int], Sequence],
                 config: Optional[RouterConfig] = None,
                 last_chance: Optional[Callable[[str], bool]] = None):
        self._selector = selector
        self._last_chance = last_chance
        self.config = config or RouterConfig()
        self._cond = threading.Condition(threading.Lock())
        self._queues: Dict[str, collections.deque] = {}
        self._seq: Dict[str, itertools.count] = {}
        self._in_flight: Dict[str, int] = {}
        self._shed: Dict[str, int] = {}
        self._dispatched: Dict[str, int] = {}
        self._stopped = False
        self._rid = itertools.count()
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="router-dispatch")
        self._worker.start()

    # ------------------------------------------------------------------
    # client surface
    # ------------------------------------------------------------------

    def submit(self, model_id: str, prompt_ids: Sequence[int],
               max_new_tokens: int,
               timeout_ms: Optional[float] = None) -> Future:
        """Enqueue one generation request for ``model_id``; returns a
        Future of the generated token ids.  The deadline (when any) is
        END-TO-END: queueing, requeues after a replica death, and every
        generated token all spend the same budget."""
        if timeout_ms is None:
            timeout_ms = self.config.default_timeout_ms
        now = time.perf_counter()
        fut: Future = Future()
        req = _RoutedRequest(
            str(model_id), [int(t) for t in prompt_ids],
            int(max_new_tokens), fut,
            now + timeout_ms / 1000.0 if timeout_ms else None,
            now, f"r{next(self._rid)}")
        with self._cond:
            if self._stopped:
                raise EngineClosed("router stopped")
            q = self._queues.setdefault(req.model_id, collections.deque())
            if len(q) >= self.config.queue_hard:
                # the scale policy gets the LAST word before any shed:
                # accepting the overflow is correct whenever capacity is
                # already on its way (warming replica / scale-out fired)
                if not (self._last_chance is not None
                        and self._last_chance(req.model_id)):
                    self._shed[req.model_id] = \
                        self._shed.get(req.model_id, 0) + 1
                    self._note_queue(req.model_id, len(q))
                    from .. import observe

                    observe.emit("fleet.shed", model=req.model_id,
                                 queue_depth=len(q),
                                 queue_hard=self.config.queue_hard)
                    raise EngineOverloaded(
                        f"router queue for model {req.model_id!r} full "
                        f"({self.config.queue_hard} pending); request "
                        f"shed")
            q.append(req)
            self._note_queue(req.model_id, len(q))
            self._cond.notify_all()
        return fut

    def generate(self, model_id: str, prompt_ids: Sequence[int],
                 max_new_tokens: int,
                 timeout_ms: Optional[float] = None) -> List[int]:
        """Blocking submit."""
        return self.submit(model_id, prompt_ids, max_new_tokens,
                           timeout_ms=timeout_ms).result()

    def queue_depth(self, model_id: str) -> int:
        with self._cond:
            return len(self._queues.get(str(model_id), ()))

    def in_flight(self, model_id: str) -> int:
        with self._cond:
            return self._in_flight.get(str(model_id), 0)

    def shed_count(self, model_id: str) -> int:
        with self._cond:
            return self._shed.get(str(model_id), 0)

    def dispatched_count(self, model_id: str) -> int:
        with self._cond:
            return self._dispatched.get(str(model_id), 0)

    def kick(self) -> None:
        """Wake the dispatcher (the fleet calls this when a replica
        turns READY so queued work doesn't wait out an idle tick)."""
        with self._cond:
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # the dispatcher
    # ------------------------------------------------------------------

    def _note_queue(self, model_id: str, depth: int) -> None:
        from ..observe import registry as _registry

        _registry().set_gauge("fleet.queue_depth", int(depth),
                              labels={"model": model_id})

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stopped:
                    break
                progress = False
            for model_id in self._model_ids():
                progress |= self._pump(model_id)
            with self._cond:
                if self._stopped:
                    break
                if not progress and not any(self._queues.values()):
                    self._cond.wait(self.config.idle_wait_s)
                elif not progress:
                    # queued work but no eligible replica right now:
                    # wait for a kick (replica ready) or new submits,
                    # bounded so deadline expiry still gets swept
                    self._cond.wait(self.config.idle_wait_s)

    def _model_ids(self) -> List[str]:
        with self._cond:
            return list(self._queues)

    def _pump(self, model_id: str) -> bool:
        """Dispatch as much of one model's queue as current capacity
        takes; returns True when anything moved."""
        moved = False
        while True:
            with self._cond:
                q = self._queues.get(model_id)
                req = None
                while q:
                    cand = q.popleft()
                    if cand.future.done():
                        continue  # client gave up / already failed
                    now = time.perf_counter()
                    if cand.deadline is not None and now > cand.deadline:
                        cand.future.set_exception(RequestTimeout(
                            f"deadline expired after "
                            f"{(now - cand.t_submit) * 1e3:.1f} ms in "
                            f"router queue"))
                        continue
                    req = cand
                    break
                self._note_queue(model_id, len(q) if q else 0)
            if req is None:
                return moved
            if not self._dispatch(req):
                # no replica could take it: put it back at the front
                # exactly as it was and let the next pass retry
                with self._cond:
                    self._queues.setdefault(
                        model_id, collections.deque()).appendleft(req)
                    self._note_queue(model_id,
                                     len(self._queues[model_id]))
                return moved
            moved = True

    def _pick(self, req: _RoutedRequest):
        """Least-loaded among the selector's candidates for this seq."""
        with self._cond:
            seq = next(self._seq.setdefault(req.model_id,
                                            itertools.count()))
        try:
            candidates = list(self._selector(req.model_id, seq) or ())
        except Exception:
            return None
        live = [r for r in candidates
                if getattr(r.engine, "alive", True)]
        if not live:
            return None
        return min(live, key=lambda r: r.load())

    def _dispatch(self, req: _RoutedRequest) -> bool:
        replica = self._pick(req)
        if replica is None:
            return False
        timeout_ms = None
        if req.deadline is not None:
            left = req.deadline - time.perf_counter()
            if left <= 0:
                req.future.set_exception(RequestTimeout(
                    "deadline expired before dispatch"))
                return True
            timeout_ms = left * 1000.0
        try:
            inner = replica.submit(req.prompt, req.max_new,
                                   timeout_ms=timeout_ms)
        except (EngineClosed, EngineOverloaded):
            # stopped engine or a full engine queue: the replica is not
            # taking work right now — count it like a death (requeue;
            # the retry cap bounds a queue-full livelock too).  Either
            # way _handle_loss consumed the request (requeued or
            # failed), so the pump must NOT put it back a second time
            self._handle_loss(req)
            return True
        except Exception as exc:  # bad request (validation): client's
            req.future.set_exception(exc)
            return True
        req.replica = replica
        with self._cond:
            self._in_flight[req.model_id] = \
                self._in_flight.get(req.model_id, 0) + 1
            self._dispatched[req.model_id] = \
                self._dispatched.get(req.model_id, 0) + 1
        inner.add_done_callback(lambda f, r=req: self._on_done(r, f))
        return True

    def _on_done(self, req: _RoutedRequest, inner: Future) -> None:
        with self._cond:
            self._in_flight[req.model_id] = max(
                0, self._in_flight.get(req.model_id, 0) - 1)
        if req.future.done():
            return
        exc = inner.exception()
        if exc is None:
            req.future.set_result(inner.result())
            return
        if isinstance(exc, (EngineClosed, DrainTimeout)):
            # the replica died under this request: not the client's
            # fault — requeue at the FRONT and redispatch to a survivor
            replica = req.replica
            if replica is not None:
                try:
                    replica.note_dead()
                except Exception:
                    pass
            if not self._handle_loss(req):
                self.kick()
            return
        req.future.set_exception(exc)

    def _handle_loss(self, req: _RoutedRequest) -> bool:
        """Requeue a request its replica lost.  Returns True when the
        request was finally failed (retry cap / router stopped)."""
        req.retries += 1
        req.replica = None
        if req.retries > self.config.retry_limit:
            req.future.set_exception(EngineClosed(
                f"request {req.rid} lost its replica "
                f"{req.retries} times; giving up"))
            return True
        with self._cond:
            if self._stopped:
                req.future.set_exception(EngineClosed("router stopped"))
                return True
            self._queues.setdefault(
                req.model_id, collections.deque()).appendleft(req)
            self._note_queue(req.model_id, len(self._queues[req.model_id]))
            self._cond.notify_all()
        return False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait for every queue to empty and every in-flight request to
        resolve.  Returns False on timeout."""
        deadline = time.perf_counter() + timeout_s
        with self._cond:
            while any(self._queues.values()) \
                    or any(self._in_flight.values()):
                left = deadline - time.perf_counter()
                if left <= 0:
                    return False
                self._cond.wait(min(left, 0.05))
        return True

    def stop(self, timeout_s: float = 10.0) -> None:
        """Stop the dispatcher; queued (undispatched) requests fail with
        :class:`EngineClosed`.  In-flight requests resolve through their
        engines as usual."""
        with self._cond:
            self._stopped = True
            leftovers = [r for q in self._queues.values() for r in q]
            for q in self._queues.values():
                q.clear()
            self._cond.notify_all()
        for r in leftovers:
            if not r.future.done():
                r.future.set_exception(EngineClosed("router stopped"))
        self._worker.join(timeout=timeout_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
