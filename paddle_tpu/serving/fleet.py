"""Serving fleet: N engine replicas x M models behind one router
(ISSUE 17 tentpole).

Everything below PR 15/16 serves from ONE engine: one replica's worth of
slots, one model, and the PR 16 canary is time-sliced (the whole replica
probes the new serial).  This module is the fleet layer those PRs were
built for:

 - **ReplicaPool lifecycle** (:class:`Replica`, inside
   :class:`ServingFleet`): ``spawn -> warm -> ready -> draining|dead``.
   A scale-out replica warms from the SAME persistent compile store the
   first replica populated (PR 4): with the cache enabled its cold start
   is cache-hit-only — ``warmup_dispatches == 0``, ``warmup_cached ==
   executable set`` — so added capacity is serving in milliseconds, not
   a compile storm.  Replica death is detected by the PR 14 census
   machinery (``elastic.write_heartbeat`` files going stale +
   ``host_loss_markers``, plus the in-process ``engine.alive`` probe);
   the dead replica's device is marked lost in the :class:`DevicePool`
   and a replacement spawns on a surviving device.
 - **Router** (:mod:`paddle_tpu.serving.router`): per-model bounded
   queues, least-loaded dispatch over live slot/queue gauges,
   end-to-end deadlines, and requeue-on-replica-death — a killed
   replica's in-flight requests fail over to survivors with ZERO shed.
 - **AutoscalePolicy** (pure, unit-testable): consumes queue depth,
   SLO-breach counts, warming-replica counts and per-replica inter-token
   p50s and distinguishes *queue pressure* (scale out) from *compile
   stall* (capacity already warming: wait) from a *straggling replica*
   (drain + replace).  Hysteresis ticks and a scale cooldown keep it
   from flapping; every knob is ``PADDLE_ROUTER_*`` in the env
   contract.  The router's queue-overflow "last chance" hook bypasses
   the hysteresis (emergency scale-out), which is what guarantees a
   ``fleet.scale_out`` event strictly before the first ``fleet.shed``.
 - **Fleet-level canary**: one replica per watched model runs the PR 16
   :class:`~paddle_tpu.serving.registry.ModelRegistry`; while its
   probation runs, the fleet routes exactly the canary fraction of that
   model's traffic to it (every k-th request, ``k = round(1 /
   PADDLE_ROUTER_CANARY_FRACTION)``) and the OTHER replicas never see
   serial N+1 — a poisoned serial rolls back on the canary replica
   (sentinel/breach, PR 16) and the rest of the fleet is bitwise
   unaffected.  A survived probation promotes FLEET-WIDE: the serial is
   loaded once and drain-swapped into every sibling replica.

Events: ``fleet.spawn`` / ``fleet.replica_ready`` /
``fleet.replica_dead`` / ``fleet.scale_out`` / ``fleet.scale_in`` /
``fleet.drain_replica`` / ``fleet.shed`` (router) /
``fleet.canary_start`` / ``fleet.canary_rollback`` /
``fleet.canary_promote`` / ``fleet.rollout``.  Gauges:
``fleet.replicas{model=}`` / ``fleet.queue_depth{model=}``; each
replica's engine mirrors its serving counters with ``model=``/
``replica=`` labels (``observe.fleet.label_sums`` joins them).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .router import Router, RouterConfig

__all__ = ["DevicePool", "Replica", "ModelSignals", "Decision",
           "AutoscalePolicy", "ServingFleet",
           "SPAWNING", "WARMING", "READY", "DRAINING", "DEAD"]

# replica lifecycle states
SPAWNING = "spawning"   # factory building the engine
WARMING = "warming"     # engine up, precompiling / cache-loading
READY = "ready"         # taking traffic
DRAINING = "draining"   # planned exit: finishing resident work
DEAD = "dead"           # gone (killed, crashed, or retired)

#: slot-utilization floor below which an idle queue reads as overcapacity
_SCALE_IN_UTILIZATION = 0.25

#: program construction goes through process-global framework state
#: (default-program/unique-name scopes), so concurrent replica spawns
#: serialize their build+warm section; with the shared compile store a
#: follow-up replica's warm is cache-hit-only, so the critical section
#: is short for everything after the first replica of an architecture
_BUILD_LOCK = threading.Lock()


def _emit(event: str, **fields) -> None:
    from .. import observe

    observe.emit(event, **fields)


def _median(vals) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return float(s[mid]) if n % 2 else (s[mid - 1] + s[mid]) / 2.0


# ---------------------------------------------------------------------------
# device pool
# ---------------------------------------------------------------------------


class DevicePool:
    """Shared logical device pool the whole fleet leases from.  A lost
    device (its replica died / its host dropped a loss marker) is never
    re-leased — re-spawn happens on surviving devices only, exactly the
    elastic supervisor's survivor-census rule applied to serving."""

    def __init__(self, n_devices: Optional[int] = None):
        if n_devices is None:
            try:
                import jax

                n_devices = max(4, jax.device_count())
            except Exception:
                n_devices = 4
        self.n_devices = int(n_devices)
        self._lock = threading.Lock()
        self._leased: set = set()
        self._lost: set = set()

    def acquire(self) -> Optional[int]:
        with self._lock:
            for d in range(self.n_devices):
                if d not in self._leased and d not in self._lost:
                    self._leased.add(d)
                    return d
            return None

    def release(self, device: int) -> None:
        with self._lock:
            self._leased.discard(int(device))

    def mark_lost(self, device: int) -> None:
        """Permanently retire a device (unplanned replica death)."""
        with self._lock:
            self._leased.discard(int(device))
            self._lost.add(int(device))

    def available(self) -> int:
        with self._lock:
            return self.n_devices - len(self._leased) - len(self._lost)

    def summary(self) -> dict:
        with self._lock:
            return {"n_devices": self.n_devices,
                    "leased": sorted(self._leased),
                    "lost": sorted(self._lost),
                    "available": self.n_devices - len(self._leased)
                    - len(self._lost)}


# ---------------------------------------------------------------------------
# one replica
# ---------------------------------------------------------------------------


class Replica:
    """One engine replica of one model: lifecycle + liveness reporting.

    ``factory(metrics_labels)`` builds the engine (a
    :class:`~paddle_tpu.serving.decode.DecodeEngine`); the labels carry
    ``model=``/``replica=`` so the process registry keeps every
    replica's serving counters separable.  The replica heartbeats into
    the fleet's ``hb_dir`` via the elastic worker protocol
    (``hb_<rank>`` files, atomic rename); the heartbeat thread dies
    with the engine, so a killed replica's file goes stale and the
    census flags it even without the in-process ``alive`` probe."""

    def __init__(self, model_id: str, name: str, rank: int, device: int,
                 factory: Callable, hb_dir: Optional[str] = None,
                 hb_interval_s: float = 0.25):
        self.model_id = str(model_id)
        self.name = str(name)
        self.rank = int(rank)
        self.device = int(device)
        self.state = SPAWNING
        self.engine = None
        self.served = 0
        self.planned_exit = False
        self.accounted = False  # census has processed this death
        self.death_reason: Optional[str] = None
        self.t_spawn = time.perf_counter()
        self._factory = factory
        self._hb_dir = hb_dir
        self._hb_interval_s = float(hb_interval_s)
        self._dead_once = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --

    def start(self, on_ready: Optional[Callable] = None) -> None:
        """Spawn asynchronously: build engine, warm, go READY."""
        self._thread = threading.Thread(
            target=self._spawn, args=(on_ready,), daemon=True,
            name=f"replica-spawn-{self.name}")
        self._thread.start()

    def _spawn(self, on_ready) -> None:
        _emit("fleet.spawn", model=self.model_id, replica=self.name,
              device=self.device, rank=self.rank)
        try:
            with _BUILD_LOCK:
                self.engine = self._factory({"model": self.model_id,
                                             "replica": self.name})
                self.state = WARMING
                self.engine.warmup()
        except Exception as exc:
            self.state = DEAD
            self.death_reason = f"spawn_failed: {exc!r}"
            _emit("fleet.replica_error", model=self.model_id,
                  replica=self.name, error=repr(exc))
            return
        self.state = READY
        self._heartbeat()
        m = self.engine.metrics
        _emit("fleet.replica_ready", model=self.model_id,
              replica=self.name, device=self.device,
              warmup_dispatches=m.counter("warmup_dispatches"),
              warmup_cached=m.counter("warmup_cached"),
              executables=self.engine.executables(),
              dur_s=round(time.perf_counter() - self.t_spawn, 6))
        if self._hb_dir:
            threading.Thread(target=self._hb_loop, daemon=True,
                             name=f"replica-hb-{self.name}").start()
        if on_ready is not None:
            try:
                on_ready(self)
            except Exception:
                pass

    def _heartbeat(self) -> None:
        if not self._hb_dir:
            return
        from ..parallel import elastic as _elastic

        _elastic.write_heartbeat(self._hb_dir, step=self.served,
                                 rank=self.rank)

    def _hb_loop(self) -> None:
        while self.state in (READY, DRAINING):
            eng = self.engine
            if eng is None or not eng.alive:
                return  # dead engine: let the file go stale
            self._heartbeat()
            time.sleep(self._hb_interval_s)

    # -- traffic (router-facing) --

    def load(self) -> float:
        """Dispatch-cost estimate: resident slots + engine queue depth
        (the live gauges the engines already keep — racy reads are fine
        for load balancing)."""
        eng = self.engine
        if eng is None or self.state != READY:
            return float("inf")
        return eng._n_active + len(eng._queue)

    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int,
               timeout_ms: Optional[float] = None):
        """Forward one request to the engine; runs the replica-kill
        fault hook against the served-request count (the deterministic
        replica-death oracle: ``PADDLE_FAULT_REPLICA_KILL_AFTER=n``
        kills THIS replica right after its n-th accepted request — the
        request fails over through the router like any crash)."""
        from ..fluid import fault as _fault

        eng = self.engine
        if eng is None:
            from .engine import EngineClosed

            raise EngineClosed(f"replica {self.name} has no engine")
        fut = eng.submit(prompt_ids, max_new_tokens,
                         timeout_ms=timeout_ms)
        self.served += 1
        if _fault.replica_kill(self.served):
            self.die("fault_injected")
        return fut

    # -- death / retirement --

    def die(self, reason: str) -> None:
        """Hard-kill the replica (crash semantics): the engine stops
        without drain, every in-flight future fails with EngineClosed
        and fails over through the router."""
        if self._dead_once.is_set():
            return
        self._dead_once.set()
        self.state = DEAD
        self.death_reason = reason
        eng = self.engine
        if eng is not None:
            try:
                eng.kill()
            except Exception:
                pass
        _emit("fleet.replica_dead", model=self.model_id,
              replica=self.name, device=self.device, reason=reason,
              served=self.served)

    def note_dead(self) -> None:
        """Router-side death report (an EngineClosed future): converge
        the state without double-emitting."""
        eng = self.engine
        if eng is not None and eng.alive:
            return  # transient (e.g. drain-rejected submit): not death
        self.die(self.death_reason or "engine_closed")

    def retire(self, drain_timeout_s: float = 30.0) -> bool:
        """Planned exit (scale-in / straggler replacement): drain
        resident work, shut down, release nothing here — the fleet owns
        the device lease."""
        self.planned_exit = True
        self.state = DRAINING
        eng = self.engine
        ok = True
        if eng is not None:
            try:
                ok = eng.shutdown(timeout_s=drain_timeout_s)
            except Exception:
                ok = False
        self._dead_once.set()  # planned: no fleet.replica_dead event
        self.state = DEAD
        self.death_reason = "retired"
        return ok


# ---------------------------------------------------------------------------
# autoscale policy (pure)
# ---------------------------------------------------------------------------


class ModelSignals:
    """One model's observed state at one policy tick — plain data, so
    :class:`AutoscalePolicy` stays enginelessly unit-testable.

    ``breaches`` is CUMULATIVE (the SLO watchdog's running count as
    visible to this model); the policy differentiates it internally.
    ``intertoken_p50_ms`` maps replica name -> that replica's rolling
    inter-token p50 (None/missing entries are skipped)."""

    def __init__(self, queue_depth: int = 0, replicas_ready: int = 1,
                 replicas_warming: int = 0, slots_active: int = 0,
                 slots_total: int = 0, breaches: int = 0,
                 intertoken_p50_ms: Optional[Dict[str, float]] = None):
        self.queue_depth = int(queue_depth)
        self.replicas_ready = int(replicas_ready)
        self.replicas_warming = int(replicas_warming)
        self.slots_active = int(slots_active)
        self.slots_total = int(slots_total)
        self.breaches = int(breaches)
        self.intertoken_p50_ms = dict(intertoken_p50_ms or {})


class Decision:
    """One policy verdict: ``action`` in ``none | wait | scale_out |
    scale_in | drain_replica`` (+ ``replica`` for drain)."""

    def __init__(self, action: str, reason: str = "",
                 replica: Optional[str] = None):
        self.action = action
        self.reason = reason
        self.replica = replica

    def __repr__(self):
        extra = f", replica={self.replica!r}" if self.replica else ""
        return f"Decision({self.action!r}, {self.reason!r}{extra})"

    def __eq__(self, other):
        return (isinstance(other, Decision)
                and (self.action, self.replica)
                == (other.action, other.replica))


class _ModelPolicyState:
    __slots__ = ("over_ticks", "under_ticks", "last_breaches",
                 "last_scale", "birth")

    def __init__(self):
        self.over_ticks = 0
        self.under_ticks = 0
        self.last_breaches = 0
        self.last_scale = float("-inf")
        self.birth = None  # first decide() stamp: scale-in grace anchor


class AutoscalePolicy:
    """Breach-driven autoscaling, pure: ``decide(model_id, signals,
    now)`` -> :class:`Decision`.  Signal precedence:

    1. **warming replica** -> ``wait``: queue pressure while capacity is
       already compiling/cache-loading is a *compile stall*, not a
       capacity gap — scaling again would thrash the device pool.
    2. **straggling replica** (>= 2 ready, per-replica inter-token p50
       exceeds ``straggler_factor`` x the leave-one-out median of its
       siblings) -> ``drain_replica``: one slow replica drags the
       fleet p99 no matter how many healthy siblings it has.
    3. **pressure** (queue depth > ``queue_high`` OR the cumulative
       breach count advanced since the last tick) sustained
       ``hysteresis_ticks`` consecutive ticks -> ``scale_out``, bounded
       by ``max_replicas`` and the ``cooldown_s`` since the last scaling
       action.
    4. **idle** (queue depth <= ``queue_low`` AND slot utilization under
       25%) sustained the same hysteresis -> ``scale_in`` down to
       ``min_replicas``.

    All knobs default from the ``PADDLE_ROUTER_*`` env contract;
    constructor args override (tests pass exact values + explicit
    ``now`` timestamps, so runs are fully deterministic)."""

    def __init__(self, max_replicas: Optional[int] = None,
                 min_replicas: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 queue_high: Optional[int] = None,
                 queue_low: Optional[int] = None,
                 hysteresis_ticks: Optional[int] = None,
                 straggler_factor: Optional[float] = None):
        from ..fluid import envcontract as _ec

        def knob(v, name):
            return v if v is not None else _ec.get(name)

        self.max_replicas = int(knob(max_replicas,
                                     "PADDLE_ROUTER_MAX_REPLICAS"))
        self.min_replicas = int(knob(min_replicas,
                                     "PADDLE_ROUTER_MIN_REPLICAS"))
        self.cooldown_s = float(knob(cooldown_s,
                                     "PADDLE_ROUTER_COOLDOWN_S"))
        self.queue_high = int(knob(queue_high,
                                   "PADDLE_ROUTER_QUEUE_HIGH"))
        self.queue_low = int(knob(queue_low, "PADDLE_ROUTER_QUEUE_LOW"))
        self.hysteresis_ticks = int(knob(
            hysteresis_ticks, "PADDLE_ROUTER_HYSTERESIS_TICKS"))
        self.straggler_factor = float(knob(
            straggler_factor, "PADDLE_ROUTER_STRAGGLER_FACTOR"))
        self._state: Dict[str, _ModelPolicyState] = {}

    def _st(self, model_id: str) -> _ModelPolicyState:
        return self._state.setdefault(str(model_id), _ModelPolicyState())

    def decide(self, model_id: str, sig: ModelSignals,
               now: float) -> Decision:
        st = self._st(model_id)
        if st.birth is None:
            st.birth = now
        breach_delta = max(0, sig.breaches - st.last_breaches)
        st.last_breaches = sig.breaches
        # 1. capacity already on its way: never stack scale decisions
        #    on top of a warming replica (the compile-stall branch)
        if sig.replicas_warming > 0:
            st.over_ticks = 0
            st.under_ticks = 0
            return Decision("wait", "replica_warming")
        # 2. straggler: leave-one-out median over the sibling p50s
        p50s = {k: float(v) for k, v in sig.intertoken_p50_ms.items()
                if isinstance(v, (int, float))}
        if len(p50s) >= 2 and sig.replicas_ready >= 2 \
                and now - st.last_scale >= self.cooldown_s:
            for name, own in sorted(p50s.items()):
                others = [v for k, v in p50s.items() if k != name]
                base = _median(others)
                if base > 0.0 and own > base * self.straggler_factor:
                    st.last_scale = now
                    st.over_ticks = 0
                    st.under_ticks = 0
                    return Decision(
                        "drain_replica",
                        f"straggler: p50 {own:.1f}ms vs sibling median "
                        f"{base:.1f}ms (x{own / base:.1f})", replica=name)
        # 3/4. pressure vs idle, with hysteresis + cooldown
        over = sig.queue_depth > self.queue_high or breach_delta > 0
        under = (sig.queue_depth <= self.queue_low
                 and sig.slots_active
                 <= sig.slots_total * _SCALE_IN_UTILIZATION)
        st.over_ticks = st.over_ticks + 1 if over else 0
        st.under_ticks = st.under_ticks + 1 if under and not over else 0
        if st.over_ticks >= self.hysteresis_ticks:
            if sig.replicas_ready + sig.replicas_warming \
                    >= self.max_replicas:
                return Decision("none", "at_max_replicas")
            if now - st.last_scale < self.cooldown_s:
                return Decision("wait", "cooldown")
            st.last_scale = now
            st.over_ticks = 0
            return Decision("scale_out",
                            "slo_breach" if breach_delta > 0
                            else "queue_pressure")
        if st.under_ticks >= self.hysteresis_ticks:
            if sig.replicas_ready <= self.min_replicas:
                return Decision("none", "at_min_replicas")
            # scale-in honors a startup grace too (now - birth): a fleet
            # must not retire a just-warmed replica before traffic has
            # had one cooldown window to show up
            if now - st.last_scale < self.cooldown_s \
                    or now - st.birth < self.cooldown_s:
                return Decision("none", "cooldown")
            st.last_scale = now
            st.under_ticks = 0
            return Decision("scale_in", "idle")
        return Decision("none", "")


# ---------------------------------------------------------------------------
# the fleet
# ---------------------------------------------------------------------------


class _ModelState:
    """Per-model fleet bookkeeping (replicas list, canary wiring)."""

    def __init__(self, model_id: str, factory: Callable,
                 initial_replicas: int):
        self.model_id = model_id
        self.factory = factory
        self.initial_replicas = int(initial_replicas)
        self.replicas: List[Replica] = []
        self.rseq = itertools.count()
        self.spawn_lock = threading.Lock()
        # canary wiring (None until watch_checkpoints)
        self.ckpt_dir: Optional[str] = None
        self.registry = None
        self.fleet_serial = -1
        self.canary_routing = False
        self.vetoed_seen = 0

    def ready(self) -> List[Replica]:
        return [r for r in list(self.replicas) if r.state == READY]

    def warming(self) -> List[Replica]:
        return [r for r in list(self.replicas)
                if r.state in (SPAWNING, WARMING)]

    def canary_replica(self) -> Optional[Replica]:
        reg = self.registry
        if reg is None:
            return None
        for r in list(self.replicas):
            if r.engine is reg.engine:
                return r
        return None


class ServingFleet:
    """The serving-side supervisor: owns the replicas, the router and
    the policy loop.  ``model_factories`` maps model id -> a callable
    ``factory(metrics_labels) -> DecodeEngine`` (each call must build an
    INDEPENDENT engine; deterministic factories give bitwise-identical
    replicas, which is what makes failover invisible to clients).

    ::

        fleet = ServingFleet({"chat": make_chat, "code": make_code},
                             replicas=2, hb_dir=tmp)
        fleet.start()                      # spawn + warm every replica
        fut = fleet.submit("chat", [2, 3], 8, timeout_ms=2000)
        fleet.watch_checkpoints("chat", ckpt_dir)   # fleet canary
        fleet.shutdown()
    """

    def __init__(self, model_factories: Dict[str, Callable],
                 replicas=1,
                 device_pool: Optional[DevicePool] = None,
                 hb_dir: Optional[str] = None,
                 policy: Optional[AutoscalePolicy] = None,
                 router_config: Optional[RouterConfig] = None,
                 canary_fraction: Optional[float] = None,
                 canary_requests: Optional[int] = None,
                 eval_s: Optional[float] = None,
                 hb_timeout_s: Optional[float] = None,
                 drain_timeout_s: float = 30.0):
        from ..fluid import envcontract as _ec

        if not model_factories:
            raise ValueError("model_factories must name at least one "
                             "model")
        n_for = (replicas if isinstance(replicas, dict)
                 else {m: int(replicas) for m in model_factories})
        self._models: Dict[str, _ModelState] = {
            str(m): _ModelState(str(m), f, n_for.get(m, 1))
            for m, f in model_factories.items()}
        self.hb_dir = hb_dir
        self.policy = policy or AutoscalePolicy()
        # default pool: room for every model at max scale plus one
        # respawn device per model (a dead replica's device is lost)
        self.pool = device_pool or DevicePool(
            len(self._models) * (self.policy.max_replicas + 1))
        self.eval_s = float(eval_s if eval_s is not None
                            else _ec.get("PADDLE_ROUTER_EVAL_S"))
        self.hb_timeout_s = float(
            hb_timeout_s if hb_timeout_s is not None
            else _ec.get("PADDLE_ROUTER_HB_TIMEOUT_S"))
        self.canary_fraction = float(
            canary_fraction if canary_fraction is not None
            else _ec.get("PADDLE_ROUTER_CANARY_FRACTION"))
        self.canary_requests = canary_requests
        self.drain_timeout_s = float(drain_timeout_s)
        self._rank = itertools.count()
        self._stop_evt = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.router = Router(self._select, router_config,
                             last_chance=self._last_chance)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self, wait_ready_s: Optional[float] = 60.0) -> None:
        """Spawn the initial replica set and the monitor loop; blocks
        (up to ``wait_ready_s``) until every model has one READY
        replica."""
        for ms in self._models.values():
            for _ in range(ms.initial_replicas):
                self._spawn(ms, reason="initial")
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="fleet-monitor")
        self._monitor.start()
        if wait_ready_s:
            deadline = time.perf_counter() + float(wait_ready_s)
            for ms in self._models.values():
                while not ms.ready() and ms.warming() \
                        and time.perf_counter() < deadline:
                    time.sleep(0.01)

    def submit(self, model_id: str, prompt_ids: Sequence[int],
               max_new_tokens: int, timeout_ms: Optional[float] = None):
        return self.router.submit(model_id, prompt_ids, max_new_tokens,
                                  timeout_ms=timeout_ms)

    def generate(self, model_id: str, prompt_ids: Sequence[int],
                 max_new_tokens: int,
                 timeout_ms: Optional[float] = None) -> List[int]:
        return self.submit(model_id, prompt_ids, max_new_tokens,
                           timeout_ms=timeout_ms).result()

    def shutdown(self, timeout_s: float = 30.0) -> None:
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=timeout_s)
        self.router.drain(timeout_s=timeout_s)
        self.router.stop()
        for ms in self._models.values():
            for r in list(ms.replicas):
                if r.state in (READY, DRAINING, WARMING, SPAWNING):
                    r.retire(drain_timeout_s=min(timeout_s, 10.0))
                self.pool.release(r.device)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # ------------------------------------------------------------------
    # routing policy (router callbacks)
    # ------------------------------------------------------------------

    def _select(self, model_id: str, seq: int):
        """Replica candidates for one dispatch.  While a canary
        probation runs, the canary replica gets EXACTLY every k-th
        request (k from the canary fraction) and is excluded from the
        rest — the traffic split that keeps the blast radius of a bad
        serial to its slice."""
        ms = self._models.get(str(model_id))
        if ms is None:
            return []
        ready = ms.ready()
        if ms.canary_routing:
            canary = ms.canary_replica()
            if canary is not None and canary.state == READY:
                every = max(1, int(round(1.0 / max(
                    self.canary_fraction, 1e-6))))
                if seq % every == 0:
                    return [canary]
                rest = [r for r in ready if r is not canary]
                return rest or ready
        return ready

    def _last_chance(self, model_id: str) -> bool:
        """Router queue-overflow hook: the scale policy's emergency
        path.  Accept the overflow whenever capacity is already warming
        or an emergency scale-out can fire NOW (hysteresis and cooldown
        deliberately bypassed — a hard-limit overflow IS the sustained
        signal); shed only when the fleet is genuinely at its ceiling.

        Called from client threads under the router lock: touches only
        replica-list snapshots and the device pool (its own lock) —
        never the router."""
        ms = self._models.get(str(model_id))
        if ms is None:
            return False
        if ms.warming():
            return True
        live = len(ms.ready()) + len(ms.warming())
        if live >= self.policy.max_replicas:
            return False
        rep = self._spawn(ms, reason="queue_hard")
        if rep is None:
            return False
        _emit("fleet.scale_out", model=ms.model_id, reason="queue_hard",
              replica=rep.name, replicas=live + 1, emergency=True)
        return True

    # ------------------------------------------------------------------
    # replica lifecycle
    # ------------------------------------------------------------------

    def _spawn(self, ms: _ModelState, reason: str) -> Optional[Replica]:
        with ms.spawn_lock:
            device = self.pool.acquire()
            if device is None:
                _emit("fleet.spawn_blocked", model=ms.model_id,
                      reason="no_device", pool=self.pool.summary())
                return None
            rep = Replica(ms.model_id, f"{ms.model_id}-r{next(ms.rseq)}",
                          rank=next(self._rank), device=device,
                          factory=ms.factory, hb_dir=self.hb_dir,
                          hb_interval_s=max(0.05, self.hb_timeout_s / 4))
            ms.replicas.append(rep)
        rep.start(on_ready=lambda _r: self.router.kick())
        return rep

    def _retire(self, ms: _ModelState, rep: Replica,
                reason: str) -> None:
        def run():
            rep.retire(drain_timeout_s=self.drain_timeout_s)
            self.pool.release(rep.device)
            self.router.kick()

        rep.planned_exit = True
        rep.state = DRAINING
        threading.Thread(target=run, daemon=True,
                         name=f"replica-retire-{rep.name}").start()

    # ------------------------------------------------------------------
    # the monitor loop: census -> canary -> policy
    # ------------------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop_evt.wait(self.eval_s):
            try:
                self.poll_once()
            except Exception:
                import traceback

                _emit("fleet.monitor_error",
                      error=traceback.format_exc(limit=3))

    def poll_once(self) -> None:
        """One monitor step (tests/tools drive it synchronously)."""
        now = time.monotonic()
        for ms in self._models.values():
            self._census(ms)
            self._canary_step(ms)
            self._policy_step(ms, now)
            self._note_gauges(ms)

    # -- census --

    def _census(self, ms: _ModelState) -> None:
        """Death detection: the in-process liveness probe plus the
        PR 14 heartbeat/census protocol (stale ``hb_<rank>`` files and
        ``host_lost_*`` markers) — so the fleet converges on the same
        evidence whether the replica died in-process or its whole host
        went away.  An unplanned death marks the device lost and spawns
        a replacement on a surviving device."""
        from ..parallel import elastic as _elastic

        lost_markers = (_elastic.host_loss_markers(self.hb_dir)
                        if self.hb_dir else [])
        for rep in list(ms.replicas):
            if rep.state in (READY, DRAINING) and not rep.planned_exit:
                # silent-death detection over the live set
                dead_reason = None
                eng = rep.engine
                if eng is None or not eng.alive:
                    dead_reason = rep.death_reason or "engine_dead"
                elif any(m.endswith(f"_r{rep.rank}")
                         for m in lost_markers):
                    dead_reason = "host_lost"
                elif self.hb_dir:
                    hb = _elastic.read_heartbeat(self.hb_dir, rep.rank)
                    if hb is not None and \
                            time.time() - float(hb.get("ts", 0)) \
                            > self.hb_timeout_s:
                        dead_reason = "heartbeat_stale"
                if dead_reason is not None:
                    rep.die(dead_reason)
            # account every unplanned death exactly once, however it
            # was reported (census probe, router EngineClosed, fault
            # hook, manual die()): retire the device, spawn replacement
            if rep.state != DEAD or rep.planned_exit or rep.accounted:
                continue
            rep.accounted = True
            self.pool.mark_lost(rep.device)
            live = len(ms.ready()) + len(ms.warming())
            floor = max(self.policy.min_replicas, ms.initial_replicas)
            if live < min(floor, self.policy.max_replicas):
                new = self._spawn(ms, reason="respawn")
                if new is not None:
                    _emit("fleet.respawn", model=ms.model_id,
                          dead=rep.name, replica=new.name,
                          device=new.device,
                          reason=rep.death_reason or "unknown")

    # -- fleet canary --

    def watch_checkpoints(self, model_id: str, ckpt_dir: str,
                          serial: Optional[int] = None) -> None:
        """Arm the fleet canary for one model: a designated replica
        watches ``ckpt_dir`` through the PR 16 :class:`ModelRegistry`
        (canary probation, sentinel, auto-rollback); the fleet routes
        the canary traffic slice to it and rolls a SURVIVED serial out
        fleet-wide.  ``serial`` seeds the currently-served version
        (default: whatever the registry discovers first)."""
        ms = self._models[str(model_id)]
        ms.ckpt_dir = str(ckpt_dir)
        if serial is not None:
            ms.fleet_serial = int(serial)
        self._ensure_registry(ms)

    def _ensure_registry(self, ms: _ModelState) -> None:
        if ms.ckpt_dir is None:
            return
        reg = ms.registry
        if reg is not None:
            rep = ms.canary_replica()
            if rep is not None and rep.state in (READY, DRAINING):
                return
            # canary replica died: the registry died with it
            ms.registry = None
            ms.canary_routing = False
        candidates = ms.ready()
        if not candidates:
            return
        from .registry import ModelRegistry

        host = candidates[0]
        ms.registry = ModelRegistry(
            host.engine, ms.ckpt_dir,
            canary_requests=self.canary_requests,
            serial=ms.fleet_serial)
        ms.vetoed_seen = len(ms.registry.vetoed())
        _emit("fleet.canary_host", model=ms.model_id, replica=host.name,
              serial=ms.fleet_serial)

    def _canary_step(self, ms: _ModelState) -> None:
        self._ensure_registry(ms)
        reg = ms.registry
        if reg is None:
            return
        try:
            reg.poll_once()
        except Exception:
            import traceback

            _emit("fleet.canary_error", model=ms.model_id,
                  error=traceback.format_exc(limit=3))
            return
        canary = ms.canary_replica()
        vetoed = reg.vetoed()
        if len(vetoed) > ms.vetoed_seen:
            # the sentinel rolled the canary replica back: the rest of
            # the fleet never saw the bad serial — nothing to undo
            ms.vetoed_seen = len(vetoed)
            ms.canary_routing = False
            _emit("fleet.canary_rollback", model=ms.model_id,
                  serial=int(vetoed[-1]),
                  replica=canary.name if canary else None,
                  fleet_serial=ms.fleet_serial)
            return
        if reg.canary_active():
            if not ms.canary_routing:
                ms.canary_routing = True
                _emit("fleet.canary_start", model=ms.model_id,
                      serial=int(reg.serial),
                      replica=canary.name if canary else None,
                      fraction=self.canary_fraction)
            return
        ms.canary_routing = False
        if reg.serial > ms.fleet_serial:
            # probation survived (or canary disabled): promote fleet-wide
            serial = int(reg.serial)
            _emit("fleet.canary_promote", model=ms.model_id,
                  serial=serial,
                  replica=canary.name if canary else None)
            self._rollout(ms, serial)

    def _rollout(self, ms: _ModelState, serial: int) -> None:
        """Drain-swap a promoted serial into every sibling replica:
        loaded from disk ONCE, then rebound engine by engine (pause ->
        idle -> swap -> resume: zero shed, every request single-
        version)."""
        from ..fluid.trainer import CKPT_PREFIX
        from .registry import load_serial_weights

        canary = ms.canary_replica()
        targets = [r for r in ms.ready() if r is not canary]
        swapped = []
        weights = None
        for rep in targets:
            eng = rep.engine
            try:
                if weights is None:
                    names = list(eng.model.weight_names())
                    shapes = {n: tuple(np.shape(a)) for n, a in
                              eng.snapshot_weights(names).items()}
                    weights, _info = load_serial_weights(
                        os.path.join(ms.ckpt_dir,
                                     f"{CKPT_PREFIX}_{int(serial)}"),
                        names, shapes)
                eng.pause_admissions()
                try:
                    eng.wait_idle(self.drain_timeout_s)
                    eng.swap_weights(weights)
                finally:
                    eng.resume_admissions()
                eng.metrics.inc("model_swaps")
                eng.metrics.set_gauge("model_serial", int(serial))
                swapped.append(rep.name)
            except Exception as exc:
                _emit("fleet.rollout_error", model=ms.model_id,
                      replica=rep.name, serial=int(serial),
                      error=repr(exc))
        ms.fleet_serial = int(serial)
        _emit("fleet.rollout", model=ms.model_id, serial=int(serial),
              replicas=swapped,
              canary=canary.name if canary else None)

    # -- autoscaling --

    def _signals(self, ms: _ModelState) -> ModelSignals:
        ready = ms.ready()
        slots_active = 0
        slots_total = 0
        p50s: Dict[str, float] = {}
        for r in ready:
            eng = r.engine
            slots_active += eng._n_active
            slots_total += eng.model.max_slots
            snap = eng.metrics.snapshot()
            p50 = snap.get("intertoken_p50_ms")
            if isinstance(p50, (int, float)):
                p50s[r.name] = float(p50)
        from ..observe import watchdog as _watchdog

        wd = _watchdog.get_watchdog()
        breaches = int(sum(wd.breaches.values())) if wd is not None \
            else 0
        return ModelSignals(
            queue_depth=self.router.queue_depth(ms.model_id),
            replicas_ready=len(ready),
            replicas_warming=len(ms.warming()),
            slots_active=slots_active, slots_total=slots_total,
            breaches=breaches, intertoken_p50_ms=p50s)

    def _policy_step(self, ms: _ModelState, now: float) -> None:
        sig = self._signals(ms)
        decision = self.policy.decide(ms.model_id, sig, now)
        if decision.action == "scale_out":
            rep = self._spawn(ms, reason=decision.reason)
            if rep is not None:
                _emit("fleet.scale_out", model=ms.model_id,
                      reason=decision.reason, replica=rep.name,
                      replicas=sig.replicas_ready + 1, emergency=False)
        elif decision.action == "scale_in":
            ready = ms.ready()
            canary = ms.canary_replica()
            victims = [r for r in ready if r is not canary]
            if victims:
                victim = max(victims, key=lambda r: r.name)
                self._retire(ms, victim, decision.reason)
                _emit("fleet.scale_in", model=ms.model_id,
                      replica=victim.name, reason=decision.reason,
                      replicas=len(ready) - 1)
        elif decision.action == "drain_replica":
            rep = next((r for r in ms.ready()
                        if r.name == decision.replica), None)
            if rep is not None:
                replacement = self._spawn(ms, reason="straggler_replace")
                self._retire(ms, rep, decision.reason)
                _emit("fleet.drain_replica", model=ms.model_id,
                      replica=rep.name, reason=decision.reason,
                      replacement=(replacement.name
                                   if replacement else None))

    def _note_gauges(self, ms: _ModelState) -> None:
        from ..observe import registry as _registry

        reg = _registry()
        labels = {"model": ms.model_id}
        reg.set_gauge("fleet.replicas", len(ms.ready()), labels=labels)
        reg.set_gauge("fleet.replicas_warming", len(ms.warming()),
                      labels=labels)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def status(self) -> dict:
        """Structured fleet view (tools/bench/smoke read this)."""
        models = {}
        for m, ms in self._models.items():
            models[m] = {
                "replicas": [{
                    "name": r.name, "state": r.state,
                    "device": r.device, "served": r.served,
                    "death_reason": r.death_reason,
                } for r in list(ms.replicas)],
                "ready": len(ms.ready()),
                "warming": len(ms.warming()),
                "queue_depth": self.router.queue_depth(m),
                "shed": self.router.shed_count(m),
                "dispatched": self.router.dispatched_count(m),
                "fleet_serial": ms.fleet_serial,
                "canary_routing": ms.canary_routing,
            }
        return {"models": models, "pool": self.pool.summary()}
