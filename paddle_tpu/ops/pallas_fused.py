"""Fused Pallas kernels beyond attention: streaming softmax-cross-entropy
and multi-tensor optimizer updates, with tp-sharded lowerings.

This completes the fused-kernel layer ROADMAP item 2 reserves for Pallas
("Pallas only where XLA underperforms") next to ``ops/pallas_flash.py``:

 - **Streaming softmax-with-cross-entropy** (fwd + bwd): the loss head of
   every classifier/LM tiles over the vocab/class dimension with the
   online-softmax (logsumexp) recurrence in fp32 VMEM scratch, so the
   ``[batch, vocab]`` probability matrix never materializes in HBM; the
   backward recomputes ``P = exp(logits - lse)`` per tile from the saved
   logsumexp (the FlashAttention discipline applied to the loss boundary).
   Hard labels (with ``ignore_index``) and soft labels both stream.
 - **Fused optimizer updates**: momentum and adam as single multi-tensor
   kernels — one grid sweep reads param + grad + moments and writes the
   updated buffers back through ``input_output_aliases``, instead of the
   handful of separate XLA elementwise ops per parameter.  The executor's
   SSA rebinding + donation (PR 6) make the update in place on device.
 - **tp-sharded lowerings**: under an active :func:`spmd.active_mesh`
   every kernel lowers through ``shard_map`` so column/row-parallel
   operands stay sharded through the kernel (GSPMD cannot partition an
   opaque ``pallas_call``).  The softmax-xent kernel handles a tp-sharded
   vocab dim with a cross-shard max/sum (logsumexp) exchange; optimizer
   updates run on the local shard of param/moment buffers per the PR 7
   spec table; flash attention shards its head dim.

Dispatch is env-gated by ``PADDLE_TPU_FUSED`` with the same 0/1/AUTO
precedence as ``PADDLE_TPU_FLASH`` (AUTO: on for TPU backends, off on
CPU/GPU; interpret mode keeps the kernels testable on the CPU mesh), and
every fused dispatch decision increments an ``ops.fused.<kind>`` counter
(mesh-labeled under a mesh) so BENCH rounds are attributable to kernels.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

try:  # jax >= 0.8 moved shard_map to the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from jax.sharding import PartitionSpec as P

DEFAULT_BLOCK_R = 256    # rows (flattened batch) per grid step
DEFAULT_BLOCK_V = 512    # vocab/class columns per grid step
DEFAULT_BLOCK_N = 1024   # optimizer-sweep rows per grid step
LANE = 128
NEG_INF = -1e30

#: dtypes the kernels accumulate in fp32 for; anything else (f64 under the
#: package-wide x64 mode) falls back to the unfused XLA lowering.
_FUSABLE_DTYPES = ("float32", "bfloat16", "float16")


# ---------------------------------------------------------------------------
# dispatch decision + counters
# ---------------------------------------------------------------------------


def fused_decision(req: int = -1) -> bool:
    """PADDLE_TPU_FUSED gate, same precedence contract as
    ``attention_ops._flash_decision``: the env kill-switch wins over
    everything (=0 forces OFF, =1 forces ON — interpret mode off-TPU),
    then the per-call request, then AUTO (on iff the backend is a TPU;
    interpret mode is a correctness tool, not a CPU fast path)."""
    from ..fluid import envcontract

    v = envcontract.get("PADDLE_TPU_FUSED")
    if v in ("0", "false"):
        return False
    if v in ("1", "true"):
        return True
    if req != -1:
        return bool(req)
    return jax.default_backend() == "tpu"


def active_families() -> list:
    """The kernel families that would dispatch fused under the current
    env/backend — recorded in every BENCH line (bench.py) so rounds are
    attributable to kernel changes."""
    return (["softmax_xent", "momentum", "adam"] if fused_decision() else [])


def _active_mesh():
    from ..parallel import spmd

    return spmd.active_mesh()


def _note(kind: str) -> None:
    """One ``ops.fused.<kind>`` dispatch-decision counter per trace
    (mesh-labeled under an active mesh) — the observe-side evidence that
    a program actually lowered through the fused kernel."""
    try:
        from .. import observe
        from ..parallel.mesh import mesh_label

        mesh = _active_mesh()
        labels = {"mesh": mesh_label(mesh)} if mesh is not None else None
        observe.registry().inc(f"ops.fused.{kind}", labels=labels)
    except Exception:
        pass  # accounting must never fail the trace it measures


def _interp(interpret):
    return jax.default_backend() != "tpu" if interpret is None else interpret


def _fit_block(size, block):
    b = min(block, size)
    while size % b:
        b //= 2
    return b


# ---------------------------------------------------------------------------
# streaming softmax-cross-entropy
# ---------------------------------------------------------------------------


def _xent_partial_kernel(x_ref, lab_ref, *out_refs, bv, n_v, soft):
    """Grid step (row-block, vocab-block): online-logsumexp state (m, l)
    plus the label accumulator(s) in fp32 VMEM scratch, carried across the
    (sequential, minormost) vocab dimension — VMEM holds one [br, bv]
    logits tile at a time, the class dim can be arbitrarily long.

    Emits the PARTIAL per-row state (m, l, a[, b]) instead of the final
    loss, so one kernel serves both the single-device path (finalized in
    four trivial [R, 1] jnp ops) and the tp-sharded path (finalized after
    a cross-shard max/sum exchange).  ``a`` is the picked-logit sum (hard)
    or ``sum(y * logits)`` (soft); ``b`` (soft only) is ``sum(y)``."""
    if soft:
        m_out, l_out, a_out, b_out, m_ref, l_ref, a_ref, b_ref = out_refs
    else:
        m_out, l_out, a_out, m_ref, l_ref, a_ref = out_refs
        b_out = b_ref = None
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        a_ref[:] = jnp.zeros_like(a_ref)
        if b_ref is not None:
            b_ref[:] = jnp.zeros_like(b_ref)

    x = x_ref[...].astype(jnp.float32)               # [br, bv]
    m = m_ref[:]
    m_new = jnp.maximum(m, jnp.max(x, axis=1, keepdims=True))
    p = jnp.exp(x - m_new)
    corr = jnp.exp(m - m_new)
    m_ref[:] = m_new
    l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
    if soft:
        y = lab_ref[...].astype(jnp.float32)         # [br, bv]
        a_ref[:] = a_ref[:] + jnp.sum(y * x, axis=1, keepdims=True)
        b_ref[:] = b_ref[:] + jnp.sum(y, axis=1, keepdims=True)
    else:
        # all index math in i32: under the package-wide x64 mode python
        # ints promote to i64, which Mosaic's index ops reject
        cols = j * jnp.int32(bv) + lax.broadcasted_iota(
            jnp.int32, x.shape, 1)
        lab = lab_ref[...]                           # [br, 1] int32
        a_ref[:] = a_ref[:] + jnp.sum(
            jnp.where(cols == lab, x, 0.0), axis=1, keepdims=True)

    @pl.when(j == n_v - 1)
    def _flush():
        m_out[...] = m_ref[:]
        l_out[...] = l_ref[:]
        a_out[...] = a_ref[:]
        if b_out is not None:
            b_out[...] = b_ref[:]


def _xent_bwd_kernel(x_ref, lab_ref, lse_ref, g1_ref, g2_ref, dx_ref, *,
                     bv, soft):
    """Backward grid step — tiles are independent (no carry): recompute
    ``P = exp(x - lse)`` for this [br, bv] tile from the saved logsumexp
    and emit ``dx = g1 * P - g2 * target`` where target is the one-hot
    (hard) or the soft-label tile.  ``g1``/``g2`` are per-row coefficients
    precomputed on the host side of the trace (they fold the incoming loss
    cotangent, the ignore mask, ``sum(y)`` and any lse cotangent)."""
    j = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    p = jnp.exp(x - lse_ref[...])
    g1 = g1_ref[...]
    g2 = g2_ref[...]
    if soft:
        tgt = lab_ref[...].astype(jnp.float32)
    else:
        cols = j * jnp.int32(bv) + lax.broadcasted_iota(
            jnp.int32, x.shape, 1)
        tgt = (cols == lab_ref[...]).astype(jnp.float32)
    dx_ref[...] = (g1 * p - g2 * tgt).astype(dx_ref.dtype)


def _xent_partial(x2, lab2, soft, block_r, block_v, interpret):
    """Run the streaming kernel over ``x2 [R, V]``; returns per-row fp32
    ``(m, l, a, b)`` columns (``b`` is None for hard labels)."""
    from jax.experimental.pallas import tpu as pltpu

    r, v = x2.shape
    br = _fit_block(r, block_r)
    bv = _fit_block(v, block_v)
    n_v = v // bv
    col = jax.ShapeDtypeStruct((r, 1), jnp.float32)
    lab_spec = (pl.BlockSpec((br, bv), lambda i, j: (i, j)) if soft
                else pl.BlockSpec((br, 1), lambda i, j: (i, 0)))
    out_spec = pl.BlockSpec((br, 1), lambda i, j: (i, 0))
    n_out = 4 if soft else 3
    outs = pl.pallas_call(
        functools.partial(_xent_partial_kernel, bv=bv, n_v=n_v, soft=soft),
        out_shape=[col] * n_out,
        grid=(r // br, n_v),
        in_specs=[pl.BlockSpec((br, bv), lambda i, j: (i, j)), lab_spec],
        out_specs=[out_spec] * n_out,
        scratch_shapes=[pltpu.VMEM((br, 1), jnp.float32)] * n_out,
        interpret=_interp(interpret),
    )(x2, lab2)
    if soft:
        m, l, a, b = outs
    else:
        (m, l, a), b = outs, None
    return m, l, a, b


def _xent_bwd_call(x2, lab2, lse, g1, g2, soft, block_r, block_v,
                   interpret):
    r, v = x2.shape
    br = _fit_block(r, block_r)
    bv = _fit_block(v, block_v)
    lab_spec = (pl.BlockSpec((br, bv), lambda i, j: (i, j)) if soft
                else pl.BlockSpec((br, 1), lambda i, j: (i, 0)))
    col = pl.BlockSpec((br, 1), lambda i, j: (i, 0))
    return pl.pallas_call(
        functools.partial(_xent_bwd_kernel, bv=bv, soft=soft),
        out_shape=jax.ShapeDtypeStruct((r, v), x2.dtype),
        grid=(r // br, v // bv),
        in_specs=[pl.BlockSpec((br, bv), lambda i, j: (i, j)), lab_spec,
                  col, col, col],
        out_specs=pl.BlockSpec((br, bv), lambda i, j: (i, j)),
        interpret=_interp(interpret),
    )(x2, lab2, lse, g1, g2)


def _finalize_loss(m, l, a, b, lab2, soft, ignore_index):
    lse = m + jnp.log(jnp.maximum(l, jnp.float32(1e-30)))
    if soft:
        loss = lse * b - a
    else:
        loss = lse - a
        if ignore_index >= 0:
            loss = jnp.where(lab2 == jnp.int32(ignore_index), 0.0, loss)
    return loss, lse


def _bwd_coeffs(lab2, b, dloss, dlse, soft, ignore_index):
    """Per-row coefficients for the backward kernel.  ``dlse`` is the
    cotangent of the lse output (nonzero only when the op's Softmax output
    — reconstructed as ``exp(x - lse)`` — is actually consumed)."""
    e = dloss.astype(jnp.float32)
    if not soft and ignore_index >= 0:
        e = jnp.where(lab2 == jnp.int32(ignore_index), 0.0, e)
    sy = b if soft else 1.0
    g1 = e * sy + dlse.astype(jnp.float32)
    return g1, e


def _label_zeros(label):
    """The Label cotangent for custom_vjp: labels never get gradients
    (no_grad_inputs contract) — float0 for integer labels, zeros for soft
    float labels."""
    if jnp.issubdtype(label.dtype, jnp.inexact):
        return jnp.zeros_like(label)
    return np.zeros(np.shape(label), jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def softmax_xent(logits2, label2, soft_label=False, ignore_index=-100,
                 block_r=DEFAULT_BLOCK_R, block_v=DEFAULT_BLOCK_V,
                 interpret=None):
    """Streamed ``softmax_with_cross_entropy`` over ``[R, V]`` logits.

    Returns ``(loss [R, 1] fp32, lse [R, 1] fp32)``; the probability
    matrix is never materialized — callers reconstruct softmax lazily as
    ``exp(logits - lse)`` (dead-code-eliminated when unused).  Matches
    ``ops/loss_ops.py:softmax_with_cross_entropy`` semantics: hard integer
    labels [R, 1] with ``ignore_index``, or soft [R, V] distributions."""
    loss, lse, _ = _xent_fwd(logits2, label2, soft_label, ignore_index,
                             block_r, block_v, interpret)
    return loss, lse


def _xent_fwd(logits2, label2, soft, ignore, block_r, block_v, interpret):
    m, l, a, b = _xent_partial(logits2, label2, soft, block_r, block_v,
                               interpret)
    loss, lse = _finalize_loss(m, l, a, b, label2, soft, ignore)
    return loss, lse, (logits2, label2, lse, b)


def _xent_fwd_vjp(logits2, label2, soft, ignore, block_r, block_v,
                  interpret):
    loss, lse, res = _xent_fwd(logits2, label2, soft, ignore, block_r,
                               block_v, interpret)
    return (loss, lse), res


def _xent_bwd_vjp(soft, ignore, block_r, block_v, interpret, res, ct):
    dloss, dlse = ct
    logits2, label2, lse, b = res
    g1, g2 = _bwd_coeffs(label2, b, dloss, dlse, soft, ignore)
    dx = _xent_bwd_call(logits2, label2, lse, g1, g2, soft, block_r,
                        block_v, interpret)
    return dx, _label_zeros(label2)


softmax_xent.defvjp(_xent_fwd_vjp, _xent_bwd_vjp)


# -- tp-sharded lowering ----------------------------------------------------


def _xent_specs(mesh, shape, soft):
    """(rows_axis, vocab_axis) per-dim degraded to the mesh: rows shard
    over dp when divisible, vocab over the tp axis when divisible."""
    from ..parallel.spmd import resolve_tp_axis

    r, v = shape
    row_ax = ("dp" if "dp" in mesh.axis_names and mesh.shape["dp"] > 1
              and r % mesh.shape["dp"] == 0 else None)
    tp = resolve_tp_axis(mesh)
    col_ax = (tp if tp in mesh.axis_names and mesh.shape[tp] > 1
              and v % mesh.shape[tp] == 0 else None)
    xspec = P(row_ax, col_ax)
    lspec = P(row_ax, col_ax) if soft else P(row_ax, None)
    cspec = P(row_ax, None)
    return xspec, lspec, cspec, col_ax


def _shift_labels(lab_loc, col_ax, vloc, soft):
    """Hard labels arrive replicated across the vocab axis; shifting them
    by this shard's vocab offset makes the unchanged kernel's local
    column-index match exactly the global label (out-of-shard labels never
    match, contributing zero to the psum)."""
    if soft or col_ax is None:
        return lab_loc
    return lab_loc - lax.axis_index(col_ax) * jnp.int32(vloc)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def softmax_xent_sharded(logits2, label2, mesh, soft_label=False,
                         ignore_index=-100, block_r=DEFAULT_BLOCK_R,
                         block_v=DEFAULT_BLOCK_V, interpret=None):
    """:func:`softmax_xent` lowered through ``shard_map`` on ``mesh``:
    rows stay dp-sharded, the vocab dim stays tp-sharded through the
    kernel, and the per-shard partial (m, l, a[, b]) state is combined
    with one cross-shard max/sum exchange (psum/pmax over tp) before the
    loss finalizes — the logsumexp exchange of Megatron-style vocab
    parallelism.  Outputs replicate over tp (loss is a per-row scalar)."""
    loss, lse, _ = _xent_sharded_fwd(logits2, label2, mesh, soft_label,
                                     ignore_index, block_r, block_v,
                                     interpret)
    return loss, lse


def _xent_sharded_fwd(logits2, label2, mesh, soft, ignore, block_r,
                      block_v, interpret):
    xspec, lspec, cspec, col_ax = _xent_specs(mesh, logits2.shape, soft)

    def body(x_loc, lab_loc):
        lab_k = _shift_labels(lab_loc, col_ax, x_loc.shape[1], soft)
        m, l, a, b = _xent_partial(x_loc, lab_k, soft, block_r, block_v,
                                   interpret)
        if col_ax is not None:
            m_g = lax.pmax(m, col_ax)
            l = lax.psum(l * jnp.exp(m - m_g), col_ax)
            a = lax.psum(a, col_ax)
            if b is not None:
                b = lax.psum(b, col_ax)
            m = m_g
        # the ignore mask needs the ORIGINAL (unshifted) label
        loss, lse = _finalize_loss(m, l, a, b, lab_loc, soft, ignore)
        if b is None:
            b = jnp.ones_like(lse)
        return loss, lse, b

    loss, lse, b = _shard_map(
        body, mesh=mesh, in_specs=(xspec, lspec),
        out_specs=(cspec, cspec, cspec), check_rep=False)(logits2, label2)
    return loss, lse, (logits2, label2, lse, b)


def _xent_sharded_fwd_vjp(logits2, label2, mesh, soft, ignore, block_r,
                          block_v, interpret):
    loss, lse, res = _xent_sharded_fwd(logits2, label2, mesh, soft,
                                       ignore, block_r, block_v, interpret)
    return (loss, lse), res


def _xent_sharded_bwd_vjp(mesh, soft, ignore, block_r, block_v, interpret,
                          res, ct):
    dloss, dlse = ct
    logits2, label2, lse, b = res
    g1, g2 = _bwd_coeffs(label2, b if soft else None, dloss, dlse, soft,
                         ignore)
    xspec, lspec, cspec, col_ax = _xent_specs(mesh, logits2.shape, soft)

    def body(x_loc, lab_loc, lse_loc, g1_loc, g2_loc):
        lab_k = _shift_labels(lab_loc, col_ax, x_loc.shape[1], soft)
        return _xent_bwd_call(x_loc, lab_k, lse_loc, g1_loc, g2_loc, soft,
                              block_r, block_v, interpret)

    dx = _shard_map(
        body, mesh=mesh, in_specs=(xspec, lspec, cspec, cspec, cspec),
        out_specs=xspec, check_rep=False)(logits2, label2, lse, g1, g2)
    return dx, _label_zeros(label2)


softmax_xent_sharded.defvjp(_xent_sharded_fwd_vjp, _xent_sharded_bwd_vjp)


# -- op-level entry (dispatched from ops/loss_ops.py) -----------------------


def xent_fusable(logits, label, soft) -> bool:
    """Static suitability of this softmax_with_cross_entropy instance for
    the streaming kernel (the decision itself is :func:`fused_decision`)."""
    if str(logits.dtype) not in _FUSABLE_DTYPES:
        return False
    if logits.ndim < 2 or logits.shape[-1] < 2:
        return False
    if soft:
        return label.shape == logits.shape
    return True


def softmax_xent_op(logits, label, soft, ignore):
    """The ``softmax_with_cross_entropy`` op lowered through the streaming
    kernels.  The Softmax output slot is reconstructed lazily from the
    logsumexp (``exp(logits - lse)``) so it costs nothing when the program
    never reads it (the common training graph fetches only Loss; XLA DCEs
    the reconstruction)."""
    in_dtype = logits.dtype
    v = logits.shape[-1]
    lead = tuple(logits.shape[:-1])
    x2 = logits.reshape(-1, v)
    if soft:
        lab2 = label.reshape(-1, v)
    else:
        li = label
        if li.ndim == logits.ndim and li.shape[-1] == 1:
            li = li.reshape(li.shape[:-1])
        lab2 = li.astype(jnp.int32).reshape(-1, 1)
    mesh = _active_mesh()
    if mesh is not None:
        loss2, lse2 = softmax_xent_sharded(x2, lab2, mesh, soft, ignore)
    else:
        loss2, lse2 = softmax_xent(x2, lab2, soft, ignore)
    _note("softmax_xent")
    loss = loss2.reshape(lead + (1,))
    lse = lse2.reshape(lead + (1,))
    sm = jnp.exp(logits.astype(jnp.float32) - lse).astype(in_dtype)
    return {"Softmax": sm, "Loss": loss}


# ---------------------------------------------------------------------------
# fused optimizer updates (multi-tensor single-sweep kernels)
# ---------------------------------------------------------------------------


def _momentum_kernel(p_ref, g_ref, v_ref, lr_ref, po_ref, vo_ref, *, mu,
                     nesterov):
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    lr = lr_ref[0, 0]
    v_out = jnp.float32(mu) * v + g
    if nesterov:
        p_out = p - (g + jnp.float32(mu) * v_out) * lr
    else:
        p_out = p - lr * v_out
    po_ref[...] = p_out.astype(po_ref.dtype)
    vo_ref[...] = v_out.astype(vo_ref.dtype)


def _adam_kernel(p_ref, g_ref, m1_ref, m2_ref, lr_ref, po_ref, m1o_ref,
                 m2o_ref, *, b1, b2, eps):
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m1 = m1_ref[...].astype(jnp.float32)
    m2 = m2_ref[...].astype(jnp.float32)
    lr = lr_ref[0, 0]
    m1o = jnp.float32(b1) * m1 + jnp.float32(1.0 - b1) * g
    m2o = jnp.float32(b2) * m2 + jnp.float32(1.0 - b2) * g * g
    po = p - lr * m1o / (jnp.sqrt(m2o) + jnp.float32(eps))
    po_ref[...] = po.astype(po_ref.dtype)
    m1o_ref[...] = m1o.astype(m1o_ref.dtype)
    m2o_ref[...] = m2o.astype(m2o_ref.dtype)


def _sweep_shape(n: int):
    """2-D view for the flat parameter sweep: lane-aligned rows when the
    element count divides the 128-lane, a single row otherwise (interpret
    mode and Mosaic both take it; huge non-aligned params are rejected by
    :func:`opt_fusable` instead of blowing VMEM)."""
    if n % LANE == 0:
        return (n // LANE, LANE)
    return (1, n)


def _opt_sweep(kernel, arrays, lr, n_out, interpret):
    """One multi-tensor grid sweep: every tensor of the update (param,
    grad, moments) flattens to the same 2-D view, one grid step updates
    one row-block of ALL of them, and ``input_output_aliases`` writes the
    param/moment outputs back into their (donated) input buffers."""
    from jax.experimental.pallas import tpu as pltpu

    shape = arrays[0].shape
    n = int(np.prod(shape, dtype=np.int64))
    rows, cols = _sweep_shape(n)
    br = _fit_block(rows, max(1, DEFAULT_BLOCK_N // max(1, cols // LANE)))
    flat = [a.reshape(rows, cols) for a in arrays]
    lr2 = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    blk = pl.BlockSpec((br, cols), lambda i: (i, 0))
    scal = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    # outputs alias the param/moment INPUTS (grad at index 1 is read-only)
    aliases = {0: 0}
    for k in range(1, n_out):
        aliases[k + 1] = k
    outs = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((rows, cols), a.dtype)
                   for a in (arrays[:1] + arrays[2:2 + n_out - 1])],
        grid=(rows // br,),
        in_specs=[blk] * len(flat) + [scal],
        out_specs=[blk] * n_out,
        input_output_aliases=aliases,
        interpret=_interp(interpret),
    )(*flat, lr2)
    return [o.reshape(shape) for o in outs]


def opt_fusable(p, g) -> bool:
    """Static suitability of one optimizer update for the fused sweep."""
    if str(p.dtype) not in _FUSABLE_DTYPES:
        return False
    n = int(np.prod(p.shape, dtype=np.int64))
    if n == 0:
        return False
    # a non-lane-aligned tensor runs as one [1, n] row; cap it so a huge
    # ragged embedding cannot blow the VMEM budget
    if n % LANE and n > (1 << 17):
        return False
    return g is not None and g.shape == p.shape


def _param_spec(mesh, var_name: Optional[str], shape):
    """The spec-table PartitionSpec for this update's param — published by
    the sharded runners via ``spmd.param_spec_scope`` — degraded per dim
    to what the mesh/shape actually supports (absent table or name runs
    replicated inside the same shard_map)."""
    from ..parallel import spmd

    specs = spmd.active_param_specs() or {}
    spec = specs.get(var_name) if var_name else None
    if spec is None:
        return P()
    dims = [ax if (d < len(shape) and ax is not None
                   and ax in mesh.axis_names
                   and shape[d] % mesh.shape[ax] == 0) else None
            for d, ax in enumerate(tuple(spec))]
    return P(*dims)


def opt_specs_aligned(out_names) -> bool:
    """Whether every operand of one optimizer update (param + its
    accumulators, named by the op's ``*Out`` output vars) shares ONE
    PartitionSpec in the published table.  ZeRO-1 shards accumulators over
    dp while the param stays replicated — those updates keep the unfused
    lowering so GSPMD keeps the optimizer math dp-sharded (forcing the
    param's spec would reshard the moments every window and break the
    window-over-window donation aliasing)."""
    mesh = _active_mesh()
    if mesh is None:
        return True
    from ..parallel import spmd

    specs = spmd.active_param_specs()
    if specs is None:
        return True
    ss = [tuple(specs.get(n) or P()) for n in out_names if n]
    return all(s == ss[0] for s in ss) if ss else True


def _run_opt(kernel, arrays, lr, n_out, var_name, interpret):
    mesh = _active_mesh()
    if mesh is None:
        return _opt_sweep(kernel, arrays, lr, n_out, interpret)
    # sharded lowering: the update runs on the LOCAL shard of every
    # operand (elementwise math needs no exchange); a degraded/absent
    # spec runs replicated inside the same shard_map, so GSPMD never sees
    # an opaque pallas_call on sharded operands
    spec = _param_spec(mesh, var_name, arrays[0].shape)

    def body(*local):
        return tuple(_opt_sweep(kernel, list(local[:-1]), local[-1],
                                n_out, interpret))

    outs = _shard_map(body, mesh=mesh,
                      in_specs=tuple([spec] * len(arrays)) + (P(),),
                      out_specs=tuple([spec] * n_out), check_rep=False)(
        *arrays, jnp.asarray(lr, jnp.float32).reshape(()))
    return list(outs)


def fused_momentum(p, g, v, lr, mu, nesterov, var_name=None):
    """Momentum update as ONE kernel sweep over (param, grad, velocity)."""
    kernel = functools.partial(_momentum_kernel, mu=float(mu),
                               nesterov=bool(nesterov))
    po, vo = _run_opt(kernel, [p, g, v], lr, 2, var_name, None)
    _note("momentum")
    return po, vo


def fused_adam(p, g, m1, m2, lr_eff, b1, b2, eps, var_name=None):
    """Adam update as ONE kernel sweep over (param, grad, m, v); the
    bias-corrected ``lr_eff`` and the beta-pow counters are scalar math
    computed outside (they are [1]-shaped; fusing them buys nothing)."""
    kernel = functools.partial(_adam_kernel, b1=float(b1), b2=float(b2),
                               eps=float(eps))
    po, m1o, m2o = _run_opt(kernel, [p, g, m1, m2], lr_eff, 3, var_name,
                            None)
    _note("adam")
    return po, m1o, m2o


# ---------------------------------------------------------------------------
# tp-sharded flash attention (heads stay sharded through the kernel)
# ---------------------------------------------------------------------------


def flash_tp_axis(q, mesh) -> Optional[str]:
    """The axis to shard flash attention's head dim over, or None when the
    mesh has no usable tp axis / heads don't divide."""
    if mesh is None:
        return None
    from ..parallel.spmd import resolve_tp_axis

    tp = resolve_tp_axis(mesh)
    if tp in mesh.axis_names and mesh.shape[tp] > 1 \
            and q.shape[1] % mesh.shape[tp] == 0:
        return tp
    return None


def flash_attention_sharded(q, k, v, bias, scale, causal, mesh,
                            tp_axis: Optional[str] = None):
    """``pallas_flash.flash_attention`` under ``shard_map``: each tp shard
    runs the full streaming kernel on its local heads (attention is
    head-independent — no exchange), batch stays dp-sharded.  This is the
    lowering that keeps column-parallel qkv projections sharded INTO the
    kernel instead of GSPMD all-gathering around an opaque pallas_call.
    ``tp_axis=None`` (no usable tp axis / indivisible heads) still wraps,
    with heads replicated — a bare pallas_call has no partitioning rule
    under a mesh."""
    from .pallas_flash import flash_attention

    b_axis = ("dp" if "dp" in mesh.axis_names and mesh.shape["dp"] > 1
              and q.shape[0] % mesh.shape["dp"] == 0 else None)
    if bias is not None and bias.ndim and bias.shape[0] > 1 \
            and b_axis is not None \
            and bias.shape[0] % mesh.shape[b_axis] != 0:
        b_axis = None  # a per-row bias must shard WITH the batch or not at all
    spec = P(b_axis, tp_axis, None, None)

    def body(ql, kl, vl, *rest):
        bl = rest[0] if rest else None
        return flash_attention(ql, kl, vl, bl, scale, causal)

    args = [q, k, v]
    in_specs = [spec, spec, spec]
    if bias is not None:
        args.append(bias)
        ba = b_axis if (bias.ndim and bias.shape[0] > 1) else None
        in_specs.append(P(ba, *([None] * (bias.ndim - 1))))
    out = _shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                     out_specs=spec, check_rep=False)(*args)
    _note("flash_attention")
    return out
