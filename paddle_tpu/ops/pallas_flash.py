"""Flash attention as Pallas TPU kernels — forward AND backward.

The hot op of the transformer/BERT path gets hand-scheduled kernels
(SURVEY.md §7.3: "Pallas only where XLA underperforms"): one grid step
owns a [BLOCK, D] tile resident in VMEM and streams the opposing tiles
through the MXU with the online-softmax recurrence, so the [T, T] score
matrix never hits HBM — forward, dQ, and dK/dV alike.  Accumulation is
fp32 in VMEM scratch regardless of the input dtype (the same
master-accumulator discipline as fluid.amp).

Backward (Dao FlashAttention-2 formulation): the forward emits the
per-row logsumexp L, so each backward tile recomputes P = exp(S - L)
locally; with delta = rowsum(dO ∘ O) precomputed (one fused elementwise
reduce in XLA):

    dV = Pᵀ dO;   dS = P ∘ (dO Vᵀ - delta);   dQ = scale·dS K;
    dK = scale·dSᵀ Q

split into two kernels matching the reduction directions: a dQ kernel
(q-tile resident, streams K/V) and a dK/dV kernel (k-tile resident,
streams Q/dO).  Both skip dead causal blocks.

``bias`` is the additive KEY-padding bias ([B, 1, 1, Tk], the shape the
models build) — broadcast into the logits inside the kernels; it gets no
gradient (it is derived from input padding, never trained).

Falls back to interpret mode off-TPU, so the same kernel code is testable
on the CPU mesh.  ref: the reference's fused scaled_dot_product kernels
live in paddle/fluid/operators/math/ + cuDNN; this is the TPU-native
counterpart.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
NEG_INF = -1e30


def _causal_mask(logits, q_off, k_off):
    qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
    kpos = k_off + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    return jnp.where(qpos >= kpos, logits, jnp.float32(NEG_INF))


def _flash_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, n_k,
                  has_bias):
    """Forward grid step (bh, q-block, k-block): one [bq, d] query tile
    against one [bk, d] K/V tile, online-softmax state (m, l, acc) in fp32
    VMEM scratch carried across the (sequential, minormost) k dimension —
    VMEM holds one K/V TILE at a time, t_kv can be arbitrarily long."""
    if has_bias:
        bias_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
        bias_ref = None
    ki = pl.program_id(2)
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]
    # all index math in i32: under the package-wide x64 mode python ints
    # promote to i64, which Mosaic's index ops reject
    q_off = qi * jnp.int32(bq)
    k_off = ki * jnp.int32(bk)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # under causal masking, blocks strictly above the diagonal contribute
    # nothing — skip both MXU contractions for them (~2x FLOPs at long T)
    live = (k_off <= q_off + jnp.int32(bq - 1)) if causal else True

    @pl.when(live)
    def _attend():
        q = q_ref[0].astype(jnp.float32) * jnp.float32(scale)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # [bq, bk]
        if bias_ref is not None:
            logits = logits + bias_ref[0].astype(jnp.float32)  # [1, bk]
        if causal:
            logits = _causal_mask(logits, q_off, k_off)
        m = m_ref[:]
        l = l_ref[:]
        m_new = jnp.maximum(m, jnp.max(logits, axis=1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _flush():
        l = jnp.maximum(l_ref[:], jnp.float32(1e-30))
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               scale, causal, n_k, has_bias):
    """dQ grid step (bh, q-block, k-block): q/dO/lse/delta tiles resident,
    K/V tiles stream; dq accumulates in fp32 scratch over ki."""
    if has_bias:
        bias_ref, dq_ref, dq_acc = rest
    else:
        dq_ref, dq_acc = rest
        bias_ref = None
    ki = pl.program_id(2)
    qi = pl.program_id(1)
    bq, bk = q_ref.shape[1], k_ref.shape[1]
    q_off = qi * jnp.int32(bq)
    k_off = ki * jnp.int32(bk)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = (k_off <= q_off + jnp.int32(bq - 1)) if causal else True

    @pl.when(live)
    def _accum():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, q_off, k_off)
        p = jnp.exp(s - lse_ref[0])                     # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bq, bk]
        ds = p * (dp - delta_ref[0])
        dq_acc[:] += jnp.float32(scale) * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _flush():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                scale, causal, n_q, has_bias):
    """dK/dV grid step (bh, k-block, q-block): K/V tiles resident, Q/dO/
    lse/delta tiles stream; dk/dv accumulate in fp32 scratch over qi."""
    if has_bias:
        bias_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
        bias_ref = None
    qi = pl.program_id(2)
    kjj = pl.program_id(1)
    bq, bk = q_ref.shape[1], k_ref.shape[1]
    q_off = qi * jnp.int32(bq)
    k_off = kjj * jnp.int32(bk)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = (q_off + jnp.int32(bq - 1) >= k_off) if causal else True

    @pl.when(live)
    def _accum():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        # [bk, bq] orientation: k rows resident
        st = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        if bias_ref is not None:
            # key-bias is constant along q: one column vector [bk, 1]
            st = st + bias_ref[0].reshape(bk, 1).astype(jnp.float32)
        if causal:
            kpos = k_off + jax.lax.broadcasted_iota(jnp.int32, st.shape, 0)
            qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, st.shape, 1)
            st = jnp.where(qpos >= kpos, st, jnp.float32(NEG_INF))
        pt = jnp.exp(st - lse_ref[0].reshape(1, bq))    # [bk, bq]
        dv_acc[:] += jax.lax.dot_general(
            pt, do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dpt = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bk, bq]
        dst = pt * (dpt - delta_ref[0].reshape(1, bq))
        dk_acc[:] += jnp.float32(scale) * jax.lax.dot_general(
            dst, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _flush():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _blocks(t, t_kv, block_q, block_k):
    bq = min(block_q, t)
    bk = min(block_k, t_kv)
    while t % bq:
        bq //= 2
    while t_kv % bk:
        bk //= 2
    return bq, bk


def bias_supported(bias, b, t_kv) -> bool:
    """Whether the kernels can take this additive bias: key-padding shaped
    [B|1, 1, 1, Tk] or [B|1, Tk].  The SAME predicate gates the op-level
    routing (ops/attention_ops.py), so an unsupported bias falls back to
    the XLA path instead of crashing here."""
    if bias is None:
        return True
    if bias.ndim == 4:
        return (bias.shape[1] == 1 and bias.shape[2] == 1
                and bias.shape[0] in (1, b) and bias.shape[3] == t_kv)
    return bias.ndim == 2 and bias.shape[0] in (1, b) \
        and bias.shape[1] == t_kv


def _bias_2d(bias, b, h, t_kv):
    """Normalize a supported bias (see bias_supported) to [B, Tk]."""
    if bias is None:
        return None
    if not bias_supported(bias, b, t_kv):
        raise ValueError(
            f"flash_attention bias must be key-padding shaped "
            f"[B|1, 1, 1, Tk] or [B|1, Tk]; got {bias.shape}")
    if bias.ndim == 4:
        bias = bias.reshape(bias.shape[0], bias.shape[3])
    if bias.shape[0] == 1 and b > 1:
        bias = jnp.broadcast_to(bias, (b, t_kv))
    return bias


def _flash_forward(q, k, v, bias, scale, causal, block_q, block_k,
                   interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, h, t, d = q.shape
    t_kv = k.shape[2]
    bq, bk = _blocks(t, t_kv, block_q, block_k)
    n_k = t_kv // bk
    # grid iterates k-blocks innermost: TPU grids run sequentially on a
    # core, so the scratch online-softmax state carries across ki steps
    grid = (b * h, t // bq, n_k)
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * h, t_kv, d)
    vr = v.reshape(b * h, t_kv, d)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j, s: (i, j, 0)),
        pl.BlockSpec((1, bk, d), lambda i, j, s: (i, s, 0)),
        pl.BlockSpec((1, bk, d), lambda i, j, s: (i, s, 0)),
    ]
    args = [qr, kr, vr]
    if bias is not None:
        in_specs.append(pl.BlockSpec(
            (1, 1, bk), lambda i, j, s, h=h: (i // h, 0, s)))
        args.append(bias.reshape(b, 1, t_kv))
    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          n_k=n_k, has_bias=bias is not None),
        out_shape=[jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, t, 1), jnp.float32)],
        grid=grid,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, bq, d), lambda i, j, s: (i, j, 0)),
                   pl.BlockSpec((1, bq, 1), lambda i, j, s: (i, j, 0))],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running denominator
            pltpu.VMEM((bq, d), jnp.float32),   # fp32 accumulator
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, t, d), lse.reshape(b, h, t, 1)


def _flash_backward(q, k, v, bias, out, lse, do, scale, causal, block_q,
                    block_k, interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, h, t, d = q.shape
    t_kv = k.shape[2]
    bq, bk = _blocks(t, t_kv, block_q, block_k)
    n_q, n_k = t // bq, t_kv // bk
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)            # [b, h, t, 1]
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * h, t_kv, d)
    vr = v.reshape(b * h, t_kv, d)
    dor = do.reshape(b * h, t, d)
    lser = lse.reshape(b * h, t, 1)
    dr = delta.reshape(b * h, t, 1)
    has_bias = bias is not None
    bias_args = [bias.reshape(b, 1, t_kv)] if has_bias else []

    # dQ: q-tile resident, k innermost
    q_res = [pl.BlockSpec((1, bq, d), lambda i, j, s: (i, j, 0)),
             pl.BlockSpec((1, bk, d), lambda i, j, s: (i, s, 0)),
             pl.BlockSpec((1, bk, d), lambda i, j, s: (i, s, 0)),
             pl.BlockSpec((1, bq, d), lambda i, j, s: (i, j, 0)),
             pl.BlockSpec((1, bq, 1), lambda i, j, s: (i, j, 0)),
             pl.BlockSpec((1, bq, 1), lambda i, j, s: (i, j, 0))]
    if has_bias:
        q_res.append(pl.BlockSpec(
            (1, 1, bk), lambda i, j, s, h=h: (i // h, 0, s)))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          n_k=n_k, has_bias=has_bias),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=(b * h, n_q, n_k),
        in_specs=q_res,
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, s: (i, j, 0)),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, dr, *bias_args)

    # dK/dV: k-tile resident, q innermost
    kv_res = [pl.BlockSpec((1, bq, d), lambda i, j, s: (i, s, 0)),
              pl.BlockSpec((1, bk, d), lambda i, j, s: (i, j, 0)),
              pl.BlockSpec((1, bk, d), lambda i, j, s: (i, j, 0)),
              pl.BlockSpec((1, bq, d), lambda i, j, s: (i, s, 0)),
              pl.BlockSpec((1, bq, 1), lambda i, j, s: (i, s, 0)),
              pl.BlockSpec((1, bq, 1), lambda i, j, s: (i, s, 0))]
    if has_bias:
        kv_res.append(pl.BlockSpec(
            (1, 1, bk), lambda i, j, s, h=h: (i // h, 0, j)))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          n_q=n_q, has_bias=has_bias),
        out_shape=[jax.ShapeDtypeStruct((b * h, t_kv, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, t_kv, d), v.dtype)],
        grid=(b * h, n_k, n_q),
        in_specs=kv_res,
        out_specs=[pl.BlockSpec((1, bk, d), lambda i, j, s: (i, j, 0)),
                   pl.BlockSpec((1, bk, d), lambda i, j, s: (i, j, 0))],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, dr, *bias_args)
    return (dq.reshape(b, h, t, d), dk.reshape(b, h, t_kv, d),
            dv.reshape(b, h, t_kv, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, bias=None, scale=None, causal=False,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """softmax(scale · q kᵀ + bias [+ causal mask]) v, streamed (never
    materializes the [T, T] scores).  q/k/v: [B, H, T, D]; bias: additive
    key-padding bias [B, 1, 1, Tk] (or [B, Tk]) or None, non-trainable."""
    out, _ = _flash_fwd_impl(q, k, v, bias, scale, causal, block_q,
                             block_k, interpret)
    return out


def _resolve(q, scale, interpret):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


def _flash_fwd_impl(q, k, v, bias, scale, causal, block_q, block_k,
                    interpret):
    scale, interpret = _resolve(q, scale, interpret)
    bias = _bias_2d(bias, q.shape[0], q.shape[1], k.shape[2])
    return _flash_forward(q, k, v, bias, scale, causal, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, bias, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, bias, scale, causal, block_q,
                               block_k, interpret)
    return out, (q, k, v, bias, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, bias, out, lse = res
    scale, interpret = _resolve(q, scale, interpret)
    bias2 = _bias_2d(bias, q.shape[0], q.shape[1], k.shape[2])
    dq, dk, dv = _flash_backward(q, k, v, bias2, out, lse, do, scale,
                                 causal, block_q, block_k, interpret)
    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, dbias


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_bwd_reference(q, k, v, do, bias=None, scale=None, causal=False):
    """jnp recompute backward (the pre-r5 path) — kept as the OpTest
    reference the Pallas dQ/dK/dV kernels are verified against."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    qf, kf, vf = (a.astype(jnp.float32) for a in (q, k, v))
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    of = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(dof * of, axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)
