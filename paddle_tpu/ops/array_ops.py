"""LoDTensorArray / LoDRankTable ops — the DynamicRNN & beam-search substrate.

ref: paddle/fluid/operators/{tensor_array_read_write_op.cc,
lod_rank_table_op.cc, lod_tensor_to_array_op.cc, array_to_lod_tensor_op.cc,
shrink_rnn_memory_op.cc, max_sequence_len_op.cc,
reorder_lod_tensor_by_rank_op.cc, split_lod_tensor_op.cc,
merge_lod_tensor_op.cc, beam_search_op.cc, beam_search_decode_op.cc}.

TPU design: a tensor array is a trace-time Python list of fixed-shape
device arrays (indices are concrete — counters root in fill_constant or
static lod, see control_flow_exec).  The rank table is a host object
computed from static lod.  Ops that are inherently data-dependent
(split/merge by mask, beam search) require eager execution and declare
``eager=True``; the executor drops jit for programs containing them.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .registry import register_op, register_grad

EAGER_OPS = {
    "split_lod_tensor", "merge_lod_tensor", "beam_search",
    "beam_search_decode", "beam_search_pack", "is_empty",
    # data-dependent output count (LoD out) — host postprocessing, like the
    # reference's CPU-pinned kernel (multiclass_nms_op.cc)
    "multiclass_nms",
    # removes rows by VALUE: output row count depends on the data
    "sequence_erase",
    # selects inner subsequences by runtime index values
    "sub_nested_seq",
    # filesystem side effects need concrete values (save_op.cc etc.)
    "save", "load", "save_combine", "load_combine", "delete_var",
    # Faster-RCNN sampling/proposal ops: data-dependent counts + host RNG
    # (the reference pins them to CPUPlace too)
    "generate_proposals", "rpn_target_assign", "generate_proposal_labels",
    "detection_map",
}


import jax as _jax


@_jax.tree_util.register_pytree_node_class
class TensorArray:
    """LoDTensorArray value (ref: var_type LOD_TENSOR_ARRAY).

    Registered as a jax pytree (vals are children, lods are aux) so arrays
    can cross jit-segment boundaries in the eager-island executor."""

    def tree_flatten(self):
        aux = tuple(tuple(map(tuple, l)) if l is not None else None
                    for l in self.lods)
        return tuple(self.vals), aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(list(children), [tuple(l) if l is not None else None
                                    for l in aux])

    def __init__(self, vals: Optional[List] = None,
                 lods: Optional[List] = None):
        self.vals: List = list(vals or [])
        self.lods: List = list(lods or [])
        while len(self.lods) < len(self.vals):
            self.lods.append(None)

    def write(self, i: int, val, lod=None):
        while len(self.vals) <= i:
            self.vals.append(None)
            self.lods.append(None)
        self.vals[i] = val
        self.lods[i] = lod

    def read(self, i: int):
        return self.vals[i], self.lods[i]

    def __len__(self):
        return len(self.vals)

    def clone(self) -> "TensorArray":
        return TensorArray(list(self.vals), list(self.lods))

    def __add__(self, other):
        """Element-wise sum (None-aware) — grad accumulation of array grads
        by the backward's generic `sum` op."""
        if not isinstance(other, TensorArray):
            return NotImplemented
        n = max(len(self.vals), len(other.vals))
        vals = []
        for i in range(n):
            a = self.vals[i] if i < len(self.vals) else None
            b = other.vals[i] if i < len(other.vals) else None
            vals.append(b if a is None else (a if b is None else a + b))
        lods = self.lods if len(self.lods) >= len(other.lods) else other.lods
        return TensorArray(vals, list(lods))

    __radd__ = __add__


class RankTable:
    """LoDRankTable: (seq_index, length) sorted by length desc, stable
    (ref: lod_rank_table.h)."""

    def __init__(self, offsets):
        lens = [int(offsets[i + 1]) - int(offsets[i])
                for i in range(len(offsets) - 1)]
        order = sorted(range(len(lens)), key=lambda i: (-lens[i], i))
        self.items = [(i, lens[i]) for i in order]
        self.offsets = tuple(int(o) for o in offsets)

    @property
    def indices(self):
        return [i for i, _ in self.items]

    @property
    def lengths(self):
        return [l for _, l in self.items]

    def num_active(self, t: int) -> int:
        """How many (length-sorted) sequences still run at step t."""
        return sum(1 for _, l in self.items if l > t)


def _concrete_idx(v, what) -> int:
    if isinstance(v, jax.core.Tracer):
        raise NotImplementedError(
            f"{what}: index must be concrete at trace time (counter chains "
            f"rooted in fill_constant are; traced data is not)")
    return int(np.asarray(v).reshape(-1)[0])


# ---------------------------------------------------------------------------
# read/write/length
# ---------------------------------------------------------------------------


@register_op("write_to_array", no_grad_inputs=("I",))
def write_to_array(ctx):
    i = _concrete_idx(ctx.input("I"), "write_to_array")
    arr = ctx.cur_out("Out")
    arr = arr.clone() if isinstance(arr, TensorArray) else TensorArray()
    arr.write(i, ctx.input("X"), ctx.in_lod("X"))
    return {"Out": arr}


@register_grad("write_to_array")
def write_to_array_grad(ctx):
    """d X = (d Out)[i]."""
    i = _concrete_idx(ctx.input("I"), "write_to_array_grad")
    garr = ctx.input("Out@GRAD")
    x = ctx.input("X")
    if isinstance(garr, TensorArray) and i < len(garr.vals) \
            and garr.vals[i] is not None:
        return {"X@GRAD": garr.vals[i]}
    return {"X@GRAD": jnp.zeros_like(x)}


@register_op("read_from_array", no_grad_inputs=("I",))
def read_from_array(ctx):
    i = _concrete_idx(ctx.input("I"), "read_from_array")
    arr = ctx.input("X")
    if not isinstance(arr, TensorArray):
        raise TypeError("read_from_array: X is not a tensor array")
    val, lod = arr.read(i)
    return {"Out": val, "Out@LOD": [lod] if lod else [None]}


@register_grad("read_from_array")
def read_from_array_grad(ctx):
    """d X = array with (d Out) at slot i, zeros elsewhere."""
    i = _concrete_idx(ctx.input("I"), "read_from_array_grad")
    arr = ctx.input("X")
    g = ctx.input("Out@GRAD")
    garr = TensorArray(
        [jnp.zeros_like(v) if v is not None else None for v in arr.vals],
        list(arr.lods))
    if g is not None:
        garr.write(i, g, arr.lods[i] if i < len(arr.lods) else None)
    return {"X@GRAD": garr}


@register_op("lod_array_length")
def lod_array_length(ctx):
    arr = ctx.input("X")
    # host value: array lengths drive loop conditions (concrete under jit)
    return {"Out": np.asarray([len(arr)], np.int64)}


@register_op("is_empty")
def is_empty(ctx):
    x = ctx.input("X")
    n = len(x) if isinstance(x, TensorArray) else int(np.prod(x.shape))
    return {"Out": jnp.asarray([n == 0])}


# ---------------------------------------------------------------------------
# rank table / max len / shrink / reorder
# ---------------------------------------------------------------------------


@register_op("lod_rank_table", no_grad_inputs=("X",))
def lod_rank_table(ctx):
    level = int(ctx.attr("level", 0))
    lod = ctx.in_lod("X")
    x = ctx.input("X")
    if lod:
        offsets = lod[level]
    else:
        # lod-free input: every row is a length-1 sequence (ref behavior)
        offsets = tuple(range(x.shape[0] + 1))
    return {"Out": RankTable(offsets)}


@register_op("max_sequence_len", no_grad_inputs=("RankTable",))
def max_sequence_len(ctx):
    table = ctx.input("RankTable")
    mx = table.lengths[0] if table.items else 0
    # host value: drives the DynamicRNN loop condition (concrete under jit)
    return {"Out": np.asarray([mx], np.int64)}


@register_op("lod_tensor_to_array", no_grad_inputs=("RankTable",))
def lod_tensor_to_array(ctx):
    """Split packed X into per-timestep batches, sequences ordered by the
    rank table (longest first) so the batch shrinks monotonically."""
    x = ctx.input("X")
    table: RankTable = ctx.input("RankTable")
    off = np.asarray(table.offsets)
    arr = TensorArray()
    t_max = table.lengths[0] if table.items else 0
    for t in range(t_max):
        rows = [int(off[i]) + t for i, l in table.items if l > t]
        arr.write(t, x[jnp.asarray(np.asarray(rows, np.int64))])
    return {"Out": arr}


@register_grad("lod_tensor_to_array")
def lod_tensor_to_array_grad(ctx):
    x = ctx.input("X")
    table: RankTable = ctx.input("RankTable")
    garr = ctx.input("Out@GRAD")
    off = np.asarray(table.offsets)
    gx = jnp.zeros_like(x)
    if isinstance(garr, TensorArray):
        for t, gv in enumerate(garr.vals):
            if gv is None:
                continue
            rows = [int(off[i]) + t for i, l in table.items if l > t]
            gx = gx.at[jnp.asarray(np.asarray(rows, np.int64))].add(
                jnp.asarray(gv, gx.dtype))
    return {"X@GRAD": gx}


@register_op("array_to_lod_tensor", no_grad_inputs=("RankTable",))
def array_to_lod_tensor(ctx):
    """Inverse of lod_tensor_to_array: gather timestep batches back into
    packed rows with the table's original lod."""
    arr: TensorArray = ctx.input("X")
    table: RankTable = ctx.input("RankTable")
    off = np.asarray(table.offsets)
    total = int(off[-1])
    pieces, rows = [], []
    for t, v in enumerate(arr.vals):
        if v is None:
            continue
        active = [i for i, l in table.items if l > t]
        pieces.append(v)
        rows.extend(int(off[i]) + t for i in active)
    cat = jnp.concatenate(pieces, axis=0)
    inv = np.empty((total,), np.int64)
    inv[np.asarray(rows, np.int64)] = np.arange(len(rows))
    out = cat[jnp.asarray(inv)]
    lod = (tuple(int(o) for o in off),)
    return {"Out": out, "Out@LOD": [lod]}


@register_grad("array_to_lod_tensor")
def array_to_lod_tensor_grad(ctx):
    arr: TensorArray = ctx.input("X")
    table: RankTable = ctx.input("RankTable")
    g = ctx.input("Out@GRAD")
    off = np.asarray(table.offsets)
    garr = TensorArray()
    for t, v in enumerate(arr.vals):
        if v is None:
            continue
        rows = [int(off[i]) + t for i, l in table.items if l > t]
        garr.write(t, g[jnp.asarray(np.asarray(rows, np.int64))])
    return {"X@GRAD": garr}


@register_op("shrink_rnn_memory", no_grad_inputs=("I", "RankTable"))
def shrink_rnn_memory(ctx):
    """Slice memory rows down to the batch still active at step I
    (ref: shrink_rnn_memory_op.cc)."""
    x = ctx.input("X")
    i = _concrete_idx(ctx.input("I"), "shrink_rnn_memory")
    table: RankTable = ctx.input("RankTable")
    n = table.num_active(i)
    return {"Out": x[:n]}


@register_grad("shrink_rnn_memory")
def shrink_rnn_memory_grad(ctx):
    x = ctx.input("X")
    g = ctx.input("Out@GRAD")
    n = g.shape[0]
    gx = jnp.zeros_like(x)
    return {"X@GRAD": gx.at[:n].set(jnp.asarray(g, x.dtype))}


@register_op("reorder_lod_tensor_by_rank", no_grad_inputs=("RankTable",))
def reorder_lod_tensor_by_rank(ctx):
    """Reorder X's sequences into the rank table's order."""
    x = ctx.input("X")
    table: RankTable = ctx.input("RankTable")
    lod = ctx.in_lod("X")
    if lod:
        off = np.asarray(lod[-1])
        rows, out_len = [], []
        for i in table.indices:
            rows.extend(range(int(off[i]), int(off[i + 1])))
            out_len.append(int(off[i + 1]) - int(off[i]))
        out = x[jnp.asarray(np.asarray(rows, np.int64))]
        out_lod = (tuple(np.concatenate([[0], np.cumsum(out_len)]).tolist()),)
        return {"Out": out, "Out@LOD": [out_lod]}
    idx = np.asarray(table.indices, np.int64)
    return {"Out": x[jnp.asarray(idx)]}


# ---------------------------------------------------------------------------
# static (lod-free) array <-> tensor: the StaticRNN substrate.  The dynamic
# analogues are lod_tensor_to_array/array_to_lod_tensor; these unstack along
# a leading time axis instead (ref: StaticRNN's step scopes hold the same
# per-step slices).
# ---------------------------------------------------------------------------


@register_op("tensor_array_unstack")
def tensor_array_unstack(ctx):
    x = ctx.input("X")
    return {"Out": TensorArray([x[t] for t in range(x.shape[0])])}


@register_grad("tensor_array_unstack")
def tensor_array_unstack_grad(ctx):
    x = ctx.input("X")
    garr = ctx.input("Out@GRAD")
    vals = []
    for t in range(x.shape[0]):
        g = garr.vals[t] if isinstance(garr, TensorArray) and \
            t < len(garr.vals) and garr.vals[t] is not None else None
        vals.append(jnp.zeros_like(x[t]) if g is None
                    else jnp.asarray(g, x.dtype))
    return {"X@GRAD": jnp.stack(vals)}


@register_op("tensor_array_stack")
def tensor_array_stack(ctx):
    arr: TensorArray = ctx.input("X")
    vals = [v for v in arr.vals if v is not None]
    return {"Out": jnp.stack(vals)}


@register_grad("tensor_array_stack")
def tensor_array_stack_grad(ctx):
    arr: TensorArray = ctx.input("X")
    g = ctx.input("Out@GRAD")
    garr = TensorArray()
    j = 0
    for t, v in enumerate(arr.vals):
        if v is not None:
            garr.write(t, g[j])
            j += 1
    return {"X@GRAD": garr}


# ---------------------------------------------------------------------------
# IfElse substrate: split/merge by mask (eager — data-dependent shapes)
# ---------------------------------------------------------------------------


@register_op("split_lod_tensor", no_grad_inputs=("Mask",))
def split_lod_tensor(ctx):
    x = ctx.input("X")
    mask = np.asarray(ctx.input("Mask")).reshape(-1).astype(bool)
    lod = ctx.in_lod("X")
    # Row-wise split equals the reference's sequence-level split whenever
    # every sequence is a single row; only true multi-row sequences (or a
    # nonzero level attr) need the unimplemented sequence-level path
    # (split_lod_tensor_op.cc).
    if int(ctx.attr("level", 0)) != 0:
        raise NotImplementedError(
            "split_lod_tensor: only level=0 splits are supported.")
    if lod and np.any(np.diff(np.asarray(lod[-1])) != 1):
        raise NotImplementedError(
            "split_lod_tensor: sequence-level split of multi-row LoD "
            "sequences is not supported; only row-wise split where each "
            "sequence is one row. Ref: split_lod_tensor_op.cc.")
    if mask.shape[0] != np.asarray(x).shape[0]:
        raise ValueError(
            f"split_lod_tensor: mask length {mask.shape[0]} != input rows "
            f"{np.asarray(x).shape[0]}")
    t_idx = np.nonzero(mask)[0]
    f_idx = np.nonzero(~mask)[0]
    return {"OutTrue": x[jnp.asarray(t_idx)],
            "OutFalse": x[jnp.asarray(f_idx)]}


@register_grad("split_lod_tensor")
def split_lod_tensor_grad(ctx):
    x = ctx.input("X")
    mask = np.asarray(ctx.input("Mask")).reshape(-1).astype(bool)
    gx = jnp.zeros_like(x)
    gt, gf = ctx.input("OutTrue@GRAD"), ctx.input("OutFalse@GRAD")
    if gt is not None:
        gx = gx.at[jnp.asarray(np.nonzero(mask)[0])].add(
            jnp.asarray(gt, x.dtype))
    if gf is not None:
        gx = gx.at[jnp.asarray(np.nonzero(~mask)[0])].add(
            jnp.asarray(gf, x.dtype))
    return {"X@GRAD": gx}


@register_op("merge_lod_tensor", no_grad_inputs=("Mask", "X"))
def merge_lod_tensor(ctx):
    mask = np.asarray(ctx.input("Mask")).reshape(-1).astype(bool)
    in_true, in_false = ctx.input("InTrue"), ctx.input("InFalse")
    if int(ctx.attr("level", 0)) != 0:
        raise NotImplementedError(
            "merge_lod_tensor: only level=0 row-wise merge is supported.")
    n_rows = (np.asarray(in_true).shape[0] + np.asarray(in_false).shape[0])
    if mask.shape[0] != n_rows:
        raise ValueError(
            f"merge_lod_tensor: mask length {mask.shape[0]} != total rows "
            f"{n_rows}")
    shape = (len(mask),) + tuple(np.asarray(in_true).shape[1:])
    out = jnp.zeros(shape, in_true.dtype)
    out = out.at[jnp.asarray(np.nonzero(mask)[0])].set(in_true)
    out = out.at[jnp.asarray(np.nonzero(~mask)[0])].set(in_false)
    return {"Out": out}


@register_grad("merge_lod_tensor")
def merge_lod_tensor_grad(ctx):
    mask = np.asarray(ctx.input("Mask")).reshape(-1).astype(bool)
    g = ctx.input("Out@GRAD")
    return {"InTrue@GRAD": g[jnp.asarray(np.nonzero(mask)[0])],
            "InFalse@GRAD": g[jnp.asarray(np.nonzero(~mask)[0])]}


# ---------------------------------------------------------------------------
# beam search (eager)
# ---------------------------------------------------------------------------


@register_op("beam_search", no_grad_inputs=("pre_ids", "ids", "scores"))
def beam_search(ctx):
    """One beam-search step (ref: beam_search_op.cc).

    TPU-native deviation: beams are FIXED-WIDTH (no pruning of ended
    beams — they continue carrying end_id with frozen scores), the standard
    static-shape formulation.  Inputs: pre_ids [batch*beam, 1],
    ids/scores [batch*beam, K] candidates.  Outputs selected_ids/
    selected_scores [batch*beam, 1] with a 2-level lod recording, per source
    sentence, which parent beam each selected candidate came from.
    """
    pre_ids = np.asarray(ctx.input("pre_ids"))
    pre_scores = ctx.input("pre_scores")
    pre_scores = np.asarray(pre_scores) if pre_scores is not None else None
    scores = np.asarray(ctx.input("scores"))
    ids = ctx.input("ids")
    ids = np.asarray(ids) if ids is not None else None
    beam_size = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id"))
    lod = ctx.in_lod("ids") or ctx.in_lod("scores")
    if lod:
        src_off = lod[0]
    else:
        n_src = max(1, pre_ids.shape[0] // beam_size)
        src_off = tuple(np.arange(n_src + 1) * beam_size)

    sel_ids, sel_scores, parents = [], [], []
    out_off = [0]
    for s in range(len(src_off) - 1):
        lo, hi = int(src_off[s]), int(src_off[s + 1])
        cand = []  # (score, id, parent_row)
        for row in range(lo, hi):
            if int(pre_ids[row, 0]) == end_id:
                # ended beam: sole candidate is end_id with the score it
                # ended at (pre_scores), NOT re-accumulated step scores
                frozen = float(pre_scores[row].reshape(-1)[0]) \
                    if pre_scores is not None else float(scores[row].max())
                cand.append((frozen, end_id, row))
                continue
            for k in range(scores.shape[1]):
                cid = int(ids[row, k]) if ids is not None else k
                cand.append((float(scores[row, k]), cid, row))
        cand.sort(key=lambda t: -t[0])
        top = cand[: beam_size]
        # the level-1 parent-offset lod below (and beam_search_decode's
        # searchsorted backtrack) requires output rows GROUPED BY PARENT
        # row; selection order is by score, so regroup (stable: score
        # order is kept within a parent)
        top.sort(key=lambda t: t[2])
        for sc, cid, prow in top:
            sel_ids.append(cid)
            sel_scores.append(sc)
            parents.append(prow)
        out_off.append(out_off[-1] + len(top))

    # level 1 = per-PARENT-ROW offsets over the output rows (the decode
    # backtrack contract: searchsorted(level1, out_row) -> parent row)
    n_prev = pre_ids.shape[0]
    counts = np.zeros((n_prev,), np.int64)
    for p in parents:
        counts[p] += 1
    par_off = np.concatenate([[0], np.cumsum(counts)])
    lod_out = (tuple(int(o) for o in out_off),
               tuple(int(o) for o in par_off))
    res_ids = jnp.asarray(np.asarray(sel_ids, np.int64).reshape(-1, 1))
    res_sc = jnp.asarray(np.asarray(sel_scores, np.float32).reshape(-1, 1))
    out = {"selected_ids": res_ids, "selected_scores": res_sc,
           "selected_ids@LOD": [lod_out], "selected_scores@LOD": [lod_out]}
    if ctx.n_outputs("parent_idx"):
        out["parent_idx"] = jnp.asarray(np.asarray(parents, np.int64))
    return out


@register_op("beam_search_decode", no_grad_inputs=("Ids", "Scores"))
def beam_search_decode(ctx):
    """Backtrack full hypotheses from per-step selected ids
    (ref: beam_search_decode_op.cc).  Ids/Scores are TensorArrays whose
    step lods carry parent offsets (level 1 = selection counts per parent
    row)."""
    ids_arr: TensorArray = ctx.input("Ids")
    scores_arr: TensorArray = ctx.input("Scores")
    end_id = int(ctx.attr("end_id", -1))
    steps = []
    for t in range(len(ids_arr.vals)):
        ids_t = np.asarray(ids_arr.vals[t]).reshape(-1)
        sc_t = np.asarray(scores_arr.vals[t]).reshape(-1)
        lod_t = ids_arr.lods[t]
        steps.append((ids_t, sc_t, lod_t))

    # reconstruct parent chains: at each step, lod level-1 maps selected
    # rows to parent rows of the previous step.  Per the reference output
    # contract (beam_search_decode_op.h), SentenceScores carries the
    # per-step score along each backtracked chain (not the final score
    # repeated), and each source's hypotheses are sorted best-first.
    n_final = len(steps[-1][0]) if steps else 0
    final_lod = steps[-1][2] if steps else None
    if final_lod and len(final_lod) >= 1 and len(final_lod[0]) > 1:
        src_off = [int(o) for o in final_lod[0]]
    else:
        src_off = [0, n_final]

    groups = []  # per source: list of (final_score, chain_ids, chain_scores)
    for s in range(len(src_off) - 1):
        group = []
        for j in range(src_off[s], src_off[s + 1]):
            chain, chain_sc = [], []
            row = j
            for t in range(len(steps) - 1, -1, -1):
                ids_t, sc_t, lod_t = steps[t]
                chain.append(int(ids_t[row]))
                chain_sc.append(float(sc_t[row]))
                if lod_t and len(lod_t) > 1:
                    par_off = lod_t[1]
                    row = int(np.searchsorted(np.asarray(par_off), row,
                                              side="right") - 1)
            chain.reverse()
            chain_sc.reverse()
            if end_id >= 0 and end_id in chain:
                k = chain.index(end_id) + 1
                chain, chain_sc = chain[:k], chain_sc[:k]
            group.append((float(steps[-1][1][j]), chain, chain_sc))
        group.sort(key=lambda t: -t[0])
        groups.append(group)

    flat_ids = [t for g in groups for _, h, _ in g for t in h]
    flat_sc = [s for g in groups for _, _, hs in g for s in hs]
    lens = [len(h) for g in groups for _, h, _ in g]
    off = tuple(np.concatenate([[0], np.cumsum(lens)]).astype(int).tolist())
    src_counts = np.concatenate([[0], np.cumsum([len(g) for g in groups])])
    lod = (tuple(int(o) for o in src_counts), off)
    out_ids = jnp.asarray(np.asarray(flat_ids, np.int64).reshape(-1, 1))
    out_sc = jnp.asarray(np.asarray(flat_sc, np.float32).reshape(-1, 1))
    return {"SentenceIds": out_ids, "SentenceScores": out_sc,
            "SentenceIds@LOD": [lod], "SentenceScores@LOD": [lod]}


@register_op("beam_search_pack",
             no_grad_inputs=("HistIds", "HistParents", "HistScores",
                             "NumSteps"))
def beam_search_pack(ctx):
    """Boundary op of the JITTED beam search (ops/beam_search_jit.py): turn
    the while_loop's dense [n_steps, batch, beam] histories into the same
    2-level-LoD SentenceIds/SentenceScores contract beam_search_decode
    emits (ref: beam_search_decode_op.cc) — backtrack parent chains,
    truncate at the first end_id, best-final-score-first per source.  The
    only data-dependent (hence eager/host) step of the whole decode."""
    from .beam_search_jit import NEG_INF

    h_ids = np.asarray(ctx.input("HistIds"))
    h_par = np.asarray(ctx.input("HistParents"))
    h_sc = np.asarray(ctx.input("HistScores"))
    n = int(np.asarray(ctx.input("NumSteps")).reshape(-1)[0])
    end_id = int(ctx.attr("end_id"))
    _, B, K = h_ids.shape

    groups = []
    for b in range(B):
        group = []
        for k in range(K):
            chain, chain_sc, row = [], [], k
            for t in range(n - 1, -1, -1):
                chain.append(int(h_ids[t, b, row]))
                chain_sc.append(float(h_sc[t, b, row]))
                if t > 0:
                    row = int(h_par[t, b, row])
            chain.reverse()
            chain_sc.reverse()
            final = chain_sc[-1]
            if final <= NEG_INF / 2:
                continue  # dead lane (beam never fanned out this wide)
            if end_id in chain:
                cut = chain.index(end_id) + 1
                chain, chain_sc = chain[:cut], chain_sc[:cut]
            group.append((final, chain, chain_sc))
        group.sort(key=lambda g: -g[0])
        groups.append(group)

    flat_ids = [t for g in groups for _, h, _ in g for t in h]
    flat_sc = [s for g in groups for _, _, hs in g for s in hs]
    lens = [len(h) for g in groups for _, h, _ in g]
    off = tuple(np.concatenate([[0], np.cumsum(lens)]).astype(int).tolist())
    src_counts = np.concatenate([[0], np.cumsum([len(g) for g in groups])])
    lod = (tuple(int(o) for o in src_counts), off)
    out_ids = jnp.asarray(np.asarray(flat_ids, np.int64).reshape(-1, 1))
    out_sc = jnp.asarray(np.asarray(flat_sc, np.float32).reshape(-1, 1))
    return {"SentenceIds": out_ids, "SentenceScores": out_sc,
            "SentenceIds@LOD": [lod], "SentenceScores@LOD": [lod]}
