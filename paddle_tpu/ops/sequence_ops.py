"""Sequence (LoD) ops — the variable-length-sequence capability.

ref: paddle/fluid/operators/sequence_*, SURVEY.md §2.4 "Sequence (LoD) ops".

TPU design: sequences stay *packed* ([sum_len, ...], reference LoD layout,
ref lod_tensor.h:58) but the offsets are static trace-time constants (see
executor.trace_block).  All index math therefore happens in numpy at trace
time and lowers to static gathers/segment ops — XLA sees fixed shapes, and
jax.ops.segment_* provide the reductions the reference hand-writes in
operators/math/sequence_pooling.cc.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .registry import register_grad, register_op


def _lengths(off) -> np.ndarray:
    off = np.asarray(off, np.int64)
    return off[1:] - off[:-1]


def _seg_ids(off) -> np.ndarray:
    return np.repeat(np.arange(len(off) - 1), _lengths(off))


def _concrete(x, what):
    """Static int values of a tensor input, or a clear error under trace."""
    if isinstance(x, jax.core.Tracer):
        raise NotImplementedError(
            f"{what} must be statically known (a constant/feed, not a traced "
            f"intermediate) — dynamic output shapes are unsupported on TPU")
    return np.asarray(x)


# ---------------------------------------------------------------------------
# pooling / softmax
# ---------------------------------------------------------------------------


@register_op("sequence_pool")
def sequence_pool(ctx):
    """ref: sequence_pool_op.cc + math/sequence_pooling.cc."""
    x = ctx.input("X")
    off = ctx.seq_offsets("X")
    lod = ctx.in_lod("X")
    pooltype = str(ctx.attr("pooltype", "AVERAGE")).upper()
    n = len(off) - 1
    seg = jnp.asarray(_seg_ids(off))
    lens = _lengths(off)
    lens_dev = jnp.asarray(lens.astype(np.float32)).reshape(
        (-1,) + (1,) * (x.ndim - 1))
    out_lod = [tuple(tuple(l) for l in lod[:-1])] if len(lod) > 1 else [None]

    maxidx = None
    if pooltype == "SUM":
        out = jax.ops.segment_sum(x, seg, num_segments=n)
    elif pooltype == "AVERAGE":
        out = jax.ops.segment_sum(x, seg, num_segments=n) / \
            jnp.maximum(lens_dev, 1.0)
    elif pooltype == "SQRT":
        out = jax.ops.segment_sum(x, seg, num_segments=n) / \
            jnp.sqrt(jnp.maximum(lens_dev, 1.0))
    elif pooltype == "MAX":
        out = jax.ops.segment_max(x, seg, num_segments=n)
        out = jnp.where(jnp.asarray(lens).reshape(
            (-1,) + (1,) * (x.ndim - 1)) > 0, out, 0.0)
        # arg position within each sequence (ref outputs MaxIndex)
        if ctx.n_outputs("MaxIndex"):
            eq = x == out[seg]
            pos = jnp.arange(x.shape[0]).reshape((-1,) + (1,) * (x.ndim - 1))
            big = x.shape[0] + 1
            cand = jnp.where(eq, pos, big)
            maxidx = jax.ops.segment_min(
                jnp.broadcast_to(cand, x.shape), seg, num_segments=n)
            maxidx = (maxidx - jnp.asarray(
                np.concatenate([[0], np.cumsum(lens)[:-1]])).reshape(
                    (-1,) + (1,) * (x.ndim - 1))).astype(jnp.int32)
            # empty sequences: segment_min returned the `big` sentinel;
            # mask those rows to 0 the same way Out is masked
            maxidx = jnp.where(jnp.asarray(lens).reshape(
                (-1,) + (1,) * (x.ndim - 1)) > 0, maxidx, 0)
    elif pooltype == "LAST":
        idx = np.where(lens > 0, np.asarray(off[1:]) - 1, 0)
        out = x[jnp.asarray(idx)]
        out = jnp.where(jnp.asarray(lens).reshape(
            (-1,) + (1,) * (x.ndim - 1)) > 0, out, 0.0)
    elif pooltype == "FIRST":
        idx = np.where(lens > 0, np.asarray(off[:-1]), 0)
        out = x[jnp.asarray(idx)]
        out = jnp.where(jnp.asarray(lens).reshape(
            (-1,) + (1,) * (x.ndim - 1)) > 0, out, 0.0)
    else:
        raise ValueError(f"unknown pooltype {pooltype}")
    res = {"Out": out, "Out@LOD": out_lod}
    if maxidx is not None:
        res["MaxIndex"] = maxidx
    return res


@register_op("sequence_softmax")
def sequence_softmax(ctx):
    """ref: sequence_softmax_op.cc — softmax within each sequence."""
    x = ctx.input("X")
    off = ctx.seq_offsets("X")
    n = len(off) - 1
    seg = jnp.asarray(_seg_ids(off))
    flat = x.reshape(-1)
    smax = jax.ops.segment_max(flat, seg, num_segments=n)
    e = jnp.exp(flat - smax[seg])
    denom = jax.ops.segment_sum(e, seg, num_segments=n)
    return {"Out": (e / denom[seg]).reshape(x.shape)}


# ---------------------------------------------------------------------------
# expand / concat / reverse / reshape / slice
# ---------------------------------------------------------------------------


@register_op("sequence_expand", no_grad_inputs=("Y",))
def sequence_expand(ctx):
    """ref: sequence_expand_op.cc — repeat each X sequence per Y's lod at
    ref_level."""
    x = ctx.input("X")
    y_lod = ctx.in_lod("Y")
    ref_level = int(ctx.attr("ref_level", -1))
    if not y_lod:
        raise ValueError("sequence_expand: Y carries no LoD")
    ref = y_lod[ref_level]
    x_lod = ctx.in_lod("X")
    if x_lod:
        x_off = np.asarray(x_lod[-1])
    else:
        x_off = np.arange(x.shape[0] + 1)
    n_ref = len(ref) - 1
    if len(x_off) - 1 != n_ref:
        raise ValueError(
            f"sequence_expand: X has {len(x_off) - 1} sequences but Y lod "
            f"level {ref_level} has {n_ref}")
    rep = _lengths(ref)
    idx, out_len = [], []
    for i in range(n_ref):
        rows = np.arange(x_off[i], x_off[i + 1])
        for _ in range(int(rep[i])):
            idx.append(rows)
            out_len.append(len(rows))
    idx = np.concatenate(idx) if idx else np.zeros((0,), np.int64)
    out = x[jnp.asarray(idx)]
    out_lod = (tuple(np.concatenate([[0], np.cumsum(out_len)]).tolist()),)
    return {"Out": out, "Out@LOD": [out_lod]}


@register_op("sequence_expand_as", no_grad_inputs=("Y",))
def sequence_expand_as(ctx):
    """ref: sequence_expand_as_op.cc — row i of X repeated y_len[i] times."""
    x = ctx.input("X")
    y_off = ctx.seq_offsets("Y", level=0)
    rep = _lengths(y_off)
    if x.shape[0] != len(rep):
        raise ValueError("sequence_expand_as: X rows != Y sequence count")
    idx = np.repeat(np.arange(x.shape[0]), rep)
    out_lod = (tuple(int(v) for v in y_off),)
    return {"Out": x[jnp.asarray(idx)], "Out@LOD": [out_lod]}


@register_op("sequence_concat")
def sequence_concat(ctx):
    """ref: sequence_concat_op.cc — concat the j-th sequence of every input."""
    xs = ctx.inputs_list("X")
    offs = [np.asarray(ctx.seq_offsets("X", idx=i)) for i in range(len(xs))]
    n = len(offs[0]) - 1
    if any(len(o) - 1 != n for o in offs):
        raise ValueError("sequence_concat: inputs disagree on sequence count")
    base = np.concatenate([[0], np.cumsum([x.shape[0] for x in xs])])[:-1]
    idx, out_len = [], []
    for j in range(n):
        total = 0
        for i, o in enumerate(offs):
            rows = np.arange(o[j], o[j + 1]) + base[i]
            idx.append(rows)
            total += len(rows)
        out_len.append(total)
    idx = np.concatenate(idx) if idx else np.zeros((0,), np.int64)
    cat = jnp.concatenate(xs, axis=0)
    out_lod = (tuple(np.concatenate([[0], np.cumsum(out_len)]).tolist()),)
    return {"Out": cat[jnp.asarray(idx)], "Out@LOD": [out_lod]}


@register_op("sequence_reverse")
def sequence_reverse(ctx):
    """ref: sequence_reverse_op.h — reverse rows within each sequence."""
    x = ctx.input("X")
    off = np.asarray(ctx.seq_offsets("X"))
    idx = np.concatenate(
        [np.arange(off[i + 1] - 1, off[i] - 1, -1)
         for i in range(len(off) - 1)]) if len(off) > 1 \
        else np.zeros((0,), np.int64)
    return {"Y": x[jnp.asarray(idx)]}


@register_op("sequence_reshape")
def sequence_reshape(ctx):
    """ref: sequence_reshape_op.cc — re-chunk each sequence's flattened data
    to rows of new_dim."""
    x = ctx.input("X")
    off = np.asarray(ctx.seq_offsets("X"))
    new_dim = int(ctx.attr("new_dim"))
    d = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
    lens = _lengths(off) * d
    if np.any(lens % new_dim):
        raise ValueError("sequence_reshape: sequence bytes not divisible by "
                         f"new_dim={new_dim}")
    new_lens = lens // new_dim
    out = x.reshape(-1, new_dim)
    out_lod = (tuple(np.concatenate([[0], np.cumsum(new_lens)]).tolist()),)
    return {"Out": out, "Out@LOD": [out_lod]}


@register_op("sequence_slice", no_grad_inputs=("Offset", "Length"))
def sequence_slice(ctx):
    """ref: sequence_slice_op.cc — per-sequence [offset, offset+length)."""
    x = ctx.input("X")
    off = np.asarray(ctx.seq_offsets("X"))
    o = _concrete(ctx.input("Offset"), "sequence_slice Offset").reshape(-1)
    l = _concrete(ctx.input("Length"), "sequence_slice Length").reshape(-1)
    idx, out_len = [], []
    for i in range(len(off) - 1):
        s = off[i] + int(o[i])
        idx.append(np.arange(s, s + int(l[i])))
        out_len.append(int(l[i]))
    idx = np.concatenate(idx) if idx else np.zeros((0,), np.int64)
    out_lod = (tuple(np.concatenate([[0], np.cumsum(out_len)]).tolist()),)
    return {"Out": x[jnp.asarray(idx)], "Out@LOD": [out_lod]}


# ---------------------------------------------------------------------------
# pad / unpad / mask / enumerate / lod_reset
# ---------------------------------------------------------------------------


@register_op("sequence_pad", no_grad_inputs=("PadValue",))
def sequence_pad(ctx):
    """ref: sequence_pad_op.cc — packed -> [num_seq, pad_len, ...] + Length.

    The input lod is stashed on Out (static metadata) so sequence_unpad can
    restore the exact packing without reading the Length tensor's values.
    """
    x = ctx.input("X")
    pad_value = ctx.input("PadValue")
    off = np.asarray(ctx.seq_offsets("X"))
    lod = ctx.in_lod("X")
    lens = _lengths(off)
    pad_len = int(ctx.attr("padded_length", -1))
    if pad_len in (-1, 0, None):
        pad_len = int(lens.max()) if len(lens) else 0
    if len(lens) and int(lens.max()) > pad_len:
        raise ValueError(f"padded_length {pad_len} < max sequence length "
                         f"{int(lens.max())}")
    n = len(off) - 1
    idx = np.full((n, pad_len), x.shape[0], np.int64)  # point at pad row
    for i in range(n):
        idx[i, : lens[i]] = np.arange(off[i], off[i + 1])
    pv = jnp.asarray(pad_value, x.dtype)
    pad_row = jnp.broadcast_to(pv, x.shape[1:]).reshape((1,) + x.shape[1:])
    xp = jnp.concatenate([x, pad_row], axis=0)
    out = xp[jnp.asarray(idx)]
    return {"Out": out, "Out@LOD": [lod],
            "Length": jnp.asarray(lens.astype(np.int64))}


@register_op("sequence_unpad", no_grad_inputs=("Length",))
def sequence_unpad(ctx):
    """ref: sequence_unpad_op.cc — [num_seq, pad_len, ...] + lengths ->
    packed."""
    x = ctx.input("X")
    lod = ctx.in_lod("X")
    if lod:
        off = np.asarray(lod[-1])
        lens = _lengths(off)
    else:
        lens = _concrete(ctx.input("Length"),
                         "sequence_unpad Length").reshape(-1).astype(np.int64)
        off = np.concatenate([[0], np.cumsum(lens)])
    n, pad_len = x.shape[0], x.shape[1]
    rows = []
    for i in range(n):
        rows.append(np.arange(i * pad_len, i * pad_len + lens[i]))
    idx = np.concatenate(rows) if rows else np.zeros((0,), np.int64)
    flat = x.reshape((n * pad_len,) + x.shape[2:])
    out_lod = (tuple(int(v) for v in off),)
    return {"Out": flat[jnp.asarray(idx)], "Out@LOD": [out_lod]}


@register_op("sequence_mask", no_grad_inputs=("X",))
def sequence_mask(ctx):
    """ref: sequence_mask_op.cc — lengths -> [..., maxlen] 0/1 mask."""
    x = ctx.input("X")
    maxlen = int(ctx.attr("maxlen", -1))
    if maxlen < 0:
        maxlen = int(_concrete(x, "sequence_mask lengths (maxlen=-1)").max())
    dt = ctx.attr("out_dtype", "int64")
    from ..fluid import core as _core

    np_dt = _core.np_dtype(dt) if not isinstance(dt, type) else dt
    mask = (jnp.arange(maxlen) < x[..., None]).astype(jnp.dtype(np_dt))
    return {"Y": mask}


@register_op("sequence_enumerate", no_grad_inputs=("X",))
def sequence_enumerate(ctx):
    """ref: sequence_enumerate_op.cc — sliding win_size windows per
    sequence, pad_value beyond the end."""
    x = ctx.input("X")
    off = np.asarray(ctx.seq_offsets("X"))
    win = int(ctx.attr("win_size"))
    pad = ctx.attr("pad_value", 0)
    total = x.shape[0]
    seg = _seg_ids(off)
    base = np.arange(total)
    cols = []
    flat = x.reshape(total) if x.ndim > 1 else x
    flatp = jnp.concatenate([flat, jnp.full((1,), pad, flat.dtype)])
    ends = np.asarray(off)[seg + 1] if total else np.zeros((0,), np.int64)
    for k in range(win):
        j = base + k
        valid = j < ends
        cols.append(jnp.asarray(np.where(valid, j, total)))
    out = jnp.stack([flatp[c] for c in cols], axis=1)
    return {"Out": out}


@register_op("lod_reset", no_grad_inputs=("Y",))
def lod_reset(ctx):
    """ref: lod_reset_op.cc — replace X's lod from Y (its lod, else its
    values as offsets) or from the target_lod attr."""
    x = ctx.input("X")
    y = ctx.input("Y")
    if y is not None:
        y_lod = ctx.in_lod("Y")
        if y_lod:
            new = tuple(tuple(int(v) for v in lvl) for lvl in y_lod)
        else:
            off = _concrete(y, "lod_reset Y offsets").reshape(-1)
            new = (tuple(int(v) for v in off),)
    else:
        tgt = ctx.attr("target_lod")
        if not tgt:
            raise ValueError("lod_reset: no Y input and empty target_lod")
        new = (tuple(int(v) for v in tgt),)
    if new[-1][-1] != x.shape[0]:
        raise ValueError(f"lod_reset: offsets end {new[-1][-1]} != rows "
                         f"{x.shape[0]}")
    return {"Out": x, "Out@LOD": [new]}


# ---------------------------------------------------------------------------
# sequence_conv / row_conv
# ---------------------------------------------------------------------------


@register_op("sequence_conv", no_grad_inputs=("PaddingData",))
def sequence_conv(ctx):
    """ref: sequence_conv_op.cc + math/context_project.h — gather a
    [contextLength] window of rows around each position (zero outside the
    sequence) and project: Out = im2col(X) @ Filter.  Without a Filter
    input the op returns the bare windowed concat (the context_project
    role alone — v2 context_projection)."""
    x = ctx.input("X")
    filt = ctx.input("Filter") if ctx.has_input("Filter") else None
    off = np.asarray(ctx.seq_offsets("X"))
    ctx_len = int(ctx.attr("contextLength"))
    ctx_start = int(ctx.attr("contextStart", -((ctx_len - 1) // 2)))
    stride = int(ctx.attr("contextStride", 1))
    if stride != 1:
        raise NotImplementedError("sequence_conv: contextStride must be 1 "
                                  "(matches the reference's restriction)")
    total, d = x.shape[0], x.shape[1]
    seg = _seg_ids(off)
    starts = np.asarray(off)[seg] if total else np.zeros((0,), np.int64)
    ends = np.asarray(off)[seg + 1] if total else np.zeros((0,), np.int64)
    xp = jnp.concatenate([x, jnp.zeros((1, d), x.dtype)], axis=0)
    pieces = []
    base = np.arange(total)
    for k in range(ctx_len):
        j = base + ctx_start + k
        valid = (j >= starts) & (j < ends)
        pieces.append(xp[jnp.asarray(np.where(valid, j, total))])
    cols = jnp.concatenate(pieces, axis=1)  # [total, ctx_len*d]
    return {"Out": cols if filt is None else cols @ filt}


@register_op("row_conv")
def row_conv(ctx):
    """ref: row_conv_op.cc — lookahead convolution:
    out[t] = sum_k filter[k] * x[t+k], within each sequence."""
    x = ctx.input("X")
    filt = ctx.input("Filter")  # [future_context_size + 1, D]
    off = np.asarray(ctx.seq_offsets("X"))
    k_len = filt.shape[0]
    total = x.shape[0]
    seg = _seg_ids(off)
    ends = np.asarray(off)[seg + 1] if total else np.zeros((0,), np.int64)
    xp = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)], axis=0)
    base = np.arange(total)
    out = jnp.zeros_like(x)
    for k in range(k_len):
        j = base + k
        valid = j < ends
        out = out + xp[jnp.asarray(np.where(valid, j, total))] * filt[k]
    return {"Out": out}


@register_op("sequence_erase", no_grad_inputs=("X",))
def sequence_erase(ctx):
    """Remove listed token values from packed sequences (ref:
    sequence_erase_op.cc — post-processing for CTC-style decode output).

    The output row count depends on the DATA, so this is an eager host op
    (array_ops.EAGER_OPS): the executor runs it between jitted segments
    with concrete values, the same way the reference pins data-dependent
    kernels to CPUPlace."""
    x = np.asarray(ctx.input("X"))
    tokens = set(int(t) for t in (ctx.attr("tokens") or []))
    off = ctx.seq_offsets("X")
    if x.size == 0:  # all-empty sequences: nothing to erase
        return {"Out": jnp.asarray(x),
                "Out@LOD": (tuple(int(o) for o in off),)}
    flat = x.reshape(len(x), -1)[:, 0]
    keep = np.array([int(v) not in tokens for v in flat], bool)
    new_off = [0]
    for s, e in zip(off, off[1:]):
        new_off.append(new_off[-1] + int(keep[s:e].sum()))
    out = x[keep]
    return {"Out": jnp.asarray(out), "Out@LOD": (tuple(new_off),)}


# ---------------------------------------------------------------------------
# lambda_cost (LambdaRank)
# ---------------------------------------------------------------------------


def _lambda_max_dcg(lab_s, k, m):
    """Ideal (max) DCG@k plus its zero-relevance-safe divisor."""
    discounts = 1.0 / jnp.log(jnp.arange(m, dtype=jnp.float32) + 2.0)
    gains = jnp.power(2.0, lab_s) - 1.0
    ideal = jnp.sort(gains)[::-1]
    max_dcg = jnp.sum((ideal * discounts)[:k])
    # all-zero relevance: the list carries no ranking signal — NDCG 0
    # and zero lambdas (the legacy layer CHECKs; a data guard is kinder)
    return max_dcg, jnp.where(max_dcg > 0, max_dcg, 1.0), discounts, gains


def _lambda_ndcg(out_s, lab_s, ndcg_num):
    """Reference LambdaCost::calcNDCG for ONE sequence."""
    m = out_s.shape[0]
    k = min(int(ndcg_num), m)
    max_dcg, safe_max, discounts, gains = _lambda_max_dcg(lab_s, k, m)
    order_by_out = jnp.argsort(-out_s)
    dcg = jnp.sum((gains[order_by_out] * discounts)[:k])
    return jnp.where(max_dcg > 0, dcg / safe_max, 0.0)


def _lambda_grads(out_s, lab_s, ndcg_num, sort_size):
    """Reference LambdaCost::calcGrad for ONE sequence, vectorized:
    pair lambdas over (i < j) in LABEL-sorted order."""
    m = out_s.shape[0]
    k = min(int(ndcg_num), m)
    ss = m if sort_size in (-1, None) else min(int(sort_size), m)
    max_dcg, safe_max, discounts, _ = _lambda_max_dcg(lab_s, k, m)
    order = jnp.argsort(-lab_s)
    g = jnp.power(2.0, lab_s[order])          # 2^label, sorted desc
    o = out_s[order]
    dii = discounts[:, None] - discounts[None, :]
    dcg_dif = (g[:, None] - g[None, :]) * dii
    if ss < m:
        # pairs whose j falls outside the sorted window use only 1/ln(i+2)
        tail = (g[:, None] - g[None, :]) * discounts[:, None]
        col = jnp.arange(m)
        dcg_dif = jnp.where(col[None, :] >= ss, tail, dcg_dif)
    lam = -jnp.abs(dcg_dif) / (1.0 + jnp.exp(o[:, None] - o[None, :]))
    row = jnp.arange(m)
    mask = (row[:, None] < ss) & (row[None, :] > row[:, None])
    lam = jnp.where(mask & (max_dcg > 0), lam, 0.0) / safe_max
    grad_sorted = lam.sum(axis=1) - lam.sum(axis=0)
    inv = jnp.zeros(m, jnp.int32).at[order].set(jnp.arange(m, dtype=jnp.int32))
    return grad_sorted[inv]


@register_op("lambda_cost", no_grad_inputs=("Label",))
def lambda_cost(ctx):
    """LambdaRank (ref legacy CostLayer.cpp LambdaCost; v2 layers.py
    lambda_cost — absent from the fluid op set, a beyond-fluid op here).
    Forward emits each sequence's NDCG@k replicated per row; the
    gradient is the hand-crafted lambda pair update, attached below."""
    x = ctx.input("X").reshape(-1)
    lab = ctx.input("Label").reshape(-1).astype(jnp.float32)
    off = np.asarray(ctx.seq_offsets("X"))
    k = int(ctx.attr("NDCG_num", 5))
    rows = []
    for s, e in zip(off[:-1], off[1:]):
        s, e = int(s), int(e)
        ndcg = _lambda_ndcg(x[s:e], lab[s:e], k)
        rows.append(jnp.full((e - s,), ndcg))
    return {"Out": jnp.concatenate(rows).reshape(-1, 1)}


@register_grad("lambda_cost")
def lambda_cost_grad(ctx):
    """The reference injects the lambda gradients directly (backward
    ignores the NDCG's own derivative).  Deviation noted: each
    sequence's lambdas are scaled by the SUM of its rows' incoming
    grads — the reference's implicit weight-1-per-row convention, so a
    mean()-reduced cost weights sequences by their length over the
    batch total."""
    x = ctx.input("X").reshape(-1)
    lab = ctx.input("Label").reshape(-1).astype(jnp.float32)
    dout = ctx.input("Out@GRAD").reshape(-1)
    off = np.asarray(ctx.seq_offsets("X"))
    k = int(ctx.attr("NDCG_num", 5))
    ss = int(ctx.attr("max_sort_size", -1))
    grads = []
    for s, e in zip(off[:-1], off[1:]):
        s, e = int(s), int(e)
        lam = _lambda_grads(x[s:e], lab[s:e], k, ss)
        grads.append(lam * jnp.mean(dout[s:e]) * (e - s))
    return {"X@GRAD": jnp.concatenate(grads).reshape(-1, 1)}


@register_op("sub_nested_seq", no_grad_inputs=("SelectedIndices",))
def sub_nested_seq(ctx):
    """Trim a NESTED (2-level) sequence to the selected inner sequences
    (ref: v2 sub_nested_seq_layer / legacy SubNestedSequenceLayer).  For
    each outer sequence, SelectedIndices' row values pick which inner
    subsequences survive, in the given order; the output is a plain
    1-level sequence of the survivors.  Output row count depends on the
    DATA, so this is an eager host op (array_ops.EAGER_OPS)."""
    x = np.asarray(ctx.input("X"))
    gather, new_off = _sub_nested_gather(ctx)
    return {"Out": jnp.asarray(x[gather]),
            "Out@LOD": (tuple(new_off),)}


def _sub_nested_gather(ctx):
    """Shared forward/backward index walk for sub_nested_seq."""
    sel = np.asarray(ctx.input("SelectedIndices")).reshape(-1).astype(np.int64)
    lod = ctx.in_lod("X")
    if not lod or len(lod) < 2:
        raise ValueError("sub_nested_seq: X must be a 2-level nested "
                         "sequence (feed a LoDTensor with lod_level=2)")
    outer, inner = np.asarray(lod[0]), np.asarray(lod[1])
    sel_off = ctx.seq_offsets("SelectedIndices")
    if len(sel_off) - 1 != len(outer) - 1:
        raise ValueError(
            f"sub_nested_seq: SelectedIndices has {len(sel_off) - 1} "
            f"sequences but X has {len(outer) - 1} outer sequences")
    rows, new_off = [], [0]
    for o in range(len(outer) - 1):
        n_inner = int(outer[o + 1] - outer[o])
        for idx in sel[int(sel_off[o]):int(sel_off[o + 1])]:
            if not 0 <= idx < n_inner:
                raise ValueError(
                    f"sub_nested_seq: index {int(idx)} out of range for "
                    f"outer sequence {o} with {n_inner} subsequences")
            g = int(outer[o]) + int(idx)
            s, e = int(inner[g]), int(inner[g + 1])
            rows.append(np.arange(s, e))
            new_off.append(new_off[-1] + (e - s))
    gather = np.concatenate(rows) if rows else np.zeros((0,), np.int64)
    return gather, new_off


@register_grad("sub_nested_seq")
def sub_nested_seq_grad(ctx):
    """Scatter the output grads back to the selected rows (the legacy
    SubNestedSequenceLayer backprops through its gather the same way).
    Runs eagerly like the forward, so the indices are concrete."""
    x = np.asarray(ctx.input("X"))
    dout = np.asarray(ctx.input("Out@GRAD"))
    gather, _ = _sub_nested_gather(ctx)
    dx = np.zeros_like(x)
    np.add.at(dx, gather, dout)
    return {"X@GRAD": jnp.asarray(dx)}
