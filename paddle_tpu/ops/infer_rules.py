"""Explicit static shape/dtype infer rules (paddle_tpu.analysis pass 1).

Ops without a rule here are abstractly evaluated through ``jax.eval_shape``
over their registered forward impl (analysis/infer.py), which covers the
long tail for free.  A rule earns its place by one of:

 - a *named* diagnostic beating a generic trace error — the matmul-family
   contraction check reports "K mismatch: x[64,32] @ y[16,10]" with the
   operand VAR names instead of a dot_general stack trace;
 - catching what abstract evaluation cannot: the integer-id ops coerce
   their index inputs with ``.astype(int32)``, so a float label/id tensor
   traces fine and silently truncates at runtime — only a static dtype
   rule sees it;
 - skipping a jax trace for the hottest op families (elementwise chains,
   optimizer updates) so whole-program verification stays in the
   sub-50ms budget.

Rule contract (ops/registry.py:register_infer): ``rule(op, ins)`` with
``ins[slot] = [(shape, dtype) | None, ...]``; return ``{slot: [(shape,
dtype) | None]}`` (None = unknown), or raise ``InferMismatch``.
"""

from __future__ import annotations

import numpy as np

from .registry import InferMismatch, register_infer

_INT_DTYPES = ("int8", "int16", "int32", "int64", "uint8", "bool")


def _in(ins, slot, i=0):
    vals = ins.get(slot) or []
    return vals[i] if i < len(vals) and vals[i] is not None else None


def _names(op, slot):
    return ", ".join(repr(n) for n in op.inputs.get(slot, []) if n) or slot


def _require_int(op, ins, slot):
    v = _in(ins, slot)
    if v is not None and v[1] is not None and v[1] not in _INT_DTYPES:
        raise InferMismatch(
            f"{op.type}: input {_names(op, slot)} must be an integer "
            f"index/label tensor, got dtype {v[1]} (the kernel would "
            f"silently truncate it with astype(int32))", code="AN102")
    return v


def _flat2(shape, ncol):
    lead = int(np.prod(shape[:ncol], dtype=np.int64)) if ncol else 1
    rest = int(np.prod(shape[ncol:], dtype=np.int64)) if ncol < len(shape) \
        else 1
    return lead, rest


@register_infer("mul")
def infer_mul(op, ins):
    x, y = _in(ins, "X"), _in(ins, "Y")
    if x is None or y is None:
        return None
    xnc = op.attr("x_num_col_dims", 1)
    ync = op.attr("y_num_col_dims", 1)
    _, k1 = _flat2(x[0], xnc)
    k2, _ = _flat2(y[0], ync)
    if k1 != k2:
        raise InferMismatch(
            f"mul: contraction mismatch — {_names(op, 'X')} {list(x[0])} "
            f"flattened at {xnc} gives K={k1}, but {_names(op, 'Y')} "
            f"{list(y[0])} flattened at {ync} gives K={k2}")
    out = tuple(x[0][:xnc]) + tuple(y[0][ync:])
    return {"Out": [(out, x[1])]}


@register_infer("matmul")
def infer_matmul(op, ins):
    x, y = _in(ins, "X"), _in(ins, "Y")
    if x is None or y is None:
        return None
    xs, ys = list(x[0]), list(y[0])
    if len(xs) == 1:
        xs = [1] + xs
    if len(ys) == 1:
        ys = ys + [1]
    if op.attr("transpose_X", False):
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if op.attr("transpose_Y", False):
        ys[-1], ys[-2] = ys[-2], ys[-1]
    if xs[-1] != ys[-2]:
        raise InferMismatch(
            f"matmul: contraction mismatch — {_names(op, 'X')} "
            f"{list(x[0])} x {_names(op, 'Y')} {list(y[0])} contracts "
            f"{xs[-1]} against {ys[-2]}")
    try:
        batch = tuple(np.broadcast_shapes(tuple(xs[:-2]), tuple(ys[:-2])))
    except ValueError:
        raise InferMismatch(
            f"matmul: batch dims of {_names(op, 'X')} {list(x[0])} and "
            f"{_names(op, 'Y')} {list(y[0])} do not broadcast")
    return {"Out": [(batch + (xs[-2], ys[-1]), x[1])]}


def _infer_elementwise(op, ins):
    x, y = _in(ins, "X"), _in(ins, "Y")
    if x is None:
        return None
    if y is None:
        return {"Out": [x]}
    xs, ys = x[0], y[0]
    axis = op.attr("axis", -1)
    if len(ys) > len(xs):
        # a higher-rank Y still works when plain numpy broadcasting does
        # (scalar-ish operands: [] + [1] -> [1])
        try:
            return {"Out": [(tuple(np.broadcast_shapes(xs, ys)), x[1])]}
        except ValueError:
            raise InferMismatch(
                f"{op.type}: operand {_names(op, 'Y')} {list(ys)} does "
                f"not broadcast against {_names(op, 'X')} {list(xs)}")
    if axis is None or axis == -1:
        axis = len(xs) - len(ys)
    for d, yd in enumerate(ys):
        xd = xs[axis + d] if 0 <= axis + d < len(xs) else None
        if yd != 1 and xd is not None and yd != xd:
            raise InferMismatch(
                f"{op.type}: operand {_names(op, 'Y')} {list(ys)} does "
                f"not broadcast against {_names(op, 'X')} {list(xs)} "
                f"at axis {axis} (dim {yd} vs {xd})")
    return {"Out": [x]}


for _t in ("elementwise_add", "elementwise_sub", "elementwise_mul",
           "elementwise_div", "elementwise_max", "elementwise_min",
           "elementwise_pow"):
    register_infer(_t)(_infer_elementwise)


@register_infer("lookup_table")
def infer_lookup_table(op, ins):
    ids = _require_int(op, ins, "Ids")
    w = _in(ins, "W")
    if ids is None or w is None or len(w[0]) != 2:
        return None
    idshape = tuple(ids[0])
    if len(idshape) >= 2 and idshape[-1] == 1:
        idshape = idshape[:-1]
    return {"Out": [(idshape + (w[0][1],), w[1])]}


@register_infer("cross_entropy")
def infer_cross_entropy(op, ins):
    x = _in(ins, "X")
    if not op.attr("soft_label", False):
        _require_int(op, ins, "Label")
    if x is None:
        return None
    return {"Y": [(tuple(x[0][:-1]) + (1,), "float32"
                   if x[1] in ("float16", "bfloat16") else x[1])]}


@register_infer("softmax_with_cross_entropy")
def infer_softmax_xent(op, ins):
    logits = _in(ins, "Logits")
    if not op.attr("soft_label", False):
        _require_int(op, ins, "Label")
    if logits is None:
        return None
    loss = tuple(logits[0][:-1]) + (1,)
    return {"Softmax": [logits], "Loss": [(loss, logits[1])]}


@register_infer("mean")
def infer_mean(op, ins):
    x = _in(ins, "X")
    return {"Out": [((1,), x[1]) if x is not None else None]}


@register_infer("sum")
def infer_sum(op, ins):
    vals = [v for v in ins.get("X", []) if v is not None]
    if not vals:
        return None
    shapes = {tuple(v[0]) for v in vals}
    if len(shapes) > 1:
        raise InferMismatch(
            f"sum: operands {_names(op, 'X')} disagree on shape: "
            f"{sorted(map(list, shapes))}")
    return {"Out": [vals[0]]}


@register_infer("cast")
def infer_cast(op, ins):
    from ..fluid import core as _core

    x = _in(ins, "X")
    if x is None:
        return None
    dt = str(np.dtype(_core.np_dtype(
        op.attr("out_dtype", op.attr("dtype", "float32")))))
    return {"Out": [(x[0], dt)]}


def _infer_same(op, ins):
    """Out mirrors X — the unary activation/identity family."""
    x = _in(ins, "X")
    out = {}
    for slot in op.outputs:
        out[slot] = [x] * len(op.outputs[slot])
    return out


for _t in ("relu", "sigmoid", "tanh", "softmax", "exp", "log", "sqrt",
           "square", "abs", "relu6", "leaky_relu", "elu", "softplus",
           "softsign", "gelu", "scale", "clip", "sign", "dropout",
           "fill_any_like", "assign", "floor", "ceil", "round",
           "softshrink", "hard_sigmoid", "swish", "pow", "brelu",
           "layer_norm_noop"):
    register_infer(_t)(_infer_same)


@register_infer("reshape", "reshape2")
def infer_reshape(op, ins):
    x = _in(ins, "X")
    if x is None:
        return None
    want = list(op.attr("shape") or ())
    if not want:
        return None
    n = int(np.prod(x[0], dtype=np.int64))
    fixed = int(np.prod([d for d in want if d > 0], dtype=np.int64))
    if 0 in want:
        want = [x[0][i] if d == 0 and i < len(x[0]) else d
                for i, d in enumerate(want)]
        fixed = int(np.prod([d for d in want if d > 0], dtype=np.int64))
    if -1 in want:
        if fixed == 0 or n % fixed:
            raise InferMismatch(
                f"reshape: {_names(op, 'X')} {list(x[0])} ({n} elements) "
                f"does not fit target shape {want}")
        want = [n // fixed if d == -1 else d for d in want]
    elif fixed != n:
        raise InferMismatch(
            f"reshape: {_names(op, 'X')} {list(x[0])} has {n} elements, "
            f"target shape {want} has {fixed}")
    out = {"Out": [(tuple(int(d) for d in want), x[1])]}
    if "XShape" in op.outputs:
        out["XShape"] = [((0,) + tuple(x[0]), x[1])]
    return out


@register_infer("concat")
def infer_concat(op, ins):
    vals = [v for v in ins.get("X", []) if v is not None]
    if len(vals) != len(ins.get("X", [])) or not vals:
        return None
    axis = op.attr("axis", 0)
    base = list(vals[0][0])
    axis = axis if axis >= 0 else axis + len(base)
    total = 0
    for v in vals:
        s = list(v[0])
        if len(s) != len(base) or any(
                i != axis and s[i] != base[i] for i in range(len(base))):
            raise InferMismatch(
                f"concat: operands {_names(op, 'X')} disagree off axis "
                f"{axis}: {[list(v[0]) for v in vals]}")
        total += s[axis]
    base[axis] = total
    return {"Out": [(tuple(base), vals[0][1])]}


@register_infer("fill_constant")
def infer_fill_constant(op, ins):
    from ..fluid import core as _core

    shape = tuple(int(d) for d in (op.attr("shape") or ()))
    dt = str(np.dtype(_core.np_dtype(op.attr("dtype", "float32"))))
    return {"Out": [(shape, dt)]}


def _infer_random(op, ins):
    """Shape-attr random initializers — the bulk of every startup
    program, so a rule here keeps startup verification trivially cheap."""
    from ..fluid import core as _core

    shape = tuple(int(d) for d in (op.attr("shape") or ()))
    if not shape or any(d < 0 for d in shape):
        return None
    dt = str(np.dtype(_core.np_dtype(op.attr("dtype", "float32"))))
    return {"Out": [(shape, dt)]}


for _t in ("uniform_random", "gaussian_random",
           "truncated_gaussian_random"):
    register_infer(_t)(_infer_random)


@register_infer("ring_attention")
def infer_ring_attention(op, ins):
    """Out mirrors Q — an explicit rule so the verifier never abstractly
    evaluates the Pallas flash / shard_map lowerings (fast, and priced
    identically whichever kernel the env gate picks at dispatch time)."""
    q = _in(ins, "Q")
    return {"Out": [q]}


@register_infer("kv_cache_update")
def infer_kv_cache_update(op, ins):
    """Decode-step KV-cache scatter (ISSUE 15): Out mirrors Cache, and the
    static contract — window fits the cache, index vectors are integer
    and agree with the window's row count — is exactly what abstract
    evaluation cannot name (a bad Pos dtype would silently truncate, a
    too-long window would silently clamp)."""
    cache, new = _in(ins, "Cache"), _in(ins, "New")
    slots = _require_int(op, ins, "Slots")
    pos = _require_int(op, ins, "Pos")
    if cache is None:
        return None
    if new is not None:
        if len(new[0]) != len(cache[0]):
            raise InferMismatch(
                f"kv_cache_update: window {_names(op, 'New')} "
                f"{list(new[0])} must match cache {_names(op, 'Cache')} "
                f"{list(cache[0])} rank (rows, window, feature...)")
        if new[0][1] > cache[0][1]:
            raise InferMismatch(
                f"kv_cache_update: window length {new[0][1]} exceeds "
                f"cache max_len {cache[0][1]} "
                f"({_names(op, 'New')} vs {_names(op, 'Cache')})")
        if tuple(new[0][2:]) != tuple(cache[0][2:]):
            raise InferMismatch(
                f"kv_cache_update: feature dims {list(new[0][2:])} of "
                f"{_names(op, 'New')} do not match cache feature dims "
                f"{list(cache[0][2:])}")
        for slot_name, v in (("Slots", slots), ("Pos", pos)):
            if v is not None and int(np.prod(v[0], dtype=np.int64)) \
                    != new[0][0]:
                raise InferMismatch(
                    f"kv_cache_update: {slot_name} {_names(op, slot_name)} "
                    f"{list(v[0])} must carry one index per window row "
                    f"({new[0][0]})")
    return {"Out": [cache]}


@register_infer("kv_cache_scatter")
def infer_kv_cache_scatter(op, ins):
    """Per-token KV scatter (ISSUE 20): Out mirrors Cache; New must carry
    the cache's feature dims, and Rows/Offs one integer index per written
    token (a float index would silently truncate, a count mismatch would
    silently drop or duplicate writes)."""
    cache, new = _in(ins, "Cache"), _in(ins, "New")
    rows = _require_int(op, ins, "Rows")
    offs = _require_int(op, ins, "Offs")
    if cache is None:
        return None
    if new is not None:
        if tuple(new[0][1:]) != tuple(cache[0][2:]):
            raise InferMismatch(
                f"kv_cache_scatter: token rows {_names(op, 'New')} "
                f"{list(new[0])} must carry the cache feature dims "
                f"{list(cache[0][2:])} ({_names(op, 'Cache')})")
        for slot_name, v in (("Rows", rows), ("Offs", offs)):
            if v is not None and int(np.prod(v[0], dtype=np.int64)) \
                    != new[0][0]:
                raise InferMismatch(
                    f"kv_cache_scatter: {slot_name} "
                    f"{_names(op, slot_name)} {list(v[0])} must carry one "
                    f"index per written token ({new[0][0]})")
    return {"Out": [cache]}


@register_infer("spec_accept")
def infer_spec_accept(op, ins):
    """Greedy speculative acceptance (ISSUE 20): Tokens is [S, k+1]
    int64, NumAccept [S] int64; the draft must be exactly one token
    narrower than the scored window (k drafted, k + 1 verified) and the
    mask one flag per slot — off-by-one here would silently accept the
    wrong prefix."""
    logits = _in(ins, "Logits")
    draft = _require_int(op, ins, "Draft")
    mask = _in(ins, "Mask")
    if logits is None:
        return None
    if len(logits[0]) != 3:
        raise InferMismatch(
            f"spec_accept: logits {_names(op, 'Logits')} "
            f"{list(logits[0])} must be [slots, k+1, vocab]")
    if draft is not None:
        if len(draft[0]) != 2 or draft[0][0] != logits[0][0] \
                or draft[0][1] != logits[0][1] - 1:
            raise InferMismatch(
                f"spec_accept: draft {_names(op, 'Draft')} "
                f"{list(draft[0])} must be [slots, k] against verify "
                f"logits {list(logits[0])} (k + 1 scored positions)")
    if mask is not None and int(np.prod(mask[0], dtype=np.int64)) \
            != logits[0][0]:
        raise InferMismatch(
            f"spec_accept: mask {_names(op, 'Mask')} {list(mask[0])} "
            f"must carry one flag per slot ({logits[0][0]})")
    return {"Tokens": [(tuple(logits[0][:-1]), "int64")],
            "NumAccept": [((logits[0][0],), "int64")]}


@register_infer("paged_attention")
def infer_paged_attention(op, ins):
    """Paged decode attention (ISSUE 19): Out mirrors Q — an explicit
    rule (like ring_attention's) so the verifier never abstractly
    evaluates the Pallas paged kernel, plus the static page-table
    contract abstract evaluation cannot name: an integer table, one row
    per query slot, and ``pages_per_slot * page_size`` exactly covering
    the bias's key length (a mismatch would silently attend to a
    truncated or over-gathered window)."""
    q = _in(ins, "Q")
    ck = _in(ins, "CacheK")
    bias = _in(ins, "Bias")
    pt = _require_int(op, ins, "PageTable")
    if ck is not None and len(ck[0]) != 3:
        raise InferMismatch(
            f"paged_attention: cache {_names(op, 'CacheK')} {list(ck[0])} "
            f"must be [num_pages + 1, page_size, d_model]")
    if q is not None and pt is not None and len(pt[0]) == 2 \
            and pt[0][0] != q[0][0]:
        raise InferMismatch(
            f"paged_attention: page table {_names(op, 'PageTable')} "
            f"{list(pt[0])} must carry one row per query slot "
            f"({q[0][0]})")
    if pt is not None and ck is not None and bias is not None \
            and len(pt[0]) == 2 and len(bias[0]) == 3 \
            and pt[0][1] * ck[0][1] != bias[0][2]:
        raise InferMismatch(
            f"paged_attention: gathered length {pt[0][1]} pages x "
            f"{ck[0][1]} tokens/page != bias key length {bias[0][2]} "
            f"({_names(op, 'PageTable')} vs {_names(op, 'Bias')})")
    if q is not None and ck is not None and q[0][-1] != ck[0][-1]:
        raise InferMismatch(
            f"paged_attention: feature dim {q[0][-1]} of {_names(op, 'Q')} "
            f"does not match cache feature dim {ck[0][-1]}")
    return {"Out": [q]}


@register_infer("token_select")
def infer_token_select(op, ins):
    """Greedy token choice: Out is [S] int64 off [S, V] logits; an
    inactive-slot mask must be one value per slot."""
    logits = _in(ins, "Logits")
    mask = _in(ins, "Mask")
    if logits is None:
        return None
    if len(logits[0]) < 2:
        raise InferMismatch(
            f"token_select: logits {_names(op, 'Logits')} "
            f"{list(logits[0])} must be [slots, vocab]")
    if mask is not None and int(np.prod(mask[0], dtype=np.int64)) \
            != logits[0][0]:
        raise InferMismatch(
            f"token_select: mask {_names(op, 'Mask')} {list(mask[0])} "
            f"must carry one flag per slot ({logits[0][0]})")
    return {"Out": [(tuple(logits[0][:-1]), "int64")]}


def _infer_param_update(op, ins):
    """Optimizer-family updates: each '<X>Out' output mirrors input slot
    '<X>' (ParamOut <- Param, MomentOut <- Moment, ...)."""
    out = {}
    for slot, names in op.outputs.items():
        src = slot[:-3] if slot.endswith("Out") else slot
        out[slot] = [_in(ins, src, i) for i in range(len(names))]
    return out


for _t in ("sgd", "momentum", "adam", "adamax", "adagrad", "rmsprop",
           "decayed_adagrad", "ftrl", "lars_momentum"):
    register_infer(_t)(_infer_param_update)
