"""Operator registry: OpDesc -> JAX implementation.

TPU-native analogue of the reference's OpRegistry/OpKernel machinery
(ref: paddle/fluid/framework/op_registry.h:64, operator.cc:657).  Where the
reference dispatches each op to a hand-written CPU/CUDA kernel at runtime, here
every registered op is a pure JAX function; the Executor traces a whole block
of them into one XLA computation (so "kernel fusion" is XLA's job, not ours).

Gradients: the reference requires a hand-written GradOpDescMaker + grad kernel
per op (ref: grad_op_desc_maker.h).  Here the *descriptor* side still exists
(backward.py emits ``<type>_grad`` ops so transpilers can see/edit the backward
graph), but the grad *implementation* is generic: ``jax.vjp`` over the forward
impl.  XLA CSE merges the recomputed forward with the original, so this costs
nothing at runtime.  Ops whose backward must reuse saved randomness or has
non-vjp semantics register an explicit grad impl.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

GRAD_SUFFIX = "@GRAD"


class ExecContext:
    """What an op impl sees: input arrays by slot, attrs, and (optionally) rng."""

    __slots__ = ("op_type", "inputs", "outputs_spec", "attrs", "_rng_box")

    def __init__(self, op_type, inputs, outputs_spec, attrs, rng_box=None):
        self.op_type = op_type
        self.inputs: Dict[str, List[Any]] = inputs
        self.outputs_spec: Dict[str, List[str]] = outputs_spec
        self.attrs: Dict[str, Any] = attrs
        self._rng_box = rng_box

    def input(self, slot: str, idx: int = 0):
        vals = self.inputs.get(slot) or []
        return vals[idx] if idx < len(vals) else None

    def inputs_list(self, slot: str):
        return self.inputs.get(slot) or []

    def has_input(self, slot: str) -> bool:
        return bool(self.inputs.get(slot))

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def cur_out(self, slot: str, idx: int = 0):
        """Current value of an output var (in-out semantics, e.g. a tensor
        array being appended to).  Injected by the executor."""
        vals = self.inputs.get(slot + "@CURRENT") or []
        return vals[idx] if idx < len(vals) else None

    def in_lod(self, slot: str, idx: int = 0):
        """Static LoD (tuple of offset tuples) of the idx-th input of a slot,
        or None.  Injected by the executor from `<name>@LOD` env entries."""
        vals = self.inputs.get(slot + "@LOD") or []
        return vals[idx] if idx < len(vals) else None

    def seq_offsets(self, slot: str, idx: int = 0, level: int = -1):
        """Finest (or given) level offsets of an input's LoD, as a tuple."""
        lod = self.in_lod(slot, idx)
        if not lod:
            raise ValueError(
                f"op {self.op_type}: input slot {slot} carries no LoD "
                f"(feed it as a LoDTensor / set recursive_sequence_lengths)")
        return lod[level]

    def n_outputs(self, slot: str) -> int:
        return len(self.outputs_spec.get(slot) or [])

    def rng(self):
        """Split a fresh PRNG key off the threaded rng state."""
        if self._rng_box is None:
            raise RuntimeError(
                f"op {self.op_type} needs rng but executor supplied none")
        key, sub = jax.random.split(self._rng_box[0])
        self._rng_box[0] = key
        return sub


class OpDef:
    __slots__ = ("type", "fn", "grad_fn", "infer_shape", "no_grad_inputs",
                 "stateful", "infer_var_types")

    def __init__(self, type, fn, grad_fn=None, infer_shape=None,
                 no_grad_inputs=(), stateful=False):
        self.type = type
        self.fn = fn
        self.grad_fn = grad_fn
        self.infer_shape = infer_shape
        self.no_grad_inputs = frozenset(no_grad_inputs)
        self.stateful = stateful


REGISTRY: Dict[str, OpDef] = {}


def register_op(op_type: str, *, infer_shape: Optional[Callable] = None,
                no_grad_inputs: Sequence[str] = (), stateful: bool = False):
    """Decorator: register ``fn(ctx) -> {slot: array | [arrays]}`` for op_type."""

    def deco(fn):
        if op_type in REGISTRY:
            raise ValueError(f"op {op_type} registered twice")
        REGISTRY[op_type] = OpDef(op_type, fn, infer_shape=infer_shape,
                                  no_grad_inputs=no_grad_inputs,
                                  stateful=stateful)
        return fn

    return deco


def register_grad(op_type: str):
    """Decorator: attach a custom grad impl to a registered op.

    The grad fn sees a ctx whose inputs contain the forward inputs (same slot
    names), forward outputs, and output grads under ``<slot>@GRAD``; it returns
    ``{"<slot>@GRAD": value}`` for each differentiable input slot.
    """

    def deco(fn):
        REGISTRY[op_type].grad_fn = fn
        return fn

    return deco


# ---------------------------------------------------------------------------
# Static shape/dtype infer rules (paddle_tpu.analysis pass 1)
#
# The analogue of the reference's InferShape/InferVarType registered per op
# (ref: operator.h InferShapeContext) — here a rule is optional: ops without
# one are abstractly evaluated via jax.eval_shape over the forward impl, so
# explicit rules exist only where (a) a precise named diagnostic beats a
# generic trace error (matmul contraction mismatch, integer-id inputs) or
# (b) abstract evaluation cannot see the semantics.  Registered next to the
# dispatch table on purpose: adding an op and adding its infer rule are the
# same review.
# ---------------------------------------------------------------------------

INFER_REGISTRY: Dict[str, Callable] = {}


class InferMismatch(Exception):
    """Raised by an infer rule on a static contract violation.  ``code``
    selects the diagnostic family (AN101 shape / AN102 dtype)."""

    def __init__(self, message: str, code: str = "AN101"):
        super().__init__(message)
        self.code = code


def register_infer(*op_types: str):
    """Decorator: ``rule(op, ins) -> {slot: [(shape, dtype) | None]}``.

    ``ins`` maps input slot -> list of ``(shape, dtype)`` tuples (entries
    are None for vars whose shape is statically unknown).  Rules raise
    :class:`InferMismatch` to report a violation; returning None marks all
    outputs unknown."""

    def deco(fn):
        for t in op_types:
            INFER_REGISTRY[t] = fn
        return fn

    return deco


def get_infer_rule(op_type: str) -> Optional[Callable]:
    return INFER_REGISTRY.get(op_type)


def get_op_def(op_type: str) -> OpDef:
    try:
        return REGISTRY[op_type]
    except KeyError:
        raise NotImplementedError(
            f"op '{op_type}' has no registered TPU implementation") from None


def is_registered(op_type: str) -> bool:
    return op_type in REGISTRY


# ---------------------------------------------------------------------------
# Generic vjp-based grad execution
# ---------------------------------------------------------------------------


def _is_inexact(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)


def run_grad_generic(fwd_def: OpDef, ctx: ExecContext) -> Dict[str, Any]:
    """Execute ``<type>_grad`` via jax.vjp over the forward impl.

    ctx.inputs holds forward input slots, forward output slots, and
    ``<out_slot>@GRAD`` slots.  ctx.outputs_spec names the wanted
    ``<in_slot>@GRAD`` outputs.
    """
    if fwd_def.stateful and fwd_def.grad_fn is None:
        raise NotImplementedError(
            f"stateful op {fwd_def.type} requires an explicit grad impl")

    # Which forward input slots do we need grads for?
    want_slots = []
    for out_slot in ctx.outputs_spec:
        if not out_slot.endswith(GRAD_SUFFIX):
            raise ValueError(f"bad grad output slot {out_slot}")
        s = out_slot[: -len(GRAD_SUFFIX)]
        if s in fwd_def.no_grad_inputs:
            continue
        want_slots.append(s)

    # Which forward output slots have incoming grads?
    fwd_out_grads = {}
    for slot, vals in ctx.inputs.items():
        if slot.endswith(GRAD_SUFFIX):
            s = slot[: -len(GRAD_SUFFIX)]
            if any(v is not None for v in vals):
                fwd_out_grads[s] = vals

    diff_tree = {s: [v for v in ctx.inputs_list(s)] for s in want_slots}
    nondiff = {
        s: vals
        for s, vals in ctx.inputs.items()
        if s not in diff_tree and not s.endswith(GRAD_SUFFIX)
    }

    out_slots = sorted(fwd_out_grads)

    def f(dt):
        merged = dict(nondiff)
        merged.update(dt)
        fctx = ExecContext(fwd_def.type, merged, {}, ctx.attrs)
        outs = _normalize_outputs(fwd_def.fn(fctx))
        res = {}
        for s in out_slots:
            vals = outs.get(s)
            if vals is None:
                continue
            res[s] = [v for v in vals if _is_inexact(v)]
        return res

    primals_out, vjp_fn = jax.vjp(f, diff_tree)
    # Build cotangent tree matching primals_out.
    cot = {}
    for s in primals_out:
        gs = fwd_out_grads[s]
        vals = []
        for i, p in enumerate(primals_out[s]):
            g = gs[i] if i < len(gs) else None
            if g is None:
                g = jnp.zeros_like(p)
            vals.append(jnp.asarray(g, p.dtype))
        cot[s] = vals
    (grads,) = vjp_fn(cot)

    result = {}
    for s in want_slots:
        gvals = grads.get(s)
        if gvals is None:
            continue
        result[s + GRAD_SUFFIX] = gvals
    return result


def _normalize_outputs(outs) -> Dict[str, List[Any]]:
    norm = {}
    if outs is None:
        return norm
    for slot, v in outs.items():
        if isinstance(v, (list, tuple)):
            norm[slot] = list(v)
        else:
            norm[slot] = [v]
    return norm
