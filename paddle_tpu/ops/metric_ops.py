"""Metric ops (ref: accuracy_op.*, auc_op.*, mean_iou_op, precision_recall)."""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


@register_op("accuracy", no_grad_inputs=("Out", "Indices", "Label"))
def accuracy(ctx):
    indices = ctx.input("Indices")  # [N, k] top-k indices
    label = ctx.input("Label")      # [N, 1]
    if label.ndim == 2:
        label = label.reshape(-1)
    hit = jnp.any(indices == label[:, None].astype(indices.dtype), axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.array(indices.shape[0], jnp.int32)
    acc = correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": acc.reshape(1), "Correct": correct.reshape(1),
            "Total": total.reshape(1)}


@register_op("auc", no_grad_inputs=("Predict", "Label", "StatPos", "StatNeg"))
def auc(ctx):
    """Streaming AUC over histogram buckets (ref: auc_op.h)."""
    predict = ctx.input("Predict")  # [N, 2] probs
    label = ctx.input("Label").reshape(-1)
    stat_pos = ctx.input("StatPos")
    stat_neg = ctx.input("StatNeg")
    num_thresholds = ctx.attr("num_thresholds", 4095)
    pos_prob = predict[:, -1]
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                      num_thresholds)
    is_pos = (label > 0)
    stat_pos = stat_pos.at[bucket].add(is_pos.astype(stat_pos.dtype))
    stat_neg = stat_neg.at[bucket].add((~is_pos).astype(stat_neg.dtype))
    # integrate: iterate buckets from high threshold to low
    pos_cum = jnp.cumsum(stat_pos[::-1])
    neg_cum = jnp.cumsum(stat_neg[::-1])
    tot_pos = pos_cum[-1]
    tot_neg = neg_cum[-1]
    # trapezoid area between consecutive operating points
    prev_pos = jnp.concatenate([jnp.zeros(1, pos_cum.dtype), pos_cum[:-1]])
    prev_neg = jnp.concatenate([jnp.zeros(1, neg_cum.dtype), neg_cum[:-1]])
    area = jnp.sum((neg_cum - prev_neg) * (pos_cum + prev_pos) / 2.0)
    auc_val = jnp.where((tot_pos > 0) & (tot_neg > 0),
                        area / jnp.maximum(tot_pos * tot_neg, 1e-12), 0.0)
    return {"AUC": auc_val.reshape(1).astype(jnp.float64)
            if auc_val.dtype == jnp.float64 else auc_val.reshape(1),
            "StatPosOut": stat_pos, "StatNegOut": stat_neg}


@register_op("mean_iou", no_grad_inputs=("Predictions", "Labels"))
def mean_iou(ctx):
    pred = ctx.input("Predictions").reshape(-1).astype(jnp.int32)
    label = ctx.input("Labels").reshape(-1).astype(jnp.int32)
    n = ctx.attr("num_classes")
    conf = jnp.zeros((n, n), jnp.float32).at[label, pred].add(1.0)
    inter = jnp.diag(conf)
    union = conf.sum(0) + conf.sum(1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1e-12), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return {"OutMeanIou": miou.reshape(1), "OutWrong": (conf.sum(1) - inter),
            "OutCorrect": inter}
