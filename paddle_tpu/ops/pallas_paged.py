"""Paged decode attention as a Pallas TPU kernel (ISSUE 19 tentpole).

The decode engine's K/V cache lives as fixed-size pages in one
``[num_pages + 1, page_size, d_model]`` buffer per layer (the last row is
the trash page absorbing inactive-slot writes), and each tick feeds a
``[slots, pages_per_slot]`` page table.  The dense decode step gathers the
whole table with ``jnp.take`` before one big attention matmul; this kernel
moves the gather INSIDE the attention loop: the page table rides the
grid's scalar-prefetch slot, so each (slot, page) grid step DMAs exactly
one K/V page — ``BlockSpec`` index maps read ``pt[s, j]`` — and the
``[slots, L]`` score matrix never round-trips through a gathered HBM copy.

Bitwise discipline (the PR 15 sequential-equivalence invariant): scores
accumulate per page into a VMEM ``[1, L]`` scratch row and the softmax at
the LAST page iteration replays ``jax.nn.softmax``'s exact sequence
(max, exp(x - max), divide by sum) over the full row — NOT the online
recurrence flash attention uses, which is numerically but not bitwise
equal.  Validity masking arrives as the same additive ``-inf`` bias the
dense step uses, so trash/stale pages contribute exp(-inf) = 0 exactly.
(Kernel vs the XLA fallback still differs at fp32 ULP under jit —
reduction-order freedom in the batched dots — which is why the engine
pins ONE lowering per deployment: the sequential-equivalence oracle is
exact within either lowering, and ``PADDLE_TPU_FUSED=0`` restores the
unfused one verbatim.)

Falls back to interpret mode off-TPU so CPU tier-1 exercises the same
page-table math (``ops/decode_ops.py`` holds the XLA ``take`` unfused
twin behind the ``PADDLE_TPU_FUSED`` kill switch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _paged_kernel(pt_ref, q_ref, k_ref, v_ref, bias_ref, o_ref,
                  scores_ref, vbuf_ref, *, scale, n_pages, ps):
    """Grid step (slot, page): score ONE gathered K/V page against the
    slot's single query row, park the partial score segment + fp32 V copy
    in VMEM scratch, and run the exact full-row softmax at the last page.

    ``pt_ref`` is the scalar-prefetched page table — it is consumed by the
    in_spec index maps (``pt[s, j]`` picks the cache block), not read here.
    """
    del pt_ref
    j = pl.program_id(1)
    # all index math in i32: under the package-wide x64 mode python ints
    # promote to i64, which Mosaic's index ops reject
    off = j * jnp.int32(ps)
    q = q_ref[0].astype(jnp.float32)                    # [1, d]
    if scale != 1.0:
        q = q * jnp.float32(scale)
    k = k_ref[0].astype(jnp.float32)                    # [ps, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)             # [1, ps]
    s = s + bias_ref[0].astype(jnp.float32)
    scores_ref[:, pl.ds(off, ps)] = s
    vbuf_ref[pl.ds(off, ps), :] = v_ref[0].astype(jnp.float32)

    @pl.when(j == jnp.int32(n_pages - 1))
    def _flush():
        z = scores_ref[:]                               # [1, L]
        m = jnp.max(z, axis=-1, keepdims=True)
        e = jnp.exp(z - m)
        p = e / jnp.sum(e, axis=-1, keepdims=True)
        o_ref[0] = jax.lax.dot_general(
            p, vbuf_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(o_ref.dtype)


def paged_attention(q, cache_k, cache_v, page_table, bias, scale=1.0,
                    interpret=None):
    """``softmax(scale · q Kᵀ + bias) V`` where K/V are gathered through
    ``page_table`` from a paged cache.

    q: ``[S, 1, D]`` (one decode step per slot); cache_k/cache_v:
    ``[P + 1, ps, D]`` (row P is the trash page); page_table: ``[S,
    n_pages]`` int (unmapped entries point at the trash page); bias:
    ``[S, 1, L]`` additive validity bias with ``L == n_pages * ps`` and
    exact ``-inf`` beyond each slot's live length.  Returns ``[S, 1, D]``.
    """
    from jax.experimental.pallas import tpu as pltpu

    s_n, _, d = q.shape
    n_pages = page_table.shape[1]
    ps = cache_k.shape[1]
    ell = n_pages * ps
    if bias.shape != (s_n, 1, ell):
        raise ValueError(
            f"paged_attention bias must be [S, 1, n_pages * page_size] = "
            f"[{s_n}, 1, {ell}]; got {bias.shape}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s_n, n_pages),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda s, j, pt: (s, 0, 0)),
            pl.BlockSpec((1, ps, d), lambda s, j, pt: (pt[s, j], 0, 0)),
            pl.BlockSpec((1, ps, d), lambda s, j, pt: (pt[s, j], 0, 0)),
            pl.BlockSpec((1, 1, ps), lambda s, j, pt: (s, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda s, j, pt: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, ell), jnp.float32),   # full score row
            pltpu.VMEM((ell, d), jnp.float32),   # gathered fp32 V
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, scale=float(scale),
                          n_pages=n_pages, ps=ps),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_n, 1, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), q, cache_k, cache_v, bias)
