"""Op library: importing this package registers every op implementation."""

from . import registry  # noqa: F401
from .registry import register_op, register_grad, is_registered, get_op_def  # noqa: F401

from . import (  # noqa: F401
    math_ops,
    activation_ops,
    reduce_ops,
    shape_ops,
    random_ops,
    nn_ops,
    loss_ops,
    optimizer_ops,
    metric_ops,
    sequence_ops,
    rnn_ops,
    array_ops,
    struct_loss_ops,
    detection_ops,
    quant_ops,
    attention_ops,
    misc_ops,
    rcnn_ops,
    moe_ops,
    pipeline_ops,
    transformer_ops,
    decode_ops,
)
from . import infer_rules  # noqa: F401,E402  (static infer rules, after impls)
