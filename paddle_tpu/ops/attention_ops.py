"""Attention ops — including sequence-parallel ring attention, a
first-class TPU capability the reference lacks (SURVEY.md §5.7: SP/CP
"Absent"; its sequence story is LoD packing on one device).

``ring_attention`` is mesh-aware: traced under a ShardedTrainStep whose
mesh has an "sp" axis, it runs the ppermute ring (parallel/ring_attention
.py) over ICI; traced single-device (plain Executor) it degrades to the
mathematically identical full-softmax attention, so programs are portable
across places — the same portability contract the reference gives ops via
per-place kernels (op_registry.h OpKernelType).
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


@register_op("ring_attention")
def ring_attention_op(ctx):
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")  # [B, H, T, D]
    bias = ctx.input("Bias") if ctx.has_input("Bias") else None
    causal = ctx.attr("causal", False)
    sp_axis = ctx.attr("sp_axis", "sp")
    scale = ctx.attr("scale", 0.0) or None
    from ..parallel import ring_attention as ra
    from ..parallel import spmd

    flash_req = int(ctx.attr("flash", -1))
    mesh = spmd.active_mesh()
    if mesh is not None and sp_axis in mesh.axis_names \
            and mesh.shape[sp_axis] > 1:
        out = ra.ring_attention(q, k, v, mesh, sp_axis, causal, scale,
                                bias=bias)
    elif _flash_decision(flash_req):
        from . import pallas_fused
        from .pallas_flash import bias_supported, flash_attention

        if bias_supported(bias, q.shape[0], k.shape[2]):
            if mesh is not None:
                # tp-sharded lowering: heads stay sharded through the
                # kernel (GSPMD cannot partition an opaque pallas_call —
                # a mesh-less wrap would all-gather q/k/v around it)
                out = pallas_fused.flash_attention_sharded(
                    q, k, v, bias, scale, causal, mesh,
                    pallas_fused.flash_tp_axis(q, mesh))
            else:
                out = flash_attention(q, k, v, bias, scale, causal)
        else:
            out = ra.full_attention(q, k, v, causal, scale, bias=bias)
    else:
        out = ra.full_attention(q, k, v, causal, scale, bias=bias)
    return {"Out": out}


def _flash_decision(flash_req: int = -1) -> bool:
    """Pallas flash-attention kernel gate.

    Precedence: the PADDLE_TPU_FLASH env kill-switch wins over everything
    (=0 forces OFF even for models built with flash=True — it is the
    tunnel safeguard bench.py relies on; =1 forces ON), then the per-op
    attr (1 on / 0 off), then AUTO: on when the backend is a TPU (the
    kernels compile natively on a TPU VM and stream K/V through VMEM —
    ops/pallas_flash.py), off on CPU/GPU (interpret mode is a correctness
    tool, not a fast path).  Read through the declared env contract
    (fluid.envcontract) like every other knob."""
    import jax

    from ..fluid import envcontract

    v = envcontract.get("PADDLE_TPU_FLASH")
    if v in ("0", "false"):
        return False
    if v in ("1", "true"):
        return True
    if flash_req != -1:
        return bool(flash_req)
    return jax.default_backend() == "tpu"


def _use_flash() -> bool:
    """AUTO-mode gate (no per-op request) — see _flash_decision."""
    return _flash_decision(-1)
