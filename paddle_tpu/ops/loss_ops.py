"""Loss ops (ref: cross_entropy_op.*, softmax_with_cross_entropy_op.*,
sigmoid_cross_entropy_with_logits_op, huber_loss_op, smooth_l1_loss_op,
log_loss_op, hinge_loss_op, rank_loss_op, margin_rank_loss_op,
squared_l2_norm_op, squared_l2_distance_op)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _hard_xent(probs, label, ignore_index=-100):
    if label.ndim == probs.ndim and label.shape[-1] == 1:
        label = label.reshape(label.shape[:-1])
    li = label.astype(jnp.int32)
    picked = jnp.take_along_axis(probs, li[..., None], axis=-1)
    loss = -jnp.log(jnp.maximum(picked, 1e-20))
    if ignore_index >= 0:
        loss = jnp.where((li == ignore_index)[..., None], 0.0, loss)
    return loss


@register_op("cross_entropy", no_grad_inputs=("Label",))
def cross_entropy(ctx):
    from ..fluid import amp

    x = ctx.input("X")  # probabilities [N, C]
    if amp.is_low_float(x.dtype):
        x = x.astype(jnp.float32)  # log() at the loss boundary is fp32
    label = ctx.input("Label")
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), -1, keepdims=True)
        return {"Y": loss}
    return {"Y": _hard_xent(x, label, ctx.attr("ignore_index", -100))}


@register_op("softmax_with_cross_entropy", no_grad_inputs=("Label",))
def softmax_with_cross_entropy(ctx):
    logits = ctx.input("Logits")
    label = ctx.input("Label")
    from ..fluid import amp
    from . import pallas_fused

    soft = ctx.attr("soft_label", False)
    if pallas_fused.fused_decision() \
            and pallas_fused.xent_fusable(logits, label, soft):
        # streaming Pallas lowering: the [batch, vocab] probability matrix
        # never materializes in HBM; backward recomputes P per tile from
        # the saved logsumexp (ops/pallas_fused.py)
        return pallas_fused.softmax_xent_op(
            logits, label, soft, ctx.attr("ignore_index", -100))

    in_dtype = logits.dtype
    if amp.is_low_float(in_dtype):
        logits = logits.astype(jnp.float32)  # fp32 at the loss boundary
    sm = jax.nn.softmax(logits, axis=-1).astype(in_dtype)
    logp = jax.nn.log_softmax(logits, axis=-1)
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * logp, -1, keepdims=True)
    else:
        li = label
        if li.ndim == logits.ndim and li.shape[-1] == 1:
            li = li.reshape(li.shape[:-1])
        li = li.astype(jnp.int32)
        loss = -jnp.take_along_axis(logp, li[..., None], axis=-1)
        ignore = ctx.attr("ignore_index", -100)
        if ignore >= 0:
            loss = jnp.where((li == ignore)[..., None], 0.0, loss)
    return {"Softmax": sm, "Loss": loss}


@register_op("sigmoid_cross_entropy_with_logits", no_grad_inputs=("Label",))
def sigmoid_ce(ctx):
    x = ctx.input("X")
    label = ctx.input("Label")
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = ctx.attr("ignore_index", -100)
    if ignore >= 0:
        loss = jnp.where(label == ignore, 0.0, loss)
    return {"Out": loss}


@register_op("huber_loss", no_grad_inputs=("Y",))
def huber_loss(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    d = ctx.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= d, 0.5 * r * r, d * (ar - 0.5 * d))
    return {"Out": loss, "Residual": r}


@register_op("smooth_l1_loss", no_grad_inputs=("Y",))
def smooth_l1_loss(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    sigma = ctx.attr("sigma", 1.0)
    s2 = sigma * sigma
    iw = ctx.input("InsideWeight")
    ow = ctx.input("OutsideWeight")
    d = x - y
    if iw is not None:
        d = d * iw
    ad = jnp.abs(d)
    val = jnp.where(ad < 1.0 / s2, 0.5 * d * d * s2, ad - 0.5 / s2)
    if ow is not None:
        val = val * ow
    out = jnp.sum(val.reshape(val.shape[0], -1), axis=1, keepdims=True)
    return {"Out": out, "Diff": d}


@register_op("log_loss", no_grad_inputs=("Labels",))
def log_loss(ctx):
    p = ctx.input("Predicted")
    y = ctx.input("Labels")
    eps = ctx.attr("epsilon", 1e-4)
    out = -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)
    return {"Loss": out}


@register_op("hinge_loss", no_grad_inputs=("Labels",))
def hinge_loss(ctx):
    logits = ctx.input("Logits")
    y = ctx.input("Labels")
    return {"Loss": jnp.maximum(1.0 - (2.0 * y - 1.0) * logits, 0.0)}


@register_op("rank_loss", no_grad_inputs=("Label",))
def rank_loss(ctx):
    label = ctx.input("Label")
    left, right = ctx.input("Left"), ctx.input("Right")
    d = left - right
    return {"Out": jnp.log1p(jnp.exp(d)) - label * d}


@register_op("margin_rank_loss", no_grad_inputs=("Label",))
def margin_rank_loss(ctx):
    label = ctx.input("Label")
    x1, x2 = ctx.input("X1"), ctx.input("X2")
    m = ctx.attr("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + m)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("squared_l2_norm")
def squared_l2_norm(ctx):
    x = ctx.input("X")
    return {"Out": jnp.sum(x * x).reshape(1)}


@register_op("squared_l2_distance", no_grad_inputs=())
def squared_l2_distance(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    d = x - y
    return {"Out": jnp.sum(d * d, axis=tuple(range(1, d.ndim)), keepdims=False)
            .reshape(-1, 1), "sub_result": d}


@register_op("bpr_loss", no_grad_inputs=("Label",))
def bpr_loss(ctx):
    x = ctx.input("X")  # [N, C] logits
    label = ctx.input("Label")
    if label.ndim == x.ndim and label.shape[-1] == 1:
        label = label.reshape(label.shape[:-1])
    li = label.astype(jnp.int32)
    pos = jnp.take_along_axis(x, li[..., None], axis=-1)
    # mean of -log(sigmoid(pos - neg)) over the C-1 true negatives
    # (ref: bpr_loss_op.h excludes j == label)
    lls = jax.nn.log_sigmoid(pos - x)
    mask = jax.nn.one_hot(li, x.shape[-1], dtype=x.dtype)
    n_neg = x.shape[-1] - 1
    loss = -jnp.sum(lls * (1.0 - mask), axis=-1, keepdims=True) / n_neg
    return {"Y": loss}


@register_op("kldiv_loss", no_grad_inputs=("Target",))
def kldiv_loss(ctx):
    x = ctx.input("X")  # log-probs
    t = ctx.input("Target")
    loss = t * (jnp.log(jnp.maximum(t, 1e-20)) - x)
    red = ctx.attr("reduction", "mean")
    if red == "mean":
        return {"Loss": jnp.mean(loss)}
    if red == "sum":
        return {"Loss": jnp.sum(loss)}
    if red == "batchmean":
        return {"Loss": jnp.sum(loss) / x.shape[0]}
    return {"Loss": loss}
