"""Activation ops (ref: paddle/fluid/operators/activation_op.{cc,cu,h} —
~20 activations registered via macro; here each is one jnp expression and the
backward falls out of the generic vjp rule)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _unary(name, fn):
    @register_op(name)
    def _impl(ctx, _fn=fn):
        return {"Out": _fn(ctx.input("X"))}
    return _impl


_unary("relu", jax.nn.relu)
_unary("sigmoid", jax.nn.sigmoid)
_unary("logsigmoid", jax.nn.log_sigmoid)
_unary("tanh", jnp.tanh)
_unary("tanh_shrink", lambda x: x - jnp.tanh(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("abs", jnp.abs)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: 1.0 / jnp.sqrt(x))
_unary("reciprocal", lambda x: 1.0 / x)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("round", jnp.round)
_unary("cos", jnp.cos)
_unary("sin", jnp.sin)
_unary("softplus", jax.nn.softplus)
_unary("softsign", jax.nn.soft_sign)
_unary("softshrink", lambda x: jnp.where(x > 0.5, x - 0.5, jnp.where(x < -0.5, x + 0.5, 0.0)))
_unary("gelu", jax.nn.gelu)


@register_op("relu6")
def relu6(ctx):
    t = ctx.attr("threshold", 6.0)
    return {"Out": jnp.clip(ctx.input("X"), 0.0, t)}


@register_op("leaky_relu")
def leaky_relu(ctx):
    a = ctx.attr("alpha", 0.02)
    x = ctx.input("X")
    return {"Out": jnp.where(x >= 0, x, a * x)}


@register_op("elu")
def elu(ctx):
    a = ctx.attr("alpha", 1.0)
    x = ctx.input("X")
    return {"Out": jnp.where(x >= 0, x, a * (jnp.exp(x) - 1.0))}


@register_op("pow")
def pow_op(ctx):
    return {"Out": jnp.power(ctx.input("X"), ctx.attr("factor", 1.0))}


@register_op("stanh")
def stanh(ctx):
    a = ctx.attr("scale_a", 0.67)
    b = ctx.attr("scale_b", 1.7159)
    return {"Out": b * jnp.tanh(a * ctx.input("X"))}


@register_op("hard_sigmoid")
def hard_sigmoid(ctx):
    slope = ctx.attr("slope", 0.2)
    offset = ctx.attr("offset", 0.5)
    return {"Out": jnp.clip(slope * ctx.input("X") + offset, 0.0, 1.0)}


@register_op("hard_shrink")
def hard_shrink(ctx):
    t = ctx.attr("threshold", 0.5)
    x = ctx.input("X")
    return {"Out": jnp.where(jnp.abs(x) > t, x, 0.0)}


@register_op("thresholded_relu")
def thresholded_relu(ctx):
    t = ctx.attr("threshold", 1.0)
    x = ctx.input("X")
    return {"Out": jnp.where(x > t, x, 0.0)}


@register_op("soft_relu")
def soft_relu(ctx):
    t = ctx.attr("threshold", 40.0)
    x = jnp.clip(ctx.input("X"), -t, t)
    return {"Out": jnp.log(1.0 + jnp.exp(x))}


@register_op("brelu")
def brelu(ctx):
    t_min = ctx.attr("t_min", 0.0)
    t_max = ctx.attr("t_max", 24.0)
    return {"Out": jnp.clip(ctx.input("X"), t_min, t_max)}


@register_op("swish")
def swish(ctx):
    b = ctx.attr("beta", 1.0)
    x = ctx.input("X")
    return {"Out": x * jax.nn.sigmoid(b * x)}


@register_op("prelu")
def prelu(ctx):
    x = ctx.input("X")
    alpha = ctx.input("Alpha")
    mode = ctx.attr("mode", "all")
    if mode == "all":
        a = alpha.reshape(())
    elif mode == "channel":
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:  # element
        a = alpha.reshape((1,) + x.shape[1:])
    return {"Out": jnp.where(x >= 0, x, a * x)}


@register_op("softmax")
def softmax(ctx):
    x = ctx.input("X")
    from ..fluid import amp

    if amp.is_low_float(x.dtype):
        # exp/renormalize in fp32 (bf16 exponentials lose the tail mass);
        # restore the input dtype so attention maps stay low-precision
        return {"Out": jax.nn.softmax(x.astype(jnp.float32),
                                      axis=-1).astype(x.dtype)}
    return {"Out": jax.nn.softmax(x, axis=-1)}


@register_op("log_softmax")
def log_softmax(ctx):
    return {"Out": jax.nn.log_softmax(ctx.input("X"), axis=ctx.attr("axis", -1))}
