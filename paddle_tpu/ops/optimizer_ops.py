"""Optimizer update ops (ref: sgd_op.*, momentum_op.*, adam_op.*, adagrad_op.*,
adamax_op.*, adadelta_op.*, rmsprop_op.*, decayed_adagrad_op.*, ftrl_op.*).

Each is a pure function from (param, grad, accumulators, lr) to new values; the
Executor's SSA rebinding makes them in-place on device (donated buffers)."""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _lr(ctx):
    return ctx.input("LearningRate").reshape(())


def _grad(ctx, p):
    """Dense view of the Grad input.  A SelectedRows grad (sparse embedding
    backward) is folded by scatter-add; moment-carrying optimizers then run
    exact dense semantics.  (Deviation from the reference's row-lazy sparse
    adam/adagrad — ref adam_op.h SelectedRows branch skips moment decay on
    untouched rows — is deliberate: dense decay is the mathematically
    standard update and XLA fuses the scatter, so there is no kernel-launch
    saving to chase on TPU.  The latency-critical sparse path is sgd, which
    stays truly sparse below.)"""
    from ..fluid.selected_rows import SelectedRows

    g = ctx.input("Grad")
    if isinstance(g, SelectedRows):
        return g.to_dense(p.shape[0]).astype(p.dtype)
    return g


@register_op("sgd", no_grad_inputs=("Param", "Grad", "LearningRate"))
def sgd(ctx):
    from ..fluid.selected_rows import SelectedRows

    p, g = ctx.input("Param"), ctx.input("Grad")
    if isinstance(g, SelectedRows):
        # touch only the looked-up rows; duplicates fold in the scatter-add
        # (ref: sgd_op.h SelectedRows branch)
        return {"ParamOut": g.scatter_sub_into(p, _lr(ctx))}
    return {"ParamOut": p - _lr(ctx) * g}


def _fused_opt_ok(ctx, p, g, out_slots):
    """Route this update through the single-sweep Pallas kernel?  Gate +
    static suitability + (under a mesh) spec alignment of param and
    accumulators — ZeRO-1-diverged updates keep the unfused lowering."""
    from . import pallas_fused

    if not (pallas_fused.fused_decision() and pallas_fused.opt_fusable(p, g)):
        return False
    names = [(ctx.outputs_spec.get(s) or [None])[0] for s in out_slots]
    return pallas_fused.opt_specs_aligned(names)


@register_op("momentum", no_grad_inputs=("Param", "Grad", "Velocity", "LearningRate"))
def momentum(ctx):
    p, v = ctx.input("Param"), ctx.input("Velocity")
    g = _grad(ctx, p)
    mu = ctx.attr("mu")
    lr = _lr(ctx)
    if _fused_opt_ok(ctx, p, g, ("ParamOut", "VelocityOut")):
        from . import pallas_fused

        p_out, v_out = pallas_fused.fused_momentum(
            p, g, v, lr, mu, ctx.attr("use_nesterov", False),
            var_name=(ctx.outputs_spec.get("ParamOut") or [None])[0])
        return {"ParamOut": p_out, "VelocityOut": v_out}
    v_out = mu * v + g
    if ctx.attr("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("adam", no_grad_inputs=("Param", "Grad", "LearningRate", "Moment1",
                                     "Moment2", "Beta1Pow", "Beta2Pow"))
def adam(ctx):
    p = ctx.input("Param")
    g = _grad(ctx, p)
    m1, m2 = ctx.input("Moment1"), ctx.input("Moment2")
    b1p, b2p = ctx.input("Beta1Pow").reshape(()), ctx.input("Beta2Pow").reshape(())
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx) * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    if _fused_opt_ok(ctx, p, g, ("ParamOut", "Moment1Out", "Moment2Out")):
        from . import pallas_fused

        # the bias-corrected lr and beta-pow counters are [1]-shaped
        # scalar math; the sweep fuses the four big buffers
        po, m1o, m2o = pallas_fused.fused_adam(
            p, g, m1, m2, lr, b1, b2, eps,
            var_name=(ctx.outputs_spec.get("ParamOut") or [None])[0])
        return {"ParamOut": po, "Moment1Out": m1o, "Moment2Out": m2o,
                "Beta1PowOut": (b1p * b1).reshape(1),
                "Beta2PowOut": (b2p * b2).reshape(1)}
    m1o = b1 * m1 + (1.0 - b1) * g
    m2o = b2 * m2 + (1.0 - b2) * g * g
    po = p - lr * m1o / (jnp.sqrt(m2o) + eps)
    return {"ParamOut": po, "Moment1Out": m1o, "Moment2Out": m2o,
            "Beta1PowOut": (b1p * b1).reshape(1), "Beta2PowOut": (b2p * b2).reshape(1)}


@register_op("adagrad", no_grad_inputs=("Param", "Grad", "Moment", "LearningRate"))
def adagrad(ctx):
    p, m = ctx.input("Param"), ctx.input("Moment")
    g = _grad(ctx, p)
    eps = ctx.attr("epsilon", 1e-6)
    mo = m + g * g
    return {"ParamOut": p - _lr(ctx) * g / (jnp.sqrt(mo) + eps), "MomentOut": mo}


@register_op("adamax", no_grad_inputs=("Param", "Grad", "LearningRate", "Moment",
                                       "InfNorm", "Beta1Pow"))
def adamax(ctx):
    p = ctx.input("Param")
    g = _grad(ctx, p)
    m, inf = ctx.input("Moment"), ctx.input("InfNorm")
    b1p = ctx.input("Beta1Pow").reshape(())
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    mo = b1 * m + (1.0 - b1) * g
    info = jnp.maximum(b2 * inf, jnp.abs(g))
    lr = _lr(ctx) / (1.0 - b1p)
    return {"ParamOut": p - lr * mo / (info + eps), "MomentOut": mo,
            "InfNormOut": info}


@register_op("adadelta", no_grad_inputs=("Param", "Grad", "AvgSquaredGrad",
                                         "AvgSquaredUpdate"))
def adadelta(ctx):
    p = ctx.input("Param")
    g = _grad(ctx, p)
    asg, asu = ctx.input("AvgSquaredGrad"), ctx.input("AvgSquaredUpdate")
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    asg_o = rho * asg + (1.0 - rho) * g * g
    upd = -jnp.sqrt((asu + eps) / (asg_o + eps)) * g
    asu_o = rho * asu + (1.0 - rho) * upd * upd
    return {"ParamOut": p + upd, "AvgSquaredGradOut": asg_o,
            "AvgSquaredUpdateOut": asu_o}


@register_op("rmsprop", no_grad_inputs=("Param", "Grad", "MeanSquare", "Moment",
                                        "LearningRate"))
def rmsprop(ctx):
    p = ctx.input("Param")
    g = _grad(ctx, p)
    ms, mom = ctx.input("MeanSquare"), ctx.input("Moment")
    eps = ctx.attr("epsilon", 1e-10)
    decay = ctx.attr("decay", 0.9)
    mu = ctx.attr("momentum", 0.0)
    ms_o = decay * ms + (1.0 - decay) * g * g
    mom_o = mu * mom + _lr(ctx) * g / jnp.sqrt(ms_o + eps)
    return {"ParamOut": p - mom_o, "MeanSquareOut": ms_o, "MomentOut": mom_o}


@register_op("decayed_adagrad", no_grad_inputs=("Param", "Grad", "Moment",
                                                "LearningRate"))
def decayed_adagrad(ctx):
    p, m = ctx.input("Param"), ctx.input("Moment")
    g = _grad(ctx, p)
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    mo = decay * m + (1.0 - decay) * g * g
    return {"ParamOut": p - _lr(ctx) * g / (jnp.sqrt(mo) + eps), "MomentOut": mo}


@register_op("ftrl", no_grad_inputs=("Param", "Grad", "SquaredAccumulator",
                                     "LinearAccumulator", "LearningRate"))
def ftrl(ctx):
    p = ctx.input("Param")
    g = _grad(ctx, p)
    sq, lin = ctx.input("SquaredAccumulator"), ctx.input("LinearAccumulator")
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    lr = _lr(ctx)
    new_sq = sq + g * g
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2.0 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2.0 * l2
    x = l1 * jnp.sign(new_lin) - new_lin
    p_out = jnp.where(jnp.abs(new_lin) > l1, x / denom, jnp.zeros_like(p))
    return {"ParamOut": p_out, "SquaredAccumOut": new_sq, "LinearAccumOut": new_lin}


@register_op("proximal_gd", no_grad_inputs=("Param", "Grad",
                                             "LearningRate"))
def proximal_gd(ctx):
    """ref: proximal_gd_op.* — SGD step followed by the proximal operator
    for l1/l2 regularization: soft-threshold then shrink."""
    p = ctx.input("Param")
    g = ctx.input("Grad")
    lr = ctx.input("LearningRate").reshape(()).astype(p.dtype)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    prox = p - lr * g
    out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)         / (1.0 + lr * l2)
    return {"ParamOut": out.astype(p.dtype)}


@register_op("proximal_adagrad", no_grad_inputs=("Param", "Grad", "Moment",
                                                 "LearningRate"))
def proximal_adagrad(ctx):
    """ref: proximal_adagrad_op.* — adagrad-scaled step + proximal l1/l2."""
    p = ctx.input("Param")
    g = ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(()).astype(p.dtype)
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    m_out = m + g * g
    prox = p - lr * g / jnp.sqrt(m_out + 1e-10)
    # threshold/shrink with the SCALAR lr (ref proximal_adagrad_op.h) —
    # a per-element effective lr would decay the l1 threshold to zero
    out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) \
        / (1.0 + lr * l2)
    return {"ParamOut": out.astype(p.dtype), "MomentOut": m_out}


@register_op("average_accumulates",
             no_grad_inputs=("param", "in_sum_1", "in_sum_2", "in_sum_3",
                             "in_num_accumulates", "in_old_num_accumulates",
                             "in_num_updates"))
def average_accumulates(ctx):
    """ModelAverage support (ref: average_accumulates_op.*)."""
    param = ctx.input("param")
    s1, s2, s3 = ctx.input("in_sum_1"), ctx.input("in_sum_2"), ctx.input("in_sum_3")
    na = ctx.input("in_num_accumulates").reshape(())
    ona = ctx.input("in_old_num_accumulates").reshape(())
    nu = ctx.input("in_num_updates").reshape(())
    avg_window = ctx.attr("average_window", 0.0)
    max_avg = ctx.attr("max_average_window", 10000)
    min_avg = ctx.attr("min_average_window", 10000)
    k_max_acc = 16384  # ref: kMaxNumAccumulates in average_accumulates_op.h
    na = na + 1
    nu = nu + 1
    s1 = s1 + param
    # periodic fold of sum_1 into sum_2 to bound fp accumulation error
    fold = (nu % k_max_acc) == 0
    s2 = jnp.where(fold, s2 + s1, s2)
    s1 = jnp.where(fold, jnp.zeros_like(s1), s1)
    # window trigger: snapshot sums into sum_3 and restart the window
    trigger = (na >= min_avg) & \
        (na >= jnp.minimum(float(max_avg), avg_window * nu))
    s3 = jnp.where(trigger, s1 + s2, s3)
    s1 = jnp.where(trigger, jnp.zeros_like(s1), s1)
    s2 = jnp.where(trigger, jnp.zeros_like(s2), s2)
    ona = jnp.where(trigger, na, ona)
    na = jnp.where(trigger, jnp.zeros_like(na), na)
    idt = ctx.input("in_num_accumulates").dtype
    return {"out_sum_1": s1, "out_sum_2": s2, "out_sum_3": s3,
            "out_num_accumulates": na.reshape(1).astype(idt),
            "out_old_num_accumulates": ona.reshape(1).astype(idt),
            "out_num_updates": nu.reshape(1).astype(idt)}
