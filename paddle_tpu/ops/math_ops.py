"""Dense math ops (ref families: paddle/fluid/operators mul_op.*, matmul_op.cc,
elementwise_*, sum_op, scale_op, cast_op, clip_op, compare_op, logical_op).

Each impl is a pure JAX function; XLA maps matmuls onto the MXU directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _flatten2(x, num_col_dims):
    """Fold leading dims: paddle's mul op flattens x to 2-D at num_col_dims."""
    shape = x.shape
    lead = 1
    for d in shape[:num_col_dims]:
        lead *= d
    rest = 1
    for d in shape[num_col_dims:]:
        rest *= d
    return x.reshape(lead, rest)


@register_op("mul")
def mul(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    xnc = ctx.attr("x_num_col_dims", 1)
    ync = ctx.attr("y_num_col_dims", 1)
    x2 = _flatten2(x, xnc)
    y2 = _flatten2(y, ync)
    from ..fluid import amp

    x2, y2, back = amp.cast_operands(x2, y2)
    out = amp.restore_astype(jnp.matmul(x2, y2), back)
    # restore leading dims of x and trailing dims of y
    out_shape = x.shape[:xnc] + y.shape[ync:]
    return {"Out": out.reshape(out_shape)}


@register_op("matmul")
def matmul(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    tx, ty = ctx.attr("transpose_X", False), ctx.attr("transpose_Y", False)
    alpha = ctx.attr("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    from ..fluid import amp

    x, y, back = amp.cast_operands(x, y)
    out = amp.restore_astype(jnp.matmul(x, y), back)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


def _bcast_y(x, y, axis):
    """Fluid elementwise broadcast: y's dims align to x starting at `axis`
    (ref: elementwise_op_function.h).  axis=-1 means trailing alignment,
    which matches numpy broadcasting directly."""
    if y.ndim == x.ndim or y.ndim == 0:
        return y
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    new_shape = [1] * axis + list(y.shape) + [1] * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def _elementwise(name, fn):
    @register_op(name)
    def _impl(ctx, _fn=fn):
        x, y = ctx.input("X"), ctx.input("Y")
        y = _bcast_y(x, y, ctx.attr("axis", -1))
        from ..fluid import amp

        if (amp.keep_low_activations() and x.dtype != y.dtype
                and jnp.issubdtype(x.dtype, jnp.floating)
                and jnp.issubdtype(y.dtype, jnp.floating)):
            # pure-low-activation regime: the broadcast operand (fp32
            # bias/scale params) follows the main operand's dtype so a
            # bias add can't silently re-promote activations to fp32
            y = y.astype(x.dtype)
        return {"Out": _fn(x, y)}
    return _impl


_elementwise("elementwise_add", jnp.add)
_elementwise("elementwise_sub", jnp.subtract)
_elementwise("elementwise_mul", jnp.multiply)
_elementwise("elementwise_div", jnp.divide)
_elementwise("elementwise_max", jnp.maximum)
_elementwise("elementwise_min", jnp.minimum)
_elementwise("elementwise_pow", jnp.power)
_elementwise("elementwise_mod", jnp.mod)
_elementwise("elementwise_floordiv", jnp.floor_divide)


@register_op("scale")
def scale(ctx):
    x = ctx.input("X")
    s = ctx.attr("scale", 1.0)
    b = ctx.attr("bias", 0.0)
    after = ctx.attr("bias_after_scale", True)
    out = x * s + b if after else (x + b) * s
    return {"Out": out}


@register_op("sum")
def sum_op(ctx):
    from ..fluid.selected_rows import SelectedRows

    xs = [v for v in ctx.inputs_list("X") if v is not None]
    sparse = [v for v in xs if isinstance(v, SelectedRows)]
    if sparse:
        if len(sparse) == len(xs):
            # all-sparse: concatenation IS the sum (ref: sum over
            # SelectedRows, math/selected_rows_functor.h Add)
            out = sparse[0]
            for v in sparse[1:]:
                out = out.merge_with(v)
            return {"Out": out}
        # mixed: densify the sparse parts into the dense accumulator
        dense = [v for v in xs if not isinstance(v, SelectedRows)]
        out = dense[0]
        for v in dense[1:]:
            out = out + v
        for v in sparse:
            out = out.at[v.rows].add(v.values.astype(out.dtype))
        return {"Out": out}
    out = xs[0]
    for v in xs[1:]:
        out = out + v
    return {"Out": out}


@register_op("mean")
def mean(ctx):
    # Fluid's mean outputs shape [1], not a 0-d scalar (ref: mean_op.cc)
    return {"Out": jnp.mean(ctx.input("X")).reshape(1)}


@register_op("cast", no_grad_inputs=())
def cast(ctx):
    from ..fluid import core as _core

    dt = _core.np_dtype(ctx.attr("out_dtype", ctx.attr("dtype", "float32")))
    # .astype preserves host-ness: numpy in -> numpy out (counter path)
    return {"Out": ctx.input("X").astype(dt)}


@register_op("clip")
def clip(ctx):
    return {"Out": jnp.clip(ctx.input("X"), ctx.attr("min"), ctx.attr("max"))}


@register_op("clip_by_norm")
def clip_by_norm(ctx):
    x = ctx.input("X")
    max_norm = ctx.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(x * x))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": x * scale.astype(x.dtype)}


def _host(*vals):
    """True when every value is a host (numpy) array — the counter path.
    Host values stay concrete through jit traces (see fill_constant's
    force_cpu), so loop conditions computed from them can drive trace-time
    unrolling of while sub-blocks."""
    import numpy as np

    return all(isinstance(v, np.ndarray) for v in vals)


def _compare(name, fn, npfn):
    @register_op(name, no_grad_inputs=("X", "Y"))
    def _impl(ctx, _fn=fn, _npfn=npfn):
        x, y = ctx.input("X"), ctx.input("Y")
        if _host(x, y):
            return {"Out": _npfn(x, y)}
        y = _bcast_y(x, y, ctx.attr("axis", -1))
        return {"Out": _fn(x, y)}
    return _impl


import numpy as _np  # noqa: E402

_compare("less_than", jnp.less, _np.less)
_compare("less_equal", jnp.less_equal, _np.less_equal)
_compare("greater_than", jnp.greater, _np.greater)
_compare("greater_equal", jnp.greater_equal, _np.greater_equal)
_compare("equal", jnp.equal, _np.equal)
_compare("not_equal", jnp.not_equal, _np.not_equal)


def _logical(name, fn, npfn, binary=True):
    if binary:
        @register_op(name, no_grad_inputs=("X", "Y"))
        def _impl(ctx, _fn=fn, _npfn=npfn):
            x, y = ctx.input("X"), ctx.input("Y")
            return {"Out": _npfn(x, y) if _host(x, y) else _fn(x, y)}
    else:
        @register_op(name, no_grad_inputs=("X",))
        def _impl(ctx, _fn=fn, _npfn=npfn):
            x = ctx.input("X")
            return {"Out": _npfn(x) if _host(x) else _fn(x)}
    return _impl


_logical("logical_and", jnp.logical_and, _np.logical_and)
_logical("logical_or", jnp.logical_or, _np.logical_or)
_logical("logical_xor", jnp.logical_xor, _np.logical_xor)
_logical("logical_not", jnp.logical_not, _np.logical_not, binary=False)


@register_op("isfinite", no_grad_inputs=("X",))
def isfinite(ctx):
    return {"Out": jnp.all(jnp.isfinite(ctx.input("X"))).reshape(1)}


@register_op("has_inf", no_grad_inputs=("X",))
def has_inf(ctx):
    return {"Out": jnp.any(jnp.isinf(ctx.input("X"))).reshape(1)}


@register_op("has_nan", no_grad_inputs=("X",))
def has_nan(ctx):
    return {"Out": jnp.any(jnp.isnan(ctx.input("X"))).reshape(1)}


@register_op("sign")
def sign(ctx):
    return {"Out": jnp.sign(ctx.input("X"))}


@register_op("increment")
def increment(ctx):
    x = ctx.input("X")
    step = ctx.attr("step", 1.0)
    if _host(x):
        return {"Out": _np.asarray(x + step).astype(x.dtype)}
    return {"Out": (x + step).astype(x.dtype)}


@register_op("maximum")
def maximum(ctx):
    return {"Out": jnp.maximum(ctx.input("X"), ctx.input("Y"))}


@register_op("minimum")
def minimum(ctx):
    return {"Out": jnp.minimum(ctx.input("X"), ctx.input("Y"))}


@register_op("dot")
def dot(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    return {"Out": jnp.sum(x * y, axis=-1, keepdims=True)}
