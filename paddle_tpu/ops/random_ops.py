"""Random / fill / assign ops (ref: uniform_random_op.*, gaussian_random_op.*,
fill_constant_op.cc, fill_zeros_like_op, assign_op, dropout_op, random_crop).

RNG design: the reference seeds cuRAND per op; here randomness is a threefry
key threaded through the traced program as hidden state (@RNG_STATE@), so a
Program with random_seed set replays identically — the determinism contract
the reference's OpTest relies on (SURVEY.md hard part #6).  An op with an
explicit nonzero ``seed`` attr uses its own fixed key instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, register_grad


def _np_dtype(ctx, attr="dtype", default="float32"):
    from ..fluid import core as _core

    return _core.np_dtype(ctx.attr(attr, default))


def _key(ctx):
    seed = ctx.attr("seed", 0)
    if seed:
        return jax.random.PRNGKey(seed)
    return ctx.rng()


@register_op("fill_constant")
def fill_constant(ctx):
    dt = _np_dtype(ctx)
    shape = tuple(ctx.attr("shape", []))
    value = ctx.attr("value", 0.0)
    # Always a host (numpy) value: constants fold into the trace either way,
    # and host-ness keeps loop counters / conditions concrete under jit so
    # while sub-blocks can unroll (the role force_cpu plays in the
    # reference; here it is the default).  jnp consumers auto-promote.
    import numpy as np

    return {"Out": np.full(shape, value, dt)}


@register_op("fill_constant_batch_size_like")
def fill_constant_batch_size_like(ctx):
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    in_idx = ctx.attr("input_dim_idx", 0)
    out_idx = ctx.attr("output_dim_idx", 0)
    shape[out_idx] = x.shape[in_idx]
    return {"Out": jnp.full(tuple(shape), ctx.attr("value", 0.0), _np_dtype(ctx))}


@register_op("fill_zeros_like")
def fill_zeros_like(ctx):
    return {"Out": jnp.zeros_like(ctx.input("X"))}


@register_op("fill_any_like")
def fill_any_like(ctx):
    return {"Out": jnp.full_like(ctx.input("X"), ctx.attr("value", 0.0))}


@register_op("assign")
def assign(ctx):
    return {"Out": ctx.input("X")}


@register_op("assign_value")
def assign_value(ctx):
    import numpy as np

    dt = _np_dtype(ctx)
    vals = ctx.attr("fp32_values") or ctx.attr("int32_values") or ctx.attr("values")
    # Host (numpy) value like fill_constant above: a jnp constant would
    # become a traced op under jit, and ops that need static values
    # (sequence_slice Offset/Length, loop bounds) could no longer consume
    # an assigned constant.  jnp consumers auto-promote.
    return {"Out": np.array(vals, dt).reshape(ctx.attr("shape"))}


@register_op("uniform_random", stateful=True)
def uniform_random(ctx):
    dt = _np_dtype(ctx)
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    shape = tuple(ctx.attr("shape"))
    return {"Out": jax.random.uniform(_key(ctx), shape, dt, lo, hi)}


@register_op("uniform_random_batch_size_like", stateful=True)
def uniform_random_batch_size_like(ctx):
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    return {"Out": jax.random.uniform(_key(ctx), tuple(shape), _np_dtype(ctx), lo, hi)}


@register_op("gaussian_random", stateful=True)
def gaussian_random(ctx):
    dt = _np_dtype(ctx)
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    shape = tuple(ctx.attr("shape"))
    return {"Out": mean + std * jax.random.normal(_key(ctx), shape, dt)}


@register_op("gaussian_random_batch_size_like", stateful=True)
def gaussian_random_batch_size_like(ctx):
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    return {"Out": mean + std * jax.random.normal(_key(ctx), tuple(shape), _np_dtype(ctx))}


@register_op("truncated_gaussian_random", stateful=True)
def truncated_gaussian_random(ctx):
    dt = _np_dtype(ctx)
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    shape = tuple(ctx.attr("shape"))
    out = jax.random.truncated_normal(_key(ctx), -2.0, 2.0, shape, dt)
    return {"Out": mean + std * out}


@register_op("sampling_id", stateful=True, no_grad_inputs=("X",))
def sampling_id(ctx):
    x = ctx.input("X")  # [N, C] probabilities
    key = _key(ctx)
    return {"Out": jax.random.categorical(key, jnp.log(jnp.maximum(x, 1e-20)), axis=-1)
            .astype(jnp.int64)}


@register_op("dropout", stateful=True)
def dropout(ctx):
    x = ctx.input("X")
    p = ctx.attr("dropout_prob", 0.5)
    is_test = ctx.attr("is_test", False)
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if is_test:
        if impl == "upscale_in_train":
            return {"Out": x, "Mask": jnp.ones_like(x)}
        return {"Out": x * (1.0 - p), "Mask": jnp.ones_like(x)}
    keep = jax.random.bernoulli(_key(ctx), 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        mask = keep.astype(x.dtype) / max(1.0 - p, 1e-12)
    else:
        mask = keep.astype(x.dtype)
    return {"Out": x * mask, "Mask": mask}


@register_grad("dropout")
def dropout_grad(ctx):
    """Backward reuses the saved mask — the one place generic vjp can't apply
    (fresh rng would decorrelate); ref: dropout_op.h DropoutGradKernel."""
    mask = ctx.input("Mask")
    dout = ctx.input("Out@GRAD")
    return {"X@GRAD": dout * mask}


@register_op("shuffle_channel")
def shuffle_channel(ctx):
    x = ctx.input("X")
    g = ctx.attr("group", 1)
    n, c, h, w = x.shape
    return {"Out": x.reshape(n, g, c // g, h, w).swapaxes(1, 2).reshape(n, c, h, w)}


@register_op("range", no_grad_inputs=("Start", "End", "Step"))
def range_op(ctx):
    s = ctx.input("Start").reshape(())
    e = ctx.input("End").reshape(())
    st = ctx.input("Step").reshape(())
    # static shapes required: assume python scalars were baked via attrs if present
    n = ctx.attr("_static_len", None)
    if n is None:
        raise NotImplementedError("range op requires static length on TPU")
    return {"Out": s + st * jnp.arange(n, dtype=s.dtype)}
