"""Decode-step op surface for continuous batching (ISSUE 15).

Two ops make an autoregressive decode step expressible as a fixed-shape
fluid program the serving engine can dispatch once per iteration:

 - ``kv_cache_update``: scatter a window of freshly projected K/V rows
   into a persistable ``[max_slots, max_len, ...]`` cache at per-row
   (slot, position) destinations.  The op's output IS the cache var
   (in-place by name), so the executor commits it as persistent state
   after every dispatch and — with ``program._donate_state`` set — the
   donation machinery aliases the cache buffer window-over-window
   instead of copying it (the PR 6 donated-carry idiom, applied to the
   serving path).
 - ``token_select``: greedy next-token choice per slot —
   ``argmax(logits)`` where the slot is active, the ``end_id`` pad token
   where it is not, so retired/free slots emit inert tokens without a
   host round trip inside the step.

Both are row-independent over the slot dim on purpose: a slot's token
stream is a function of its own prompt and cache rows only, which is
what makes continuous-batching output bitwise identical to per-request
sequential decode (the ISSUE 15 convoy oracle's correctness half).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

__all__ = []


@register_op("kv_cache_update", stateful=True,
             no_grad_inputs=("Slots", "Pos"))
def kv_cache_update(ctx):
    """Cache [S, L, ...], New [n, w, ...], Slots [n] int, Pos [n] int ->
    Out = Cache with ``New[j]`` written at ``Cache[Slots[j], Pos[j]:
    Pos[j]+w]``.  Callers keep ``Pos[j] + w <= L`` (the engine's
    max_len admission check); ``dynamic_update_slice`` clamps anything
    else rather than corrupting neighbor rows."""
    cache = ctx.input("Cache")
    new = ctx.input("New").astype(cache.dtype)
    slots = ctx.input("Slots").astype(jnp.int32).reshape(-1)
    pos = ctx.input("Pos").astype(jnp.int32).reshape(-1)
    rows = jnp.take(cache, slots, axis=0)          # [n, L, ...]

    def write(row, window, p):
        start = (p,) + (jnp.int32(0),) * (row.ndim - 1)
        return jax.lax.dynamic_update_slice(row, window, start)

    rows = jax.vmap(write)(rows, new, pos)
    return {"Out": cache.at[slots].set(rows)}


@register_op("token_select", no_grad_inputs=("Mask",))
def token_select(ctx):
    """Logits [S, V] (+ optional Mask [S]) -> Out [S] int64: per-slot
    greedy argmax; inactive slots (mask == 0) emit ``end_id`` so free
    slots never contribute spurious tokens.  argmax ties break to the
    lowest index — deterministic for a fixed executable, part of the
    bitwise sequential-equivalence contract."""
    logits = ctx.input("Logits")
    end_id = int(ctx.attr("end_id", 0))
    out = jnp.argmax(logits, axis=-1).astype(jnp.int64)
    mask = ctx.input("Mask") if ctx.has_input("Mask") else None
    if mask is not None:
        out = jnp.where(mask.reshape(-1) > 0, out, jnp.int64(end_id))
    return {"Out": out}
