"""Decode-step op surface for continuous batching (ISSUE 15).

Two ops make an autoregressive decode step expressible as a fixed-shape
fluid program the serving engine can dispatch once per iteration:

 - ``kv_cache_update``: scatter a window of freshly projected K/V rows
   into a persistable ``[max_slots, max_len, ...]`` cache at per-row
   (slot, position) destinations.  The op's output IS the cache var
   (in-place by name), so the executor commits it as persistent state
   after every dispatch and — with ``program._donate_state`` set — the
   donation machinery aliases the cache buffer window-over-window
   instead of copying it (the PR 6 donated-carry idiom, applied to the
   serving path).
 - ``token_select``: greedy next-token choice per slot —
   ``argmax(logits)`` where the slot is active, the ``end_id`` pad token
   where it is not, so retired/free slots emit inert tokens without a
   host round trip inside the step.

Both are row-independent over the slot dim on purpose: a slot's token
stream is a function of its own prompt and cache rows only, which is
what makes continuous-batching output bitwise identical to per-request
sequential decode (the ISSUE 15 convoy oracle's correctness half).

ISSUE 19 adds ``paged_attention``: decode attention over a page-pool
cache (``[num_pages + 1, page_size, d_model]`` + a per-tick ``[slots,
pages_per_slot]`` page table from serving/kvpool).  Dispatch follows the
PR 12 fused discipline — ``PADDLE_TPU_FUSED`` gates the Pallas kernel
(ops/pallas_paged.py, scalar-prefetch gather inside the kernel) against
an XLA ``take``-based unfused twin that runs the exact same page-table
math, so CPU tier-1 proves the indirection and the kill switch restores
the unfused lowering bitwise.

ISSUE 20 adds the speculative-decode pair:

 - ``kv_cache_scatter``: per-token K/V writes at explicit (row, offset)
   destinations.  The verify step writes k + 1 positions per slot in one
   dispatch; ``kv_cache_update``'s whole-row scatter loses writes when
   the same slot appears twice (last duplicated row wins), so the wide
   step needs true element-granular destinations.  One op covers both
   layouts: dense caches pass (slot, absolute position), paged caches
   pass (page, in-page offset).  Out-of-range rows are JAX-scatter-
   dropped — the dense-mode "trash slot" that mirrors the pool's trash
   page.
 - ``spec_accept``: device-side greedy acceptance — the longest prefix
   where the draft token equals the verify argmax, plus the first
   correction token.  Because every emitted token IS a target argmax
   at a position whose cache prefix matches sequential decode, accepted
   output is bitwise identical to one-token greedy by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

__all__ = []


@register_op("kv_cache_update", stateful=True,
             no_grad_inputs=("Slots", "Pos"))
def kv_cache_update(ctx):
    """Cache [S, L, ...], New [n, w, ...], Slots [n] int, Pos [n] int ->
    Out = Cache with ``New[j]`` written at ``Cache[Slots[j], Pos[j]:
    Pos[j]+w]``.  Callers keep ``Pos[j] + w <= L`` (the engine's
    max_len admission check); ``dynamic_update_slice`` clamps anything
    else rather than corrupting neighbor rows."""
    cache = ctx.input("Cache")
    new = ctx.input("New").astype(cache.dtype)
    slots = ctx.input("Slots").astype(jnp.int32).reshape(-1)
    pos = ctx.input("Pos").astype(jnp.int32).reshape(-1)
    rows = jnp.take(cache, slots, axis=0)          # [n, L, ...]

    def write(row, window, p):
        start = (p,) + (jnp.int32(0),) * (row.ndim - 1)
        return jax.lax.dynamic_update_slice(row, window, start)

    rows = jax.vmap(write)(rows, new, pos)
    return {"Out": cache.at[slots].set(rows)}


@register_op("kv_cache_scatter", stateful=True,
             no_grad_inputs=("Rows", "Offs"))
def kv_cache_scatter(ctx):
    """Cache [R, W, ...], New [n, ...], Rows [n] int, Offs [n] int ->
    Out = Cache with ``New[j]`` written at ``Cache[Rows[j], Offs[j]]``.
    Unlike ``kv_cache_update`` this scatters single positions, so a slot
    may appear in ``Rows`` many times (the verify step's k + 1 writes)
    as long as each (row, off) pair is unique.  Rows >= R (or < 0) are
    dropped by JAX scatter semantics — callers steer masked-out lanes
    there on purpose."""
    cache = ctx.input("Cache")
    new = ctx.input("New").astype(cache.dtype)
    rows = ctx.input("Rows").astype(jnp.int32).reshape(-1)
    offs = ctx.input("Offs").astype(jnp.int32).reshape(-1)
    return {"Out": cache.at[rows, offs].set(new)}


@register_op("spec_accept", no_grad_inputs=("Draft", "Mask"))
def spec_accept(ctx):
    """Logits [S, k+1, V], Draft [S, k] int (+ optional Mask [S]) ->
    Tokens [S, k+1] int64, NumAccept [S] int64.

    ``Tokens[s] = argmax(Logits[s], -1)`` is what sequential greedy
    decode would emit at each of the k + 1 scored positions given the
    accepted prefix; ``NumAccept[s] = n`` is the longest prefix with
    ``Draft[s, i] == Tokens[s, i]`` — the engine consumes tokens
    ``Tokens[s, :n+1]`` (n accepted + 1 correction/bonus), all of them
    target argmaxes, so output is bitwise greedy by construction.
    Inactive slots (mask == 0) emit ``end_id`` everywhere and accept 0,
    the token_select idiom widened."""
    logits = ctx.input("Logits")
    draft = ctx.input("Draft").astype(jnp.int64)
    end_id = int(ctx.attr("end_id", 0))
    toks = jnp.argmax(logits, axis=-1).astype(jnp.int64)   # [S, k+1]
    match = (draft == toks[:, :-1]).astype(jnp.int64)      # [S, k]
    nacc = jnp.cumprod(match, axis=1).sum(axis=1)          # [S]
    mask = ctx.input("Mask") if ctx.has_input("Mask") else None
    if mask is not None:
        live = mask.reshape(-1) > 0
        toks = jnp.where(live[:, None], toks, jnp.int64(end_id))
        nacc = jnp.where(live, nacc, jnp.int64(0))
    return {"Tokens": toks, "NumAccept": nacc}


@register_op("paged_attention", no_grad_inputs=("PageTable", "Bias"))
def paged_attention_op(ctx):
    """Q [S, 1, D], CacheK/CacheV [P + 1, ps, D], PageTable [S, n] int,
    Bias [S, 1, n·ps] -> Out [S, 1, D]: one decode step of attention with
    K/V gathered through the page table (row P is the trash page; the
    bias carries exact ``-inf`` past each slot's live length, so trash
    and stale pages contribute exp(-inf) = 0 — the same masking that
    makes the dense step's retired slots inert).

    The unfused lowering mirrors the dense step's op sequence exactly
    (``matmul`` with transposed Y, ``+ bias``, ``jax.nn.softmax``,
    ``matmul``) over the ``jnp.take``-gathered pages, so with the same
    fp32 cache content it is bitwise identical to the dense attention —
    the paged≡dense sequential-equivalence oracle rides on that."""
    q = ctx.input("Q")
    ck = ctx.input("CacheK")
    cv = ctx.input("CacheV")
    pt = ctx.input("PageTable")
    bias = ctx.input("Bias")
    scale = float(ctx.attr("scale", 1.0))
    fused_req = int(ctx.attr("fused", -1))
    from . import pallas_fused

    # The Pallas kernel is specialized to one query row per slot; the
    # speculative verify step passes k + 1 rows and always takes the
    # generic unfused lowering (bitwise-identical math either way).
    if q.shape[1] == 1 and pallas_fused.fused_decision(fused_req):
        from .pallas_paged import paged_attention

        out = paged_attention(q, ck, cv, pt, bias, scale)
        pallas_fused._note("paged_attention")
        return {"Out": out}
    qs = q if scale == 1.0 else q * q.dtype.type(scale)
    pt32 = pt.astype(jnp.int32)
    n_pages = pt32.shape[1]
    ps = ck.shape[1]
    gk = jnp.take(ck, pt32, axis=0).reshape(
        q.shape[0], n_pages * ps, ck.shape[2])
    gv = jnp.take(cv, pt32, axis=0).reshape(
        q.shape[0], n_pages * ps, cv.shape[2])
    scores = jnp.matmul(qs, jnp.swapaxes(gk, -1, -2)) + bias
    probs = jax.nn.softmax(scores, axis=-1)
    return {"Out": jnp.matmul(probs, gv)}


@register_op("token_select", no_grad_inputs=("Mask",))
def token_select(ctx):
    """Logits [S, V] (+ optional Mask [S]) -> Out [S] int64: per-slot
    greedy argmax; inactive slots (mask == 0) emit ``end_id`` so free
    slots never contribute spurious tokens.  argmax ties break to the
    lowest index — deterministic for a fixed executable, part of the
    bitwise sequential-equivalence contract."""
    logits = ctx.input("Logits")
    end_id = int(ctx.attr("end_id", 0))
    out = jnp.argmax(logits, axis=-1).astype(jnp.int64)
    mask = ctx.input("Mask") if ctx.has_input("Mask") else None
    if mask is not None:
        out = jnp.where(mask.reshape(-1) > 0, out, jnp.int64(end_id))
    return {"Out": out}
