"""Whole-program beam search: ONE ``lax.while_loop`` over static
[batch, beam] state (VERDICT r4 missing #1).

The reference runs decode *inside* the graph as per-step ops
(ref: paddle/fluid/operators/beam_search_op.cc:24 one expansion step,
beam_search_decode_op.cc trace-back) driven by a host While loop — one
device dispatch per op per step.  The TPU-native formulation compiles the
entire generation loop into a single XLA program: static shapes
([batch, beam] tokens/scores/finished plus [batch*beam, ...] cell states),
``lax.while_loop`` with a finished-mask early exit, and history buffers
written with ``dynamic_update_index_in_dim``.  Only the final LoD packaging
(data-dependent hypothesis lengths) leaves the program — as one host op.

Semantics match the eager ``beam_search`` op (ops/array_ops.py:462), i.e.
the fixed-width static-shape formulation: a beam that has emitted
``end_id`` keeps exactly one candidate — ``end_id`` again with its score
frozen — so ended hypotheses survive selection without re-accumulation,
and the step loop can stop early once every beam has ended (score state is
then invariant, so stopping early is exact, not approximate).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1.0e30  # finite "minus infinity": keeps top_k ties deterministic
                   # and avoids (-inf) + (-inf) edge cases in f32


def beam_search_step(step_fn: Callable, states: Sequence, tokens, scores,
                     finished, *, beam_size: int, vocab_size: int,
                     end_id: int):
    """ONE beam-search expansion in step form (ISSUE 15): advance the
    cell, fan candidates out, select the top-k per source, reorder the
    cell states along the chosen parents.

    This is the loop body of :func:`beam_search_loop` factored out so an
    iteration-level scheduler (the serving DecodeEngine) can drive beam
    decode token-by-token with its own admit/retire policy between
    steps — same math, one expansion per call.

    tokens/scores/finished: [batch, beam]; states: list of
    [batch*beam, ...] arrays.  Returns ``(new_tokens, parents,
    new_scores, new_finished, new_states)`` with parents [batch, beam]
    int32 (the trace-back row the caller appends to its history)."""
    B, K = tokens.shape
    V = int(vocab_size)
    assert K == int(beam_size)
    probs, new_states = step_fn(states, tokens.reshape(B * K, 1))
    logp = jnp.log(jnp.maximum(probs.astype(jnp.float32), 1e-30))
    cand = scores[:, :, None] + logp.reshape(B, K, V)
    # ended beam: sole candidate is end_id at its frozen score
    # (mirrors ops/array_ops.py beam_search's ended-beam branch)
    cand = jnp.where(finished[:, :, None], NEG_INF, cand)
    cand = cand.at[:, :, end_id].set(
        jnp.where(finished, scores, cand[:, :, end_id]))

    top_sc, top_idx = lax.top_k(cand.reshape(B, K * V), K)
    parent = (top_idx // V).astype(jnp.int32)
    new_tok = (top_idx % V).astype(jnp.int64)
    par_fin = jnp.take_along_axis(finished, parent, axis=1)
    new_fin = par_fin | (new_tok == end_id)
    # dead lanes (score still NEG_INF) must not flip finished off
    new_fin = new_fin | (top_sc <= NEG_INF / 2)

    rows = (jnp.arange(B, dtype=jnp.int32)[:, None] * K
            + parent).reshape(-1)
    new_states = [s[rows] for s in new_states]
    return new_tok, parent, top_sc, new_fin, new_states


def beam_search_loop(step_fn: Callable, init_states: Sequence,
                     init_ids, init_scores, *, beam_size: int,
                     vocab_size: int, max_len: int, end_id: int):
    """Run the full generation loop as one compiled program.

    step_fn(states, tokens) -> (probs, new_states): advance the decoder
    cell one step for every live hypothesis.  ``states`` is a list of
    [batch*beam, ...] arrays, ``tokens`` is [batch*beam, 1] int64 (last
    emitted token per hypothesis), ``probs`` is [batch*beam, vocab]
    post-softmax.

    init_states: list of [batch, ...] arrays (one hypothesis per source,
    like the DSL's InitState); tiled ``beam_size``-wide here.
    init_ids / init_scores: [batch, 1] (or [batch]) start token and score.

    Returns (hist_ids, hist_parents, hist_scores, n_steps):
    [max_len+1, batch, beam] histories whose row 0 is the init step (the
    eager path stores init_ids at array index 0 too, and the trace-back
    includes it), and n_steps = number of valid history rows.  Beams are
    dense: dead hypotheses carry score NEG_INF and parent 0.
    """
    B = int(init_ids.shape[0])
    K = int(beam_size)
    V = int(vocab_size)
    L = int(max_len)

    tokens0 = jnp.broadcast_to(
        jnp.asarray(init_ids, jnp.int64).reshape(B, 1), (B, K))
    # beam 0 carries the init hypothesis; the rest are dead until the
    # first expansion fans out (the DSL starts width-1 via LoD [[1]*B])
    scores0 = jnp.full((B, K), NEG_INF, jnp.float32)
    scores0 = scores0.at[:, 0].set(
        jnp.asarray(init_scores, jnp.float32).reshape(B))
    finished0 = jnp.zeros((B, K), bool)
    states0 = [jnp.repeat(jnp.asarray(s), K, axis=0) for s in init_states]

    hist_ids0 = jnp.zeros((L + 1, B, K), jnp.int64).at[0].set(tokens0)
    hist_par0 = jnp.zeros((L + 1, B, K), jnp.int32)
    hist_sc0 = jnp.full((L + 1, B, K), NEG_INF, jnp.float32) \
        .at[0].set(scores0)

    def cond(carry):
        t, _, _, finished = carry[:4]
        return (t <= L) & ~jnp.all(finished)

    def body(carry):
        t, tokens, scores, finished, states, h_ids, h_par, h_sc = carry
        new_tok, parent, top_sc, new_fin, new_states = beam_search_step(
            step_fn, states, tokens, scores, finished, beam_size=K,
            vocab_size=V, end_id=end_id)

        h_ids = lax.dynamic_update_index_in_dim(h_ids, new_tok, t, 0)
        h_par = lax.dynamic_update_index_in_dim(h_par, parent, t, 0)
        h_sc = lax.dynamic_update_index_in_dim(h_sc, top_sc, t, 0)
        return (t + 1, new_tok, top_sc, new_fin, new_states,
                h_ids, h_par, h_sc)

    carry = (jnp.asarray(1, jnp.int32), tokens0, scores0, finished0,
             states0, hist_ids0, hist_par0, hist_sc0)
    t, _, _, _, _, h_ids, h_par, h_sc = lax.while_loop(cond, body, carry)
    return h_ids, h_par, h_sc, t
