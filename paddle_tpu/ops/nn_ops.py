"""NN ops: conv / pool / norm / embedding (ref: conv_op.*, conv_cudnn_op.cu.cc,
pool_op.*, batch_norm_op.*, layer_norm_op.*, lrn_op.*, lookup_table_op.*).

All convs lower to ``lax.conv_general_dilated`` — XLA tiles them onto the MXU;
there is no cuDNN-style algo selection to port.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_grad, register_op


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


def _conv(ctx, x, w):
    from ..fluid import amp

    strides = _pair(ctx.attr("strides", [1, 1]))
    paddings = _pair(ctx.attr("paddings", [0, 0]))
    dilations = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1) or 1
    nd = x.ndim - 2
    dn = ("NCHW", "OIHW", "NCHW") if nd == 2 else ("NCDHW", "OIDHW", "NCDHW")
    pad = [(p, p) for p in paddings]
    x, w, back = amp.cast_operands(x, w)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32 else None)
    return amp.restore_astype(out, back)


@register_op("conv2d")
def conv2d(ctx):
    return {"Output": _conv(ctx, ctx.input("Input"), ctx.input("Filter"))}


@register_op("conv3d")
def conv3d(ctx):
    return {"Output": _conv(ctx, ctx.input("Input"), ctx.input("Filter"))}


@register_op("depthwise_conv2d")
def depthwise_conv2d(ctx):
    x, w = ctx.input("Input"), ctx.input("Filter")
    strides = _pair(ctx.attr("strides", [1, 1]))
    paddings = _pair(ctx.attr("paddings", [0, 0]))
    dilations = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1) or x.shape[1]
    pad = [(p, p) for p in paddings]
    from ..fluid import amp

    x, w, back = amp.cast_operands(x, w)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad, rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"), feature_group_count=groups)
    return {"Output": amp.restore_astype(out, back)}


def _transpose_pad(w_spatial, paddings, dilations):
    """Paddle conv_transpose padding -> jax conv_transpose padding.

    Paddle: out = (in-1)*stride + (k-1)*dilation + 1 - 2*pad.  jax's
    ``padding`` pairs pad the stride-dilated input directly, so the full
    transpose of a VALID region needs (k_eff - 1 - p) on each side."""
    return [((k - 1) * d + 1 - 1 - p, (k - 1) * d + 1 - 1 - p)
            for k, p, d in zip(w_spatial, paddings, dilations)]


def _grouped_conv_transpose(x, w, strides, pad, dilations, dn, groups):
    """jax.lax.conv_transpose has no feature_group_count; grouped transpose
    convs split channels (static group count, so XLA still sees G parallel
    convs it can fuse)."""
    if groups <= 1:
        return jax.lax.conv_transpose(
            x, w, strides=strides, padding=pad, rhs_dilation=dilations,
            dimension_numbers=dn, transpose_kernel=True)
    outs = [
        jax.lax.conv_transpose(
            xg, wg, strides=strides, padding=pad, rhs_dilation=dilations,
            dimension_numbers=dn, transpose_kernel=True)
        for xg, wg in zip(jnp.split(x, groups, axis=1),
                          jnp.split(w, groups, axis=0))]
    return jnp.concatenate(outs, axis=1)


@register_op("conv2d_transpose")
def conv2d_transpose(ctx):
    x, w = ctx.input("Input"), ctx.input("Filter")  # w: [C_in, C_out/g, kH, kW]
    strides = _pair(ctx.attr("strides", [1, 1]))
    paddings = _pair(ctx.attr("paddings", [0, 0]))
    dilations = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1) or 1
    pad = _transpose_pad(w.shape[2:], paddings, dilations)
    from ..fluid import amp

    x, w, back = amp.cast_operands(x, w)
    # transpose_kernel=True flips the kernel and swaps its I/O, so the spec
    # labels the kernel post-swap: OIHW for a [C_in, C_out, kH, kW] layout
    out = _grouped_conv_transpose(x, w, strides, pad, dilations,
                                  ("NCHW", "OIHW", "NCHW"), groups)
    return {"Output": amp.restore_astype(out, back)}


def _pool2d_impl(x, ptype, ksize, strides, paddings, exclusive, global_pooling,
                 adaptive=False):
    if global_pooling or (adaptive and list(ksize) == [1, 1]):
        axis = (2, 3)
        out = jnp.max(x, axis, keepdims=True) if ptype == "max" \
            else jnp.mean(x, axis, keepdims=True)
        return out
    window = (1, 1) + tuple(ksize)
    strides_ = (1, 1) + tuple(strides)
    pad = ((0, 0), (0, 0)) + tuple((p, p) for p in paddings)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, strides_, pad)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_, pad)
    if exclusive and any(paddings):
        ones = jnp.ones_like(x)
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides_, pad)
        return s / cnt
    return s / float(np.prod(ksize))


@register_op("pool2d")
def pool2d(ctx):
    x = ctx.input("X")
    out = _pool2d_impl(
        x, ctx.attr("pooling_type", "max"), _pair(ctx.attr("ksize")),
        _pair(ctx.attr("strides", [1, 1])), _pair(ctx.attr("paddings", [0, 0])),
        ctx.attr("exclusive", True), ctx.attr("global_pooling", False),
        ctx.attr("adaptive", False))
    return {"Out": out}


@register_op("batch_norm", no_grad_inputs=("Mean", "Variance"))
def batch_norm(ctx):
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    mean, var = ctx.input("Mean"), ctx.input("Variance")
    momentum = ctx.attr("momentum", 0.9)
    eps = ctx.attr("epsilon", 1e-5)
    is_test = ctx.attr("is_test", False)
    layout = ctx.attr("data_layout", "NCHW")
    if layout == "NCHW":
        axes = tuple(i for i in range(x.ndim) if i != 1)
        bshape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        axes = tuple(range(x.ndim - 1))
        bshape = (1,) * (x.ndim - 1) + (-1,)
    # low-precision inputs (AMP keep-activations regime): statistics and
    # normalization in fp32, output restored to the input dtype — the
    # master-fp32 discipline for norms
    from ..fluid import amp

    low = amp.is_low_float(x.dtype)
    xf = x.astype(jnp.float32) if low else x
    if is_test:
        use_mean, use_var = mean, var
        saved_mean, saved_var = mean, var
        mean_out, var_out = mean, var
    else:
        use_mean = jnp.mean(xf, axes)
        use_var = jnp.var(xf, axes)
        saved_mean, saved_var = use_mean, use_var
        mean_out = momentum * mean + (1.0 - momentum) * use_mean
        var_out = momentum * var + (1.0 - momentum) * use_var
    inv = jax.lax.rsqrt(use_var + eps)
    y = (xf - use_mean.reshape(bshape)) * inv.reshape(bshape) * \
        scale.reshape(bshape) + bias.reshape(bshape)
    if low:
        y = y.astype(x.dtype)
    return {"Y": y, "MeanOut": mean_out, "VarianceOut": var_out,
            "SavedMean": saved_mean, "SavedVariance": inv}


@register_op("layer_norm")
def layer_norm(ctx):
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    axis = ctx.attr("begin_norm_axis", 1)
    eps = ctx.attr("epsilon", 1e-5)
    axes = tuple(range(axis, x.ndim))
    from ..fluid import amp

    low = amp.is_low_float(x.dtype)
    xf = x.astype(jnp.float32) if low else x
    mean = jnp.mean(xf, axes, keepdims=True)
    var = jnp.var(xf, axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    rest = int(np.prod(x.shape[axis:]))
    if scale is not None:
        y = y * scale.reshape((1,) * axis + x.shape[axis:])
    if bias is not None:
        y = y + bias.reshape((1,) * axis + x.shape[axis:])
    if low:
        y = y.astype(x.dtype)
    return {"Y": y, "Mean": mean.reshape(-1), "Variance": var.reshape(-1)}


@register_op("lrn")
def lrn(ctx):
    x = ctx.input("X")  # NCHW
    n = ctx.attr("n", 5)
    k = ctx.attr("k", 2.0)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": x / jnp.power(mid, beta), "MidOut": mid}


def _lookup_ids(ctx):
    ids = ctx.input("Ids").astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    return ids


@register_op("lookup_table", no_grad_inputs=("Ids",))
def lookup_table(ctx):
    w = ctx.input("W")
    ids = _lookup_ids(ctx)
    padding_idx = ctx.attr("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return {"Out": out}


@register_grad("lookup_table")
def lookup_table_grad(ctx):
    """is_sparse=True emits a SelectedRows grad — (occurrence ids, per-
    occurrence rows of dOut) with NO dense [V, D] materialization (ref:
    lookup_table_op.cc LookupTableGradOpDescMaker switches the grad var to
    SELECTED_ROWS on the same attr; sparse consumers scatter instead).
    Dense mode scatter-adds into zeros like the reference's dense kernel."""
    from ..fluid.selected_rows import SelectedRows

    w = ctx.input("W")
    ids = _lookup_ids(ctx)
    dout = ctx.input("Out@GRAD")
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None].astype(dout.dtype)
        dout = dout * mask
    rows = ids.reshape(-1)
    vals = dout.reshape(-1, dout.shape[-1])
    if ctx.attr("is_sparse", False):
        return {"W@GRAD": SelectedRows(rows, vals, height=w.shape[0])}
    dw = jnp.zeros_like(w).at[rows].add(vals.astype(w.dtype))
    return {"W@GRAD": dw}


@register_op("maxout")
def maxout(ctx):
    x = ctx.input("X")  # NCHW
    g = ctx.attr("groups")
    n, c, h, w = x.shape
    return {"Out": jnp.max(x.reshape(n, c // g, g, h, w), axis=2)}


@register_op("im2sequence")
def im2sequence(ctx):
    x = ctx.input("X")  # NCHW
    kernels = ctx.attr("kernels")
    strides = _pair(ctx.attr("strides", [1, 1]))
    paddings = ctx.attr("paddings", [0, 0, 0, 0])
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (paddings[0], paddings[2]),
                     (paddings[1], paddings[3])))
    kh, kw = kernels
    oh = (xp.shape[2] - kh) // strides[0] + 1
    ow = (xp.shape[3] - kw) // strides[1] + 1
    patches = jax.lax.conv_general_dilated_patches(
        xp, (kh, kw), strides, padding=[(0, 0), (0, 0)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, oh, ow] -> [N*oh*ow, C*kh*kw]
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    return {"Out": out}


@register_op("group_norm")
def group_norm(ctx):
    x = ctx.input("X")  # NCHW
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    groups = ctx.attr("groups")
    eps = ctx.attr("epsilon", 1e-5)
    n, c = x.shape[:2]
    xg = x.reshape((n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axes, keepdims=True)
    var = jnp.var(xg, axes, keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return {"Y": y, "Mean": mean.reshape(n, groups), "Variance": var.reshape(n, groups)}


@register_op("spp")
def spp(ctx):
    """Spatial pyramid pooling (ref: spp_op.*)."""
    x = ctx.input("X")
    levels = ctx.attr("pyramid_height")
    ptype = ctx.attr("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for l in range(levels):
        bins = 2 ** l
        kh, kw = -(-h // bins), -(-w // bins)
        sh, sw = kh, kw
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        o = _pool2d_impl(x, ptype, [kh, kw], [sh, sw], [ph, pw], False, False)
        outs.append(o.reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}


# ---------------------------------------------------------------------------
# 3-D / indexed pooling, unpool, conv3d_transpose (ref: pool_op.* Pool3D,
# pool_with_index_op.*, unpool_op.*, conv_transpose_op.* Conv3DTranspose)
# ---------------------------------------------------------------------------


def _tuple_n(v, n):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


@register_op("pool3d")
def pool3d(ctx):
    x = ctx.input("X")  # NCDHW
    ptype = ctx.attr("pooling_type", "max")
    ksize = _tuple_n(ctx.attr("ksize"), 3)
    strides = _tuple_n(ctx.attr("strides", [1, 1, 1]), 3)
    paddings = _tuple_n(ctx.attr("paddings", [0, 0, 0]), 3)
    if ctx.attr("global_pooling", False):
        axis = (2, 3, 4)
        out = jnp.max(x, axis, keepdims=True) if ptype == "max" \
            else jnp.mean(x, axis, keepdims=True)
        return {"Out": out}
    window = (1, 1) + tuple(ksize)
    strides_ = (1, 1) + tuple(strides)
    pad = ((0, 0), (0, 0)) + tuple((p, p) for p in paddings)
    if ptype == "max":
        return {"Out": jax.lax.reduce_window(x, -jnp.inf, jax.lax.max,
                                             window, strides_, pad)}
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides_, pad)
    if ctx.attr("exclusive", True) and any(paddings):
        cnt = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                    window, strides_, pad)
        return {"Out": s / cnt}
    return {"Out": s / float(np.prod(ksize))}


def _pool_with_index(x, ksize, strides, paddings):
    """Max pool that also returns the argmax's flat position in the input
    plane (ref pool_with_index_op.h: mask index = h * W + w)."""
    spatial = x.shape[2:]
    nd = len(spatial)
    # flat index grid of the input plane, same spatial shape as x — int32
    # (exact for any realistic plane; float would corrupt indices > 2^24)
    flat = jnp.arange(int(np.prod(spatial)), dtype=jnp.int32).reshape(spatial)
    flat = jnp.broadcast_to(flat, x.shape)
    window = (1, 1) + tuple(ksize)
    strides_ = (1, 1) + tuple(strides)
    pad = ((0, 0), (0, 0)) + tuple((p, p) for p in paddings)

    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    out, idx = jax.lax.reduce_window(
        (x, flat),
        (jnp.asarray(-jnp.inf, x.dtype), jnp.asarray(-1, jnp.int32)),
        lambda a, b: sel(a, b), window, strides_, pad)
    return out, idx.astype(jnp.int64)


@register_op("max_pool2d_with_index", no_grad_inputs=())
def max_pool2d_with_index(ctx):
    x = ctx.input("X")
    out, idx = _pool_with_index(
        x, _tuple_n(ctx.attr("ksize"), 2),
        _tuple_n(ctx.attr("strides", [1, 1]), 2),
        _tuple_n(ctx.attr("paddings", [0, 0]), 2))
    return {"Out": out, "Mask": idx}


@register_op("max_pool3d_with_index", no_grad_inputs=())
def max_pool3d_with_index(ctx):
    x = ctx.input("X")
    out, idx = _pool_with_index(
        x, _tuple_n(ctx.attr("ksize"), 3),
        _tuple_n(ctx.attr("strides", [1, 1, 1]), 3),
        _tuple_n(ctx.attr("paddings", [0, 0, 0]), 3))
    return {"Out": out, "Mask": idx}


def _pool_with_index_grad(ctx):
    """Scatter dOut back to each window's argmax position (works for any
    spatial rank — the Mask holds flat plane indices).  Explicit because
    the tuple-carrying reduce_window in the forward has no generic vjp."""
    x = ctx.input("X")
    idx = ctx.input("Mask")
    dout = ctx.input("Out@GRAD")
    n, c = x.shape[:2]
    plane = int(np.prod(x.shape[2:]))
    dx = jnp.zeros((n, c, plane), x.dtype)
    flat_idx = idx.reshape(n, c, -1).astype(jnp.int64)
    dx = dx.at[jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
               flat_idx].add(dout.reshape(n, c, -1))
    return {"X@GRAD": dx.reshape(x.shape)}


register_grad("max_pool2d_with_index")(_pool_with_index_grad)
register_grad("max_pool3d_with_index")(_pool_with_index_grad)


@register_op("unpool", no_grad_inputs=("Indices",))
def unpool(ctx):
    """ref: unpool_op.* (max unpooling): scatter each pooled value back to
    the position its max came from."""
    x = ctx.input("X")             # [N, C, h, w]
    indices = ctx.input("Indices")  # same shape, flat positions in H*W
    out_h, out_w = ctx.attr("unpooled_height"), ctx.attr("unpooled_width")
    if not out_h or not out_w:
        ksize = _tuple_n(ctx.attr("ksize"), 2)
        strides = _tuple_n(ctx.attr("strides", [2, 2]), 2)
        out_h = (x.shape[2] - 1) * strides[0] + ksize[0]
        out_w = (x.shape[3] - 1) * strides[1] + ksize[1]
    n, c = x.shape[:2]
    out = jnp.zeros((n, c, out_h * out_w), x.dtype)
    flat_idx = indices.reshape(n, c, -1).astype(jnp.int64)
    out = out.at[jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
                 flat_idx].add(x.reshape(n, c, -1))
    return {"Out": out.reshape(n, c, out_h, out_w)}


@register_op("conv3d_transpose")
def conv3d_transpose(ctx):
    x, w = ctx.input("Input"), ctx.input("Filter")  # w: [C_in, C_out, kD, kH, kW]
    strides = _tuple_n(ctx.attr("strides", [1, 1, 1]), 3)
    paddings = _tuple_n(ctx.attr("paddings", [0, 0, 0]), 3)
    dilations = _tuple_n(ctx.attr("dilations", [1, 1, 1]), 3)
    groups = ctx.attr("groups", 1) or 1
    pad = _transpose_pad(w.shape[2:], paddings, dilations)
    from ..fluid import amp

    x, w, back = amp.cast_operands(x, w)
    # kernel layout [C_in, C_out, kD, kH, kW]; with transpose_kernel=True
    # the spec labels the kernel AFTER its I/O swap, hence OIDHW
    out = _grouped_conv_transpose(x, w, strides, pad, dilations,
                                  ("NCDHW", "OIDHW", "NCDHW"), groups)
    return {"Output": amp.restore_astype(out, back)}


# ---------------------------------------------------------------------------
# print op (ref: print_op.cc — debugging passthrough with host logging)
# ---------------------------------------------------------------------------


@register_op("print")
def print_op(ctx):
    x = ctx.input("In")
    message = ctx.attr("message", "") or ""
    first_n = ctx.attr("first_n", -1)
    fmt = []
    if ctx.attr("print_tensor_name", True):
        fmt.append(message)
    if ctx.attr("print_tensor_shape", True):
        fmt.append(f"shape={tuple(x.shape)}")
    if ctx.attr("print_tensor_dtype", True):
        fmt.append(f"dtype={x.dtype}")
    prefix = " ".join(fmt)
    # jax.debug.callback survives jit: the host callback fires per
    # execution.  The first_n counter must outlive one op invocation (eager
    # islands re-run the impl every step), so it keys off the op's attr
    # dict, which is one stable object per Program op.
    counter = _PRINT_COUNTS.setdefault(id(ctx.attrs), [0])

    summarize = ctx.attr("summarize", 20)
    if summarize is None or int(summarize) <= 0:
        summarize = 20

    def _cb(arr, transforms=None):
        if first_n is None or first_n < 0 or counter[0] < first_n:
            counter[0] += 1
            print(f"{prefix} "
                  f"values={np.asarray(arr).reshape(-1)[:int(summarize)]}")

    jax.debug.callback(_cb, x)
    return {"Out": x}


_PRINT_COUNTS: dict = {}


@register_op("scale_sub_region", no_grad_inputs=("Indices",))
def scale_sub_region(ctx):
    """ref: legacy ScaleSubRegionLayer (v2 scale_sub_region_layer) —
    multiply a per-sample [C, H, W] sub-box by ``scale``.  Indices rows
    are the reference's 1-based inclusive (c1, c2, h1, h2, w1, w2)."""
    x = ctx.input("X")              # [N, C, H, W]
    ind = ctx.input("Indices").astype(jnp.float32)  # [N, 6]
    scale = float(ctx.attr("scale", 1.0))
    n, c, h, w = x.shape
    cg = jnp.arange(c, dtype=jnp.float32)[None, :, None, None]
    hg = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    wg = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    lo = ind[:, 0::2] - 1.0         # [N, 3] zero-based lower bounds
    hi = ind[:, 1::2] - 1.0
    mask = ((cg >= lo[:, 0, None, None, None])
            & (cg <= hi[:, 0, None, None, None])
            & (hg >= lo[:, 1, None, None, None])
            & (hg <= hi[:, 1, None, None, None])
            & (wg >= lo[:, 2, None, None, None])
            & (wg <= hi[:, 2, None, None, None]))
    return {"Out": jnp.where(mask, x * scale, x)}
