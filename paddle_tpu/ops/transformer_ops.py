"""Stacked transformer encoder/decoder ops (parallel/transformer_stack.py).

Mesh-aware like ring_attention/gpipe_mlp_stack: traced under a mesh the
stack runs GPipe over "pp", Megatron TP over "mp" and ring attention over
"sp"; single-device it is a lax.scan over layers — mathematically identical,
so programs are portable across places (the portability contract the
reference gives ops via per-place kernels, op_registry.h OpKernelType).

Gradients: the forward consumes threaded RNG (residual dropout), so the
generic vjp (registry.py) cannot replay it.  The forward therefore emits the
key it used as an extra output (RngKey) and the explicit grad impl re-runs
the stack under jax.vjp with that exact key — same masks, exact gradients;
XLA CSEs the recomputed forward away.  (Same pattern as dropout's saved
Mask, ref dropout_op.h DropoutGradKernel, scaled up to a whole block.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, register_grad


def _collect(ctx, slots):
    return {s: ctx.input(s) for s in slots}


def _stack_args(ctx, decoder):
    from ..parallel import spmd
    from ..parallel import transformer_stack as ts

    from . import attention_ops

    slots = ts.DECODER_SLOTS if decoder else ts.ENCODER_SLOTS
    params = _collect(ctx, slots)
    flash_req = int(ctx.attr("flash", -1))
    return dict(
        kind="dec" if decoder else "enc",
        enc=ctx.input("EncOut") if decoder else None,
        bias=ctx.input("Bias") if ctx.has_input("Bias") else None,
        params=params,
        n_head=int(ctx.attr("n_head")),
        dropout=float(ctx.attr("dropout", 0.0)),
        is_test=bool(ctx.attr("is_test", False)),
        n_micro=int(ctx.attr("n_microbatches", 4)),
        recompute=bool(ctx.attr("recompute", False)),
        flash=attention_ops._flash_decision(flash_req),
        mesh=spmd.active_mesh(),
    )


def _forward(ctx, decoder):
    from ..parallel import transformer_stack as ts

    a = _stack_args(ctx, decoder)
    x = ctx.input("X")
    if a["dropout"] and not a["is_test"]:
        key = ctx.rng()
    else:
        key = jnp.zeros((2,), jnp.uint32)
    out = ts.stack_apply(a["kind"], x, a["enc"], a["bias"], a["params"],
                         key, n_head=a["n_head"], dropout=a["dropout"],
                         is_test=a["is_test"], n_micro=a["n_micro"],
                         mesh=a["mesh"], recompute=a["recompute"],
                         flash=a["flash"])
    return {"Out": out, "RngKey": key}


def _backward(ctx, decoder):
    from ..parallel import transformer_stack as ts

    a = _stack_args(ctx, decoder)
    x = ctx.input("X")
    key = ctx.input("RngKey")
    gout = ctx.input("Out@GRAD")

    if decoder:
        def f(xx, ee, pp):
            return ts.stack_apply(a["kind"], xx, ee, a["bias"], pp, key,
                                  n_head=a["n_head"], dropout=a["dropout"],
                                  is_test=a["is_test"], n_micro=a["n_micro"],
                                  mesh=a["mesh"], recompute=a["recompute"],
                                  flash=a["flash"])

        _, vjp = jax.vjp(f, x, a["enc"], a["params"])
        gx, genc, gparams = vjp(gout)
        res = {"X@GRAD": gx, "EncOut@GRAD": genc}
    else:
        def f(xx, pp):
            return ts.stack_apply(a["kind"], xx, None, a["bias"], pp, key,
                                  n_head=a["n_head"], dropout=a["dropout"],
                                  is_test=a["is_test"], n_micro=a["n_micro"],
                                  mesh=a["mesh"], recompute=a["recompute"],
                                  flash=a["flash"])

        _, vjp = jax.vjp(f, x, a["params"])
        gx, gparams = vjp(gout)
        res = {"X@GRAD": gx}
    for slot, g in gparams.items():
        res[slot + "@GRAD"] = g
    return res


@register_op("transformer_encoder_stack", stateful=True,
             no_grad_inputs=("Bias",))
def transformer_encoder_stack_op(ctx):
    return _forward(ctx, decoder=False)


@register_grad("transformer_encoder_stack")
def transformer_encoder_stack_grad(ctx):
    return _backward(ctx, decoder=False)


@register_op("transformer_decoder_stack", stateful=True,
             no_grad_inputs=("Bias",))
def transformer_decoder_stack_op(ctx):
    return _forward(ctx, decoder=True)


@register_grad("transformer_decoder_stack")
def transformer_decoder_stack_grad(ctx):
    return _backward(ctx, decoder=True)
