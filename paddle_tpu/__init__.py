"""paddle_tpu: a TPU-native deep-learning framework with the PaddlePaddle
Fluid API surface (reference: Operater9/Paddle @ Fluid 0.15).

Compute path: programs built through ``paddle_tpu.fluid`` trace into XLA
computations (jit/pjit); parallelism is SPMD over a ``jax.sharding.Mesh``
with collectives over ICI.  See SURVEY.md for the layer-by-layer mapping.
"""

__version__ = "0.6.0"

# Fluid's dtype contract is 64-bit-heavy (labels/ids are int64, VarDesc
# promises int64/float64 kinds — ref framework.proto:104), and jax's default
# 32-bit mode silently truncates int64 to int32 with a UserWarning per op.
# Enable x64 so ops emit what their VarDesc promises.  NOTE: this is a
# process-global jax config change, the same stance the reference takes with
# its own global flag init at import (ref python/paddle/fluid/__init__.py:
# 121-140 init_gflags) — other jax code in the process will see 64-bit
# defaults for dtype-less constructors.  Inside this package, float ctors
# pin their dtype explicitly (f32 stays f32); int ctors intentionally
# produce int64, matching the VarDesc contract.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

from . import fluid  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401

batch = reader.batch
