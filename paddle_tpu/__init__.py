"""paddle_tpu: a TPU-native deep-learning framework with the PaddlePaddle
Fluid API surface (reference: Operater9/Paddle @ Fluid 0.15).

Compute path: programs built through ``paddle_tpu.fluid`` trace into XLA
computations (jit/pjit); parallelism is SPMD over a ``jax.sharding.Mesh``
with collectives over ICI.  See SURVEY.md for the layer-by-layer mapping.
"""

__version__ = "0.1.0"

from . import fluid  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401

batch = reader.batch
