"""v2 structural type aliases.  The reference's LayerOutput (config_base
.py) is the handle every layer helper returns; on this substrate the
handle IS the fluid Variable, so the name is a true alias — isinstance
checks in ported configs keep working."""

from ..fluid.framework import Variable as LayerOutput  # noqa: F401

__all__ = ["LayerOutput"]
