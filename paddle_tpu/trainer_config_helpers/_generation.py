"""v2 beam-search generation facade (ref: python/paddle/trainer_config_
helpers/layers.py beam_search / GeneratedInput / StaticInput; usage:
demo/seqToseq gen).  The v2 contract: the SAME step function that trained
inside recurrent_group drives generation — each step receives the static
inputs plus the embedding of the previously generated token, and returns
the vocab softmax; memory() state is carried across steps and beams.

Here the facade lowers onto the fluid contrib decoder machinery
(fluid/contrib/decoder/beam_search_decoder.py): a discovery pass records
the step's memory() declarations, a StateCell carries them (plus the
score), and a custom BeamSearchDecoder.decode() loop feeds the previous
token's embedding back in — the step's own softmax scores the beams (the
base decoder would add a second projection).  `paddle_tpu.v2.inference
.infer` recognises the returned GenerationResult and auto-feeds the
bos-seeded init tensors.
"""

from __future__ import annotations

import numpy as np

from ..fluid import layers as _fl
from ..fluid import unique_name
from ..fluid.contrib.decoder import BeamSearchDecoder, InitState, StateCell

__all__ = ["StaticInput", "GeneratedInput", "BaseGeneratedInput",
           "beam_search", "GenerationResult"]


class StaticInput:
    """A per-source input replayed at every generation step (expanded to
    the live beam width by the decoder)."""

    def __init__(self, input, is_seq=False, size=None):
        self.input = input
        self.is_seq = bool(is_seq)
        self.size = size


class BaseGeneratedInput:
    """Base marker for generated inputs (ref layers.py
    BaseGeneratedInput)."""


class GeneratedInput(BaseGeneratedInput):
    """The fed-back token: embedding of the previous step's output.
    ``embedding_name`` shares the parameter with the training-time target
    embedding so trained weights drive generation."""

    def __init__(self, size, embedding_name=None, embedding_size=None):
        self.size = int(size)                  # vocab size
        self.embedding_name = embedding_name
        self.embedding_size = int(embedding_size or 0)


class GenerationResult:
    """What beam_search returns: the decode program's output vars plus
    the init-feed contract (consumed by paddle_tpu.v2.inference.infer)."""

    def __init__(self, ids, scores, init_ids_name, init_scores_name,
                 bos_id, eos_id, beam_size, n_results=0):
        self.ids = ids
        self.scores = scores
        self.init_ids_name = init_ids_name
        self.init_scores_name = init_scores_name
        self.bos_id = int(bos_id)
        self.eos_id = int(eos_id)
        self.beam_size = int(beam_size)
        self.n_results = int(n_results or 0)  # 0 = all beam_size hyps

    @property
    def block(self):  # duck-type Variable enough for program lookup
        return self.ids.block

    def init_feeds(self, batch_size):
        """The bos-seeded [N*1] lod2 init tensors the loop starts from."""
        from ..fluid import create_lod_tensor
        lod2 = [[1] * batch_size, [1] * batch_size]
        ids = create_lod_tensor(
            np.full((batch_size, 1), self.bos_id, np.int64), lod2)
        scores = create_lod_tensor(
            np.zeros((batch_size, 1), np.float32), lod2)
        return {self.init_ids_name: ids, self.init_scores_name: scores}


def _discover_memories(step, arg_builders):
    """Run the step once in a throwaway program (fresh unique-name scope,
    so the real build's parameter names are untouched) to learn which
    memories it declares: [(name, size, has_boot)]."""
    from . import _set_gen_ctx
    from ..fluid import framework

    mems = []

    def read_state(name, size, boot):
        mems.append((name, int(size), boot))
        return _fl.fill_constant(shape=[1, int(size)], dtype="float32",
                                 value=0.0)

    scratch_main, scratch_startup = framework.Program(), framework.Program()
    with unique_name.guard():
        with framework.program_guard(scratch_main, scratch_startup):
            ctx = _set_gen_ctx(read_state)
            try:
                step(*[b() for b in arg_builders])
            finally:
                _set_gen_ctx(None, restore=ctx)
    return mems


class _V2BeamSearchDecoder(BeamSearchDecoder):
    """The base loop, except the cell's own softmax scores the beams (v2
    step functions return the vocab distribution themselves) and the
    fed-back embedding can share the training-time parameter by name."""

    def __init__(self, *args, emb_param_name=None, **kw):
        self._emb_param_name = emb_param_name
        super().__init__(*args, **kw)

    def decode(self):
        cell = self._state_cell
        with self.block():
            prev_ids = self.read_array(init=self._init_ids, is_ids=True)
            prev_scores = self.read_array(init=self._init_scores,
                                          is_scores=True)
            prev_emb = _fl.embedding(
                prev_ids, size=[self._target_dict_dim, self._word_dim],
                dtype="float32", is_sparse=self._sparse_emb,
                param_attr=self._emb_param_name)

            feeds = {}
            tracked_inputs = {}
            for name, var in self._input_var_dict.items():
                stored = self.read_array(init=var)
                tracked_inputs[name] = stored
                feeds[name] = _fl.sequence_expand(stored, prev_scores)
            for name in cell._inputs:
                if name not in feeds:
                    feeds[name] = prev_emb
            for sname in cell._init_states:
                cell.set_state(
                    sname,
                    _fl.sequence_expand(cell.get_state(sname),
                                        prev_scores))

            cell.compute_state(inputs=feeds)
            # the step's own softmax IS the score — no extra projection
            prob = _fl.lod_reset(x=cell.out_state(), y=prev_scores)
            topk_scores, topk_indices = _fl.topk(prob, k=self._topk_size)
            accu = _fl.elementwise_add(
                x=_fl.log(topk_scores),
                y=_fl.reshape(prev_scores, shape=[-1]), axis=0)
            sel_ids, sel_scores = _fl.beam_search(
                prev_ids, prev_scores, topk_indices, accu,
                self._beam_size, end_id=self._end_id, level=0)

            with _fl.Switch() as switch:
                with switch.case(_fl.is_empty(sel_ids)):
                    self.early_stop()
                with switch.default():
                    cell.update_states()
                    self.update_array(prev_ids, sel_ids)
                    self.update_array(prev_scores, sel_scores)
                    for name, stored in tracked_inputs.items():
                        self.update_array(stored, feeds[name])


def beam_search(step, input, bos_id, eos_id, beam_size=5, max_length=500,
                num_results_per_sample=None, name=None):
    """ref layers.py beam_search: generate with the training step
    function.  ``input`` mixes StaticInput wrappers and exactly one
    GeneratedInput; returns a GenerationResult for v2 inference."""
    from . import _set_gen_ctx

    ins = list(input) if isinstance(input, (list, tuple)) else [input]
    gens = [i for i in ins if isinstance(i, GeneratedInput)]
    if len(gens) != 1:
        raise ValueError("beam_search needs exactly one GeneratedInput "
                         f"among its inputs, got {len(gens)}")
    gen = gens[0]
    if not gen.embedding_size:
        raise ValueError("GeneratedInput needs embedding_size")
    if not gen.embedding_name:
        raise ValueError(
            "GeneratedInput needs embedding_name (the training-time "
            "target-embedding parameter name) — without it generation "
            "would embed tokens with fresh random weights")
    prefix = name or unique_name.generate("v2_beam")

    init_ids = _fl.data(name=f"{prefix}_init_ids", shape=[1],
                        dtype="int64", lod_level=2)
    init_scores = _fl.data(name=f"{prefix}_init_scores", shape=[1],
                           dtype="float32", lod_level=2)

    # positional arg builders for the discovery pass (dummies for the
    # generated word; the real static vars only lend their shapes)
    arg_builders = []
    static_names = {}
    for idx, item in enumerate(ins):
        if isinstance(item, GeneratedInput):
            arg_builders.append(
                lambda g=gen: _fl.fill_constant(
                    shape=[1, g.embedding_size], dtype="float32",
                    value=0.0))
        else:
            v = item.input if isinstance(item, StaticInput) else item
            static_names[idx] = f"static_{idx}"
            arg_builders.append(lambda v=v: v)
    mems = _discover_memories(step, arg_builders)
    if not mems:
        raise ValueError("the step function declares no memory(); "
                         "beam_search needs recurrent state to carry")

    # cell states: every memory + the score the step returns
    states = {}
    for mname, msize, boot in mems:
        if boot is not None:
            states[mname] = InitState(init=boot, need_reorder=True)
        else:
            states[mname] = InitState(init=_fl.fill_constant_batch_size_like(
                input=init_scores, shape=[-1, msize], dtype="float32",
                value=0.0))
    states["__score__"] = InitState(init=_fl.fill_constant_batch_size_like(
        input=init_scores, shape=[-1, gen.size], dtype="float32",
        value=0.0))

    cell_inputs = {n: None for n in static_names.values()}
    cell_inputs["__word__"] = None
    cell = StateCell(inputs=cell_inputs, states=states,
                     out_state="__score__")
    mem_names = [m[0] for m in mems]

    @cell.state_updater
    def updater(c):
        def read_state(sname, size, boot):
            return c.get_state(sname)

        ctx = _set_gen_ctx(read_state)
        try:
            args = []
            for idx, item in enumerate(ins):
                if isinstance(item, GeneratedInput):
                    args.append(c.get_input("__word__"))
                else:
                    args.append(c.get_input(static_names[idx]))
            prob = step(*args)
            from . import _current_gen_named
            named = _current_gen_named()
            for mname in mem_names:
                tgt = named.get(mname)
                if tgt is None:
                    raise ValueError(
                        f"memory(name={mname!r}) has no layer of that "
                        f"name in the step function to link to")
                c.set_state(mname, tgt)
        finally:
            _set_gen_ctx(None, restore=ctx)
        c.set_state("__score__", prob)

    input_var_dict = {static_names[i]: (ins[i].input
                                        if isinstance(ins[i], StaticInput)
                                        else ins[i])
                      for i in static_names}
    bsd = _V2BeamSearchDecoder(
        cell, init_ids, init_scores, target_dict_dim=gen.size,
        word_dim=gen.embedding_size, input_var_dict=input_var_dict,
        topk_size=min(gen.size, max(50, int(beam_size))),
        sparse_emb=False,
        max_len=int(max_length), beam_size=int(beam_size),
        end_id=int(eos_id), emb_param_name=gen.embedding_name)
    bsd.decode()
    out_ids, out_scores = bsd()
    return GenerationResult(out_ids, out_scores,
                            init_ids.name, init_scores.name,
                            bos_id=bos_id, eos_id=eos_id,
                            beam_size=beam_size,
                            n_results=num_results_per_sample)
