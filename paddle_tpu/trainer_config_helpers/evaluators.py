"""v2 evaluator DSL (ref: python/paddle/trainer_config_helpers/
evaluators.py — evaluator_base:71 attaches Evaluator config entries that
the swig GradientMachine evaluates each batch/pass).

Redesign: there is no separate evaluator machine — each evaluator lowers
to Fluid metric ops INSIDE the same program (accuracy/auc/edit_distance/
chunk_eval/precision_recall), and registers its output variable so the v2
trainer fetches it alongside the cost and reports it on
EndIteration/EndPass events (paddle_tpu.v2.trainer).  The declarative
call-it-and-forget-it surface of the reference is preserved.
"""

from __future__ import annotations

from typing import List, Tuple

__all__ = [
    "classification_error_evaluator", "auc_evaluator", "pnpair_evaluator",
    "precision_recall_evaluator", "ctc_error_evaluator", "chunk_evaluator",
    "sum_evaluator", "column_sum_evaluator", "value_printer_evaluator",
    "get_evaluators", "reset_evaluators",
]

# (name, fluid Variable, cumulative) registered in declaration order; the
# v2 trainer fetches every entry belonging to the program it runs.
# cumulative=True marks evaluators whose fetched value is already a
# running accumulation across batches (stateful persistables, e.g. auc) —
# the pass-level report takes the LAST value, not the batch mean.
_EVALUATORS: List[Tuple[str, object, bool]] = []


def get_evaluators():
    return list(_EVALUATORS)


def reset_evaluators():
    del _EVALUATORS[:]


def _register(name, default, var, cumulative=False):
    base = name or default
    taken = {n for n, _, _ in _EVALUATORS}
    unique = base
    i = 0
    while unique in taken:  # two same-type evaluators must not collide
        i += 1
        unique = f"{base}_{i}"
    _EVALUATORS.append((unique, var, cumulative))
    return var


def _as_label(label):
    from . import _as_label as base_as_label

    return base_as_label(label)


def classification_error_evaluator(input, label, name=None, top_k=1,
                                   **kwargs):
    """ref evaluators.py:220 — error rate = 1 - top-k accuracy."""
    from ..fluid import layers

    acc = layers.accuracy(input=input, label=_as_label(label), k=top_k)
    err = layers.elementwise_sub(layers.fill_constant([1], "float32", 1.0),
                                 acc)
    return _register(name, "classification_error_evaluator", err)


def auc_evaluator(input, label, name=None, **kwargs):
    """ref evaluators.py:272 — ROC-AUC over the positive-class score.
    Stateful across batches (StatPos/StatNeg persistables accumulate),
    like the reference's pass-level AUC."""
    from ..fluid import layers

    auc_out, *_ = layers.auc(input=input, label=_as_label(label))
    return _register(name, "auc_evaluator", auc_out, cumulative=True)


def pnpair_evaluator(input, label, query_id, weight=None, name=None,
                     **kwargs):
    """ref evaluators.py:306 — positive/negative pair ordering stat per
    query group; reports the pos/neg ratio (the reference's headline)."""
    from ..fluid import layers
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("pnpair_evaluator")
    pos = helper.create_variable_for_type_inference("float32",
                                                    stop_gradient=True)
    neg = helper.create_variable_for_type_inference("float32",
                                                    stop_gradient=True)
    neu = helper.create_variable_for_type_inference("float32",
                                                    stop_gradient=True)
    inputs = {"Score": [input], "Label": [_as_label(label)],
              "QueryID": [query_id]}
    if weight is not None:
        inputs["Weight"] = [weight]
    helper.append_op(type="positive_negative_pair", inputs=inputs,
                     outputs={"PositivePair": [pos], "NegativePair": [neg],
                              "NeutralPair": [neu]})
    ratio = layers.elementwise_div(
        pos, layers.elementwise_max(
            neg, layers.fill_constant([1], "float32", 1.0)))
    return _register(name, "pnpair_evaluator", ratio)


def precision_recall_evaluator(input, label, positive_label=None,
                               weight=None, name=None, **kwargs):
    """ref evaluators.py:353 — reports macro-F1 (BatchMetrics[2]);
    positive_label restricts to one class in the reference, here the
    macro average is reported either way."""
    from ..fluid import layers
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("precision_recall_evaluator")
    probs, idx = layers.topk(input, k=1)
    batch = helper.create_variable_for_type_inference("float64",
                                                      stop_gradient=True)
    accum = helper.create_variable_for_type_inference("float64",
                                                      stop_gradient=True)
    states = helper.create_variable_for_type_inference("float32",
                                                       stop_gradient=True)
    inputs = {"MaxProbs": [probs], "Indices": [idx],
              "Labels": [_as_label(label)]}
    if weight is not None:
        inputs["Weights"] = [weight]
    helper.append_op(type="precision_recall", inputs=inputs,
                     outputs={"BatchMetrics": [batch],
                              "AccumMetrics": [accum],
                              "AccumStatesInfo": [states]},
                     attrs={"class_number": int(input.shape[-1])})
    f1 = layers.slice(batch, axes=[0], starts=[2], ends=[3])
    return _register(name, "precision_recall_evaluator", f1)


def ctc_error_evaluator(input, label, name=None, **kwargs):
    """ref evaluators.py:398 — normalized edit distance between the CTC
    best path and the label sequence."""
    from ..fluid import layers

    dist, _ = layers.edit_distance(input=input, label=label,
                                   normalized=True)
    return _register(name, "ctc_error_evaluator", layers.mean(dist))


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types,
                    name=None, excluded_chunk_types=None, **kwargs):
    """ref evaluators.py:425 — chunking F1 (IOB/IOE/IOBES schemes)."""
    from ..fluid import layers

    precision, recall, f1, *_ = layers.chunk_eval(
        input=input, label=label, chunk_scheme=chunk_scheme,
        num_chunk_types=num_chunk_types,
        excluded_chunk_types=excluded_chunk_types)
    return _register(name, "chunk_evaluator", f1)


def sum_evaluator(input, name=None, weight=None, **kwargs):
    """ref evaluators.py:532 — sum of the input over the batch."""
    from ..fluid import layers

    val = input if weight is None else layers.elementwise_mul(input, weight)
    return _register(name, "sum_evaluator", layers.reduce_sum(val))


def column_sum_evaluator(input, name=None, weight=None, **kwargs):
    """ref evaluators.py:558 — per-column sum over the batch dim."""
    from ..fluid import layers

    val = input if weight is None else layers.elementwise_mul(input, weight)
    return _register(name, "column_sum_evaluator",
                     layers.reduce_sum(val, dim=0))


def value_printer_evaluator(input, name=None, **kwargs):
    """ref evaluators.py:589 — print the layer value each evaluation."""
    from ..fluid import layers

    return layers.Print(input, message=name or "value_printer")
