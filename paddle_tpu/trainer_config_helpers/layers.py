"""Import-path compatibility: the reference exposes
``paddle.trainer_config_helpers.layers``; every helper lives in the
package root here (one substrate), so this module re-exports the layer
surface — everything except the activation/pooling markers, optimizer and
settings machinery, and evaluators, which have their own modules."""
from . import *  # noqa: F401,F403
from . import __all__ as _pkg_all

_NON_LAYER_SUFFIXES = ("Activation", "Pooling", "Optimizer", "_evaluator")
_NON_LAYER = {
    "settings", "get_settings", "outputs", "get_outputs",
    "set_config_args", "get_config_arg", "define_py_data_sources2",
    "build_settings_optimizer", "L2Regularization", "ExtraAttr",
    "ParamAttr", "get_evaluators", "reset_evaluators",
}

__all__ = [n for n in _pkg_all
           if not n.endswith(_NON_LAYER_SUFFIXES) and n not in _NON_LAYER]
