"""Import-path compatibility for the reference's
``paddle.trainer_config_helpers.networks`` composites."""
from . import (bidirectional_lstm, img_conv_group,  # noqa: F401
               sequence_conv_pool, simple_attention, simple_gru,
               simple_img_conv_pool, simple_lstm, vgg_16_network)

__all__ = ["simple_lstm", "bidirectional_lstm", "simple_gru",
           "simple_img_conv_pool", "img_conv_group", "simple_attention",
           "sequence_conv_pool", "vgg_16_network"]
