"""Import-path compatibility for the reference's poolings module."""
from . import (AvgPooling, CudnnAvgPooling, CudnnMaxPooling,  # noqa: F401
               MaxPooling, SquareRootNPooling, SumPooling)
