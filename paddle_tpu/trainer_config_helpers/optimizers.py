"""Import-path compatibility for the reference's optimizers module."""
from . import (AdamOptimizer, L2Regularization,  # noqa: F401
               MomentumOptimizer, settings)
