"""Import-path compatibility for the reference's attrs module."""
from . import ExtraAttr, ExtraLayerAttribute, ParamAttr  # noqa: F401

ParameterAttribute = ParamAttr
