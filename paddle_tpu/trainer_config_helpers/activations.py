"""Import-path compatibility for the reference's activations module."""
from . import (AbsActivation, BReluActivation, ExpActivation,  # noqa: F401
               IdentityActivation, LinearActivation, LogActivation,
               ReciprocalActivation, ReluActivation, SigmoidActivation,
               SoftReluActivation, SoftmaxActivation, SqrtActivation,
               SquareActivation, STanhActivation, TanhActivation)
