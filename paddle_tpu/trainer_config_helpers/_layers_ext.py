"""Extended v2 layer surface (ref: python/paddle/trainer_config_helpers/
layers.py __all__, 118 names).  Each helper lowers onto the Fluid layer
library exactly like the core set in __init__.py — one substrate, two
front ends.  Helpers follow the reference's v2 conventions: costs return
batch-mean scalars, image layers recover NCHW geometry from flat data
layers, and projection/operator markers are consumed by mixed_layer.

The v2 beam-generation machinery (beam_search / GeneratedInput /
StaticInput) lives in _generation.py, lowered onto the contrib decoder.
Deliberately absent (documented, not stubbed): beam-aware TRAINING
(BeamInput / cross_entropy_over_beam / SubsequenceInput) — raises a
clear error naming the replacement.
"""

from __future__ import annotations

from ..fluid import layers
from ..fluid.layer_helper import LayerHelper
from ..fluid.param_attr import ParamAttr as _FluidParamAttr
from . import (LinearActivation, ReluActivation,
               SigmoidActivation, TanhActivation, _act_name, _default_act,
               _param_name, _register_named, _to_nchw, _to_spatial)

__all__ = [
    # math / elementwise
    "cos_sim", "dot_prod_layer", "out_prod_layer", "l2_distance_layer",
    "interpolation_layer", "power_layer", "scaling_layer",
    "slope_intercept_layer", "sum_to_one_norm_layer", "row_l2_norm_layer",
    "clip_layer", "scale_shift_layer", "prelu_layer", "gated_unit_layer",
    "tensor_layer", "factorization_machine", "maxid_layer",
    "sampling_id_layer", "multiplex_layer", "eos_layer", "print_layer",
    "printer_layer", "get_output_layer",
    # sequence
    "expand_layer", "repeat_layer", "seq_concat_layer",
    "seq_reshape_layer", "seq_slice_layer", "sub_seq_layer",
    "block_expand_layer", "row_conv_layer", "kmax_seq_score_layer",
    # costs
    "regression_cost", "square_error_cost", "rank_cost",
    "huber_regression_cost", "huber_classification_cost", "smooth_l1_cost",
    "sum_cost", "multi_binary_label_cross_entropy", "lambda_cost",
    "crf_layer",
    "crf_decoding_layer", "ctc_layer", "warp_ctc_layer", "hsigmoid",
    "nce_layer",
    # vision
    "bilinear_interp_layer", "pad_layer", "crop_layer", "maxout_layer",
    "spp_layer", "roi_pool_layer", "priorbox_layer",
    "cross_channel_norm_layer", "trans_layer", "rotate_layer",
    "switch_order_layer", "resize_layer",
    # rnn / projections / operators
    "grumemory", "simple_gru", "recurrent_layer", "gru_step_layer",
    "dotmul_projection", "scaling_projection", "table_projection",
    "trans_full_matrix_projection", "slice_projection", "dotmul_operator",
    "conv_projection", "conv_operator", "context_projection",
    "img_conv3d_layer", "img_pool3d_layer", "conv_shift_layer",
    "linear_comb_layer", "convex_comb_layer",
    "cross_entropy_with_selfnorm", "lstm_step_layer",
    "gru_step_naive_layer", "selective_fc_layer",
    "detection_output_layer", "multibox_loss_layer", "upsample_layer",
    "scale_sub_region_layer", "sub_nested_seq_layer",
    # structural markers
    "LayerType", "AggregateLevel", "ExpandLevel", "layer_support",
    # networks composites
    "simple_attention", "sequence_conv_pool", "vgg_16_network",
]


def _mean(x):
    return layers.mean(x)


# ---------------- math / elementwise ----------------


def cos_sim(a, b, scale=1, size=1, name=None, **kw):
    """ref layers.py cos_sim (scale multiplies the similarity)."""
    out = layers.cos_sim(a, b)
    if scale != 1:
        out = layers.scale(out, scale=float(scale))
    _register_named(name, out)
    return out


def dot_prod_layer(input1, input2, name=None, **kw):
    out = layers.reduce_sum(layers.elementwise_mul(input1, input2),
                            dim=1, keep_dim=True)
    _register_named(name, out)
    return out


def out_prod_layer(input1, input2, name=None, **kw):
    """Row-wise outer product, flattened to [N, d1*d2]."""
    d1, d2 = int(input1.shape[-1]), int(input2.shape[-1])
    a = layers.reshape(input1, [-1, d1, 1])
    b = layers.reshape(input2, [-1, 1, d2])
    return layers.reshape(layers.matmul(a, b), [-1, d1 * d2])


def l2_distance_layer(x, y, name=None, **kw):
    d = layers.elementwise_sub(x, y)
    return layers.sqrt(layers.reduce_sum(layers.square(d), dim=1,
                                         keep_dim=True))


def interpolation_layer(input, weight, name=None, **kw):
    """out = w*a + (1-w)*b with w a [N, 1] layer (ref layers.py)."""
    a, b = input
    wa = layers.elementwise_mul(a, weight, axis=0)
    one_minus = layers.scale(weight, scale=-1.0, bias=1.0)
    wb = layers.elementwise_mul(b, one_minus, axis=0)
    return layers.elementwise_add(wa, wb)


def power_layer(input, weight, name=None, **kw):
    """out = x ** w, w a [N, 1] layer broadcast over features."""
    return layers.elementwise_pow(
        input, layers.expand(weight, [1, int(input.shape[-1])]))


def scaling_layer(input, weight, name=None, **kw):
    return layers.elementwise_mul(input, weight, axis=0)


def slope_intercept_layer(input, slope=1.0, intercept=0.0, name=None, **kw):
    return layers.scale(input, scale=float(slope), bias=float(intercept))


def sum_to_one_norm_layer(input, name=None, **kw):
    return layers.elementwise_div(
        input, layers.reduce_sum(input, dim=1, keep_dim=True), axis=0)


def row_l2_norm_layer(input, name=None, **kw):
    return layers.l2_normalize(input, axis=1)


def clip_layer(input, min, max, name=None, **kw):  # noqa: A002
    return layers.clip(input, float(min), float(max))


def scale_shift_layer(input, name=None, param_attr=None, bias_attr=None,
                      **kw):
    """Learned scalar w, b: w*x + b (ref layers.py scale_shift_layer)."""
    w = layers.create_parameter([1], "float32", name=_param_name(param_attr))
    out = layers.elementwise_mul(input, w)
    if bias_attr is not False:
        b = layers.create_parameter([1], "float32", is_bias=True)
        out = layers.elementwise_add(out, b)
    _register_named(name, out)
    return out


def prelu_layer(input, name=None, param_attr=None, **kw):
    return layers.prelu(input, mode="all",
                        param_attr=_param_name(param_attr))


def gated_unit_layer(input, size, act=None, name=None, gate_attr=None,
                     gate_param_attr=None, inproj_attr=None,
                     inproj_param_attr=None, **kw):
    """proj(act) ⊙ sigmoid(gate-proj) (ref layers.py gated_unit_layer)."""
    proj = layers.fc(input=input, size=int(size),
                     act=_act_name(_default_act(act, LinearActivation())),
                     param_attr=_param_name(inproj_param_attr))
    gate = layers.fc(input=input, size=int(size), act="sigmoid",
                     param_attr=_param_name(gate_param_attr))
    out = layers.elementwise_mul(proj, gate)
    _register_named(name, out)
    return out


def tensor_layer(a, b, size, act=None, name=None, param_attr=None,
                 bias_attr=None, **kw):
    """Bilinear tensor product out_k = a · W_k · b (ref layers.py
    tensor_layer), lowered as one [d1, size*d2] matmul + a broadcast
    reduce instead of size separate bilinear forms."""
    d1, d2 = int(a.shape[-1]), int(b.shape[-1])
    w = layers.create_parameter([d1, int(size) * d2], "float32",
                                name=_param_name(param_attr))
    aw = layers.reshape(layers.matmul(a, w), [-1, int(size), d2])
    prod = layers.elementwise_mul(aw, layers.reshape(b, [-1, 1, d2]))
    out = layers.reduce_sum(prod, dim=2)
    a_name = _act_name(_default_act(act, LinearActivation()))
    if a_name:
        out = getattr(layers, a_name)(out)
    _register_named(name, out)
    return out


def factorization_machine(input, factor_size, act=None, name=None,
                          param_attr=None, **kw):
    """Second-order FM interactions, 0.5*((xV)^2 - x^2 V^2) summed over
    factors (ref layers.py factorization_machine)."""
    d = int(input.shape[-1])
    v = layers.create_parameter([d, int(factor_size)], "float32",
                                name=_param_name(param_attr))
    xv2 = layers.square(layers.matmul(input, v))
    x2v2 = layers.matmul(layers.square(input), layers.square(v))
    out = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(xv2, x2v2), dim=1,
                          keep_dim=True), scale=0.5)
    a_name = _act_name(_default_act(act, LinearActivation()))
    if a_name:
        out = getattr(layers, a_name)(out)
    return out


def maxid_layer(input, name=None, **kw):
    out = layers.reshape(layers.argmax(input, axis=1), [-1, 1])
    _register_named(name, out)
    return out


def sampling_id_layer(input, name=None, **kw):
    """Sample a class id from each row's distribution (ref layers.py
    sampling_id_layer; fluid sampling_id op)."""
    helper = LayerHelper("sampling_id", name=name)
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    out.shape = (input.shape[0],)
    helper.append_op(type="sampling_id", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"min": 0.0, "max": 1.0, "seed": 0})
    return layers.reshape(out, [-1, 1])


def multiplex_layer(input, name=None, **kw):
    """First input selects per-row among the remaining inputs (ref
    layers.py multiplex_layer; fluid multiplex op)."""
    index, *candidates = input
    if index.dtype is None or "int" not in str(index.dtype):
        index = layers.cast(index, "int32")
    return layers.multiplex(inputs=list(candidates), index=index)


def eos_layer(input, eos_id, name=None, **kw):
    """1.0 where the id equals eos_id (ref layers.py eos_layer)."""
    ids = input if "int" in str(input.dtype) else layers.cast(input, "int64")
    return layers.cast(
        layers.equal(ids, layers.fill_constant(
            shape=[1], dtype="int64", value=int(eos_id))), "float32")


def print_layer(input, format=None, name=None, **kw):  # noqa: A002
    ins = input if isinstance(input, (list, tuple)) else [input]
    return [layers.Print(x, message=format or "") for x in ins]


printer_layer = print_layer


def get_output_layer(input, arg_name=None, name=None, **kw):
    """The reference picks a non-default output of a multi-output layer
    (e.g. an lstmemory's cell state).  Helpers that have extra outputs
    record them on the returned Variable as ``_v2_outputs``; anything
    else raises rather than silently returning the wrong tensor."""
    if not arg_name:
        return input
    extras = getattr(input, "_v2_outputs", {})
    if arg_name in extras:
        return extras[arg_name]
    raise NotImplementedError(
        f"get_output_layer: {arg_name!r} is not an exposed output here "
        f"(available: {sorted(extras) or 'none'}); helpers on this "
        f"substrate return their outputs directly")


# ---------------- sequence ----------------


def expand_layer(input, expand_as, expand_level=None, name=None, **kw):
    return layers.sequence_expand(input, expand_as)


def repeat_layer(input, num_repeats, as_row_vector=True, act=None,
                 name=None, **kw):
    """Tile features num_repeats times: [a b] -> [a b a b] (row-vector
    mode) or [a a b b] (ref layers.py repeat_layer)."""
    r, d = int(num_repeats), int(input.shape[-1])
    if as_row_vector:
        out = layers.expand(input, expand_times=[1, r])
    else:
        out = layers.reshape(
            layers.expand(layers.reshape(input, [-1, d, 1]),
                          expand_times=[1, 1, r]), [-1, d * r])
    a_name = _act_name(_default_act(act, LinearActivation()))
    if a_name:
        out = getattr(layers, a_name)(out)
    return out


def seq_concat_layer(a, b, name=None, **kw):
    return layers.sequence_concat([a, b])


def seq_reshape_layer(input, reshape_size, name=None, **kw):
    return layers.sequence_reshape(input, int(reshape_size))


def _static_per_seq(vals, what):
    """The static-LoD substrate needs slice geometry at build time; v2
    passes it as data layers, which cannot be supported here."""
    if hasattr(vals, "block"):  # a fluid Variable
        raise NotImplementedError(
            f"seq_slice/sub_seq {what} must be Python ints/lists on this "
            f"substrate (static-LoD); dynamic per-batch slice bounds from "
            f"a data layer are not supported")
    import numpy as _np
    arr = _np.asarray(vals, dtype=_np.int64).reshape(-1, 1)
    return layers.assign(arr)


def seq_slice_layer(input, starts, ends, name=None, **kw):
    """Slice [starts, ends) out of each sequence (ref layers.py
    seq_slice_layer; fluid sequence_slice takes offset+length).  starts/
    ends are per-sequence Python ints or lists, not data layers."""
    for v, what in ((starts, "starts"), (ends, "ends")):
        if hasattr(v, "block"):
            _static_per_seq(v, what)  # raises with the clear message
    import numpy as _np
    s = _np.asarray(starts, dtype=_np.int64).reshape(-1)
    e = _np.asarray(ends, dtype=_np.int64).reshape(-1)
    return layers.sequence_slice(
        input, offset=_static_per_seq(s, "starts"),
        length=_static_per_seq(e - s, "lengths"))


def sub_seq_layer(input, offsets, sizes, name=None, **kw):
    return layers.sequence_slice(
        input, offset=_static_per_seq(offsets, "offsets"),
        length=_static_per_seq(sizes, "sizes"))


def kmax_seq_score_layer(input, beam_size=1, name=None, **kw):
    """Top-k indices of per-step scores within each sequence (ref
    layers.py kmax_seq_score_layer) — scores arrive as a [T, 1] sequence;
    pad to dense, topk, and mark slots past a sequence's true length with
    the reference's -1 sentinel (they would otherwise index padding)."""
    padded, _ = layers.sequence_pad(
        input, layers.fill_constant([1], "float32", -1e30))
    scores = layers.reshape(padded, [0, -1])
    vals, idx = layers.topk(scores, k=int(beam_size))
    pad_hit = layers.cast(
        layers.less_than(vals, layers.fill_constant([1], "float32",
                                                    -1e29)), "int64")
    keep = layers.scale(layers.cast(pad_hit, "float32"),
                        scale=-1.0, bias=1.0)
    masked = layers.elementwise_sub(
        layers.elementwise_mul(layers.cast(idx, "float32"), keep),
        layers.cast(pad_hit, "float32"))
    return layers.cast(masked, "int64")


def block_expand_layer(input, block_x=1, block_y=1, stride_x=1, stride_y=1,
                       padding_x=0, padding_y=0, num_channels=None,
                       name=None, **kw):
    x, _ = _to_nchw(input, num_channels)
    return layers.im2sequence(
        x, filter_size=(block_y, block_x), stride=(stride_y, stride_x),
        padding=(padding_y, padding_x))


def row_conv_layer(input, context_len, act=None, name=None,
                   param_attr=None, **kw):
    return layers.row_conv(
        input, future_context_size=int(context_len) - 1,
        param_attr=_param_name(param_attr),
        act=_act_name(_default_act(act, LinearActivation())))


# ---------------- costs ----------------


def regression_cost(input, label, weight=None, name=None, **kw):
    cost = layers.square_error_cost(input, label)
    if weight is not None:
        cost = layers.elementwise_mul(cost, weight, axis=0)
    return _mean(cost)


square_error_cost = regression_cost


def rank_cost(left, right, label, weight=None, name=None, **kw):
    cost = layers.rank_loss(label, left, right)
    if weight is not None:
        cost = layers.elementwise_mul(cost, weight, axis=0)
    return _mean(cost)


def huber_regression_cost(input, label, delta=1.0, name=None, **kw):
    return _mean(layers.huber_loss(input, label, float(delta)))


def huber_classification_cost(input, label, name=None, **kw):
    """Squared-hinge Huber for {0,1} labels mapped to ±1 (ref layers.py
    huber_classification_cost): 0 if y·f>1, (1-y·f)^2 if |y·f|<=1,
    -4·y·f otherwise."""
    y = layers.scale(layers.cast(label, "float32"), scale=2.0, bias=-1.0)
    yf = layers.elementwise_mul(y, input)
    # piecewise: yf > 1 -> 0; |yf| <= 1 -> (1-yf)^2; yf < -1 -> -4yf.
    # Bands are closed on the quadratic side (1 - above - below), so the
    # exactly-representable boundary yf == -1 costs 4, not 0.
    quad = layers.square(layers.relu(layers.scale(yf, scale=-1.0, bias=1.0)))
    lin = layers.scale(yf, scale=-4.0)
    one = layers.fill_constant([1], "float32", 1.0)
    above = layers.cast(layers.less_than(one, yf), "float32")
    below = layers.cast(
        layers.less_than(yf, layers.scale(one, scale=-1.0)), "float32")
    in_band = layers.scale(layers.elementwise_add(above, below),
                           scale=-1.0, bias=1.0)
    cost = layers.elementwise_add(
        layers.elementwise_mul(in_band, quad),
        layers.elementwise_mul(below, lin))
    return _mean(cost)


def smooth_l1_cost(input, label, name=None, **kw):
    return _mean(layers.smooth_l1(input, label))


def sum_cost(input, name=None, **kw):
    return layers.reduce_sum(input)


def multi_binary_label_cross_entropy(input, label, name=None, **kw):
    """input is post-sigmoid (v2 convention): elementwise binary CE."""
    eps = 1e-8
    pos = layers.elementwise_mul(layers.cast(label, "float32"),
                                 layers.log(layers.scale(input, bias=eps)))
    neg = layers.elementwise_mul(
        layers.scale(layers.cast(label, "float32"), scale=-1.0, bias=1.0),
        layers.log(layers.scale(layers.scale(input, scale=-1.0, bias=1.0),
                                bias=eps)))
    return layers.scale(
        _mean(layers.reduce_sum(layers.elementwise_add(pos, neg), dim=1)),
        scale=-1.0)


def _crf_param_name(input, param_attr):
    """Default transition-matrix name is derived from the EMISSION var, so
    crf_layer + crf_decoding_layer over the same emission share it (the
    reference scopes the transition per layer pair) while two independent
    CRF heads in one program get distinct parameters."""
    return _param_name(param_attr) or f"crf_transition@{input.name}"


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, name=None,
                **kw):
    """LambdaRank (ref layers.py lambda_cost; legacy CostLayer.cpp
    LambdaCost).  ``input`` is the model's per-document score sequence,
    ``score`` the relevance labels.  Forward reports the per-sequence
    NDCG@k (mean over rows); the backward applies the reference's
    hand-crafted lambda pair gradients (lambda_cost op)."""
    helper = LayerHelper("lambda_cost", name=name)
    out = helper.create_variable_for_type_inference(dtype="float32")
    out.shape = (input.shape[0], 1)
    helper.append_op(
        type="lambda_cost", inputs={"X": [input], "Label": [score]},
        outputs={"Out": [out]},
        attrs={"NDCG_num": int(NDCG_num),
               "max_sort_size": int(max_sort_size)})
    return _mean(out)


def crf_layer(input, label, size=None, param_attr=None, name=None, **kw):
    """Linear-chain CRF negative log-likelihood; the transition matrix is
    name-shared with crf_decoding_layer on the same emission input."""
    ll = layers.linear_chain_crf(
        input, label,
        param_attr=_FluidParamAttr(name=_crf_param_name(input, param_attr)))
    return _mean(layers.scale(ll, scale=-1.0))


def crf_decoding_layer(input, size=None, label=None, param_attr=None,
                       name=None, **kw):
    return layers.crf_decoding(
        input, _FluidParamAttr(name=_crf_param_name(input, param_attr)),
        label=label)


def ctc_layer(input, label, size=None, norm_by_times=False, blank=None,
              name=None, **kw):
    """CTC cost (ref layers.py ctc_layer; blank defaults to size-1 there,
    warpctc uses an explicit blank id)."""
    if blank is None:
        blank = (int(size) - 1) if size else 0
    return _mean(layers.warpctc(input, label, blank=int(blank),
                                norm_by_times=bool(norm_by_times)))


def warp_ctc_layer(input, label, size=None, blank=0, norm_by_times=False,
                   name=None, **kw):
    return _mean(layers.warpctc(input, label, blank=int(blank),
                                norm_by_times=bool(norm_by_times)))


def hsigmoid(input, label, num_classes, name=None, param_attr=None,
             bias_attr=None, **kw):
    lbl = label if "int" in str(label.dtype) else layers.cast(label, "int64")
    return _mean(layers.hsigmoid(input, lbl, int(num_classes),
                                 param_attr=_param_name(param_attr)))


def nce_layer(input, label, num_classes=None, num_neg_samples=10,
              name=None, param_attr=None, bias_attr=None, **kw):
    lbl = label if "int" in str(label.dtype) else layers.cast(label, "int64")
    if len(lbl.shape or ()) == 1:
        lbl = layers.reshape(lbl, [-1, 1])
    return _mean(layers.nce(input, lbl, int(num_classes),
                            num_neg_samples=int(num_neg_samples),
                            param_attr=_param_name(param_attr)))


# ---------------- vision ----------------


def bilinear_interp_layer(input, out_size_x=None, out_size_y=None,
                          num_channels=None, name=None, **kw):
    x, _ = _to_nchw(input, num_channels)
    return layers.resize_bilinear(
        x, out_shape=[int(out_size_y), int(out_size_x)])


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, name=None, **kw):
    x, _ = _to_nchw(input, None)
    pc, ph, pw = (list(p or [0, 0]) for p in (pad_c, pad_h, pad_w))
    return layers.pad(x, [0, 0] + pc + ph + pw)


def crop_layer(input, offset, shape=None, axis=2, name=None, **kw):
    if shape is None:
        raise ValueError(
            "crop_layer needs an explicit shape= on this substrate (the "
            "reference's derive-from-second-input form is not supported)")
    x, _ = _to_nchw(input, None)
    full_off = [0] * axis + list(offset)
    full_off += [0] * (4 - len(full_off))
    return layers.crop(x, shape=shape, offsets=full_off)


def maxout_layer(input, groups, num_channels=None, name=None, **kw):
    x, _ = _to_nchw(input, num_channels)
    return layers.maxout(x, int(groups))


def spp_layer(input, pyramid_height, num_channels=None, pool_type=None,
              name=None, **kw):
    """Spatial pyramid pooling (ref layers.py spp_layer; fluid spp op)."""
    from . import _pool_name
    x, c = _to_nchw(input, num_channels)
    helper = LayerHelper("spp", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    levels = int(pyramid_height)
    bins = sum(4 ** i for i in range(levels))
    out.shape = (x.shape[0], int(c) * bins)
    ptype = _pool_name(pool_type)
    if ptype not in ("max", "avg"):
        raise ValueError(f"spp_layer supports Max/Avg pooling, got {ptype}")
    helper.append_op(type="spp", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"pyramid_height": levels,
                            "pooling_type": ptype})
    return out


def roi_pool_layer(input, rois, pooled_width, pooled_height, spatial_scale,
                   num_channels=None, name=None, **kw):
    x, _ = _to_nchw(input, num_channels)
    return layers.roi_pool(x, rois, pooled_height=int(pooled_height),
                           pooled_width=int(pooled_width),
                           spatial_scale=float(spatial_scale))


def priorbox_layer(input, image, aspect_ratio, variance, min_size,
                   max_size=(), name=None, **kw):
    x, _ = _to_nchw(input, None)
    img, _ = _to_nchw(image, None)
    boxes, variances = layers.prior_box(
        x, img, min_sizes=list(min_size), max_sizes=list(max_size) or None,
        aspect_ratios=list(aspect_ratio), variance=list(variance))
    return boxes, variances


def cross_channel_norm_layer(input, name=None, param_attr=None, **kw):
    """L2-normalize across channels, scaled per-channel (ref layers.py
    cross_channel_norm_layer — the SSD conv4_3 norm)."""
    from ..fluid.initializer import ConstantInitializer
    x, c = _to_nchw(input, None)
    normed = layers.l2_normalize(x, axis=1)
    scale = layers.create_parameter(
        [int(c)], "float32", name=_param_name(param_attr),
        default_initializer=ConstantInitializer(1.0))
    return layers.elementwise_mul(normed, scale, axis=1)


def trans_layer(input, name=None, **kw):
    return layers.transpose(input, perm=[1, 0])


def rotate_layer(input, height, width, name=None, **kw):
    """Rotate each CHW map 90° counter-clockwise (ref layers.py
    rotate_layer): transpose H/W then reverse the new H."""
    shape = input.shape
    if shape is not None and len(shape) >= 4:
        x = input
    else:
        c = int(shape[-1]) // (int(height) * int(width))
        x = layers.reshape(input, [-1, c, int(height), int(width)])
    t = layers.transpose(x, perm=[0, 1, 3, 2])
    return layers.reverse(t, axis=2)


def switch_order_layer(input, reshape_axis=3, name=None, **kw):
    """NCHW -> NHWC (ref layers.py switch_order_layer)."""
    x, _ = _to_nchw(input, None)
    return layers.transpose(x, perm=[0, 2, 3, 1])


def resize_layer(input, size, name=None, **kw):
    return layers.reshape(input, [-1, int(size)])


# ---------------- rnn / projections / operators ----------------


def _to_ncdhw(input, num_channels):
    """Recover [N, C, D, H, W] from a flat v2 data layer (shared
    geometry recovery — see _to_spatial in __init__)."""
    return _to_spatial(input, num_channels, 3)


def img_conv3d_layer(input, filter_size, num_filters, name=None,
                     num_channels=None, act=None, groups=1, stride=1,
                     padding=0, bias_attr=None, param_attr=None,
                     trans=False, layer_attr=None, **kw):
    """ref layers.py img_conv3d_layer -> fluid conv3d (NCDHW);
    trans=True lowers onto conv3d_transpose (the deconv3d path)."""
    x, _ = _to_ncdhw(input, num_channels)
    conv = layers.conv3d_transpose if trans else layers.conv3d
    out = conv(
        input=x, num_filters=int(num_filters), filter_size=filter_size,
        stride=stride, padding=padding, groups=groups,
        act=_act_name(_default_act(act, ReluActivation())),
        bias_attr=bias_attr, param_attr=_param_name(param_attr))
    _register_named(name, out)
    return out


def img_pool3d_layer(input, pool_size, name=None, num_channels=None,
                     pool_type=None, stride=1, padding=0,
                     layer_attr=None, **kw):
    """ref layers.py img_pool3d_layer -> fluid pool3d."""
    from . import _pool_name
    x, _ = _to_ncdhw(input, num_channels)
    out = layers.pool3d(input=x, pool_size=pool_size,
                        pool_type=_pool_name(pool_type),
                        pool_stride=stride, pool_padding=padding)
    _register_named(name, out)
    return out


def context_projection(input, context_len=None, context_start=None,
                       padding_attr=False, **kw):
    """Concat a window of neighboring steps per position (ref layers.py
    context_projection; math/context_project.h): out[t] =
    [in[t+start], ..., in[t+start+len-1]] with zero padding at sequence
    boundaries.  Lowered via the sequence_conv op with an identity
    filter (see _lower_context_projection)."""
    if context_len is None:
        raise ValueError("context_projection needs context_len")
    if padding_attr not in (False, None):
        raise NotImplementedError(
            "context_projection trainable boundary padding "
            "(padding_attr) is not supported; boundaries are zero-padded")
    start = -(int(context_len) // 2) if context_start is None \
        else int(context_start)
    return ("ctp", input, (int(context_len), start))


def _lower_context_projection(x, context_len, start):
    """The sequence_conv op without a Filter input IS context_project
    (ref math/context_project.h): the bare windowed concat with zero
    boundary padding."""
    d = int(x.shape[-1])
    width = context_len * d
    helper = LayerHelper("sequence_conv")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = (x.shape[0], width)
    helper.append_op(
        type="sequence_conv", inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"contextStride": 1, "contextStart": int(start),
               "contextLength": int(context_len)})
    return out


def grumemory(input, name=None, reverse=False, act=None, gate_act=None,
              param_attr=None, bias_attr=None, **kw):
    """ref layers.py grumemory: input is the pre-projected [*, 3h]
    sequence; returns the [*, h] hidden sequence."""
    size = int(input.shape[-1]) // 3
    hidden = layers.dynamic_gru(
        input, size, is_reverse=bool(reverse),
        candidate_activation=_act_name(act) or "tanh",
        gate_activation=_act_name(gate_act) or "sigmoid",
        param_attr=_param_name(param_attr))
    _register_named(name, hidden)
    return hidden


def simple_gru(input, size, name=None, reverse=False, act=None,
               gate_act=None, mixed_param_attr=None, gru_param_attr=None,
               **kw):
    """ref networks.py simple_gru: full-matrix projection to 3*size then
    a grumemory."""
    proj = layers.fc(input=input, size=int(size) * 3, act=None,
                     param_attr=_param_name(mixed_param_attr))
    return grumemory(proj, name=name, reverse=reverse, act=act,
                     gate_act=gate_act, param_attr=gru_param_attr)


def recurrent_layer(input, act=None, bias_attr=None, param_attr=None,
                    name=None, reverse=False, **kw):
    """Elman RNN: out_t = act(in_t + W·out_{t-1}) (ref layers.py
    recurrent_layer), lowered onto DynamicRNN."""
    size = int(input.shape[-1])
    act_n = _act_name(_default_act(act, SigmoidActivation())) or "sigmoid"
    seq = layers.sequence_reverse(input) if reverse else input
    rnn = layers.DynamicRNN()
    with rnn.block():
        x = rnn.step_input(seq)
        prev = rnn.memory(shape=[size], value=0.0)
        rec = layers.fc(input=prev, size=size, act=None, bias_attr=False,
                        param_attr=_param_name(param_attr))
        out = getattr(layers, act_n)(layers.elementwise_add(x, rec))
        rnn.update_memory(prev, out)
        rnn.output(out)
    res = rnn()
    if reverse:
        res = layers.sequence_reverse(res)
    _register_named(name, res)
    return res


def gru_step_layer(input, output_mem, size=None, act=None, gate_act=None,
                   name=None, param_attr=None, bias_attr=None, **kw):
    """One GRU step inside a recurrent_group (ref layers.py
    gru_step_layer): input is the [*, 3h] projection, output_mem the
    previous hidden."""
    if size is None:
        size = int(input.shape[-1]) // 3
    hidden, _, _ = layers.gru_unit(
        input, output_mem, int(size) * 3,
        activation=_act_name(act) or "tanh",
        gate_activation=_act_name(gate_act) or "sigmoid",
        param_attr=_param_name(param_attr))
    _register_named(name, hidden)
    return hidden


def dotmul_projection(input, param_attr=None, **kw):
    return ("dmp", input, _param_name(param_attr))


def scaling_projection(input, param_attr=None, **kw):
    return ("scp", input, _param_name(param_attr))


def table_projection(input, size=None, param_attr=None, **kw):
    return ("tbp", input, (size, _param_name(param_attr)))


def trans_full_matrix_projection(input, size=None, param_attr=None, **kw):
    return ("tfmp", input, {"size": size,
                            "name": _param_name(param_attr)})


def slice_projection(input, slices, **kw):
    return ("slp", input, list(slices))


def dotmul_operator(a=None, b=None, scale=1.0, **kw):
    return ("dop", (a, b), float(scale))


def _yx(v, v_y):
    """Reference conv args accept int | [x, y] (the reference unpacks
    sequences as (x, y) — layers.py conv_projection); normalize to the
    fluid (y, x) order."""
    if isinstance(v, (list, tuple)):
        return (int(v[-1]), int(v[0]))
    return (int(v_y if v_y is not None else v), int(v))


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, filter_size_y=None, stride_y=None,
                    padding_y=None, groups=1, param_attr=None, trans=False,
                    **kw):
    """Learned-filter conv inside mixed/concat (ref layers.py
    conv_projection); output is the flattened feature map."""
    return ("cvp", input, {
        "num_channels": num_channels,
        "num_filters": int(num_filters),
        "filter_size": _yx(filter_size, filter_size_y),
        "stride": _yx(stride, stride_y),
        "padding": _yx(padding, padding_y),
        "groups": int(groups),
        "param_attr": _param_name(param_attr),
        "trans": bool(trans),
    })


def conv_operator(img, filter, filter_size, num_filters,  # noqa: A002
                  num_channels=None, stride=1, padding=0,
                  filter_size_y=None, stride_y=None, padding_y=None,
                  trans=False, **kw):
    """Conv whose FILTER comes from another layer (ref layers.py
    conv_operator — the two-input cudnn conv op)."""
    if trans:
        raise NotImplementedError(
            "conv_operator(trans=True): a dynamic-filter TRANSPOSED conv "
            "has no lowering here; use conv_projection(trans=True) for a "
            "learned-filter deconv")
    ky, kx = _yx(filter_size, filter_size_y)
    return ("cvo", (img, filter), {
        "num_channels": num_channels,
        "num_filters": int(num_filters),
        "filter_size": kx,
        "filter_size_y": ky,
        "stride": _yx(stride, stride_y),
        "padding": _yx(padding, padding_y),
    })


def conv_shift_layer(a, b, name=None, **kw):
    """Circular convolution out[i] = Σ_j b[j] · a[(i+j-⌊Nb/2⌋) mod Na]
    (ref layers.py conv_shift_layer; Nb odd).  Lowered as a sum of
    statically rolled copies of ``a`` weighted by ``b``'s columns."""
    na, nb = int(a.shape[-1]), int(b.shape[-1])
    if nb % 2 != 1:
        raise ValueError(f"conv_shift_layer needs odd filter width, "
                         f"got {nb}")
    out = None
    for j in range(nb):
        shift = (j - nb // 2) % na
        rolled = a if shift == 0 else layers.concat(
            [layers.slice(a, axes=[1], starts=[shift], ends=[na]),
             layers.slice(a, axes=[1], starts=[0], ends=[shift])], axis=1)
        bj = layers.slice(b, axes=[1], starts=[j], ends=[j + 1])
        term = layers.elementwise_mul(rolled, bj, axis=0)
        out = term if out is None else layers.elementwise_add(out, term)
    _register_named(name, out)
    return out


def linear_comb_layer(weights, vectors, size=None, name=None, **kw):
    """out = Σ_i w_i · v_i with weights [N, k] and vectors [N, k·size]
    (ref layers.py linear_comb_layer)."""
    k = int(weights.shape[-1])
    if size is None:
        size = int(vectors.shape[-1]) // k
    v = layers.reshape(vectors, [-1, k, int(size)])
    w = layers.reshape(weights, [-1, k, 1])
    out = layers.reduce_sum(layers.elementwise_mul(v, w), dim=1)
    _register_named(name, out)
    return out


convex_comb_layer = linear_comb_layer  # ref: deprecated alias


def cross_entropy_with_selfnorm(input, label, name=None, coeff=1.0,
                                softmax_selfnorm_alpha=0.1, **kw):
    """CE + log(Z) + α·log(Z)² with Z the row sum of the (self-
    normalized, not exactly summing to 1) softmax output (ref legacy
    CostLayer.cpp MultiClassCrossEntropyWithSelfNorm:113-124); the
    backward is the plain autodiff of this forward, which matches the
    reference's hand-written gradient."""
    from . import _as_label

    z = layers.reduce_sum(input, dim=1, keep_dim=True)
    logz = layers.log(z)
    ce = layers.cross_entropy(input=input, label=_as_label(label))
    cost = layers.elementwise_add(
        layers.elementwise_add(ce, logz),
        layers.scale(layers.square(logz),
                     scale=float(softmax_selfnorm_alpha)))
    out = _mean(cost)
    if coeff != 1.0:
        out = layers.scale(out, scale=float(coeff))
    return out


def lstm_step_layer(input, state, size=None, act=None, name=None,
                    gate_act=None, state_act=None, bias_attr=None, **kw):
    """One LSTM step inside a recurrent_group (ref layers.py
    lstm_step_layer): ``input`` is the [N, 4h] pre-projection, ``state``
    the previous CELL.  Gate layout [i, f, c, o] (self-consistent:
    training and generation both build through this helper; loading
    legacy C++ weights is not supported anyway).  Returns the hidden;
    the new cell rides get_output_layer(..., 'state')."""
    h = int(size) if size else int(state.shape[-1])
    gate_a = _act_name(gate_act) or "sigmoid"
    cand_a = _act_name(act) or "tanh"
    cell_a = _act_name(state_act) or "tanh"
    chunks = [layers.slice(input, axes=[1], starts=[k * h],
                           ends=[(k + 1) * h]) for k in range(4)]
    i_g = getattr(layers, gate_a)(chunks[0])
    f_g = getattr(layers, gate_a)(chunks[1])
    cand = getattr(layers, cand_a)(chunks[2])
    o_g = getattr(layers, gate_a)(chunks[3])
    new_cell = layers.elementwise_add(
        layers.elementwise_mul(f_g, state),
        layers.elementwise_mul(i_g, cand))
    hidden = layers.elementwise_mul(
        o_g, getattr(layers, cell_a)(new_cell))
    hidden._v2_outputs = {"state": new_cell}
    _register_named(name, hidden)
    return hidden


def gru_step_naive_layer(input, output_mem, size=None, name=None,
                         act=None, gate_act=None, bias_attr=None,
                         param_attr=None, **kw):
    """ref layers.py gru_step_naive_layer — same math as gru_step_layer
    (the reference variants differ only in kernel strategy)."""
    return gru_step_layer(input, output_mem, size=size, act=act,
                          gate_act=gate_act, name=name,
                          param_attr=param_attr, bias_attr=bias_attr)


def selective_fc_layer(input, size, select=None, act=None, name=None,
                       param_attr=None, bias_attr=None, **kw):
    """ref layers.py selective_fc_layer: an fc whose output is only
    meaningful (and, there, only computed) at selected columns.  Here
    the full fc runs — XLA's batched matmul beats sparse gathers on
    TPU — and the selection mask zeroes the rest, which is
    output-equivalent."""
    out = layers.fc(input=input, size=int(size),
                    act=_act_name(_default_act(act, TanhActivation())),
                    param_attr=_param_name(param_attr))
    if select is not None:
        out = layers.elementwise_mul(out, select)
    _register_named(name, out)
    return out


def _stack_heads(parts, last_dim):
    """Concat per-scale SSD head outputs [N, Np_i*d] into [N, Np, d].
    Np is computed statically from the head widths so downstream
    consumers (ssd_loss's num_prior) see a concrete prior count even
    with a dynamic batch dimension."""
    xs = parts if isinstance(parts, (list, tuple)) else [parts]
    cat = xs[0] if len(xs) == 1 else layers.concat(list(xs), axis=1)
    width = sum(int(x.shape[-1]) for x in xs)
    return layers.reshape(cat, [-1, width // int(last_dim),
                                int(last_dim)])


def _priorbox_pair(priorbox):
    """Flatten the (boxes, variances) pair from priorbox_layer (fluid
    prior_box emits [H, W, P, 4]) into the [Np, 4] the ssd machinery
    takes."""
    if isinstance(priorbox, (list, tuple)) and len(priorbox) == 2:
        boxes, variances = priorbox
        boxes = layers.reshape(boxes, [-1, 4])
        variances = layers.reshape(variances, [-1, 4])
        boxes.stop_gradient = variances.stop_gradient = True
        return boxes, variances
    raise ValueError("priorbox must be the (boxes, variances) pair "
                     "returned by priorbox_layer")


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400,
                           keep_top_k=200, confidence_threshold=0.01,
                           background_id=0, name=None, **kw):
    """ref layers.py detection_output_layer -> fluid detection_output
    (decode + class-wise NMS)."""
    boxes, variances = _priorbox_pair(priorbox)
    loc = _stack_heads(input_loc, 4)
    # scores must be CLASS-major [N, C, Np] (multiclass_nms contract,
    # ops/detection_ops.py Scores layout; the reference fluid
    # detection_output applies the same transpose)
    conf = layers.transpose(
        layers.softmax(_stack_heads(input_conf, num_classes)),
        perm=[0, 2, 1])
    return layers.detection_output(
        loc, conf, boxes, variances, background_label=int(background_id),
        nms_threshold=float(nms_threshold), nms_top_k=int(nms_top_k),
        keep_top_k=int(keep_top_k),
        score_threshold=float(confidence_threshold))


def multibox_loss_layer(input_loc, input_conf, priorbox, label,
                        num_classes, overlap_threshold=0.5,
                        neg_pos_ratio=3.0, neg_overlap=0.5,
                        background_id=0, name=None, **kw):
    """ref layers.py multibox_loss_layer -> fluid ssd_loss.  ``label``
    is the LoD ground-truth [Ng, 5] rows of (class, x1, y1, x2, y2) —
    the v2 data convention."""
    boxes, variances = _priorbox_pair(priorbox)
    loc = _stack_heads(input_loc, 4)
    conf = _stack_heads(input_conf, num_classes)
    gt_label = layers.cast(
        layers.slice(label, axes=[1], starts=[0], ends=[1]), "int64")
    gt_box = layers.slice(label, axes=[1], starts=[1], ends=[5])
    gt_box = layers.lod_reset(gt_box, y=label)
    gt_label = layers.lod_reset(gt_label, y=label)
    loss = layers.ssd_loss(
        loc, conf, gt_box, gt_label, boxes, variances,
        background_label=int(background_id),
        overlap_threshold=float(overlap_threshold),
        neg_pos_ratio=float(neg_pos_ratio),
        neg_overlap=float(neg_overlap))
    return _mean(loss)


def upsample_layer(input, name=None, scale=None, scale_y=None,
                   upsample_size=None, upsample_size_y=None,
                   pad_out_x=False, pad_out_y=False, **kw):
    """The DePooling process (ref layers.py upsample_layer): input is
    [data_layer, max-with-mask pool layer]; each pooled value scatters
    back to the position its max came from (fluid unpool op).  The mask
    encodes flat positions in the POOL-INPUT plane, so that plane is the
    only valid output geometry — a mismatching scale/upsample_size/pad
    request raises instead of silently corrupting the scatter."""
    data, pooled = input
    mask = getattr(pooled, "_v2_outputs", {}).get("mask")
    geom = getattr(pooled, "_v2_pool_geom", None)
    if mask is None or geom is None:
        raise ValueError(
            "upsample_layer's second input must be an img_pool_layer "
            "with pool_type=MaxWithMaskPooling()")
    in_h, in_w = geom
    if upsample_size is not None:
        req_h = int(upsample_size_y or upsample_size)
        req_w = int(upsample_size)
    elif scale is not None:
        req_h = int(data.shape[2]) * int(scale_y or scale) \
            + (1 if pad_out_y else 0)
        req_w = int(data.shape[3]) * int(scale) + (1 if pad_out_x else 0)
    else:
        req_h, req_w = in_h, in_w
    if (req_h, req_w) != (in_h, in_w):
        raise ValueError(
            f"upsample_layer output must match the pool input plane "
            f"({in_h}x{in_w}) that the mask indexes; the given scale/"
            f"upsample_size/pad_out imply {req_h}x{req_w}")
    helper = LayerHelper("unpool", name=name)
    out = helper.create_variable_for_type_inference(dtype=data.dtype)
    out.shape = (data.shape[0], data.shape[1], in_h, in_w)
    helper.append_op(
        type="unpool", inputs={"X": [data], "Indices": [mask]},
        outputs={"Out": [out]},
        attrs={"unpooled_height": in_h, "unpooled_width": in_w})
    _register_named(name, out)
    return out


def scale_sub_region_layer(input, indices, value, name=None, **kw):
    """Scale a per-sample [C, H, W] sub-box by ``value`` (ref layers.py
    scale_sub_region_layer; indices rows are the reference's 1-based
    inclusive (c1, c2, h1, h2, w1, w2))."""
    x, _ = _to_nchw(input, None)
    helper = LayerHelper("scale_sub_region", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = tuple(x.shape)
    helper.append_op(
        type="scale_sub_region", inputs={"X": [x], "Indices": [indices]},
        outputs={"Out": [out]}, attrs={"scale": float(value)})
    _register_named(name, out)
    return out


# ---------------- structural markers (ref layers.py __all__) ----------


class LayerType:
    """Layer-type name constants (ref layers.py LayerType).  The fluid
    substrate types layers by their emitted ops; the names survive for
    config compatibility."""
    DATA = "data"
    FC_LAYER = "fc"
    CONV_LAYER = "conv"
    POOL_LAYER = "pool"
    BATCH_NORM_LAYER = "batch_norm"
    LSTMEMORY = "lstmemory"
    GRUMEMORY = "gated_recurrent"
    COST = "cost"

    @staticmethod
    def is_layer_type(type_name):
        return isinstance(type_name, str)


class AggregateLevel:
    """Sequence aggregation level (ref layers.py AggregateLevel)."""
    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    EACH_TIMESTEP = TO_NO_SEQUENCE   # deprecated alias
    EACH_SEQUENCE = TO_SEQUENCE      # deprecated alias


class ExpandLevel:
    """Expansion level (ref layers.py ExpandLevel)."""
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    FROM_TIMESTEP = FROM_NO_SEQUENCE  # deprecated alias


def layer_support(*attrs):
    """ref layers.py layer_support decorator — attribute-support
    bookkeeping for the proto generator; behavior rides the helpers
    themselves here, so this is the identity decorator."""
    def deco(fn):
        return fn
    return deco


# ---------------- networks composites ----------------


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     name=None, **kw):
    """Bahdanau-style additive attention (ref networks.py
    simple_attention): score_t = v·tanh(enc_proj_t + W·s), weights =
    seq-softmax(score), context = Σ w_t · enc_t."""
    state_proj = layers.fc(input=decoder_state,
                           size=int(encoded_proj.shape[-1]), act=None,
                           bias_attr=False,
                           param_attr=_param_name(transform_param_attr))
    expanded = layers.sequence_expand(state_proj, encoded_proj)
    combined = layers.tanh(layers.elementwise_add(encoded_proj, expanded))
    scores = layers.fc(input=combined, size=1, act=None, bias_attr=False,
                       param_attr=_param_name(softmax_param_attr))
    # fc does not propagate sequence structure; re-attach the encoder LoD
    scores = layers.lod_reset(scores, y=encoded_sequence)
    weights = layers.sequence_softmax(scores)
    weighted = layers.elementwise_mul(encoded_sequence, weights, axis=0)
    return layers.sequence_pool(weighted, "sum")


def sequence_conv_pool(input, context_len, hidden_size, name=None,
                       context_start=None, pool_type=None, act=None, **kw):
    from . import SigmoidActivation, _pool_name
    from ..fluid import nets
    # v2 default act is sigmoid (ref networks.py); an explicit
    # LinearActivation() stays linear (act=None at the fluid conv)
    return nets.sequence_conv_pool(
        input, num_filters=int(hidden_size), filter_size=int(context_len),
        act=_act_name(_default_act(act, SigmoidActivation())),
        pool_type=_pool_name(pool_type))


def vgg_16_network(input_image, num_channels, num_classes=1000, **kw):
    """ref networks.py vgg_16_network — five conv groups then two
    dropout+fc blocks and the softmax classifier."""
    from . import SoftmaxActivation, dropout_layer, fc_layer, img_conv_group
    x = input_image
    for i, (filters, reps) in enumerate(
            [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]):
        x = img_conv_group(
            x, conv_num_filter=[filters] * reps, pool_size=2,
            num_channels=num_channels if i == 0 else None,
            conv_act=ReluActivation(), pool_stride=2)
    x = dropout_layer(x, 0.5)
    x = fc_layer(x, 4096, act=LinearActivation())
    # batch norm on the flat fc output directly: fluid batch_norm treats
    # 2-D input as [N, C] (per-neuron statistics, the reference's
    # semantics) — batch_norm_layer would reshape it to fake NCHW
    x = layers.batch_norm(input=x, act="relu")
    x = dropout_layer(x, 0.5)
    x = fc_layer(x, 4096, act=LinearActivation())
    return fc_layer(x, int(num_classes), act=SoftmaxActivation())


# ---------------- documented absences ----------------

_ABSENT = {
    "SubsequenceInput": "nested-sequence generation has no counterpart; "
                        "use beam_search with flat sequences",
    "BeamInput": "beam-feedback training has no counterpart; use "
                 "fluid.contrib.decoder TrainingDecoder",
    "cross_entropy_over_beam": "beam-aware training cost has no "
                               "counterpart; train teacher-forced",
}


def _absent_getattr(attr):
    """PEP 562 module __getattr__ shared by this module and the package
    __init__: documented absences raise with the replacement named."""
    if attr in _ABSENT:
        raise NotImplementedError(
            f"v2 {attr} is not part of the facade: {_ABSENT[attr]}")
    raise AttributeError(attr)


__getattr__ = _absent_getattr


def sub_nested_seq_layer(input, selected_indices, name=None, **kw):
    """Trim a nested (lod_level=2) sequence to the inner subsequences
    picked by ``selected_indices`` (ref layers.py sub_nested_seq_layer;
    legacy SubNestedSequenceLayer).  Runs as an eager host op — the
    output row count depends on the selection values."""
    helper = LayerHelper("sub_nested_seq", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    out.shape = input.shape
    helper.append_op(
        type="sub_nested_seq",
        inputs={"X": [input], "SelectedIndices": [selected_indices]},
        outputs={"Out": [out]})
    _register_named(name, out)
    return out
