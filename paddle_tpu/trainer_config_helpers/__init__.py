"""trainer_config_helpers — the legacy v2-generation model-config DSL,
lowered onto Fluid programs (ref: python/paddle/trainer_config_helpers/
layers.py — img_conv_layer :2331, batch_norm_layer :3050, img_pool_layer
:2542, fc_layer :1003, addto_layer :3434; networks.py img_conv_group;
optimizers.py settings/MomentumOptimizer; attrs.py ExtraAttr).

The reference generation builds a protobuf ModelConfig consumed by the C++
GradientMachine (legacy/gserver/gradientmachines/GradientMachine.h:75); its
layer/trainer capabilities are a strict subset of the Fluid surface, so
here each helper simply appends the equivalent Fluid ops to the default
program and returns the fluid Variable — one substrate, two front ends.
The subset implemented is what the reference's own v2-era benchmark
configs use (benchmark/paddle/image/{vgg,resnet}.py + common extras); a
config file written against the reference runs unchanged after swapping
the import.

v2 configs are geometry-implicit (data_layer carries a flat ``size``; conv
layers recover [C, H, W] from ``num_channels`` assuming square images, the
reference's own default when the provider does not say otherwise).
"""

from __future__ import annotations

import math

from ..fluid import layers, nets, optimizer as fluid_opt, regularizer

__all__ = [
    "get_config_arg", "set_config_args", "settings", "outputs",
    "data_layer", "fc_layer", "img_conv_layer", "img_pool_layer",
    "batch_norm_layer", "addto_layer", "img_conv_group", "dropout_layer",
    "embedding_layer", "img_cmrnorm_layer", "concat_layer",
    "cross_entropy", "classification_cost",
    "LinearActivation", "ReluActivation", "SoftmaxActivation",
    "TanhActivation", "SigmoidActivation", "MaxPooling", "AvgPooling",
    "MomentumOptimizer", "AdamOptimizer", "L2Regularization", "ExtraAttr",
    "ParamAttr", "define_py_data_sources2", "get_settings",
]


# --- config args (ref: the trainer binary's --config_args) ---------------

_config_args = {}


def set_config_args(**kwargs):
    """Test/driver hook standing in for the reference's --config_args."""
    _config_args.update(kwargs)


def get_config_arg(name, type_, default=None):
    v = _config_args.get(name, default)
    if v is None:
        return None
    if isinstance(v, type_):
        return v
    if type_ is bool and isinstance(v, str):
        # the reference DSL parses bool config args numerically;
        # bool("0")/bool("False") == True would silently flip flags
        return v.strip().lower() not in ("", "0", "false", "no", "off")
    return type_(v)


# --- activations / pooling markers (ref: activations.py, poolings.py) ----


class _Activation:
    fluid_name = None

    def __repr__(self):
        return type(self).__name__


class LinearActivation(_Activation):
    fluid_name = None


class ReluActivation(_Activation):
    fluid_name = "relu"


class SoftmaxActivation(_Activation):
    fluid_name = "softmax"


class TanhActivation(_Activation):
    fluid_name = "tanh"


class SigmoidActivation(_Activation):
    fluid_name = "sigmoid"


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, str):
        return act or None
    return act.fluid_name


class MaxPooling:
    fluid_name = "max"


class AvgPooling:
    fluid_name = "avg"


def _pool_name(p):
    return getattr(p, "fluid_name", None) or "max"


# --- attrs / optimizers / settings ---------------------------------------


class ExtraAttr:
    """ref attrs.py ExtraLayerAttribute — only drop_rate is meaningful on
    the Fluid substrate (device placement is XLA's business)."""

    def __init__(self, drop_rate=0.0, **kwargs):
        self.drop_rate = drop_rate


ExtraLayerAttribute = ExtraAttr


class ParamAttr:
    def __init__(self, name=None, initial_std=None, initial_mean=None,
                 learning_rate=None, **kwargs):
        self.name = name


def _param_name(param_attr):
    """Thread a v2 ParamAttr name down to the fluid layer so that legacy
    configs sharing one parameter by name across layers get genuinely tied
    weights (the fluid scope is name-keyed, so same name == same storage
    and the backward accumulates both consumers' gradients)."""
    return getattr(param_attr, "name", None)


class MomentumOptimizer:
    def __init__(self, momentum=0.9):
        self.momentum = momentum

    def build(self, lr, reg):
        return fluid_opt.Momentum(learning_rate=lr, momentum=self.momentum,
                                  regularization=reg)


class AdamOptimizer:
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.kw = dict(beta1=beta1, beta2=beta2, epsilon=epsilon)

    def build(self, lr, reg):
        return fluid_opt.Adam(learning_rate=lr, regularization=reg,
                              **self.kw)


class L2Regularization:
    def __init__(self, rate):
        self.rate = rate

    def build(self):
        return regularizer.L2DecayRegularizer(self.rate)


_settings = {}


def settings(batch_size=None, learning_rate=1e-3, learning_method=None,
             regularization=None, **kwargs):
    """ref optimizers.py settings(): record the training hyper-parameters;
    v2.trainer.SGD (or the caller) turns them into a Fluid optimizer."""
    _settings.clear()
    _settings.update(batch_size=batch_size, learning_rate=learning_rate,
                     learning_method=learning_method,
                     regularization=regularization)


def get_settings():
    return dict(_settings)


def build_settings_optimizer():
    """Fluid optimizer from the last settings() call."""
    method = _settings.get("learning_method") or MomentumOptimizer(0.0)
    reg = _settings.get("regularization")
    return method.build(_settings.get("learning_rate", 1e-3),
                        reg.build() if reg is not None else None)


_outputs = []


def outputs(*layers_):
    """ref config_parser outputs(): mark the topology's sink layers."""
    _outputs[:] = list(layers_)


def get_outputs():
    return list(_outputs)


def define_py_data_sources2(train_list, test_list, module=None, obj=None,
                            args=None):
    """Data comes from Python readers on this substrate; the declaration
    is accepted for config compatibility and otherwise inert."""
    return None


# --- layers --------------------------------------------------------------


def data_layer(name, size, height=None, width=None, depth=None):
    """Flat [size] float input (v2 geometry convention).  Labels are
    declared with data_layer too in v2 configs; integer-classification use
    is detected at the cost layer, not here."""
    v = layers.data(name=name, shape=[int(size)], dtype="float32")
    v._v2_geom = (height, width)
    return v


def _to_nchw(input, num_channels):
    """Recover [N, C, H, W] from a flat v2 data layer when needed."""
    shape = input.shape
    if shape is not None and len(shape) >= 4:
        return input, int(shape[1])
    size = int(shape[-1])
    geom = getattr(input, "_v2_geom", None) or (None, None)
    if num_channels is None:
        num_channels = 3 if size % 3 == 0 else 1
    if geom[0]:
        h, w = int(geom[0]), int(geom[1] or geom[0])
    else:
        h = w = int(math.isqrt(size // num_channels))
    return layers.reshape(input, [-1, num_channels, h, w]), num_channels


# the reference DSL wraps every layer in @wrap_act_default; configs rely
# on these implicit activations (fc->tanh, conv/bn->relu, addto->linear)
def _default_act(act, default):
    return default if act is None else act


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, layer_attr=None):
    act = _default_act(act, TanhActivation())
    out = layers.fc(input=input, size=int(size), act=_act_name(act),
                    param_attr=_param_name(param_attr), name=name)
    if layer_attr is not None and getattr(layer_attr, "drop_rate", 0):
        out = layers.dropout(out, dropout_prob=layer_attr.drop_rate)
    return out


def img_conv_layer(input, filter_size, num_filters, name=None,
                   num_channels=None, act=None, groups=1, stride=1,
                   padding=0, bias_attr=None, param_attr=None,
                   trans=False, layer_attr=None):
    act = _default_act(act, ReluActivation())
    x, _ = _to_nchw(input, num_channels)
    return layers.conv2d(input=x, num_filters=int(num_filters),
                         filter_size=filter_size, stride=stride,
                         padding=padding, groups=groups,
                         act=_act_name(act), bias_attr=bias_attr,
                         param_attr=_param_name(param_attr), name=name)


def img_pool_layer(input, pool_size, name=None, num_channels=None,
                   pool_type=None, stride=1, padding=0, layer_attr=None,
                   **kwargs):
    x, _ = _to_nchw(input, num_channels)
    return layers.pool2d(input=x, pool_size=pool_size,
                         pool_type=_pool_name(pool_type),
                         pool_stride=stride, pool_padding=padding)


def batch_norm_layer(input, act=None, name=None, num_channels=None,
                     use_global_stats=None, moving_average_fraction=0.9,
                     layer_attr=None, **kwargs):
    act = _default_act(act, ReluActivation())
    x, _ = _to_nchw(input, num_channels)
    return layers.batch_norm(input=x, act=_act_name(act),
                             is_test=bool(use_global_stats),
                             momentum=moving_average_fraction)


def addto_layer(input, act=None, name=None, bias_attr=None):
    if not isinstance(input, (list, tuple)):
        input = [input]
    out = input[0]
    for other in input[1:]:
        out = layers.elementwise_add(out, other)
    a = _act_name(act)  # reference default: LinearActivation
    if a:
        out = getattr(layers, a)(out)
    return out


def img_cmrnorm_layer(input, size, scale=0.0128, power=0.75, name=None,
                      num_channels=None, layer_attr=None):
    """Cross-map response normalization (ref layers.py:3199; AlexNet's
    LRN).  The v2 ``scale`` is the per-window alpha of the fluid lrn op."""
    x, _ = _to_nchw(input, num_channels)
    return layers.lrn(x, n=int(size), k=1.0, alpha=scale, beta=power,
                      name=name)


def concat_layer(input, act=None, name=None, layer_attr=None,
                 bias_attr=None):
    """Channel concat (ref layers.py:3527; default IdentityActivation)."""
    out = layers.concat(list(input), axis=1)
    a = _act_name(act)
    if a:
        out = getattr(layers, a)(out)
    return out


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_batchnorm_drop_rate=0, conv_with_batchnorm=False,
                   pool_stride=1, pool_type=None, **kwargs):
    x, _ = _to_nchw(input, num_channels)
    return nets.img_conv_group(
        input=x, conv_num_filter=list(conv_num_filter),
        pool_size=pool_size, conv_padding=conv_padding,
        conv_filter_size=conv_filter_size, conv_act=_act_name(conv_act),
        conv_with_batchnorm=conv_with_batchnorm,
        conv_batchnorm_drop_rate=conv_batchnorm_drop_rate,
        pool_stride=pool_stride, pool_type=_pool_name(pool_type))


def dropout_layer(input, dropout_rate, name=None):
    return layers.dropout(input, dropout_prob=dropout_rate)


def embedding_layer(input, size, name=None, param_attr=None):
    return layers.embedding(input=input, size=size,
                            param_attr=_param_name(param_attr))


def _as_label(label):
    """v2 declares classification labels as data_layer(size=num_class);
    the cost layer reinterprets them as int64 class ids [N, 1]."""
    if label.dtype is not None and "int" in str(label.dtype):
        return label
    relabeled = layers.cast(label, "int64")
    return layers.reshape(relabeled, [-1, 1]) \
        if len(relabeled.shape or ()) == 2 and relabeled.shape[-1] != 1 \
        else relabeled


def cross_entropy(input, label, name=None, **kwargs):
    return layers.mean(
        layers.cross_entropy(input=input, label=_as_label(label)))


def classification_cost(input, label, name=None, **kwargs):
    return cross_entropy(input, label, name=name)
