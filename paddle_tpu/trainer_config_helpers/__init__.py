"""trainer_config_helpers — the legacy v2-generation model-config DSL,
lowered onto Fluid programs (ref: python/paddle/trainer_config_helpers/
layers.py — img_conv_layer :2331, batch_norm_layer :3050, img_pool_layer
:2542, fc_layer :1003, addto_layer :3434; networks.py img_conv_group;
optimizers.py settings/MomentumOptimizer; attrs.py ExtraAttr).

The reference generation builds a protobuf ModelConfig consumed by the C++
GradientMachine (legacy/gserver/gradientmachines/GradientMachine.h:75); its
layer/trainer capabilities are a strict subset of the Fluid surface, so
here each helper simply appends the equivalent Fluid ops to the default
program and returns the fluid Variable — one substrate, two front ends.
The subset implemented is what the reference's own v2-era benchmark
configs use (benchmark/paddle/image/{vgg,resnet}.py + common extras); a
config file written against the reference runs unchanged after swapping
the import.

v2 configs are geometry-implicit (data_layer carries a flat ``size``; conv
layers recover [C, H, W] from ``num_channels`` assuming square images, the
reference's own default when the provider does not say otherwise).
"""

from __future__ import annotations

import math

from ..fluid import layers as _fl
from ..fluid import nets, optimizer as fluid_opt, regularizer

__all__ = [
    "get_config_arg", "set_config_args", "settings", "outputs",
    "data_layer", "fc_layer", "img_conv_layer", "img_pool_layer",
    "batch_norm_layer", "addto_layer", "img_conv_group", "dropout_layer",
    "embedding_layer", "img_cmrnorm_layer", "concat_layer",
    "cross_entropy", "classification_cost",
    "LinearActivation", "ReluActivation", "SoftmaxActivation",
    "TanhActivation", "SigmoidActivation", "MaxPooling", "AvgPooling",
    "MomentumOptimizer", "AdamOptimizer", "L2Regularization", "ExtraAttr",
    "ParamAttr", "define_py_data_sources2", "get_settings",
]


# --- config args (ref: the trainer binary's --config_args) ---------------

_config_args = {}


def set_config_args(**kwargs):
    """Test/driver hook standing in for the reference's --config_args.
    Replaces the previous args wholesale — the reference passes
    --config_args per trainer invocation, so args must not leak from one
    config run into the next (e.g. an image config's num_class reaching
    a later rnn config)."""
    _config_args.clear()
    _config_args.update(kwargs)


def get_config_arg(name, type_, default=None):
    v = _config_args.get(name, default)
    if v is None:
        return None
    if isinstance(v, type_):
        return v
    if type_ is bool and isinstance(v, str):
        # the reference DSL parses bool config args numerically;
        # bool("0")/bool("False") == True would silently flip flags
        return v.strip().lower() not in ("", "0", "false", "no", "off")
    return type_(v)


# --- activations / pooling markers (ref: activations.py, poolings.py) ----


class _Activation:
    fluid_name = None

    def __repr__(self):
        return type(self).__name__


class LinearActivation(_Activation):
    fluid_name = None


class ReluActivation(_Activation):
    fluid_name = "relu"


class SoftmaxActivation(_Activation):
    fluid_name = "softmax"


class TanhActivation(_Activation):
    fluid_name = "tanh"


class SigmoidActivation(_Activation):
    fluid_name = "sigmoid"


class IdentityActivation(_Activation):
    fluid_name = None


class ExpActivation(_Activation):
    fluid_name = "exp"


class LogActivation(_Activation):
    fluid_name = "log"


class AbsActivation(_Activation):
    fluid_name = "abs"


class SquareActivation(_Activation):
    fluid_name = "square"


class SqrtActivation(_Activation):
    fluid_name = "sqrt"


class ReciprocalActivation(_Activation):
    fluid_name = "reciprocal"


class BReluActivation(_Activation):
    fluid_name = "brelu"


class SoftReluActivation(_Activation):
    fluid_name = "soft_relu"


class STanhActivation(_Activation):
    fluid_name = "stanh"


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, str):
        return act or None
    return act.fluid_name


class MaxPooling:
    fluid_name = "max"


class AvgPooling:
    fluid_name = "avg"


class SquareRootNPooling:
    """Sum pooling scaled by 1/sqrt(len) (ref poolings.py SquareRootN)."""
    fluid_name = "sqrt"


class MaxWithMaskPooling:
    """Max pooling that also emits the argmax mask (ref poolings.py
    MaxWithMaskPooling) — pairs with upsample_layer's unpooling."""
    fluid_name = "max_with_mask"


CudnnMaxPooling = MaxPooling
CudnnAvgPooling = AvgPooling


def _pool_name(p):
    return getattr(p, "fluid_name", None) or "max"


# --- attrs / optimizers / settings ---------------------------------------


class ExtraAttr:
    """ref attrs.py ExtraLayerAttribute — only drop_rate is meaningful on
    the Fluid substrate (device placement is XLA's business)."""

    def __init__(self, drop_rate=0.0, **kwargs):
        self.drop_rate = drop_rate


ExtraLayerAttribute = ExtraAttr


class ParamAttr:
    def __init__(self, name=None, initial_std=None, initial_mean=None,
                 learning_rate=None, **kwargs):
        self.name = name


def _param_name(param_attr):
    """Thread a v2 ParamAttr name down to the fluid layer so that legacy
    configs sharing one parameter by name across layers get genuinely tied
    weights (the fluid scope is name-keyed, so same name == same storage
    and the backward accumulates both consumers' gradients)."""
    return getattr(param_attr, "name", None)


class MomentumOptimizer:
    def __init__(self, momentum=0.9):
        self.momentum = momentum

    def build(self, lr, reg):
        return fluid_opt.Momentum(learning_rate=lr, momentum=self.momentum,
                                  regularization=reg)


class AdamOptimizer:
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.kw = dict(beta1=beta1, beta2=beta2, epsilon=epsilon)

    def build(self, lr, reg):
        return fluid_opt.Adam(learning_rate=lr, regularization=reg,
                              **self.kw)


class L2Regularization:
    def __init__(self, rate):
        self.rate = rate

    def build(self):
        return regularizer.L2DecayRegularizer(self.rate)


_settings = {}


def settings(batch_size=None, learning_rate=1e-3, learning_method=None,
             regularization=None, gradient_clipping_threshold=None,
             **kwargs):
    """ref optimizers.py settings(): record the training hyper-parameters;
    v2.trainer.SGD (or the caller) turns them into a Fluid optimizer."""
    _settings.clear()
    _settings.update(batch_size=batch_size, learning_rate=learning_rate,
                     learning_method=learning_method,
                     regularization=regularization,
                     gradient_clipping_threshold=gradient_clipping_threshold)


def get_settings():
    return dict(_settings)


def build_settings_optimizer():
    """Fluid optimizer from the last settings() call.  Applies the
    config's gradient_clipping_threshold (ref: by-global-norm semantics)
    to every parameter built so far."""
    thresh = _settings.get("gradient_clipping_threshold")
    if thresh:
        from ..fluid import clip

        clip.set_gradient_clip(
            clip.GradientClipByGlobalNorm(float(thresh)))
    method = _settings.get("learning_method") or MomentumOptimizer(0.0)
    reg = _settings.get("regularization")
    return method.build(_settings.get("learning_rate", 1e-3),
                        reg.build() if reg is not None else None)


_outputs = []


def outputs(*layers_):
    """ref config_parser outputs(): mark the topology's sink layers."""
    _outputs[:] = list(layers_)


def get_outputs():
    return list(_outputs)


def define_py_data_sources2(train_list, test_list, module=None, obj=None,
                            args=None):
    """Data comes from Python readers on this substrate; the declaration
    is accepted for config compatibility and otherwise inert."""
    return None


# --- layers --------------------------------------------------------------


def data_layer(name, size, height=None, width=None, depth=None):
    """Flat [size] float input (v2 geometry convention).  Labels are
    declared with data_layer too in v2 configs; integer-classification use
    is detected at the cost layer, not here."""
    v = _fl.data(name=name, shape=[int(size)], dtype="float32")
    v._v2_geom = (height, width)
    v._v2_depth = depth
    return v


def _to_spatial(input, num_channels, rank):
    """Recover [N, C, (D,) H, W] from a flat v2 data layer: declared
    height/width (+depth) win, with the channel count derived from the
    declared geometry when not given; otherwise square/cube guesses with
    the reference's 3-channel heuristic."""
    shape = input.shape
    if shape is not None and len(shape) >= 2 + rank:
        return input, int(shape[1])
    size = int(shape[-1])
    geom = getattr(input, "_v2_geom", None) or (None, None)
    depth = getattr(input, "_v2_depth", None)
    c = num_channels
    if geom[0]:
        h, w = int(geom[0]), int(geom[1] or geom[0])
        if rank == 3:
            spatial = [int(depth) if depth else None, h, w]
        else:
            spatial = [h, w]
        known = math.prod(v for v in spatial if v)
        if c is None:
            c = size // known if None not in spatial else \
                (3 if size % 3 == 0 else 1)
        missing = size // (int(c) * known)
        spatial = [v if v else missing for v in spatial]
    else:
        if c is None:
            c = 3 if size % 3 == 0 else 1
        edge = int(math.isqrt(size // c)) if rank == 2 else \
            round((size // c) ** (1.0 / 3.0))
        spatial = [edge] * rank
    if int(c) * math.prod(spatial) != size:
        raise ValueError(
            f"cannot recover [C,{'D,' if rank == 3 else ''}H,W] from "
            f"size {size} with channels={c} spatial={spatial}")
    return _fl.reshape(input, [-1, int(c)] + spatial), int(c)


def _to_nchw(input, num_channels):
    """Recover [N, C, H, W] from a flat v2 data layer when needed."""
    return _to_spatial(input, num_channels, 2)


# the reference DSL wraps every layer in @wrap_act_default; configs rely
# on these implicit activations (fc->tanh, conv/bn->relu, addto->linear)
def _default_act(act, default):
    return default if act is None else act


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, layer_attr=None):
    act = _default_act(act, TanhActivation())
    out = _fl.fc(input=input, size=int(size), act=_act_name(act),
                    param_attr=_param_name(param_attr), name=name)
    if layer_attr is not None and getattr(layer_attr, "drop_rate", 0):
        out = _fl.dropout(out, dropout_prob=layer_attr.drop_rate)
    return out


def img_conv_layer(input, filter_size, num_filters, name=None,
                   num_channels=None, act=None, groups=1, stride=1,
                   padding=0, bias_attr=None, param_attr=None,
                   trans=False, layer_attr=None):
    act = _default_act(act, ReluActivation())
    x, _ = _to_nchw(input, num_channels)
    conv = _fl.conv2d_transpose if trans else _fl.conv2d
    return conv(input=x, num_filters=int(num_filters),
                filter_size=filter_size, stride=stride,
                padding=padding, groups=groups,
                act=_act_name(act), bias_attr=bias_attr,
                param_attr=_param_name(param_attr), name=name)


def img_pool_layer(input, pool_size, name=None, num_channels=None,
                   pool_type=None, stride=1, padding=0, layer_attr=None,
                   **kwargs):
    x, _ = _to_nchw(input, num_channels)
    if _pool_name(pool_type) == "max_with_mask":
        # max pool + argmax mask (for upsample_layer's unpooling)
        from ..fluid.layer_helper import LayerHelper

        def _pair(v, v_y):
            # list args follow the reference's [x, y] convention; unpack
            # to fluid's (y, x) order exactly like _layers_ext._yx
            if isinstance(v, (list, tuple)):
                return [int(v[-1]), int(v[0])]
            return [int(v_y if v_y is not None else v), int(v)]

        ky, kx = _pair(pool_size, kwargs.get("pool_size_y"))
        sy, sx = _pair(stride, kwargs.get("stride_y"))
        py, px = _pair(padding, kwargs.get("padding_y"))
        helper = LayerHelper("max_pool2d_with_index", name=name)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        mask = helper.create_variable_for_type_inference(dtype="int64")
        mask.stop_gradient = True
        n, c, h, w = x.shape
        oshape = (n, c, (int(h) + 2 * py - ky) // sy + 1,
                  (int(w) + 2 * px - kx) // sx + 1)
        out.shape = mask.shape = oshape
        helper.append_op(
            type="max_pool2d_with_index", inputs={"X": [x]},
            outputs={"Out": [out], "Mask": [mask]},
            attrs={"ksize": [ky, kx], "strides": [sy, sx],
                   "paddings": [py, px]})
        out._v2_outputs = {"mask": mask}
        out._v2_pool_geom = (int(h), int(w))
        _register_named(name, out)
        return out
    return _fl.pool2d(input=x, pool_size=pool_size,
                         pool_type=_pool_name(pool_type),
                         pool_stride=stride, pool_padding=padding)


def batch_norm_layer(input, act=None, name=None, num_channels=None,
                     use_global_stats=None, moving_average_fraction=0.9,
                     layer_attr=None, **kwargs):
    act = _default_act(act, ReluActivation())
    x, _ = _to_nchw(input, num_channels)
    return _fl.batch_norm(input=x, act=_act_name(act),
                             is_test=bool(use_global_stats),
                             momentum=moving_average_fraction)


def addto_layer(input, act=None, name=None, bias_attr=None):
    if not isinstance(input, (list, tuple)):
        input = [input]
    out = input[0]
    for other in input[1:]:
        out = _fl.elementwise_add(out, other)
    a = _act_name(act)  # reference default: LinearActivation
    if a:
        out = getattr(_fl, a)(out)
    return out


def img_cmrnorm_layer(input, size, scale=0.0128, power=0.75, name=None,
                      num_channels=None, layer_attr=None):
    """Cross-map response normalization (ref layers.py:3199; AlexNet's
    LRN).  The v2 ``scale`` is the per-window alpha of the fluid lrn op."""
    x, _ = _to_nchw(input, num_channels)
    return _fl.lrn(x, n=int(size), k=1.0, alpha=scale, beta=power,
                      name=name)


def concat_layer(input, act=None, name=None, layer_attr=None,
                 bias_attr=None):
    """Channel concat (ref layers.py:3527; default IdentityActivation).
    Accepts projection markers (conv_projection etc.) like the
    reference's concat."""
    parts = [_lower_projection(p, None) if isinstance(p, tuple) else p
             for p in _as_proj_list(input)]
    out = _fl.concat(parts, axis=1)
    a = _act_name(act)
    if a:
        out = getattr(_fl, a)(out)
    _register_named(name, out)
    return out


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_batchnorm_drop_rate=0, conv_with_batchnorm=False,
                   pool_stride=1, pool_type=None, **kwargs):
    x, _ = _to_nchw(input, num_channels)
    return nets.img_conv_group(
        input=x, conv_num_filter=list(conv_num_filter),
        pool_size=pool_size, conv_padding=conv_padding,
        conv_filter_size=conv_filter_size, conv_act=_act_name(conv_act),
        conv_with_batchnorm=conv_with_batchnorm,
        conv_batchnorm_drop_rate=conv_batchnorm_drop_rate,
        pool_stride=pool_stride, pool_type=_pool_name(pool_type))


def dropout_layer(input, dropout_rate, name=None):
    return _fl.dropout(input, dropout_prob=dropout_rate)


def _as_label(label):
    """v2 declares classification labels as data_layer(size=num_class);
    the cost layer reinterprets them as int64 class ids [N, 1]."""
    if label.dtype is not None and "int" in str(label.dtype):
        return label
    relabeled = _fl.cast(label, "int64")
    return _fl.reshape(relabeled, [-1, 1]) \
        if len(relabeled.shape or ()) == 2 and relabeled.shape[-1] != 1 \
        else relabeled


def cross_entropy(input, label, name=None, **kwargs):
    return _fl.mean(
        _fl.cross_entropy(input=input, label=_as_label(label)))


def classification_cost(input, label, name=None, **kwargs):
    return cross_entropy(input, label, name=name)


# --- rnn-era surface (ref: layers.py lstmemory/recurrent_group/seq ops, --
# --- networks.py composites; VERDICT r4 missing #2) ----------------------


def _as_id_sequence(input):
    """v2 types inputs at the PROVIDER (integer_value_sequence), not the
    config: a data_layer feeding an embedding is a word-id SEQUENCE.  The
    flat float declaration data_layer made is replaced in-place (same
    name, so feeding is unchanged) with an int64 lod_level=1 var."""
    if getattr(input, "is_data", False) and input.dtype == "float32":
        block = input.block
        for op in block.ops:
            if input.name in op.input_arg_names:
                raise ValueError(
                    f"data_layer {input.name!r} already feeds a float "
                    f"layer; it cannot also be an embedding's id sequence "
                    f"— declare a separate data_layer for the ids")
        block.vars.pop(input.name, None)
        return _fl.data(name=input.name, shape=[1], dtype="int64",
                           lod_level=1)
    return input


def embedding_layer(input, size, name=None, param_attr=None):
    return _fl.embedding(input=_as_id_sequence(input),
                            size=[_vocab_guess(input), int(size)]
                            if not isinstance(size, (list, tuple))
                            else size,
                            param_attr=_param_name(param_attr))


def _vocab_guess(input):
    """v2 embedding_layer takes only the OUT dim; the vocab is the data
    layer's declared size (one-hot convention)."""
    shape = getattr(input, "shape", None) or (30000,)
    return int(shape[-1])


def lstmemory(input, name=None, reverse=False, act=None,
              gate_act=None, state_act=None, bias_attr=None,
              param_attr=None, layer_attr=None):
    """ref layers.py lstmemory: input is the pre-projected [*, 4h]
    sequence; returns the [*, h] hidden sequence."""
    size = int(input.shape[-1])
    hidden, cell = _fl.dynamic_lstm(
        input=input, size=size, is_reverse=bool(reverse),
        use_peepholes=False,
        candidate_activation=_act_name(act) or "tanh",
        gate_activation=_act_name(gate_act) or "sigmoid",
        cell_activation=_act_name(state_act) or "tanh",
        param_attr=_param_name(param_attr), name=name)
    hidden._v2_outputs = {"state": cell}  # get_output_layer('state')
    _register_named(name, hidden)
    return hidden


def simple_lstm(input, size, name=None, reverse=False, act=None,
                gate_act=None, state_act=None, mat_param_attr=None,
                bias_param_attr=None, inner_param_attr=None,
                lstm_bias_attr=None, lstm_layer_attr=None):
    """ref networks.py simple_lstm: full-matrix projection to 4*size then
    an lstmemory."""
    proj = _fl.fc(input=input, size=int(size) * 4, act=None,
                     param_attr=_param_name(mat_param_attr))
    return lstmemory(proj, name=name, reverse=reverse, act=act,
                     gate_act=gate_act, state_act=state_act,
                     param_attr=inner_param_attr)


def bidirectional_lstm(input, size, name=None, return_seq=False, **kw):
    """ref networks.py bidirectional_lstm: forward + backward simple_lstm;
    return_seq=False concatenates last fwd step with first bwd step,
    return_seq=True concatenates the full sequences feature-wise."""
    fwd = simple_lstm(input, size, name=(name + "_fwd") if name else None)
    bwd = simple_lstm(input, size, name=(name + "_bwd") if name else None,
                      reverse=True)
    if return_seq:
        return _fl.concat([fwd, bwd], axis=1)
    return _fl.concat([_fl.sequence_last_step(fwd),
                          _fl.sequence_first_step(bwd)], axis=1)


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride=1, act=None, num_channel=None,
                         pool_type=None, **kw):
    """ref networks.py simple_img_conv_pool -> fluid.nets equivalent."""
    x, _ = _to_nchw(input, num_channel)
    return nets.simple_img_conv_pool(
        input=x, filter_size=filter_size, num_filters=int(num_filters),
        pool_size=pool_size, pool_stride=pool_stride,
        act=_act_name(_default_act(act, ReluActivation())),
        pool_type=_pool_name(pool_type))


def last_seq(input, name=None, **kw):
    out = _fl.sequence_last_step(input)
    _register_named(name, out)
    return out


def first_seq(input, name=None, **kw):
    out = _fl.sequence_first_step(input)
    _register_named(name, out)
    return out


class SumPooling:
    fluid_name = "sum"


def pooling_layer(input, pooling_type=None, name=None, **kw):
    """ref layers.py pooling_layer (seq_pool family): sequence-level
    max/avg/sum pooling.  v2 default is MaxPooling."""
    out = _fl.sequence_pool(input, _pool_name(pooling_type))
    _register_named(name, out)
    return out


# recurrent_group / memory: the v2 step-function RNN.  memory(name=X)
# reads the PREVIOUS step's output of the layer NAMED X (zero boot), the
# name-link resolved when the group closes — same contract as ref
# layers.py:3524 recurrent_group + memory.
_rnn_ctx = None


def _register_named(name, var):
    if name and _rnn_ctx is not None:
        _rnn_ctx["named"][name] = var


def _set_gen_ctx(read_state, restore=None):
    """Install a GENERATION-mode rnn context (memory() reads decoder
    state instead of a DynamicRNN memory — see _generation.py), or
    restore a previous context when read_state is None.  Returns the
    context that was active before the call."""
    global _rnn_ctx
    prev = _rnn_ctx
    _rnn_ctx = restore if read_state is None else \
        {"mode": "gen", "named": {}, "read_state": read_state}
    return prev


def _current_gen_named():
    if _rnn_ctx is None or _rnn_ctx.get("mode") != "gen":
        raise ValueError("no generation context is active")
    return _rnn_ctx["named"]


def memory(name, size, boot_layer=None, **kw):
    if _rnn_ctx is None:
        raise ValueError("memory() is only meaningful inside a "
                         "recurrent_group or beam_search step function")
    if _rnn_ctx.get("mode") == "gen":
        return _rnn_ctx["read_state"](name, int(size), boot_layer)
    rnn = _rnn_ctx["rnn"]
    # need_reorder: a v2 boot tensor is batch-ordered; DynamicRNN runs
    # sequences in length-sorted order, so the init must be reordered or
    # each sequence would start from another example's state
    mem = rnn.memory(init=boot_layer, need_reorder=True) \
        if boot_layer is not None \
        else rnn.memory(shape=[int(size)], value=0.0)
    _rnn_ctx["mems"].append((name, mem))
    return mem


def recurrent_group(step, input, reverse=False, name=None):
    global _rnn_ctx
    if _rnn_ctx is not None:
        raise ValueError("nested recurrent_group is not supported")
    ins = list(input) if isinstance(input, (list, tuple)) else [input]
    if reverse:
        ins = [_fl.sequence_reverse(x) for x in ins]
    rnn = _fl.DynamicRNN()
    _rnn_ctx = {"rnn": rnn, "mems": [], "named": {}}
    try:
        with rnn.block():
            step_ins = [rnn.step_input(x) for x in ins]
            out = step(*step_ins)
            for mname, mem in _rnn_ctx["mems"]:
                tgt = _rnn_ctx["named"].get(mname)
                if tgt is None:
                    raise ValueError(
                        f"memory(name={mname!r}) has no layer of that "
                        f"name in the step function to link to")
                rnn.update_memory(mem, tgt)
            rnn.output(*(out if isinstance(out, (list, tuple)) else
                         [out]))
    finally:
        _rnn_ctx = None
    res = rnn()
    if reverse:
        if isinstance(res, (list, tuple)):
            res = [_fl.sequence_reverse(r) for r in res]
        else:
            res = _fl.sequence_reverse(res)
    return res


def full_matrix_projection(input, size=None, param_attr=None):
    """ref layers.py full_matrix_projection — a marker consumed by
    mixed_layer/concat_layer (the marker carries its own size so a
    size-less consumer like concat can still lower it)."""
    return ("fmp", input, {"size": size, "name": _param_name(param_attr)})


def identity_projection(input, **kw):
    return ("idp", input, None)


_PROJ_KINDS = ("fmp", "idp", "dmp", "scp", "tbp", "slp", "dop", "tfmp",
               "cvp", "cvo", "ctp")


def _lower_projection(p, size):
    """Turn one projection/operator marker (or a bare Variable ≡ fmp)
    into its summand Variable (shared by mixed_layer and concat_layer)."""
    kind, x, extra = p if isinstance(p, tuple) else ("fmp", p, None)
    if kind == "idp":
        return x
    if kind == "dmp":  # dotmul: learned per-feature weight
        w = _fl.create_parameter([int(x.shape[-1])], "float32",
                                 name=extra)
        return _fl.elementwise_mul(x, w, axis=1)
    if kind == "scp":  # scaling: learned scalar
        w = _fl.create_parameter([1], "float32", name=extra)
        return _fl.elementwise_mul(x, w)
    if kind == "tbp":  # table: embedding lookup of an id sequence
        tsize, pname = extra
        if tsize is None and size is None:
            raise ValueError("mixed_layer needs size= (or "
                             "table_projection size=) for "
                             "table_projection inputs")
        width = int(tsize or size)
        return _fl.embedding(input=_as_id_sequence(x),
                             size=[_vocab_guess(x), width],
                             param_attr=pname)
    if kind == "slp":  # slice columns [(start, end), ...]
        pieces = [_fl.slice(x, axes=[1], starts=[int(s)], ends=[int(e)])
                  for s, e in extra]
        return pieces[0] if len(pieces) == 1 else _fl.concat(pieces,
                                                             axis=1)
    if kind == "dop":  # dotmul_operator: a ⊙ b * scale
        a_in, b_in = x
        out = _fl.elementwise_mul(a_in, b_in)
        if extra != 1.0:
            out = _fl.scale(out, scale=extra)
        return out
    if kind == "tfmp":
        # x @ W^T where the tied W has the PARTNER's [size, d] shape,
        # so a name-shared full_matrix_projection weight really is
        # used transposed (the reference's tied-autoencoder pattern)
        psize, pname = _proj_size_name(extra, size)
        if psize is None:
            raise ValueError("trans_full_matrix_projection needs size= "
                             "(on the projection or its mixed_layer)")
        w = _fl.create_parameter([int(psize), int(x.shape[-1])],
                                 "float32", name=pname)
        return _fl.matmul(x, w, transpose_y=True)
    if kind == "cvp":  # conv_projection: learned-filter conv, flattened
        cfg = dict(extra)
        img, _ = _to_nchw(x, cfg.pop("num_channels"))
        conv = _fl.conv2d_transpose if cfg.pop("trans", False) \
            else _fl.conv2d
        out = conv(input=img, act=None, bias_attr=False, **cfg)
        return _fl.reshape(out, [-1, _prod(out.shape[1:])])
    if kind == "cvo":  # conv_operator: the FILTER comes from a layer
        img_in, filt = x
        cfg = dict(extra)
        img, cin = _to_nchw(img_in, cfg.pop("num_channels"))
        nf, k, ky = cfg["num_filters"], cfg["filter_size"], \
            cfg["filter_size_y"]
        w = _fl.reshape(filt, [int(nf), int(cin), int(ky), int(k)])
        out = _conv_with_filter_var(img, w, stride=cfg["stride"],
                                    padding=cfg["padding"])
        return _fl.reshape(out, [-1, _prod(out.shape[1:])])
    if kind == "ctp":  # context window concat per sequence step
        from ._layers_ext import _lower_context_projection

        context_len, start = extra
        return _lower_context_projection(x, context_len, start)
    if kind == "fmp":
        psize, pname = _proj_size_name(extra, size)
        if psize is None:
            raise ValueError("full_matrix_projection needs size= (on "
                             "the projection or its mixed_layer)")
        return _fl.fc(input=x, size=int(psize), act=None,
                      param_attr=pname, bias_attr=False)
    raise ValueError(f"unknown projection kind {kind!r}")


def _proj_size_name(extra, consumer_size):
    """fmp/tfmp markers carry {'size', 'name'}; a bare Variable
    shorthand arrives with extra=None.  The projection's own size wins,
    else the consumer's (mixed_layer size=)."""
    if isinstance(extra, dict):
        return (extra.get("size") if extra.get("size") is not None
                else consumer_size), extra.get("name")
    return consumer_size, extra


def _conv_with_filter_var(img, w, stride=(1, 1), padding=(0, 0)):
    """conv2d whose Filter is an arbitrary Variable (the conv2d OP takes
    any var; only the layers.conv2d wrapper insists on creating a
    parameter) — the cudnn conv_op role (ref layers.py conv_operator).
    stride/padding are (y, x) pairs."""
    from ..fluid.layer_helper import LayerHelper

    helper = LayerHelper("conv2d")
    out = helper.create_variable_for_type_inference(dtype=img.dtype)
    n, _, h, wd = img.shape
    nf, _, ky, kx = w.shape
    (sy, sx), (py, px) = ([int(v) for v in stride],
                          [int(v) for v in padding])
    out.shape = (n, int(nf), (int(h) + 2 * py - int(ky)) // sy + 1,
                 (int(wd) + 2 * px - int(kx)) // sx + 1)
    helper.append_op(
        type="conv2d", inputs={"Input": [img], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": [sy, sx], "paddings": [py, px],
               "dilations": [1, 1], "groups": 1, "use_cudnn": False})
    return out


def _prod(xs):
    return math.prod(int(v) for v in xs)


def _as_proj_list(input):
    """A single bare projection marker, a list, or a single Variable."""
    if (isinstance(input, tuple) and len(input) == 3
            and input[0] in _PROJ_KINDS):
        return [input]
    if isinstance(input, (list, tuple)):
        return list(input)
    return [input]


def mixed_layer(size=None, input=None, act=None, bias_attr=None,
                name=None, layer_attr=None):
    """ref layers.py mixed_layer: sum of projections + activation."""
    act = _default_act(act, LinearActivation())
    parts = [_lower_projection(p, size) for p in _as_proj_list(input)]
    out = parts[0]
    for other in parts[1:]:
        out = _fl.elementwise_add(out, other)
    if size is None:  # identity-only form: width from the projection
        size = (parts[0].shape or (None,))[-1]
    if bias_attr is not False and size is not None:
        out = _fl.elementwise_add(
            out, _fl.create_parameter([int(size)], "float32",
                                         name=None))
    a = _act_name(act)
    if a:
        out = getattr(_fl, a)(out)
    _register_named(name, out)
    return out


__all__ += [
    "lstmemory", "simple_lstm", "bidirectional_lstm",
    "simple_img_conv_pool", "last_seq", "first_seq", "pooling_layer",
    "SumPooling", "memory", "recurrent_group", "mixed_layer",
    "full_matrix_projection", "identity_projection",
]


# --- evaluators (ref: trainer_config_helpers/evaluators.py; the config
# DSL star-imports them so a legacy config calls them bare) --------------
from .evaluators import (auc_evaluator, chunk_evaluator,  # noqa: E402
                         classification_error_evaluator,
                         column_sum_evaluator, ctc_error_evaluator,
                         get_evaluators, pnpair_evaluator,
                         precision_recall_evaluator, reset_evaluators,
                         sum_evaluator, value_printer_evaluator)

__all__ += [
    "classification_error_evaluator", "auc_evaluator", "pnpair_evaluator",
    "precision_recall_evaluator", "ctc_error_evaluator", "chunk_evaluator",
    "sum_evaluator", "column_sum_evaluator", "value_printer_evaluator",
    "get_evaluators", "reset_evaluators",
]

__all__ += [
    "IdentityActivation", "ExpActivation", "LogActivation",
    "AbsActivation", "SquareActivation", "SqrtActivation",
    "ReciprocalActivation", "BReluActivation", "SoftReluActivation",
    "STanhActivation", "SquareRootNPooling", "CudnnMaxPooling",
    "CudnnAvgPooling", "MaxWithMaskPooling",
]

# --- extended layer surface (costs, seq ops, vision, projections, ---
# --- composites — ref layers.py's remaining __all__) ------------------
from ._layers_ext import *  # noqa: E402,F401,F403
from ._layers_ext import _absent_getattr  # noqa: E402
from ._layers_ext import __all__ as _ext_all  # noqa: E402

__all__ += list(_ext_all)


# --- v2 generation machinery (beam_search / StaticInput / GeneratedInput
# — ref layers.py beam_search; lowered onto the contrib decoder) ---------
from ._generation import (BaseGeneratedInput, GeneratedInput,  # noqa: E402
                          GenerationResult, StaticInput, beam_search)
from .framework_types import LayerOutput  # noqa: E402

__all__ += ["beam_search", "StaticInput", "GeneratedInput",
            "BaseGeneratedInput", "GenerationResult", "LayerOutput"]

# Reference-compatible submodule import paths (paddle.trainer_config_
# helpers.{layers,networks,activations,poolings,attrs,optimizers}).
# Imported explicitly so the package attribute `layers` is the compat
# submodule, not the fluid layer library (which lives here as _fl).
from . import (activations, attrs, evaluators,  # noqa: E402,F401
               layers, networks, optimizers, poolings)


# PEP 562: documented absences fail loudly (shared with _layers_ext)
__getattr__ = _absent_getattr
