"""Mesh construction (ref analogue: platform/nccl_helper.h NCCLContextMap —
rank math over trainers × local GPUs becomes an N-D device mesh)."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def local_device_count(platform=None) -> int:
    try:
        return len(jax.devices(platform)) if platform else len(jax.devices())
    except RuntimeError:
        return 0


def make_mesh(n_devices=None, tp=1, axis_names=("dp", "mp")) -> Mesh:
    """Build a (dp × tp) mesh over the first n_devices devices.

    tp ("mp" axis) shards model weights; dp shards the batch.  On a real pod
    the mesh should map tp to the innermost ICI dimension — jax device order
    already enumerates ICI-adjacent chips first.
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, only {len(devs)} visible")
    if n % tp != 0:
        raise ValueError(f"n_devices={n} not divisible by tp={tp}")
    arr = np.array(devs[:n]).reshape(n // tp, tp)
    return Mesh(arr, axis_names)
