"""Mesh construction (ref analogue: platform/nccl_helper.h NCCLContextMap —
rank math over trainers × local GPUs becomes an N-D device mesh).

Named multi-axis meshes (ISSUE 7): ``PADDLE_TPU_MESH`` carries the
topology as a compact spec string — ``dp4,tp2`` is a 4×2 mesh whose first
axis shards the batch and whose second shards model weights; axis order =
spec order, later axes map to faster-varying (more ICI-adjacent) device
indices.  Recognized axis names: ``dp`` (data), ``tp`` (tensor/Megatron),
``fsdp`` (parameter sharding), plus the legacy ``mp``/``sp``/``ep``/``pp``
names the dryruns use.  ``mesh_from_spec()`` is the one constructor every
consumer (DistributeTranspiler → ParallelExecutor → ShardedWindowRunner)
goes through, and ``mesh_label()`` (``dp4xtp2``) is the observe/metrics
label for the topology.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh

MESH_ENV = "PADDLE_TPU_MESH"

_AXIS_RE = re.compile(r"([a-zA-Z_]+?)(\d+)$")


def local_device_count(platform=None) -> int:
    try:
        return len(jax.devices(platform)) if platform else len(jax.devices())
    except RuntimeError:
        return 0


def make_mesh(n_devices=None, tp=1, axis_names=("dp", "mp")) -> Mesh:
    """Build a (dp × tp) mesh over the first n_devices devices.

    tp ("mp" axis) shards model weights; dp shards the batch.  On a real pod
    the mesh should map tp to the innermost ICI dimension — jax device order
    already enumerates ICI-adjacent chips first.
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, only {len(devs)} visible")
    if n % tp != 0:
        raise ValueError(f"n_devices={n} not divisible by tp={tp}")
    arr = np.array(devs[:n]).reshape(n // tp, tp)
    return Mesh(arr, axis_names)


def parse_mesh_spec(spec: str) -> Dict[str, int]:
    """``"dp4,tp2"`` -> ``{"dp": 4, "tp": 2}`` (insertion-ordered).

    Raises ``ValueError`` on malformed tokens or duplicate axes so a typo
    in ``PADDLE_TPU_MESH`` fails loudly at mesh construction, not as an
    opaque reshape error deep in jit."""
    axes: Dict[str, int] = {}
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        m = _AXIS_RE.fullmatch(tok)
        if m is None:
            raise ValueError(
                f"bad mesh axis {tok!r} in spec {spec!r} — expected "
                f"<name><extent> tokens like 'dp4,tp2'")
        name, size = m.group(1), int(m.group(2))
        if name in axes:
            raise ValueError(f"duplicate mesh axis {name!r} in {spec!r}")
        if size < 1:
            raise ValueError(f"mesh axis {tok!r} must have extent >= 1")
        axes[name] = size
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}")
    return axes


def env_mesh_spec() -> Optional[str]:
    """The ``PADDLE_TPU_MESH`` spec string, or None when unset/empty."""
    from ..fluid import envcontract

    return envcontract.get(MESH_ENV) or None


def mesh_from_spec(spec: Optional[str] = None, devices=None) -> Mesh:
    """Build a named mesh from a ``dp4,tp2``-style spec.

    ``spec=None`` reads ``PADDLE_TPU_MESH``; with neither, the result is a
    1-axis ``("dp",)`` mesh over all (given) devices — the degenerate
    data-parallel mesh the old ParallelExecutor always built.  Later spec
    axes map onto faster-varying device indices (the ICI-adjacent
    dimension), so put the most communication-hungry axis last."""
    if spec is None:
        spec = env_mesh_spec()
    devs = list(devices) if devices is not None else list(jax.devices())
    if not spec:
        return Mesh(np.array(devs), ("dp",))
    axes = parse_mesh_spec(spec)
    sizes = tuple(axes.values())
    n = int(np.prod(sizes))
    if n > len(devs):
        raise ValueError(
            f"mesh spec {spec!r} needs {n} devices, only {len(devs)} "
            f"visible")
    arr = np.array(devs[:n]).reshape(sizes)
    return Mesh(arr, tuple(axes))


def mesh_label(mesh: Mesh) -> str:
    """Canonical topology label for metrics/events: ``dp4xtp2``."""
    return "x".join(f"{a}{mesh.shape[a]}" for a in mesh.axis_names)


def axes_of(mesh=None) -> Dict[str, int]:
    """Ordered ``{axis: extent}`` for a ``jax.sharding.Mesh``, a spec
    string (``"dp4,tp2"``), an already-parsed ``[[name, extent], ...]``
    list (the form checkpoint meta records), or ``None`` (the
    ``PADDLE_TPU_MESH`` env spec).  ``{}`` when nothing is known — the
    one normal form every mesh consumer (data sharding, reshard-on-load,
    checkpoint meta) compares topologies in."""
    if mesh is None:
        spec = env_mesh_spec()
        return parse_mesh_spec(spec) if spec else {}
    if isinstance(mesh, str):
        return parse_mesh_spec(mesh)
    if isinstance(mesh, (list, tuple)):
        return {str(a): int(e) for a, e in mesh}
    return {name: int(mesh.shape[name]) for name in mesh.axis_names}


def axes_label(axes: Dict[str, int]) -> Optional[str]:
    """``{"dp": 4, "tp": 2}`` -> ``dp4xtp2`` (None for an empty dict) —
    the :func:`mesh_label` form for topologies known only by shape."""
    if not axes:
        return None
    return "x".join(f"{a}{int(e)}" for a, e in axes.items())


def make_mesh_nd(**axes) -> Mesh:
    """N-D mesh from named axis sizes, e.g. ``make_mesh_nd(dp=2, mp=2,
    pp=2)``.  Axis order = keyword order (python dicts preserve it); later
    axes map to faster-varying device indices, i.e. the innermost/most-
    ICI-adjacent dimension — put the most communication-hungry axis last."""
    names = tuple(axes)
    sizes = tuple(int(s) for s in axes.values())
    n = int(np.prod(sizes))
    devs = jax.devices()
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, only {len(devs)} visible")
    arr = np.array(devs[:n]).reshape(sizes)
    return Mesh(arr, names)
