"""SPMD sharding of traced Programs over a mesh.

This is the TPU-native replacement for the reference's
multi_devices_graph_pass (ref: details/multi_devices_graph_pass.cc:323):
instead of replicating ops per device and inserting AllReduce op-handles, we
annotate shardings on the ONE traced XLA program and let GSPMD partition it:

 - batch ("dp" axis): every fed tensor sharded on dim 0 → data parallelism;
   gradient all-reduce falls out of the partitioned backward matmuls.
 - tensor parallelism ("mp" axis): 2-D parameters (fc/embedding weights) and
   their optimizer accumulators sharded on the output dim; XLA inserts the
   activation all-gathers/reduce-scatters over ICI.

ZeRO-1 style optimizer-state sharding (BuildStrategy.ReduceStrategy.Reduce)
uses the same mechanism with accumulator specs sharded on "dp".
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..fluid import core
from ..fluid.executor import BlockPlan, _MISSING, global_scope, trace_block
from ..fluid.framework import Parameter, Program, RNG_STATE_VAR


def batch_spec(mesh: Mesh) -> P:
    return P("dp") if "dp" in mesh.axis_names else P(mesh.axis_names[0])


def infer_param_specs(program: Program, plan: BlockPlan, mesh: Mesh,
                      tp_axis: str = "mp", zero1: bool = False) -> Dict[str, P]:
    """Choose a PartitionSpec per state var.

    2-D params with a dim divisible by the tp axis size get sharded on that
    dim (prefer the output/last dim); accumulators follow their param (same
    shape) — matching how Megatron-style TP shards fc/embedding weights.
    """
    if tp_axis not in mesh.axis_names:
        return {n: P() for n in set(plan.state_in) | set(plan.state_out)}
    tp_size = mesh.shape[tp_axis]
    gb = program.global_block()

    def spec_for_shape(shape) -> P:
        if shape is None or len(shape) < 2:
            return P()
        # shard last dim if divisible, else second-to-last, else replicate
        if shape[-1] is not None and shape[-1] % tp_size == 0 and shape[-1] >= tp_size:
            return P(*([None] * (len(shape) - 1) + [tp_axis]))
        if shape[0] is not None and shape[0] % tp_size == 0 and shape[0] >= tp_size:
            return P(*([tp_axis] + [None] * (len(shape) - 1)))
        return P()

    specs: Dict[str, P] = {}
    param_shapes = {}
    for name in set(plan.state_in) | set(plan.state_out):
        if name == RNG_STATE_VAR:
            specs[name] = P()
            continue
        if gb._has_var_recursive(name):
            v = gb._var_recursive(name)
            if isinstance(v, Parameter) and v.shape is not None \
                    and len(v.shape) == 2:
                specs[name] = spec_for_shape(v.shape)
                param_shapes[name] = tuple(v.shape)
                continue
        specs[name] = None  # decide below (maybe accumulator)
    # accumulators are named "<acc>_<param.name>_<k>" and share the param's
    # shape; give them the param's spec so optimizer math stays local
    for name, spec in list(specs.items()):
        if spec is not None:
            continue
        v = gb._var_recursive(name) if gb._has_var_recursive(name) else None
        shape = tuple(v.shape) if v is not None and v.shape else None
        matched = P()
        for pname, pshape in param_shapes.items():
            if pname in name and shape == pshape:
                matched = specs[pname]
                break
        specs[name] = matched
    return specs


class ShardedTrainStep:
    """A Program's block jitted over a mesh with explicit shardings.

    Used by __graft_entry__.dryrun_multichip and the multihost runner; the
    single-host ParallelExecutor uses the degenerate dp-only version.
    """

    def __init__(self, program: Program, feed_names: List[str],
                 fetch_names: List[str], mesh: Mesh, tp_axis: str = "mp",
                 donate: bool = False):
        self.program = program
        self.mesh = mesh
        self.plan = BlockPlan(program, 0, feed_names, fetch_names)
        self.specs = infer_param_specs(program, self.plan, mesh, tp_axis)
        self.bspec = batch_spec(mesh)

        plan = self.plan

        def fn(feed_vals, state_vals):
            return trace_block(program, 0, plan, feed_vals, state_vals)

        # input shardings are carried by the device_put arrays (place_feed /
        # place_state); pin only the output state so updated params keep
        # their layout across steps.
        out_state_names = list(plan.state_out) + \
            ([RNG_STATE_VAR] if plan.needs_rng else [])
        out_shardings = (
            None,
            {k: NamedSharding(mesh, self.specs.get(k, P()))
             for k in out_state_names},
        )
        self._fn = jax.jit(
            fn,
            out_shardings=out_shardings,
            donate_argnums=(1,) if donate else ())

    def place_state(self, scope=None):
        """Device-put scope state with the chosen shardings."""
        scope = scope or global_scope()
        state = {}
        for name in self.plan.state_in:
            val = scope.get(name, _MISSING)
            if val is _MISSING:
                raise RuntimeError(f"state var {name} missing from scope")
            sh = NamedSharding(self.mesh, self.specs.get(name, P()))
            state[name] = jax.device_put(jnp.asarray(val), sh)
        if self.plan.needs_rng:
            rk = scope.get(RNG_STATE_VAR, _MISSING)
            if rk is _MISSING:
                rk = jax.random.PRNGKey(self.program.random_seed or 0)
            state[RNG_STATE_VAR] = jax.device_put(
                rk, NamedSharding(self.mesh, P()))
        return state

    def place_feed(self, feed: Dict[str, np.ndarray]):
        sh = NamedSharding(self.mesh, self.bspec)
        out = {}
        gb = self.program.global_block()
        for k, v in feed.items():
            arr = np.asarray(v)
            if gb._has_var_recursive(k):
                want = core.np_dtype(gb._var_recursive(k).dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            out[k] = jax.device_put(arr, sh)
        return out

    def __call__(self, feed, state):
        return self._fn(feed, state)


def shard_program_step(program, feed_names, fetch_names, mesh, **kw):
    return ShardedTrainStep(program, feed_names, fetch_names, mesh, **kw)
