"""SPMD sharding of traced Programs over a mesh.

This is the TPU-native replacement for the reference's
multi_devices_graph_pass (ref: details/multi_devices_graph_pass.cc:323):
instead of replicating ops per device and inserting AllReduce op-handles, we
annotate shardings on the ONE traced XLA program and let GSPMD partition it:

 - batch ("dp" axis): every fed tensor sharded on dim 0 → data parallelism;
   gradient all-reduce falls out of the partitioned backward matmuls.
 - tensor parallelism ("mp" axis): 2-D parameters (fc/embedding weights) and
   their optimizer accumulators sharded on the output dim; XLA inserts the
   activation all-gathers/reduce-scatters over ICI.

ZeRO-1 style optimizer-state sharding (BuildStrategy.ReduceStrategy.Reduce)
uses the same mechanism with accumulator specs sharded on "dp".
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..fluid import core
from ..fluid.executor import BlockPlan, _MISSING, global_scope, trace_block
from ..fluid.framework import Parameter, Program, RNG_STATE_VAR


def batch_spec(mesh: Mesh) -> P:
    return P("dp") if "dp" in mesh.axis_names else P(mesh.axis_names[0])


# -- active-mesh context: ops whose implementation is mesh-aware (ring
# attention) discover the mesh their trace is being partitioned over --
_ACTIVE_MESH: List[Mesh] = []


def active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH[-1] if _ACTIVE_MESH else None


class mesh_scope:
    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        _ACTIVE_MESH.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _ACTIVE_MESH.pop()
        return False


def infer_param_specs(program: Program, plan: BlockPlan, mesh: Mesh,
                      tp_axis: str = "mp", zero1: bool = False,
                      dp_axis: str = "dp") -> Dict[str, P]:
    """Choose a PartitionSpec per state var.

    2-D params with a dim divisible by the tp axis size get sharded on that
    dim (prefer the output/last dim); accumulators follow their param (same
    shape) — matching how Megatron-style TP shards fc/embedding weights.

    zero1=True additionally shards optimizer accumulators over the dp axis
    (ReduceStrategy.Reduce ≡ ZeRO-1, ref multi_devices_graph_pass.cc:434-446
    kReduce): params stay replicated, their m/v/momentum state is partitioned
    on dp, and GSPMD all-gathers the updated params after the (now sharded)
    optimizer math — the reduce-to-owner + broadcast-param dataflow of the
    reference expressed as shardings.
    """
    has_tp = tp_axis in mesh.axis_names
    has_dp = zero1 and dp_axis in mesh.axis_names and mesh.shape[dp_axis] > 1

    def hint_spec(v) -> Optional[P]:
        """Params created with sharding hints.

        ``dist_spec``: a per-dim tuple of mesh-axis names/None (stacked
        transformer params — e.g. ("pp", None, "mp")); axes absent from the
        mesh or with non-divisible dims degrade to replicated PER DIM, so
        the same program runs on any mesh shape.  A param with a dist_spec
        never falls through to the generic 2-D TP heuristic (a stacked
        [L, d] layer-norm scale must NOT shard d over mp — the shard_map
        body expects it replicated).

        ``dist_hint``: a single axis name (expert weights → "ep",
        pipeline-stacked weights → "pp") sharding dim 0 on that axis.
        """
        ds = getattr(v, "dist_spec", None)
        if ds is not None:
            shape = v.shape or ()
            dims = []
            for d, ax in enumerate(ds[: len(shape)]):
                ok = (ax is not None and ax in mesh.axis_names
                      and mesh.shape[ax] > 1 and shape[d] is not None
                      and shape[d] % mesh.shape[ax] == 0)
                dims.append(ax if ok else None)
            return P(*dims)
        axis = getattr(v, "dist_hint", None)
        if axis is None or axis not in mesh.axis_names \
                or mesh.shape[axis] <= 1:
            return None
        shape = v.shape
        if not shape or shape[0] is None or shape[0] % mesh.shape[axis] != 0:
            return None
        return P(*([axis] + [None] * (len(shape) - 1)))

    has_hints = any(
        getattr(v, "dist_hint", None) in mesh.axis_names
        or any(ax in mesh.axis_names
               for ax in (getattr(v, "dist_spec", None) or ()) if ax)
        for v in program.global_block().vars.values()
        if isinstance(v, Parameter))
    if not has_tp and not has_dp and not has_hints:
        return {n: P() for n in set(plan.state_in) | set(plan.state_out)}
    tp_size = mesh.shape[tp_axis] if has_tp else 1
    dp_size = mesh.shape[dp_axis] if has_dp else 1
    gb = program.global_block()

    def spec_for_shape(shape) -> P:
        if not has_tp or shape is None or len(shape) < 2:
            return P()
        # shard last dim if divisible, else second-to-last, else replicate
        if shape[-1] is not None and shape[-1] % tp_size == 0 and shape[-1] >= tp_size:
            return P(*([None] * (len(shape) - 1) + [tp_axis]))
        if shape[0] is not None and shape[0] % tp_size == 0 and shape[0] >= tp_size:
            return P(*([tp_axis] + [None] * (len(shape) - 1)))
        return P()

    def zero1_spec(shape, base: P) -> P:
        """Shard an accumulator's first dp-divisible, not-already-sharded
        dim on dp (ZeRO-1)."""
        if not has_dp or shape is None:
            return base
        used = list(base) + [None] * (len(shape) - len(base))
        for d, n in enumerate(shape):
            if used[d] is None and n is not None and n % dp_size == 0 \
                    and n >= dp_size:
                used[d] = dp_axis
                return P(*used)
        return base

    specs: Dict[str, P] = {}
    param_shapes = {}
    for name in set(plan.state_in) | set(plan.state_out):
        if name == RNG_STATE_VAR:
            specs[name] = P()
            continue
        if gb._has_var_recursive(name):
            v = gb._var_recursive(name)
            hs = hint_spec(v) if isinstance(v, Parameter) else None
            if hs is not None:
                specs[name] = hs
                param_shapes[name] = tuple(v.shape)
                continue
            if isinstance(v, Parameter) and v.shape is not None \
                    and len(v.shape) == 2:
                specs[name] = spec_for_shape(v.shape)
                param_shapes[name] = tuple(v.shape)
                continue
            if isinstance(v, Parameter):
                specs[name] = P()
                param_shapes[name] = tuple(v.shape) if v.shape else None
                continue
        specs[name] = None  # decide below (maybe accumulator)
    # accumulators share their param's spec (plus dp under ZeRO-1) so
    # optimizer math stays local.  Ownership comes from the optimizer's
    # explicit registry (Program._accumulator_owner, written by
    # Optimizer._add_accumulator); the name-containment fallback only covers
    # programs rebuilt without an optimizer object (e.g. deserialized).
    acc_owner = getattr(program, "_accumulator_owner", {})
    for name, spec in list(specs.items()):
        if spec is not None:
            continue
        v = gb._var_recursive(name) if gb._has_var_recursive(name) else None
        shape = tuple(v.shape) if v is not None and v.shape else None
        matched = P()
        pname = acc_owner.get(name)
        if pname is not None:
            if pname in param_shapes and shape == param_shapes[pname] \
                    and shape is not None:
                matched = zero1_spec(shape, specs[pname])
            # else: shape-[1] state like beta_pow stays replicated
        else:
            for pname, pshape in param_shapes.items():
                if pname in name and shape == pshape and shape is not None:
                    matched = zero1_spec(shape, specs[pname])
                    break
        specs[name] = matched
    return specs


class ShardedTrainStep:
    """A Program's block jitted over a mesh with explicit shardings.

    Used by __graft_entry__.dryrun_multichip and the multihost runner; the
    single-host ParallelExecutor uses the degenerate dp-only version.
    """

    def __init__(self, program: Program, feed_names: List[str],
                 fetch_names: List[str], mesh: Mesh, tp_axis: str = "mp",
                 donate: bool = False, zero1: bool = False,
                 multihost: bool = False,
                 feed_specs: Optional[Dict[str, P]] = None):
        self.program = program
        self.mesh = mesh
        self.multihost = multihost
        self.plan = BlockPlan(program, 0, feed_names, fetch_names)
        self.specs = infer_param_specs(program, self.plan, mesh, tp_axis,
                                       zero1=zero1)
        self.bspec = batch_spec(mesh)
        # per-feed PartitionSpec overrides (e.g. long sequences sharded on
        # an "sp" axis at the SOURCE: P("dp", "sp") for [N, T] token feeds
        # avoids an all-gather+reslice before the first ring step); axes
        # absent from the mesh degrade to replicated per dim
        self.feed_specs = {}
        for name, spec in (feed_specs or {}).items():
            dims = [ax if (ax is None or (ax in mesh.axis_names
                                          and mesh.shape[ax] > 1)) else None
                    for ax in tuple(spec)]
            self.feed_specs[name] = P(*dims)
        self._bdiv = None  # lazy: jax.process_index needs initialized dist

        plan = self.plan

        def fn(feed_vals, state_vals):
            with mesh_scope(mesh):
                return trace_block(program, 0, plan, feed_vals, state_vals)

        # input shardings are carried by the placed arrays (place_feed /
        # place_state); pin the output state so updated params keep their
        # layout across steps, and pin fetches replicated so every host can
        # materialize them (Fluid fetch semantics: full value on host).
        out_state_names = list(plan.state_out) + \
            ([RNG_STATE_VAR] if plan.needs_rng else [])
        out_shardings = (
            NamedSharding(mesh, P()),
            {k: NamedSharding(mesh, self.specs.get(k, P()))
             for k in out_state_names},
        )
        self._fn = jax.jit(
            fn,
            out_shardings=out_shardings,
            donate_argnums=(1,) if donate else ())

    def _place(self, val, sh: NamedSharding, from_full: bool = False):
        """from_full=True: ``val`` is the FULL global value on every host
        (state vars after identical init) — sharded specs slice it.
        from_full=False: ``val`` is this process's LOCAL piece (feeds) —
        sharded specs concatenate across processes."""
        if isinstance(val, jax.Array) and getattr(val, "sharding", None) == sh:
            return val
        if self.multihost:
            if isinstance(val, jax.Array) and not val.is_fully_addressable:
                return val  # already a global array from a previous step
            from . import multihost as mh

            arr = np.asarray(val)
            if sh.spec == P() or from_full:
                # State must be bit-identical across hosts; broadcast
                # process 0's value rather than trusting per-host init
                # (ref: parallel_executor.cc:234 BCastParamsToDevices).
                from jax.experimental import multihost_utils as mhu

                arr = np.asarray(mhu.broadcast_one_to_all(arr))
            if from_full and sh.spec != P():
                # full value everywhere + sharded spec (ZeRO-1 accumulators,
                # mp weights): each device takes ITS SLICE of the full
                # array — host_local concatenation would inflate the shape
                return jax.make_array_from_callback(
                    arr.shape, sh, lambda idx, a=arr: a[idx])
            return mh.host_local_to_global(arr, self.mesh, sh.spec)
        return jax.device_put(jnp.asarray(val), sh)

    def place_state(self, scope=None):
        """Place scope state onto the mesh with the chosen shardings."""
        scope = scope or global_scope()
        state = {}
        for name in self.plan.state_in:
            val = scope.get(name, _MISSING)
            if val is _MISSING:
                raise RuntimeError(f"state var {name} missing from scope")
            sh = NamedSharding(self.mesh, self.specs.get(name, P()))
            state[name] = self._place(val, sh, from_full=True)
        if self.plan.needs_rng:
            rk = scope.get(RNG_STATE_VAR, _MISSING)
            if rk is _MISSING:
                rk = jax.random.PRNGKey(self.program.random_seed or 0)
            state[RNG_STATE_VAR] = self._place(
                rk, NamedSharding(self.mesh, P()), from_full=True)
        return state

    def _batch_divisor(self) -> int:
        """How many equal shards this process's feed must split into: the
        whole batch-axis size single-host, but only the LOCAL extent of the
        batch axes multihost (each process feeds its local batch; the batch
        axis may span processes — dp over DCN — or live inside one)."""
        axes = [ax for ax in self.bspec if ax is not None]
        if not axes:
            return 1
        if not self.multihost:
            n = 1
            for ax in axes:
                n *= self.mesh.shape[ax]
            return n
        pid = jax.process_index()
        devs = self.mesh.devices
        local = np.vectorize(lambda d: d.process_index == pid)(devs)
        n = 1
        for ax in axes:
            ai = list(self.mesh.axis_names).index(ax)
            n *= sum(1 for i in range(devs.shape[ai])
                     if np.take(local, i, axis=ai).any())
        return n

    def place_feed(self, feed: Dict[str, np.ndarray]):
        """Shard feeds on the batch axis.  Multihost: each process passes its
        LOCAL batch; the global batch is num_processes x local.

        Uneven final batches (ref: details/data_balance_op_handle.cc — the
        reference redistributes short batches so no device sees a ragged
        shard): a batch whose leading dim is NOT divisible by the dp size
        cannot shard evenly, so it executes REPLICATED — every device
        computes the full short batch, which is mathematically identical to
        the single-device result (exact loss, exact update; no padding
        bias).  It costs the dp speedup for that one (final) batch and one
        extra compile for its shape — the shape change forces a recompile
        anyway."""
        if self._bdiv is None:
            self._bdiv = self._batch_divisor()
        dp_size = self._bdiv
        arrays = {k: np.asarray(v) for k, v in feed.items()}
        # 0-d feeds (scalars like a fed learning rate) have no batch dim to
        # shard; they replicate regardless and must not veto dp sharding
        batched = {k: a for k, a in arrays.items() if a.ndim > 0}
        divisible = all(a.shape[0] % dp_size == 0 for a in batched.values())
        if not divisible and self.multihost:
            raise ValueError(
                "multihost batches must be dp-divisible per process "
                f"(local dp extent {dp_size}); pad or drop the final short "
                f"batch "
                f"(got shapes { {k: a.shape for k, a in batched.items()} })")
        sh = NamedSharding(self.mesh,
                           self.bspec if divisible else P())
        rep = NamedSharding(self.mesh, P())
        out = {}
        gb = self.program.global_block()
        for k, arr in arrays.items():
            if gb._has_var_recursive(k):
                want = core.np_dtype(gb._var_recursive(k).dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            spec = self.feed_specs.get(k)
            if spec is not None and divisible and all(
                    ax is None or (d < arr.ndim
                                   and arr.shape[d] % self.mesh.shape[ax] == 0)
                    for d, ax in enumerate(tuple(spec))):
                # every sharded dim divides evenly; a ragged dim (odd
                # seq len on sp2) degrades to the default batch sharding
                # instead of crashing in device_put
                use = NamedSharding(self.mesh, spec)
            else:
                use = sh if arr.ndim > 0 else rep
            out[k] = self._place(arr, use)
        return out

    def fetch_to_host(self, val) -> np.ndarray:
        from . import multihost as mh

        return mh.fetch_to_host(val)

    def __call__(self, feed, state):
        return self._fn(feed, state)


def shard_program_step(program, feed_names, fetch_names, mesh, **kw):
    return ShardedTrainStep(program, feed_names, fetch_names, mesh, **kw)
