"""SPMD sharding of traced Programs over a mesh.

This is the TPU-native replacement for the reference's
multi_devices_graph_pass (ref: details/multi_devices_graph_pass.cc:323):
instead of replicating ops per device and inserting AllReduce op-handles, we
annotate shardings on the ONE traced XLA program and let GSPMD partition it:

 - batch ("dp" axis): every fed tensor sharded on dim 0 → data parallelism;
   gradient all-reduce falls out of the partitioned backward matmuls.
 - tensor parallelism ("tp", legacy "mp"): 2-D parameters (fc/embedding
   weights) and their optimizer accumulators sharded per the canonical
   :class:`SpecLayout` table (Megatron column/row alternation) on named
   meshes, or on the output dim under the legacy heuristic; XLA inserts
   the activation all-gathers/reduce-scatters over ICI.

ZeRO-1 style optimizer-state sharding (BuildStrategy.ReduceStrategy.Reduce)
uses the same mechanism with accumulator specs sharded on "dp"; an "fsdp"
mesh axis shards the complementary parameter dim.

Two execution surfaces: :class:`ShardedTrainStep` (one step per dispatch —
ParallelExecutor.run, the dryruns, the multihost runner) and
:class:`ShardedWindowRunner` (N steps per dispatch — the production fast
path, ISSUE 7; guardian + dynamic fp16 loss scale in the scan carry,
donated state, compile-cache warm starts keyed on mesh + spec table).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..fluid import core
from ..fluid.executor import (BlockPlan, _MISSING, build_window_fn,
                              global_scope, trace_block)
from ..fluid.framework import Parameter, Program, RNG_STATE_VAR
from .mesh import mesh_label


def batch_spec(mesh: Mesh) -> P:
    return P("dp") if "dp" in mesh.axis_names else P(mesh.axis_names[0])


def resolve_tp_axis(mesh: Mesh, tp_axis: Optional[str] = None) -> str:
    """The mesh's tensor-parallel axis name: an explicit request wins, the
    canonical ``tp`` name (PADDLE_TPU_MESH meshes) is preferred, and the
    legacy dryrun name ``mp`` is the fallback."""
    if tp_axis is not None:
        return tp_axis
    return "tp" if "tp" in mesh.axis_names else "mp"


# ---------------------------------------------------------------------------
# SpecLayout: the canonical PartitionSpec table (SNIPPETS.md [2] shape)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs per mesh axis role.

    One table maps every ProgramDesc persistable class to its sharding —
    the Megatron column/row alternation for linear chains, column-sharded
    embedding tables, batch-sharded activations — instead of scattering
    per-op dispatch decisions.  Axes absent from the actual mesh (or dims
    that don't divide) degrade to replicated PER DIM at application time
    (:func:`infer_param_specs`), so ONE layout serves every mesh shape."""

    data_axis: str = "dp"
    tp_axis: str = "tp"
    fsdp_axis: str = "fsdp"

    def batch(self) -> P:
        """Activations / fed tensors: batch dim over the data axis."""
        return P(self.data_axis)

    def embeddings(self) -> P:
        """Embedding tables [vocab, d_model]: shard d_model over tp (the
        row gather stays device-local), vocab over fsdp when present."""
        return P(self.fsdp_axis, self.tp_axis)

    def qkv_projection(self) -> P:
        """Column-parallel linear [d_in, d_out]: outputs sharded over tp
        (the Megatron qkv/ffn-up split); fsdp shards the input rows."""
        return P(self.fsdp_axis, self.tp_axis)

    def attn_output(self) -> P:
        """Row-parallel linear: contraction dim over tp, so the matmul's
        partial sums all-reduce once per block (Megatron attn-out/ffn-down
        split)."""
        return P(self.tp_axis, self.fsdp_axis)

    def ffn_up(self) -> P:
        return self.qkv_projection()

    def ffn_down(self) -> P:
        return self.attn_output()


def _param_roles(program: Program) -> Dict[str, Tuple[str, int]]:
    """Classify persistable parameters by their consuming ops.

    Returns ``name -> (role, order)`` where role is ``"embedding"``
    (lookup_table weight) or ``"linear"`` (mul/matmul weight) and order is
    the parameter's position in the program's matmul chain — the
    column/row alternation index (qkv/ffn-up at even depth, attn-out/
    ffn-down at odd depth, matching the Megatron pairing)."""
    roles: Dict[str, Tuple[str, int]] = {}
    order = 0
    for block in program.blocks:
        for op in block.ops:
            if op.type == "lookup_table":
                for n in op.inputs.get("W", []):
                    if n and n not in roles:
                        roles[n] = ("embedding", 0)
            elif op.type in ("mul", "matmul"):
                for n in op.inputs.get("Y", []):
                    if n and n not in roles:
                        roles[n] = ("linear", order)
                        order += 1
    return roles


def _fit_spec(spec: P, shape, mesh: Mesh) -> P:
    """Degrade a layout spec to the mesh/shape: axes absent from the mesh,
    with extent 1, or whose dim does not divide evenly become None."""
    if shape is None:
        return P()
    dims = []
    used = set()
    for d in range(len(shape)):
        ax = spec[d] if d < len(spec) else None
        ok = (ax is not None and ax in mesh.axis_names and ax not in used
              and mesh.shape[ax] > 1 and shape[d] is not None
              and shape[d] % mesh.shape[ax] == 0)
        if ok:
            used.add(ax)
        dims.append(ax if ok else None)
    return P(*dims)


def table_signature(specs: Dict[str, Optional[P]]) -> List[list]:
    """The spec table as a jsonable ``[[var_name, [axis|None per dim]]]``
    list — the form the compile-cache fingerprint folds in (var names are
    canonicalized through the program's rename map there, so the signature
    is rename-invariant but mesh/axis-layout-sensitive)."""
    out = []
    for name in sorted(specs):
        spec = specs[name]
        axes = [(list(ax) if isinstance(ax, tuple) else ax)
                for ax in tuple(spec)] if spec is not None else None
        out.append([name, axes])
    return out


# -- active-mesh context: ops whose implementation is mesh-aware (ring
# attention) discover the mesh their trace is being partitioned over --
_ACTIVE_MESH: List[Mesh] = []


def active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH[-1] if _ACTIVE_MESH else None


class mesh_scope:
    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __enter__(self):
        _ACTIVE_MESH.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _ACTIVE_MESH.pop()
        return False


# -- active spec-table context: the sharded runners publish their state
# spec table during the trace so per-op fused lowerings (the Pallas
# optimizer sweeps, ops/pallas_fused.py) can shard_map each update over
# its param's canonical PartitionSpec instead of forcing GSPMD to
# all-gather around an opaque pallas_call --
_ACTIVE_SPECS: List[Dict[str, Optional[P]]] = []


def active_param_specs() -> Optional[Dict[str, Optional[P]]]:
    return _ACTIVE_SPECS[-1] if _ACTIVE_SPECS else None


class param_spec_scope:
    def __init__(self, specs: Dict[str, Optional[P]]):
        self.specs = specs

    def __enter__(self):
        _ACTIVE_SPECS.append(self.specs)
        return self.specs

    def __exit__(self, *exc):
        _ACTIVE_SPECS.pop()
        return False


def infer_param_specs(program: Program, plan: BlockPlan, mesh: Mesh,
                      tp_axis: str = "mp", zero1: bool = False,
                      dp_axis: str = "dp",
                      layout: Optional[SpecLayout] = None) -> Dict[str, P]:
    """Choose a PartitionSpec per state var.

    With a :class:`SpecLayout` (named-axis meshes), parameters are mapped
    through the canonical table: lookup_table weights get the embedding
    spec, mul/matmul weights alternate column/row splits along the
    program's linear chain, fsdp (when the mesh has that axis) shards the
    complementary dim.  Without one (legacy ``mp`` meshes), 2-D params
    with a dim divisible by the tp axis size get sharded on that dim
    (prefer the output/last dim).  Either way accumulators follow their
    param (same shape) — matching how Megatron-style TP shards
    fc/embedding weights.

    zero1=True additionally shards optimizer accumulators over the dp axis
    (ReduceStrategy.Reduce ≡ ZeRO-1, ref multi_devices_graph_pass.cc:434-446
    kReduce): params stay replicated, their m/v/momentum state is partitioned
    on dp, and GSPMD all-gathers the updated params after the (now sharded)
    optimizer math — the reduce-to-owner + broadcast-param dataflow of the
    reference expressed as shardings.
    """
    has_tp = tp_axis in mesh.axis_names
    has_dp = zero1 and dp_axis in mesh.axis_names and mesh.shape[dp_axis] > 1
    has_fsdp = (layout is not None and layout.fsdp_axis in mesh.axis_names
                and mesh.shape[layout.fsdp_axis] > 1)

    def hint_spec(v) -> Optional[P]:
        """Params created with sharding hints.

        ``dist_spec``: a per-dim tuple of mesh-axis names/None (stacked
        transformer params — e.g. ("pp", None, "mp")); axes absent from the
        mesh or with non-divisible dims degrade to replicated PER DIM, so
        the same program runs on any mesh shape.  A param with a dist_spec
        never falls through to the generic 2-D TP heuristic (a stacked
        [L, d] layer-norm scale must NOT shard d over mp — the shard_map
        body expects it replicated).

        ``dist_hint``: a single axis name (expert weights → "ep",
        pipeline-stacked weights → "pp") sharding dim 0 on that axis.
        """
        ds = getattr(v, "dist_spec", None)
        if ds is not None:
            shape = v.shape or ()
            dims = []
            for d, ax in enumerate(ds[: len(shape)]):
                ok = (ax is not None and ax in mesh.axis_names
                      and mesh.shape[ax] > 1 and shape[d] is not None
                      and shape[d] % mesh.shape[ax] == 0)
                dims.append(ax if ok else None)
            return P(*dims)
        axis = getattr(v, "dist_hint", None)
        if axis is None or axis not in mesh.axis_names \
                or mesh.shape[axis] <= 1:
            return None
        shape = v.shape
        if not shape or shape[0] is None or shape[0] % mesh.shape[axis] != 0:
            return None
        return P(*([axis] + [None] * (len(shape) - 1)))

    has_hints = any(
        getattr(v, "dist_hint", None) in mesh.axis_names
        or any(ax in mesh.axis_names
               for ax in (getattr(v, "dist_spec", None) or ()) if ax)
        for v in program.global_block().vars.values()
        if isinstance(v, Parameter))
    if not has_tp and not has_dp and not has_hints and not has_fsdp:
        return {n: P() for n in set(plan.state_in) | set(plan.state_out)}
    tp_size = mesh.shape[tp_axis] if has_tp else 1
    dp_size = mesh.shape[dp_axis] if has_dp else 1
    gb = program.global_block()
    roles = _param_roles(program) if layout is not None else {}

    def layout_spec(name, shape) -> Optional[P]:
        """Canonical-table spec for a classified 2-D parameter (None =
        unclassified; fall through to the generic heuristic)."""
        role = roles.get(name)
        if role is None or shape is None or len(shape) != 2:
            return None
        kind, order = role
        if kind == "embedding":
            base = layout.embeddings()
        elif order % 2 == 0:
            base = layout.qkv_projection()
        else:
            base = layout.attn_output()
        return _fit_spec(base, shape, mesh)

    def spec_for_shape(shape) -> P:
        if not has_tp or shape is None or len(shape) < 2:
            return P()
        # shard last dim if divisible, else second-to-last, else replicate
        if shape[-1] is not None and shape[-1] % tp_size == 0 and shape[-1] >= tp_size:
            return P(*([None] * (len(shape) - 1) + [tp_axis]))
        if shape[0] is not None and shape[0] % tp_size == 0 and shape[0] >= tp_size:
            return P(*([tp_axis] + [None] * (len(shape) - 1)))
        return P()

    def zero1_spec(shape, base: P) -> P:
        """Shard an accumulator's first dp-divisible, not-already-sharded
        dim on dp (ZeRO-1)."""
        if not has_dp or shape is None:
            return base
        used = list(base) + [None] * (len(shape) - len(base))
        for d, n in enumerate(shape):
            if used[d] is None and n is not None and n % dp_size == 0 \
                    and n >= dp_size:
                used[d] = dp_axis
                return P(*used)
        return base

    specs: Dict[str, P] = {}
    param_shapes = {}
    for name in set(plan.state_in) | set(plan.state_out):
        if name == RNG_STATE_VAR:
            specs[name] = P()
            continue
        if gb._has_var_recursive(name):
            v = gb._var_recursive(name)
            hs = hint_spec(v) if isinstance(v, Parameter) else None
            if hs is not None:
                specs[name] = hs
                param_shapes[name] = tuple(v.shape)
                continue
            if isinstance(v, Parameter) and v.shape is not None \
                    and len(v.shape) == 2:
                ls = layout_spec(name, tuple(v.shape))
                specs[name] = ls if ls is not None \
                    else spec_for_shape(v.shape)
                param_shapes[name] = tuple(v.shape)
                continue
            if isinstance(v, Parameter):
                specs[name] = P()
                param_shapes[name] = tuple(v.shape) if v.shape else None
                continue
        specs[name] = None  # decide below (maybe accumulator)
    # accumulators share their param's spec (plus dp under ZeRO-1) so
    # optimizer math stays local.  Ownership comes from the optimizer's
    # explicit registry (Program._accumulator_owner, written by
    # Optimizer._add_accumulator); the name-containment fallback only covers
    # programs rebuilt without an optimizer object (e.g. deserialized).
    acc_owner = getattr(program, "_accumulator_owner", {})
    for name, spec in list(specs.items()):
        if spec is not None:
            continue
        v = gb._var_recursive(name) if gb._has_var_recursive(name) else None
        shape = tuple(v.shape) if v is not None and v.shape else None
        matched = P()
        pname = acc_owner.get(name)
        if pname is not None:
            if pname in param_shapes and shape == param_shapes[pname] \
                    and shape is not None:
                matched = zero1_spec(shape, specs[pname])
            # else: shape-[1] state like beta_pow stays replicated
        else:
            for pname, pshape in param_shapes.items():
                if pname in name and shape == pshape and shape is not None:
                    matched = zero1_spec(shape, specs[pname])
                    break
        specs[name] = matched
    return specs


class ShardedTrainStep:
    """A Program's block jitted over a mesh with explicit shardings.

    Used by __graft_entry__.dryrun_multichip and the multihost runner; the
    single-host ParallelExecutor uses the degenerate dp-only version.
    """

    def __init__(self, program: Program, feed_names: List[str],
                 fetch_names: List[str], mesh: Mesh,
                 tp_axis: Optional[str] = None,
                 donate: bool = False, zero1: bool = False,
                 multihost: bool = False,
                 feed_specs: Optional[Dict[str, P]] = None):
        self.program = program
        self.mesh = mesh
        self.label = mesh_label(mesh)
        self.multihost = multihost
        self.tp_axis = resolve_tp_axis(mesh, tp_axis)
        # canonical-table layout for named ("tp"/"fsdp") meshes; legacy
        # "mp" meshes keep the original last-dim heuristic bit-for-bit
        self.layout = (SpecLayout(tp_axis=self.tp_axis)
                       if "tp" in mesh.axis_names
                       or "fsdp" in mesh.axis_names else None)
        self.plan = BlockPlan(program, 0, feed_names, fetch_names)
        self.specs = infer_param_specs(program, self.plan, mesh,
                                       self.tp_axis, zero1=zero1,
                                       layout=self.layout)
        self.zero1 = bool(zero1)
        self.bspec = batch_spec(mesh)
        self._probe_ctx = {"zero1": bool(zero1), "donate": bool(donate)}
        self._dispatched = False
        # per-feed PartitionSpec overrides (e.g. long sequences sharded on
        # an "sp" axis at the SOURCE: P("dp", "sp") for [N, T] token feeds
        # avoids an all-gather+reslice before the first ring step); axes
        # absent from the mesh degrade to replicated per dim
        self.feed_specs = {}
        for name, spec in (feed_specs or {}).items():
            dims = [ax if (ax is None or (ax in mesh.axis_names
                                          and mesh.shape[ax] > 1)) else None
                    for ax in tuple(spec)]
            self.feed_specs[name] = P(*dims)
        self._bdiv = None  # lazy: jax.process_index needs initialized dist

        plan = self.plan
        specs = self.specs

        def fn(feed_vals, state_vals):
            with mesh_scope(mesh), param_spec_scope(specs):
                return trace_block(program, 0, plan, feed_vals, state_vals)

        # input shardings are carried by the placed arrays (place_feed /
        # place_state); pin the output state so updated params keep their
        # layout across steps, and pin fetches replicated so every host can
        # materialize them (Fluid fetch semantics: full value on host).
        out_state_names = list(plan.state_out) + \
            ([RNG_STATE_VAR] if plan.needs_rng else [])
        out_shardings = (
            NamedSharding(mesh, P()),
            {k: NamedSharding(mesh, self.specs.get(k, P()))
             for k in out_state_names},
        )
        self._fn = jax.jit(
            fn,
            out_shardings=out_shardings,
            donate_argnums=(1,) if donate else ())

    def _place(self, val, sh: NamedSharding, from_full: bool = False):
        """from_full=True: ``val`` is the FULL global value on every host
        (state vars after identical init) — sharded specs slice it.
        from_full=False: ``val`` is this process's LOCAL piece (feeds) —
        sharded specs concatenate across processes."""
        if isinstance(val, jax.Array) and getattr(val, "sharding", None) == sh:
            return val
        if self.multihost:
            if isinstance(val, jax.Array) and not val.is_fully_addressable:
                return val  # already a global array from a previous step
            from . import multihost as mh

            arr = np.asarray(val)
            if sh.spec == P() or from_full:
                # State must be bit-identical across hosts; broadcast
                # process 0's value rather than trusting per-host init
                # (ref: parallel_executor.cc:234 BCastParamsToDevices).
                from jax.experimental import multihost_utils as mhu

                arr = np.asarray(mhu.broadcast_one_to_all(arr))
            if from_full and sh.spec != P():
                # full value everywhere + sharded spec (ZeRO-1 accumulators,
                # mp weights): each device takes ITS SLICE of the full
                # array — host_local concatenation would inflate the shape
                return jax.make_array_from_callback(
                    arr.shape, sh, lambda idx, a=arr: a[idx])
            return mh.host_local_to_global(arr, self.mesh, sh.spec)
        return jax.device_put(jnp.asarray(val), sh)

    def place_state(self, scope=None):
        """Place scope state onto the mesh with the chosen shardings."""
        scope = scope or global_scope()
        state = {}
        for name in self.plan.state_in:
            val = scope.get(name, _MISSING)
            if val is _MISSING:
                raise RuntimeError(f"state var {name} missing from scope")
            sh = NamedSharding(self.mesh, self.specs.get(name, P()))
            state[name] = self._place(val, sh, from_full=True)
        if self.plan.needs_rng:
            rk = scope.get(RNG_STATE_VAR, _MISSING)
            if rk is _MISSING:
                rk = jax.random.PRNGKey(self.program.random_seed or 0)
            state[RNG_STATE_VAR] = self._place(
                rk, NamedSharding(self.mesh, P()), from_full=True)
        return state

    def _batch_divisor(self) -> int:
        """How many equal shards this process's feed must split into: the
        whole batch-axis size single-host, but only the LOCAL extent of the
        batch axes multihost (each process feeds its local batch; the batch
        axis may span processes — dp over DCN — or live inside one)."""
        axes = [ax for ax in self.bspec if ax is not None]
        if not axes:
            return 1
        if not self.multihost:
            n = 1
            for ax in axes:
                n *= self.mesh.shape[ax]
            return n
        pid = jax.process_index()
        devs = self.mesh.devices
        local = np.vectorize(lambda d: d.process_index == pid)(devs)
        n = 1
        for ax in axes:
            ai = list(self.mesh.axis_names).index(ax)
            n *= sum(1 for i in range(devs.shape[ai])
                     if np.take(local, i, axis=ai).any())
        return n

    def indivisible_batch_error(self, bad: Dict[str, int]) -> ValueError:
        """The clear, named error for a batch that cannot shard evenly:
        names the offending feed(s) and batch size(s), the mesh batch
        axis/axes, and the divisor — instead of the opaque XLA sharding
        error the raw device_put would raise."""
        axes = [ax for ax in self.bspec if ax is not None] or ["dp"]
        div = self._bdiv if self._bdiv else 1
        what = ", ".join(f"'{k}' batch {v}" for k, v in sorted(bad.items()))
        return ValueError(
            f"global batch is not divisible by the mesh batch extent: "
            f"{what} vs divisor {div} (axis "
            f"{'x'.join(str(a) for a in axes)} of mesh {self.label}"
            f"{', local extent' if self.multihost else ''}); pad or drop "
            f"the short batch, or pick a global batch that is a multiple "
            f"of {div}")

    def place_feed(self, feed: Dict[str, np.ndarray], strict: bool = False):
        """Shard feeds on the batch axis.  Multihost: each process passes its
        LOCAL batch; the global batch is num_processes x local.

        ``strict=True`` (the windowed/production path) turns the
        replicated-execution fallback for indivisible batches into the
        clear :meth:`indivisible_batch_error` — a fused window must not
        silently recompile a replicated variant mid-run.

        Uneven final batches (ref: details/data_balance_op_handle.cc — the
        reference redistributes short batches so no device sees a ragged
        shard): a batch whose leading dim is NOT divisible by the dp size
        cannot shard evenly, so it executes REPLICATED — every device
        computes the full short batch, which is mathematically identical to
        the single-device result (exact loss, exact update; no padding
        bias).  It costs the dp speedup for that one (final) batch and one
        extra compile for its shape — the shape change forces a recompile
        anyway."""
        if self._bdiv is None:
            self._bdiv = self._batch_divisor()
        dp_size = self._bdiv
        arrays = {k: (v if isinstance(v, jax.Array) else np.asarray(v))
                  for k, v in feed.items()}
        # 0-d feeds (scalars like a fed learning rate) have no batch dim to
        # shard; they replicate regardless and must not veto dp sharding
        batched = {k: a for k, a in arrays.items() if a.ndim > 0}
        divisible = all(a.shape[0] % dp_size == 0 for a in batched.values())
        if not divisible and (self.multihost or strict):
            bad = {k: int(a.shape[0]) for k, a in batched.items()
                   if a.shape[0] % dp_size != 0}
            raise self.indivisible_batch_error(bad)
        sh = NamedSharding(self.mesh,
                           self.bspec if divisible else P())
        rep = NamedSharding(self.mesh, P())
        out = {}
        gb = self.program.global_block()
        for k, arr in arrays.items():
            if gb._has_var_recursive(k):
                want = core.np_dtype(gb._var_recursive(k).dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            spec = self.feed_specs.get(k)
            if spec is not None and divisible and all(
                    ax is None or (d < arr.ndim
                                   and arr.shape[d] % self.mesh.shape[ax] == 0)
                    for d, ax in enumerate(tuple(spec))):
                # every sharded dim divides evenly; a ragged dim (odd
                # seq len on sp2) degrades to the default batch sharding
                # instead of crashing in device_put
                use = NamedSharding(self.mesh, spec)
            else:
                use = sh if arr.ndim > 0 else rep
            out[k] = self._place(arr, use)
        return out

    def fetch_to_host(self, val) -> np.ndarray:
        from . import multihost as mh

        return mh.fetch_to_host(val)

    def cache_extra(self, **more) -> dict:
        """The compile-cache fingerprint extra for this sharded program:
        mesh axis names AND extents fold in (dp8 vs dp4,tp2 must be
        distinct executables), as do the jit-level toggles."""
        from ..fluid import amp as _amp

        extra = {"platform": "spmd",
                 "mesh": [[a, int(self.mesh.shape[a])]
                          for a in self.mesh.axis_names],
                 "multihost": self.multihost,
                 "amp": _amp.compute_dtype(),
                 "flash": os.environ.get("PADDLE_TPU_FLASH", ""),
                 "fused": os.environ.get("PADDLE_TPU_FUSED", "")}
        extra.update(self._probe_ctx)
        extra.update(more)
        return extra

    def __call__(self, feed, state):
        import time as _time

        from ..fluid import profiler as _prof
        from .. import compile_cache as _cc
        from .. import observe

        probe = None
        if not self._dispatched:
            # persistent-cache consult before the first (compiling)
            # dispatch — warm starts of the SAME mesh topology hit; a
            # reshaped mesh or relaid spec table misses by construction
            probe = _cc.executor_probe(
                self.program, feed, self.plan.fetch_names,
                extra=self.cache_extra(kind="sharded_step"),
                spec_table=table_signature(self.specs))
        observe.note_mesh(self.label)
        fresh = not self._dispatched
        t0 = _time.perf_counter()
        out = self._fn(feed, state)
        self._dispatched = True
        _prof.record_counter("executor.dispatches")
        observe.registry().inc("executor.dispatches",
                               labels={"mesh": self.label})
        if probe is not None:
            jax.block_until_ready(out)
            probe.finish(_time.perf_counter() - t0, self.program,
                         meta={"kind": "sharded_step", "mesh": self.label})
        if self.program._params_grads is not None:
            from ..observe import goodput as _goodput

            # per-step sharded dispatch: first call compiles (lazy jit)
            _goodput.note("compile" if fresh else "device",
                          _time.perf_counter() - t0, mesh=self.label)
        return out


def shard_program_step(program, feed_names, fetch_names, mesh, **kw):
    return ShardedTrainStep(program, feed_names, fetch_names, mesh, **kw)


# ---------------------------------------------------------------------------
# Collective accounting: what GSPMD actually inserted into the executable
# ---------------------------------------------------------------------------

_COLL_KINDS = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
               "collective-permute")
_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")
_COLL_OP_RE = re.compile(
    r"^(.*?)\s((?:%s)(?:-start)?)\(" % "|".join(_COLL_KINDS))
_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
                "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8, "c64": 8, "c128": 16}


def collective_stats(hlo_text: str) -> dict:
    """Count GSPMD-inserted collectives in an optimized HLO module and sum
    their result bytes — the ``spmd.collective_bytes`` gauge's source.
    Async pairs count once (``-start`` counted, ``-done`` skipped)."""
    total_bytes = 0
    counts: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        if " = " not in line:
            continue
        rhs = line.split(" = ", 1)[1].strip()
        m = _COLL_OP_RE.match(rhs)
        if m is None:
            continue
        kind = m.group(2).replace("-start", "")
        nbytes = 0
        for dt, dims in _SHAPE_RE.findall(m.group(1)):
            size = _DTYPE_BYTES.get(dt)
            if size is None:
                continue  # token/opaque operands carry no payload
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * size
        counts[kind] = counts.get(kind, 0) + 1
        total_bytes += nbytes
    return {"bytes": int(total_bytes),
            "count": int(sum(counts.values())),
            "by_kind": counts}


# ---------------------------------------------------------------------------
# ShardedWindowRunner: run_steps on a mesh (ISSUE 7 tentpole)
# ---------------------------------------------------------------------------


class ShardedWindowRunner:
    """N training steps per dispatch on a named mesh.

    The sharded twin of ``Executor.run_steps``: the SAME scan body
    (:func:`~paddle_tpu.fluid.executor.build_window_fn` — guardian
    commit-gate and dynamic fp16 loss scale riding the carry, per-step
    fault injection vectorized) jitted over a multi-axis mesh with the
    :class:`SpecLayout` spec table pinned onto the carried state, the
    mutable state donated so parameters and optimizer shards update in
    place, and the executable AOT-compiled once — which also yields the
    optimized HLO the ``spmd.collective_*`` gauges are read from.  The
    persistent compile cache is consulted before the first dispatch with
    the mesh shape + spec table folded into the fingerprint, so an elastic
    restart of the same dp×tp job warm-starts."""

    def __init__(self, program: Program, feed_names: List[str],
                 fetch_names: List[str], mesh: Mesh, n_steps: int,
                 feed_per_step: bool = False,
                 tp_axis: Optional[str] = None, zero1: bool = False,
                 donate: Optional[bool] = None, multihost: bool = False):
        from ..fluid import guardian as _guardian
        from ..fluid.executor import Executor

        self.program = program
        self.mesh = mesh
        self.label = mesh_label(mesh)
        self.n_steps = int(n_steps)
        self.feed_per_step = bool(feed_per_step)
        self.fetch_names = [str(f) for f in fetch_names]
        self.n_user = len(self.fetch_names)
        guard = _guardian.for_program(program)
        self.guard = guard
        plan_fetches = list(self.fetch_names)
        if guard is not None:
            plan_fetches += guard.extra_fetch_names()
        # the composed ShardedTrainStep supplies plan, spec table and all
        # placement machinery; its per-step jit wrapper stays untraced
        self.step = ShardedTrainStep(program, list(feed_names), plan_fetches,
                                     mesh, tp_axis=tp_axis, zero1=zero1,
                                     multihost=multihost)
        plan = self.step.plan
        if plan.needs_eager:
            raise RuntimeError(
                "sharded window: program contains data-dependent eager "
                "ops; use the per-step ParallelExecutor.run path")
        if guard is not None and guard.scale_vars:
            # the scale/good-steps vars are read/written only by the
            # guarded wrapper — gather them with the rest of state
            for n in guard.scale_vars:
                if n not in plan.state_in:
                    plan.state_in.append(n)
        self.plan = plan
        self.specs = self.step.specs
        if donate is None:
            donate = Executor._donate_argnums(None, program) != ()
        self.donate = bool(donate)

        specs = self.specs

        def trace(feed_vals, state_vals):
            with mesh_scope(mesh), param_spec_scope(specs):
                return trace_block(program, 0, plan, feed_vals, state_vals)

        rep = NamedSharding(mesh, P())

        def finalize(last, mut_final, agg):
            # pin the carried state to its spec-table layout (so donation
            # aliases buffer-for-buffer across windows) and fetches/health
            # replicated (Fluid fetch semantics: full value on every host)
            last = [jax.lax.with_sharding_constraint(v, rep) for v in last]
            mut_final = {
                k: jax.lax.with_sharding_constraint(
                    v, NamedSharding(mesh, self.specs.get(k) or P()))
                for k, v in mut_final.items()}
            if agg is not None:
                agg = {k: jax.lax.with_sharding_constraint(v, rep)
                       for k, v in agg.items()}
            return last, mut_final, agg

        kfn = build_window_fn(program, plan, guard, self.n_user,
                              self.n_steps, self.feed_per_step,
                              trace=trace, finalize=finalize)
        self._jit = jax.jit(kfn,
                            donate_argnums=(2,) if self.donate else ())
        self._compiled = None
        self.collectives: Optional[dict] = None
        self.cost: Optional[dict] = None
        self.memory: Optional[dict] = None

    # -- placement --
    def place_feed_window(self, feed: Dict[str, object]):
        """Place one window's feeds with the batch axis sharded over the
        mesh's dp axes.  ``feed_per_step`` windows are ``(n_steps, batch,
        ...)`` stacks (batch = dim 1); fixed feeds shard dim 0.  An
        indivisible batch raises the clear named error — the fused window
        must not silently recompile a replicated variant mid-run."""
        step = self.step
        if step._bdiv is None:
            step._bdiv = step._batch_divisor()
        div = step._bdiv
        bdim = 1 if self.feed_per_step else 0
        arrays, bad = {}, {}
        for k, v in feed.items():
            arr = v if isinstance(v, jax.Array) else np.asarray(v)
            arrays[k] = arr
            if self.feed_per_step and arr.ndim > 0 \
                    and arr.shape[0] != self.n_steps:
                raise ValueError(
                    f"feed '{k}' leading dim {arr.shape[0]} != window "
                    f"n_steps {self.n_steps} (feed_per_step windows stack "
                    f"one batch per step)")
            if arr.ndim > bdim and arr.shape[bdim] % div != 0:
                bad[k] = int(arr.shape[bdim])
        if bad:
            raise step.indivisible_batch_error(bad)
        out = {}
        for k, arr in arrays.items():
            spec = (P(*([None] * bdim + list(step.bspec)))
                    if arr.ndim > bdim else P())
            out[k] = step._place(arr, NamedSharding(self.mesh, spec))
        return out

    def _note_collectives(self) -> None:
        """Read the optimized HLO of the just-compiled window executable
        and publish what GSPMD inserted as mesh-labeled gauges — plus the
        executable's cost analysis (flops / bytes accessed), which backs
        the ``device.mfu{mesh=...}`` attribution gauges per dispatch."""
        from ..observe import memory as _obsmem
        from ..observe import trace as _trace

        try:
            self.cost = _trace.cost_of(self._compiled)
        except Exception:
            self.cost = None
        # compiled memory truth: the AOT executable is already in hand, so
        # the memory.peak_bytes{mesh=} gauge family is free on this path
        self.memory = _obsmem.memory_stats(self._compiled)
        _obsmem.note_compiled_memory(self.memory, mesh=self.label,
                                     kind="sharded_window",
                                     n_steps=self.n_steps)
        try:
            txt = self._compiled.as_text()
        except Exception:
            return
        self.collectives = collective_stats(txt)
        try:
            from .. import observe

            labels = {"mesh": self.label}
            reg = observe.registry()
            reg.set_gauge("spmd.collective_bytes",
                          float(self.collectives["bytes"]), labels=labels)
            reg.set_gauge("spmd.collective_count",
                          float(self.collectives["count"]), labels=labels)
            observe.emit("spmd.lowered", mesh=self.label,
                         n_steps=self.n_steps,
                         collective_bytes=self.collectives["bytes"],
                         collective_count=self.collectives["count"],
                         by_kind=self.collectives["by_kind"],
                         flops=(self.cost or {}).get("flops"))
        except Exception:
            pass  # accounting must never fail the run it measures

    # -- dispatch --
    def run(self, feed: Dict[str, object], scope=None,
            return_numpy: bool = True):
        """One fused window: place, dispatch, commit state back to the
        scope.  Returns the LAST step's fetches (mirrors
        ``Executor.run_steps``)."""
        import contextlib
        import time as _time

        from ..fluid import fault as _fault
        from ..fluid import guardian as _guardian
        from ..fluid import profiler as _prof
        from ..fluid.executor import Executor
        from .. import compile_cache as _cc
        from .. import observe
        from ..observe import trace as _trace
        from ..observe import watchdog as _watchdog

        scope = scope or global_scope()
        _tstack = contextlib.ExitStack()
        with _tstack:
            wspan = _tstack.enter_context(
                _trace.span("executor.window", n_steps=self.n_steps,
                            mesh=self.label))
            t_host0 = _time.perf_counter()
            gb = self.program.global_block()
            feed_arrays = {}
            for k, v in dict(feed or {}).items():
                if isinstance(v, jax.Array):
                    feed_arrays[k] = v
                    continue
                arr = np.asarray(v)
                if gb._has_var_recursive(k):
                    want = core.np_dtype(gb._var_recursive(k).dtype)
                    if arr.dtype != want:
                        arr = arr.astype(want)
                feed_arrays[k] = arr
            t_feed0 = _time.perf_counter()
            feed_dev = self.place_feed_window(feed_arrays)
            t_feed1 = _time.perf_counter()
            return self._run_placed(
                feed_arrays, feed_dev, scope, return_numpy, wspan,
                t_host0, t_feed0, t_feed1, _time, _fault, _guardian,
                _prof, Executor, _cc, observe, _trace, _watchdog)

    def _run_placed(self, feed_arrays, feed_dev, scope, return_numpy,
                    wspan, t_host0, t_feed0, t_feed1, _time, _fault,
                    _guardian, _prof, Executor, _cc, observe, _trace,
                    _watchdog):
        window_start = 0
        if self.program._params_grads is not None:
            window_start = Executor._step_boundary(_fault, self.n_steps)
        g = _guardian.current() if self.guard is not None else None
        if g is not None:
            # one-window-lag sentinel: observe the PREVIOUS dispatch's
            # aggregated health and apply policy BEFORE this window runs
            g.on_boundary()
        t_state0 = _time.perf_counter()
        state_vals = self.step.place_state(scope)
        t_state1 = _time.perf_counter()
        mut_names = set(self.plan.state_out)
        if self.plan.needs_rng:
            mut_names.add(RNG_STATE_VAR)
        if self.guard is not None and self.guard.scale_vars:
            mut_names.update(self.guard.scale_vars)
        mut_state = {k: v for k, v in state_vals.items() if k in mut_names}
        const_state = {k: v for k, v in state_vals.items()
                       if k not in mut_names}
        rep = NamedSharding(self.mesh, P())
        sentinel = None
        dump_state = None
        if self.guard is not None:
            seed_mul, loss_mul = _fault.sentinel_injection_window(
                window_start, self.n_steps)
            # sentinel inputs placed replicated explicitly: the AOT
            # executable requires mesh-consistent input shardings
            sentinel = {
                "loss_cap": jax.device_put(
                    jnp.float32(g.loss_cap() if g is not None
                                else float("inf")), rep),
                "seed_mul": jax.device_put(jnp.asarray(seed_mul), rep),
                "loss_mul": jax.device_put(jnp.asarray(loss_mul), rep),
            }
            dump_state = state_vals
            if g is not None and g.config.policy == "dump_and_halt" \
                    and self.donate:
                # donation invalidates mutated input buffers after the
                # dispatch; dump mode keeps pre-window device copies alive
                dump_state = {k: (jnp.array(v, copy=True) if k in mut_names
                                  else v)
                              for k, v in state_vals.items()}

        probe = None
        t = _time.perf_counter()
        fresh_compile = self._compiled is None
        if self._compiled is None:
            with _trace.span("executor.compile", mesh=self.label,
                             n_steps=self.n_steps):
                probe = _cc.executor_probe(
                    self.program, feed_arrays, self.fetch_names,
                    extra=self.step.cache_extra(
                        kind="sharded_window", n_steps=self.n_steps,
                        feed_per_step=self.feed_per_step,
                        donate=self.donate,
                        guard=(self.guard.cache_token()
                               if self.guard is not None else None)),
                    spec_table=table_signature(self.specs))
                # AOT compile once; the same Compiled serves every window
                # AND yields the optimized HLO for the collective gauges +
                # the cost analysis behind device.mfu, with no second
                # trace/compile through the jit dispatch path
                self._compiled = self._jit.lower(
                    feed_dev, const_state, mut_state, sentinel).compile()
                self._note_collectives()
        observe.note_mesh(self.label)
        t_disp0 = _time.perf_counter()
        agg = None
        if self.guard is not None:
            fetches, new_state, agg = self._compiled(
                feed_dev, const_state, mut_state, sentinel)
        else:
            fetches, new_state = self._compiled(
                feed_dev, const_state, mut_state, sentinel)
        if wspan is not None or (_prof.is_profiling()
                                 and self.guard is None):
            # device-time attribution needs the dispatch retired; outside
            # tracing/profiling it stays async as before
            jax.block_until_ready((fetches, new_state))
        t_disp1 = _time.perf_counter()
        dt = t_disp1 - t
        if _prof.is_profiling():
            _prof.record_event(
                f"executor_run[{len(self.plan.ops)}ops "
                f"x{self.n_steps}steps mesh={self.label}]", dt, start=t)
        _prof.record_counter("executor.dispatches")
        _prof.record_counter("executor.windows")
        _prof.record_counter("executor.window_steps", inc=self.n_steps)
        reg = observe.registry()
        labels = {"mesh": self.label}
        reg.inc("executor.dispatches", labels=labels)
        reg.inc("executor.windows", labels=labels)
        reg.inc("executor.window_steps", self.n_steps, labels=labels)
        if probe is not None:
            meta = {"kind": "sharded_window", "n_steps": self.n_steps,
                    "mesh": self.label}
            if isinstance(self.memory, dict):
                # per-executable memory table in the cache manifest, so a
                # warm start re-reports HBM truth without re-lowering
                meta["memory"] = self.memory
            probe.finish(dt, self.program, meta=meta)
        if _fault.active() is not None:
            new_state = _fault.corrupt_state(new_state)
        for name, val in new_state.items():
            scope.set(name, val)
        Executor._check_nan_inf(list(new_state.items())
                                + list(zip(self.plan.fetch_names, fetches)))
        if g is not None and agg is not None:
            g.defer(self.guard, window_start, agg, {
                "program": self.program, "feeds": feed_arrays,
                "feed_lods": {}, "fetch_names": self.fetch_names,
                "state": dump_state, "sentinel": sentinel,
                "duration_s": dt,
                "window": {"start": window_start, "n_steps": self.n_steps,
                           "feed_per_step": self.feed_per_step}})
        if self.program._params_grads is not None:
            observe.note_step(window_start + self.n_steps - 1)
            from ..observe import memory as _obsmem

            # live-buffer ledger: mesh-labeled scope residency + watermark
            # at the window boundary
            _obsmem.note_scope_live(scope, scope_label="train",
                                    mesh=self.label,
                                    step=window_start + self.n_steps - 1)
        t_obs1 = _time.perf_counter()
        if wspan is not None:
            # per-window breakdown: feed/state staging, device dispatch,
            # host observe tail — all mesh-labeled, all under the window
            # span (prefetch-staged feeds show ~zero stage time here; the
            # staging span then lives on the prefetch worker's thread row)
            _trace.emit_span("executor.stage", t_feed0, t_feed1,
                             parent=wspan, what="feed")
            _trace.emit_span("executor.stage", t_state0, t_state1,
                             parent=wspan, what="state")
            _trace.emit_span("executor.dispatch", t_disp0, t_disp1,
                             parent=wspan, mesh=self.label)
            _trace.emit_span("executor.observe", t_disp1, t_obs1,
                             parent=wspan)
            stage_ms = ((t_feed1 - t_feed0) + (t_state1 - t_state0)) * 1e3
            _trace.note_window_breakdown(
                host_ms=max(0.0, (t_disp0 - t_host0) * 1e3 - stage_ms),
                stage_ms=stage_ms,
                device_ms=(t_disp1 - t_disp0) * 1e3,
                observe_ms=(t_obs1 - t_disp1) * 1e3,
                mesh=self.label)
            if self.cost:
                _trace.note_device_cost(self.cost, t_disp1 - t_disp0,
                                        self.n_steps, mesh=self.label)
        if self.program._params_grads is not None:
            _watchdog.observe_value(
                "executor.step_time_s",
                (t_obs1 - t_host0) / max(1, self.n_steps),
                step=window_start + self.n_steps - 1, mesh=self.label)
            from ..observe import goodput as _goodput

            # goodput ledger: the one-off AOT lower+compile is compile
            # state; the rest of the window is device compute
            cdur = t_disp0 - t if fresh_compile else 0.0
            if cdur > 0.0:
                _goodput.note("compile", cdur, mesh=self.label)
            _goodput.note("device",
                          max(0.0, (t_obs1 - t_host0) - cdur),
                          mesh=self.label)
        if return_numpy:
            return [np.asarray(self.step.fetch_to_host(v)) for v in fetches]
        return list(fetches)
