"""Multi-host (multi-process) runtime over DCN.

This is the TPU-native replacement for the reference's distributed transport
(ref: operators/distributed/ gRPC client/server, send/recv/listen_and_serv
ops, gen_nccl_id): instead of a parameter-server var transport, processes
join one JAX coordination service (`jax.distributed.initialize`) and execute
ONE GSPMD program over the global device mesh; gradient/parameter movement
becomes XLA collectives over ICI/DCN.

Role mapping:
  - pserver endpoint list  -> coordination-service address (first endpoint)
  - trainer_id / trainers  -> process_id / num_processes
  - gen_nccl_id handshake  -> jax.distributed.initialize barrier
  - send/recv param blocks -> GSPMD all-reduce / all-gather over the mesh

Env contract mirrors the reference cluster env (fluid_benchmark.py:34-82):
PADDLE_TRAINER_ID, PADDLE_TRAINERS, PADDLE_COORDINATOR_ADDR (falls back to
the first entry of PADDLE_PSERVER_EPS).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_initialized = False


def is_initialized() -> bool:
    return _initialized


def init(coordinator_addr: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         local_device_ids: Optional[Sequence[int]] = None) -> tuple:
    """Join the pod-wide coordination service.  Arguments fall back to the
    PADDLE_* cluster env vars.  Idempotent; no-op for a 1-process world.

    Returns (process_id, num_processes)."""
    global _initialized
    if coordinator_addr is None:
        coordinator_addr = os.environ.get("PADDLE_COORDINATOR_ADDR")
        if not coordinator_addr:
            eps = os.environ.get("PADDLE_PSERVER_EPS", "")
            coordinator_addr = eps.split(",")[0].strip() or None
    if num_processes is None:
        num_processes = int(os.environ.get("PADDLE_TRAINERS", "1"))
    if process_id is None:
        process_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if num_processes <= 1:
        return process_id, num_processes
    if _initialized:
        return jax.process_index(), jax.process_count()
    if coordinator_addr is None:
        raise ValueError(
            "multihost.init: trainers > 1 but no coordinator address; set "
            "PADDLE_COORDINATOR_ADDR (or PADDLE_PSERVER_EPS) or pass "
            "coordinator_addr")
    try:
        jax.distributed.initialize(coordinator_addr, num_processes,
                                   process_id, local_device_ids)
    except RuntimeError as exc:
        raise RuntimeError(
            "jax.distributed.initialize failed — it must run BEFORE any JAX "
            "computation initializes the backend.  Call "
            "DistributeTranspiler.transpile() (or multihost.init()) before "
            "running the startup program or any other device work."
        ) from exc
    _initialized = True
    return jax.process_index(), jax.process_count()


def ensure_init(dist_info: dict) -> None:
    """Initialize from a DistributeTranspiler annotation (program._dist_info)."""
    if dist_info and int(dist_info.get("trainers", 1)) > 1:
        init(dist_info.get("coordinator"), int(dist_info["trainers"]),
             int(dist_info.get("trainer_id", 0)))


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    return jax.process_index() if _initialized else 0


def process_count() -> int:
    return jax.process_count() if _initialized else 1


def global_mesh(axis_names: Sequence[str] = ("dp",),
                mesh_shape: Optional[Sequence[int]] = None) -> Mesh:
    """Mesh over ALL processes' devices (ICI within a host, DCN across).

    With no mesh_shape, all devices land on the first axis — pure DP.
    A multi-axis shape lays the LAST axis over the fastest-varying device
    index so tp/sp collectives ride ICI, dp rides DCN."""
    devices = np.array(jax.devices())
    if mesh_shape is None:
        mesh_shape = [len(devices)] + [1] * (len(axis_names) - 1)
    return Mesh(devices.reshape(tuple(mesh_shape)), tuple(axis_names))


def host_local_to_global(arr, mesh: Mesh, spec: P):
    """Per-process host value -> global jax.Array (batch-sharded feeds use
    P('dp'): global batch = num_processes x local batch; P() replicates)."""
    from jax.experimental import multihost_utils as mhu

    return mhu.host_local_array_to_global_array(np.asarray(arr), mesh, spec)


def fetch_to_host(val) -> np.ndarray:
    """Materialize a (replicated) global array on this host."""
    if hasattr(val, "is_fully_addressable") and not val.is_fully_addressable:
        return np.asarray(val.addressable_data(0))
    return np.asarray(val)
