"""Multi-host (multi-process) runtime over DCN.

This is the TPU-native replacement for the reference's distributed transport
(ref: operators/distributed/ gRPC client/server, send/recv/listen_and_serv
ops, gen_nccl_id): instead of a parameter-server var transport, processes
join one JAX coordination service (`jax.distributed.initialize`) and execute
ONE GSPMD program over the global device mesh; gradient/parameter movement
becomes XLA collectives over ICI/DCN.

Role mapping:
  - pserver endpoint list  -> coordination-service address (first endpoint)
  - trainer_id / trainers  -> process_id / num_processes
  - gen_nccl_id handshake  -> jax.distributed.initialize barrier
  - send/recv param blocks -> GSPMD all-reduce / all-gather over the mesh

Env contract mirrors the reference cluster env (fluid_benchmark.py:34-82):
PADDLE_TRAINER_ID, PADDLE_TRAINERS, PADDLE_COORDINATOR_ADDR (falls back to
the first entry of PADDLE_PSERVER_EPS).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_initialized = False


def is_initialized() -> bool:
    return _initialized


def _local_device_ids_from_env() -> Optional[list]:
    """PADDLE_LOCAL_DEVICE_IDS="0,1,2,3" -> [0, 1, 2, 3]; blank entries
    (trailing commas from shell templating) are skipped like the
    PADDLE_PSERVER_EPS list handling below."""
    ids = os.environ.get("PADDLE_LOCAL_DEVICE_IDS", "")
    parsed = [int(x) for x in ids.split(",") if x.strip()]
    return parsed or None


def init(coordinator_addr: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         local_device_ids: Optional[Sequence[int]] = None) -> tuple:
    """Join the pod-wide coordination service.  Arguments fall back to the
    PADDLE_* cluster env vars.  Idempotent; no-op for a 1-process world.

    Returns (process_id, num_processes)."""
    global _initialized
    if coordinator_addr is None:
        coordinator_addr = os.environ.get("PADDLE_COORDINATOR_ADDR")
        if not coordinator_addr:
            eps = os.environ.get("PADDLE_PSERVER_EPS", "")
            coordinator_addr = eps.split(",")[0].strip() or None
    if num_processes is None:
        num_processes = int(os.environ.get("PADDLE_TRAINERS", "1"))
    if process_id is None:
        process_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if local_device_ids is None:
        local_device_ids = _local_device_ids_from_env()
    if num_processes <= 1:
        return process_id, num_processes
    if _initialized:
        return jax.process_index(), jax.process_count()
    if coordinator_addr is None:
        raise ValueError(
            "multihost.init: trainers > 1 but no coordinator address; set "
            "PADDLE_COORDINATOR_ADDR (or PADDLE_PSERVER_EPS) or pass "
            "coordinator_addr")
    from ..fluid.log import VLOG

    VLOG(1, f"multihost: jax.distributed.initialize coordinator="
            f"{coordinator_addr} procs={num_processes} id={process_id}")
    try:
        if jax.config.jax_platforms == "cpu" or \
                os.environ.get("JAX_PLATFORMS", "") == "cpu":
            # the CPU PJRT client refuses cross-process computations
            # ("Multiprocess computations aren't implemented on the CPU
            # backend") unless the gloo collectives implementation is
            # selected BEFORE backend init — without this, every
            # multi-process CPU test/run dies at its first collective
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass  # older jaxlib without the option: keep old behavior
        jax.distributed.initialize(coordinator_addr, num_processes,
                                   process_id, local_device_ids)
    except RuntimeError as exc:
        raise RuntimeError(
            "jax.distributed.initialize failed — it must run BEFORE any JAX "
            "computation initializes the backend.  Call "
            "DistributeTranspiler.transpile() (or multihost.init()) before "
            "running the startup program or any other device work."
        ) from exc
    _initialized = True
    return jax.process_index(), jax.process_count()


def ensure_init(dist_info: dict) -> None:
    """Initialize from a DistributeTranspiler annotation (program._dist_info)."""
    if dist_info and int(dist_info.get("trainers", 1)) > 1:
        init(dist_info.get("coordinator"), int(dist_info["trainers"]),
             int(dist_info.get("trainer_id", 0)))


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    return jax.process_index() if _initialized else 0


def process_count() -> int:
    return jax.process_count() if _initialized else 1


def barrier(tag: str = "barrier", timeout_s: float = 300.0) -> float:
    """Pod-wide rendezvous (no-op in a 1-process world).  THE hook point
    for the wedged-collective fault: an armed barrier stall sleeps here,
    which is exactly where a real wedged host stops heartbeating from.

    Prefers the coordination-service barrier (control-plane gRPC with a
    real timeout — a dead peer surfaces as an error here instead of a
    silent infinite hang, and no device computation is involved, so it
    also works on hosts whose backend cannot run multiprocess XLA);
    falls back to a device sync when no coordination client exists.

    Returns this rank's wait time (seconds): per-rank barrier-wait is the
    straggler signature in a gang-scheduled fleet — the SLOW rank arrives
    last and waits ~zero, every healthy rank's wait inflates — so each
    wait is published as a ``barrier.wait`` run event + counter and
    ``barrier``-state goodput time (ISSUE 13)."""
    import time as _t

    from ..fluid import fault as _fault

    _fault.barrier_stall(tag)
    if not _initialized:
        return 0.0
    t0 = _t.perf_counter()
    client = getattr(
        __import__("jax._src.distributed", fromlist=["global_state"])
        .global_state, "client", None)
    if client is not None:
        client.wait_at_barrier(tag, int(timeout_s * 1000))
    else:
        from jax.experimental import multihost_utils as mhu

        mhu.sync_global_devices(tag)
    dur = _t.perf_counter() - t0
    try:
        from .. import observe
        from ..observe import goodput as _goodput

        observe.registry().inc("barrier.wait_seconds", dur)
        observe.emit("barrier.wait", tag=tag, dur_s=round(dur, 6))
        _goodput.note("barrier", dur)
    except Exception:
        pass  # accounting must never wedge the rendezvous it measures
    return dur


def heartbeat(step: Optional[int] = None) -> None:
    """Emit an elastic-supervisor liveness heartbeat for this process when
    a heartbeat dir is configured (PADDLE_ELASTIC_HB_DIR — set by
    parallel.elastic when it launches the pod); no-op otherwise."""
    hb_dir = os.environ.get("PADDLE_ELASTIC_HB_DIR")
    if hb_dir:
        from .elastic import write_heartbeat

        write_heartbeat(hb_dir, step=step, rank=process_index())


def global_mesh(axis_names: Sequence[str] = ("dp",),
                mesh_shape: Optional[Sequence[int]] = None) -> Mesh:
    """Mesh over ALL processes' devices (ICI within a host, DCN across).

    With no mesh_shape, all devices land on the first axis — pure DP.
    A multi-axis shape lays the LAST axis over the fastest-varying device
    index so tp/sp collectives ride ICI, dp rides DCN."""
    devices = np.array(jax.devices())
    if mesh_shape is None:
        mesh_shape = [len(devices)] + [1] * (len(axis_names) - 1)
    return Mesh(devices.reshape(tuple(mesh_shape)), tuple(axis_names))


def host_local_to_global(arr, mesh: Mesh, spec: P):
    """Per-process host value -> global jax.Array (batch-sharded feeds use
    P('dp'): global batch = num_processes x local batch; P() replicates)."""
    from jax.experimental import multihost_utils as mhu

    return mhu.host_local_array_to_global_array(np.asarray(arr), mesh, spec)


def fetch_to_host(val) -> np.ndarray:
    """Materialize a (replicated) global array on this host."""
    if hasattr(val, "is_fully_addressable") and not val.is_fully_addressable:
        return np.asarray(val.addressable_data(0))
    return np.asarray(val)


# ---------------------------------------------------------------------------
# Sharded checkpointing (the multihost face of trainer.save_checkpoint)
#
# ref analogue: the pserver saves its own param shards on checkpoint_notify
# (go/pserver/service.go:346 saves the local shard + etcd meta;
# io.py:771 _save_lookup_tables_by_notify).  Here each process writes only
# its ADDRESSABLE shards of every global array plus an index manifest; the
# checkpoint directory is assumed shared (GCS/NFS — the same assumption the
# reference's save_dirname on a cluster makes), so restore can rebuild
# global arrays on any number of processes, even a different process count.
# ---------------------------------------------------------------------------


def _safe_name(name: str) -> str:
    return name.replace("/", "%2F").replace("@", "%40")


def save_sharded(state: dict, ckpt_dir: str) -> None:
    """Write this process's addressable shards of every array in ``state``.

    Layout: ckpt_dir/shard_<pid>/<var>.<i>.npy + manifest.json recording
    each shard's global index slices.  Replicated values are written once,
    by a deterministically assigned process (round-robin over var names),
    so checkpoint IO spreads across hosts instead of duplicating."""
    import json

    from ..fluid import fault as _fault
    from ..fluid.retry import retry_io
    from ..fluid.transpiler.ps_dispatcher import assign_writer

    def _save_npy(path, host_arr):
        def _write():
            _fault.io_delay()
            _fault.io_error(path, "write")
            np.save(path, host_arr)

        retry_io(_write, what="ckpt.shard_write")

    pid = process_index()
    d = os.path.join(ckpt_dir, f"shard_{pid}")
    os.makedirs(d, exist_ok=True)
    # balance replicated-var writes across hosts (the pserver-shard write
    # layout, ref go/pserver/service.go:346) instead of every process (or
    # only process 0) writing identical full blobs; every process derives
    # the identical name->writer map.  NOTE a replicated array in a
    # multihost world is NOT fully_addressable (its sharding spans other
    # processes' devices) — replication shows up as a local shard whose
    # index covers the whole array, handled in the shard loop below.
    writer_of = assign_writer(list(state), max(1, process_count()))
    manifest = {}
    for name, arr in state.items():
        if not isinstance(arr, jax.Array):
            arr = jax.numpy.asarray(arr)
        entry = {"shape": [int(s) for s in arr.shape],
                 "dtype": str(np.dtype(arr.dtype)), "shards": []}
        if arr.is_fully_addressable:
            # whole value visible on this host (replicated, or a single-
            # host run): one blob, written by its assigned process
            if writer_of.get(name, 0) == pid or not _initialized:
                fn = f"{_safe_name(name)}.full.npy"
                _save_npy(os.path.join(d, fn), np.asarray(arr))
                entry["shards"].append({"file": fn, "index": None})
        else:
            seen = set()
            for i, sh in enumerate(arr.addressable_shards):
                idx = tuple(
                    (0 if sl.start is None else int(sl.start),
                     int(dim) if sl.stop is None else int(sl.stop))
                    for sl, dim in zip(sh.index, arr.shape))
                if idx in seen:  # replicated across local devices
                    continue
                seen.add(idx)
                full_cover = all(a == 0 and b == dim for (a, b), dim
                                 in zip(idx, arr.shape))
                if full_cover and writer_of.get(name, 0) != pid:
                    # replicated across processes (incl. scalars, whose
                    # empty index is trivially full): one assigned writer
                    continue
                fn = f"{_safe_name(name)}.{i}.npy"
                _save_npy(os.path.join(d, fn), np.asarray(sh.data))
                entry["shards"].append({"file": fn,
                                        "index": [list(p) for p in idx]})
        if entry["shards"]:
            manifest[name] = entry
    # manifest is written LAST: its presence marks this process's shard dir
    # complete (a preempted writer leaves .npy files but no manifest)
    mf_path = os.path.join(d, "manifest.json")

    def _write_manifest():
        _fault.io_error(mf_path, "write")
        with open(mf_path, "w") as f:
            json.dump({"process_count": process_count(),
                       "vars": manifest}, f)

    retry_io(_write_manifest, what="ckpt.shard_manifest")


def load_sharded(ckpt_dir: str, mesh: Optional[Mesh], specs: dict) -> dict:
    """Rebuild global arrays from every shard_*/ manifest under ckpt_dir.

    Requires the checkpoint directory to be readable by all processes
    (shared storage).  Arrays come back with NamedSharding(mesh,
    specs.get(name, P())), so restore works across a different process
    count than the save ran with.  ``mesh=None`` skips device placement
    and returns host numpy arrays (scope-level restore)."""
    import json

    from ..fluid import fault as _fault
    from ..fluid.retry import retry_io

    def _read_json(path):
        # transient OSError retries; garbage content raises ValueError
        # unretried — load_sharded_latest's corrupt-serial fallback owns it
        def _read():
            _fault.io_error(path, "read")
            with open(path) as f:
                return f.read()

        return json.loads(retry_io(_read, what="ckpt.shard_manifest"))

    # process 0's manifest is canonical for the world size: stale higher-
    # index shard dirs from an older, larger-world save in the same
    # directory must be ignored, not merged over fresh weights
    mf0 = os.path.join(ckpt_dir, "shard_0", "manifest.json")
    if not os.path.exists(mf0):
        raise IOError(
            f"sharded checkpoint {ckpt_dir}: shard_0/manifest.json missing "
            f"— no complete checkpoint here")
    expected_procs = int(_read_json(mf0).get("process_count", 1))

    assembled: dict = {}
    covered: dict = {}
    found_procs = set()
    for sub in sorted(os.listdir(ckpt_dir)):
        sd = os.path.join(ckpt_dir, sub)
        mf = os.path.join(sd, "manifest.json")
        if not sub.startswith("shard_"):
            continue
        pid = int(sub.split("_", 1)[1])
        if pid >= expected_procs:
            continue  # stale dir from an older save with more processes
        if not os.path.exists(mf):
            raise IOError(
                f"sharded checkpoint {ckpt_dir}: {sub} has no manifest — "
                f"its writer was interrupted; checkpoint is incomplete")
        payload = _read_json(mf)
        found_procs.add(pid)
        for name, entry in payload["vars"].items():
            shape = tuple(entry["shape"])
            if name not in assembled:
                assembled[name] = np.zeros(shape, np.dtype(entry["dtype"]))
                covered[name] = 0
            for sh in entry["shards"]:
                shard_path = os.path.join(sd, sh["file"])

                def _read_shard(path=shard_path):
                    _fault.io_error(path, "read")
                    return np.load(path)

                data = retry_io(_read_shard, what="ckpt.shard_read")
                if sh["index"] is None:
                    assembled[name][...] = data
                    covered[name] = assembled[name].size
                else:
                    sl = tuple(slice(a, b) for a, b in sh["index"])
                    assembled[name][sl] = data
                    covered[name] += int(data.size)
    if expected_procs is not None and \
            found_procs != set(range(expected_procs)):
        raise IOError(
            f"sharded checkpoint {ckpt_dir}: expected shards from "
            f"{expected_procs} processes, found {sorted(found_procs)}")
    # every element of every array must be covered by some shard — a gap
    # would otherwise restore as silent zeros (disjoint rectangular GSPMD
    # partitions make element-count a sound cover test)
    for name, host in assembled.items():
        if covered[name] < host.size:
            raise IOError(
                f"sharded checkpoint {ckpt_dir}: var '{name}' covers "
                f"{covered[name]}/{host.size} elements — missing shards")
    if mesh is None:
        return assembled
    out = {}
    for name, host in assembled.items():
        spec = specs.get(name, P())
        sharding = NamedSharding(mesh, spec if spec is not None else P())
        out[name] = jax.make_array_from_callback(
            host.shape, sharding, lambda idx, h=host: h[idx])
    return out


# ---------------------------------------------------------------------------
# Serial-dir protocol over sharded checkpoints (the multihost face of
# trainer.save_checkpoint's checkpoint_<n>/_SUCCESS convention, shared with
# the elastic supervisor): every process writes its shards of
# <root>/checkpoint_<n>/, a pod barrier proves all writers finished, then
# process 0 alone commits the serial with meta.json + _SUCCESS.  A worker
# preempted at ANY point leaves either a complete older serial or an
# unmarked dir that restore skips/cleans — never a half-readable state.
# ---------------------------------------------------------------------------

SERIAL_PREFIX = "checkpoint"
SUCCESS_MARK = "_SUCCESS"
META_FILE = "meta.json"


def _sharded_serial_dirs(root: str):
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if name.startswith(SERIAL_PREFIX + "_"):
            try:
                out.append((int(name.rsplit("_", 1)[1]), name))
            except ValueError:
                continue
    return sorted(out)


def latest_complete_sharded(root: str) -> int:
    """Newest serial whose _SUCCESS marker exists, or -1."""
    for serial, name in reversed(_sharded_serial_dirs(root)):
        if os.path.exists(os.path.join(root, name, SUCCESS_MARK)):
            return serial
    return -1


def serial_meta_topology(mesh=None) -> dict:
    """The topology stamp every sharded serial's meta carries: the mesh
    axes this fleet laid state out with (an explicit ``mesh``,  the
    active SPMD mesh, or the ``PADDLE_TPU_MESH`` env spec — whichever is
    known), the process count, and every rank's data-shard assignment.
    ``parallel.reshard`` reads exactly these keys to decide whether a
    resume needs re-layout and how to remap the per-rank cursors."""
    from ..data.sharding import shard_layout
    from .mesh import axes_of

    if mesh is None:
        from .spmd import active_mesh

        mesh = active_mesh()
    axes = axes_of(mesh)
    procs = max(1, process_count())
    out = {"process_count": procs}
    if axes:
        out["mesh_axes"] = [[a, int(e)] for a, e in axes.items()]
    try:
        out["data_shards"] = {
            str(r): [int(n), int(i)]
            for r, (n, i) in shard_layout(mesh, procs).items()}
    except ValueError:
        # a topology/host pair the data plane cannot tile never trained
        # a pipeline; record nothing rather than a wrong layout
        pass
    return out


def save_sharded_serial(state: dict, root: str, serial: int,
                        meta: Optional[dict] = None,
                        max_num: Optional[int] = None,
                        data_state: Optional[dict] = None,
                        mesh=None) -> str:
    """Commit ``state`` as <root>/checkpoint_<serial>/ under the _SUCCESS
    protocol.  ``serial`` is caller-assigned (typically the global step) so
    every process independently derives the same value with no filesystem
    race; restore hands the resume point back via ``meta``.

    ``data_state`` is this RANK's input-pipeline cursor
    (``paddle_tpu.data``): every process writes its own
    ``data_state_<rank>.json`` blob before the all-writers barrier, so
    process 0's single _SUCCESS commit covers the whole fleet's data
    plane atomically with the model shards.

    ``meta`` always lands on disk (an empty dict when the caller passed
    none) and is always enriched with the fleet topology
    (:func:`serial_meta_topology`: ``mesh_axes`` / ``process_count`` /
    per-rank ``data_shards``) — the record ``parallel.reshard`` needs to
    resume this serial on a DIFFERENT mesh.  ``mesh`` pins the topology
    explicitly; by default the active SPMD mesh or the
    ``PADDLE_TPU_MESH`` env spec is recorded.

    Ordering: shards (+ data state) -> barrier (all writers done) ->
    [p0] meta + _SUCCESS -> barrier (everyone may now trust the serial)
    -> [p0] prune.  The fault hooks bracket the _SUCCESS write exactly
    like the single-process trainer checkpoint."""
    import json as _json
    import shutil
    import time as _t

    from ..fluid import fault as _fault
    from .mesh import axes_label

    t_save0 = _t.perf_counter()
    cur = os.path.join(root, f"{SERIAL_PREFIX}_{serial}")
    os.makedirs(cur, exist_ok=True)
    save_sharded(state, cur)
    if data_state is not None:
        from ..data.checkpoint import save_data_state

        save_data_state(cur, data_state, rank=process_index())
    meta = dict(meta or {})
    topo = serial_meta_topology(mesh)
    for key, val in topo.items():
        meta.setdefault(key, val)
    mesh_tag = axes_label({a: e for a, e in meta.get("mesh_axes") or []})
    barrier_s = barrier(f"ckpt_shards_{serial}")
    if process_index() == 0:
        from ..fluid.retry import retry_io

        meta_path = os.path.join(cur, META_FILE)

        def _write_meta():
            _fault.io_error(meta_path, "write")
            with open(meta_path, "w") as f:
                _json.dump(meta, f)

        retry_io(_write_meta, what="ckpt.meta")
        # poison hook before the commit: a matching serial is rewritten
        # NaN (every rank's shards — the walk is recursive) yet still
        # gets its _SUCCESS, the serving canary's rollback oracle
        _fault.ckpt_poison(int(serial), cur)
        _fault.ckpt_crash_point("before")
        success_path = os.path.join(cur, SUCCESS_MARK)

        def _write_success():
            _fault.io_error(success_path, "write")
            with open(success_path, "w") as f:
                f.write("")

        retry_io(_write_success, what="ckpt.success")
        _fault.ckpt_crash_point("after")
        from .. import observe

        # the commit point: after _SUCCESS the serial is trusted, and the
        # run-event stream shows which step's state survives a restart
        # (mesh-labeled, so the goodput ledger prices a downgraded
        # generation's commits against the topology they ran on)
        commit_fields = {"serial": int(serial), "path": cur}
        if mesh_tag is not None:
            commit_fields["mesh"] = mesh_tag
        observe.emit("checkpoint.commit", **commit_fields)
    barrier_s += barrier(f"ckpt_commit_{serial}")
    from .. import observe
    from ..observe import goodput as _goodput

    # all ranks' shards are now covered by p0's _SUCCESS: record the
    # committed step so heartbeats price work-at-risk, book the IO as
    # checkpoint-state time (barrier waits already counted by barrier()),
    # and leave one per-rank checkpoint.save span in the stream
    commit_step = meta.get("step") if isinstance(meta, dict) else None
    observe.note_commit_step(int(commit_step) if commit_step is not None
                             else int(serial))
    dur = _t.perf_counter() - t_save0
    _goodput.note("checkpoint", max(0.0, dur - barrier_s))
    observe.emit("checkpoint.save", serial=int(serial),
                 dur_s=round(dur, 6), barrier_s=round(barrier_s, 6))
    if process_index() == 0 and max_num is not None:
        complete = [(s, n) for s, n in _sharded_serial_dirs(root)
                    if os.path.exists(os.path.join(root, n, SUCCESS_MARK))]
        for _, name in complete[:max(0, len(complete) - max_num)]:
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)
    return cur


def load_sharded_latest(root: str, mesh: Optional[Mesh], specs: dict,
                        clean_incomplete: bool = True):
    """Restore the newest complete serial under ``root``.

    Returns (serial, meta, state) or (-1, None, None) when no complete
    checkpoint exists — INCLUDING an absent/empty root and a root whose
    only serials are unmarked leftovers (the empty-root regression: this
    function must never fall off the end and hand back a bare ``None``
    the caller cannot unpack).  When the serial carries a ``data_state``
    blob for THIS rank it is returned under ``meta["data_state"]`` so
    the worker can restart its input pipeline at the first un-committed
    sample; an unreadable blob condemns the whole serial (fallback),
    absence just means legacy step-replay resume.  A complete-but-
    unreadable serial (truncated shard after commit) falls back to the
    previous complete one, mirroring trainer.load_checkpoint.

    Reshard-on-load (ISSUE 14): when the serial's recorded topology
    (``meta["mesh_axes"]`` / ``meta["process_count"]``) differs from the
    live one, the load routes through ``parallel.reshard`` — the logical
    view is assembled from the old fleet's shards, re-laid out under
    ``mesh``'s shardings, and the per-rank data cursors are merged/split
    onto this fleet's shard layout; ``meta["resharded"]`` records the
    transition.  A same-topology load takes the path below untouched.
    A topology the serial cannot viably land on raises
    ``reshard.ReshardError`` immediately (older serials are equally
    unviable — falling back would only bury the named error).

    ``clean_incomplete`` removes unmarked serial dirs left by a dead
    generation (process 0 only, behind a barrier) so a resumed run
    re-using their serial numbers never mixes stale shards with fresh
    ones."""
    import json as _json
    import shutil

    from . import reshard as _reshard

    if clean_incomplete:
        if process_index() == 0:
            for serial, name in _sharded_serial_dirs(root):
                if not os.path.exists(os.path.join(root, name,
                                                   SUCCESS_MARK)):
                    shutil.rmtree(os.path.join(root, name),
                                  ignore_errors=True)
        barrier("ckpt_clean")
    complete = [s for s, name in _sharded_serial_dirs(root)
                if os.path.exists(os.path.join(root, name, SUCCESS_MARK))]
    last_exc = None
    for serial in reversed(complete):
        cur = os.path.join(root, f"{SERIAL_PREFIX}_{serial}")
        try:
            meta = {}
            meta_path = os.path.join(cur, META_FILE)
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    meta = _json.load(f)
            if _reshard.needs_reshard(meta, mesh):
                state, data_state, info = _reshard.load_resharded(
                    cur, meta, mesh, specs)
                meta["resharded"] = info
            else:
                state = load_sharded(cur, mesh, specs)
                from ..data.checkpoint import load_data_state

                data_state = load_data_state(cur, rank=process_index())
        except _reshard.ReshardError:
            raise
        except Exception as exc:
            from ..fluid.log import LOG

            LOG(f"sharded checkpoint {cur} is unreadable ({exc!r}); "
                f"falling back to the previous complete serial")
            last_exc = exc
            continue
        if data_state is not None:
            meta["data_state"] = data_state
        return serial, meta, state
    if last_exc is not None:
        raise IOError(
            f"no loadable sharded checkpoint under {root}: every complete "
            f"serial failed to read") from last_exc
    return -1, None, None
