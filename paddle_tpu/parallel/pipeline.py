"""Pipeline parallelism: GPipe microbatch schedule over a "pp" mesh axis.

A capability the reference lacks in Fluid (SURVEY.md §2.6: PP "Absent in
Fluid"; its closest relative is the v2-era ParallelNeuralNetwork layer
pipelining, ref legacy/gserver/gradientmachines/ParallelNeuralNetwork.h:34,
which dispatches layers to devices with host threads).  The TPU formulation
is collective-based and compiles to one XLA program: stage parameters are
stacked on a leading dim sharded over "pp" (one stage per device), and
microbatches flow through the stages with one `lax.ppermute` hop per step —
activations ride ICI, the host never touches them.

Schedule: plain GPipe — M microbatches drain through S stages in
M + S - 1 steps; the bubble fraction is (S-1)/(M+S-1).  The whole schedule
is a `lax.scan`, so the backward pass is the reverse schedule for free
(ppermute/scan are differentiable) — no hand-written 1F1B needed for
correctness; XLA overlaps the ppermute with the next step's stage compute.

Composes with data parallelism: if the mesh also has a "dp" axis the batch
dim shards over it and each dp row runs an independent pipeline.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _pvary(x, axis_names):
    """Newer jax tracks varying-manual-axes types inside shard_map and
    requires per-stage-written scan carries to be pcast to varying; older
    jax has no vma tracking (and no ``lax.pcast``) — identity there."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_names, to="varying")
    return x


def _axis_size(axis_name):
    """``lax.axis_size`` appeared in newer jax; ``psum(1, axis)`` of a
    static scalar is the version-stable spelling (evaluates statically)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)

from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 moved shard_map to the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def _pipeline_body(params, x, stage_fn, pp_axis, n_micro):
    """Runs inside shard_map: params carry a leading stage dim of local
    size 1; x is this dp-row's LOCAL batch [N, ...]."""
    params = jax.tree_util.tree_map(lambda p: p[0], params)
    s_total = _axis_size(pp_axis)
    stage = lax.axis_index(pp_axis)
    n = x.shape[0]
    mb = n // n_micro
    xmb = x.reshape((n_micro, mb) + x.shape[1:])
    perm = [(j, (j + 1) % s_total) for j in range(s_total)]

    def step(carry, t):
        cur, out_buf = carry
        recv = lax.ppermute(cur, pp_axis, perm)
        in_idx = jnp.clip(t, 0, n_micro - 1)
        my_in = jnp.where(stage == 0,
                          lax.dynamic_index_in_dim(xmb, in_idx, 0,
                                                   keepdims=False),
                          recv)
        out = stage_fn(params, my_in)
        # last stage finished microbatch t-(S-1) at step t
        o_idx = jnp.clip(t - (s_total - 1), 0, n_micro - 1)
        write = (stage == s_total - 1) & (t >= s_total - 1) \
            & (t - (s_total - 1) < n_micro)
        out_buf = jnp.where(
            write,
            lax.dynamic_update_index_in_dim(out_buf, out, o_idx, 0),
            out_buf)
        return (out, out_buf), None

    # initial carries must be marked varying over the pp axis (the loop
    # writes per-stage values into them) or scan rejects the carry types;
    # zeros_like(xmb) inherits x's batch-axis vma, pcast adds pp
    cur0 = _pvary(jnp.zeros_like(xmb[0]), (pp_axis,))
    buf0 = _pvary(jnp.zeros_like(xmb), (pp_axis,))
    (_, out_buf), _ = lax.scan(step, (cur0, buf0),
                               jnp.arange(n_micro + s_total - 1))
    # only the last stage holds real results; psum replicates them across pp
    out_buf = lax.psum(
        jnp.where(stage == s_total - 1, out_buf, jnp.zeros_like(out_buf)),
        pp_axis)
    return out_buf.reshape((n,) + x.shape[1:])


def gpipe(stage_fn, stage_params, x, mesh: Mesh, pp_axis: str = "pp",
          n_microbatches: int = 4):
    """Run ``x`` through S pipeline stages of ``stage_fn``.

    stage_fn(params_slice, x_mb) -> y_mb must preserve the microbatch
    shape (homogeneous stages — the transformer/MLP-stack case).
    stage_params: pytree whose leaves have leading dim S = mesh.shape[pp].
    x: [N, ...] with N divisible by n_microbatches (per dp shard).
    """
    s = mesh.shape[pp_axis]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != s:
            raise ValueError(
                f"stage param leading dim {leaf.shape[0]} != pp size {s}")
    b_axis = "dp" if "dp" in mesh.axis_names else None
    xspec = P(*([b_axis] + [None] * (x.ndim - 1)))
    pspec = jax.tree_util.tree_map(
        lambda p: P(*([pp_axis] + [None] * (p.ndim - 1))), stage_params)
    fn = _shard_map(
        partial(_pipeline_body, stage_fn=stage_fn, pp_axis=pp_axis,
                n_micro=n_microbatches),
        mesh=mesh, in_specs=(pspec, xspec), out_specs=xspec)
    return fn(stage_params, x)


def mlp_stage_fn(act: str):
    """Stage function for a stack of equal-width fc layers: params =
    (w [L/S, D, D], b [L/S, D])."""
    def fn(params, x):
        ws, bs = params
        for i in range(ws.shape[0]):
            h = x @ ws[i] + bs[i]
            x = _apply_act(h, act)
        return x
    return fn


def _apply_act(h, act: str):
    if act == "relu":
        return jax.nn.relu(h)
    if act == "tanh":
        return jnp.tanh(h)
    if act == "gelu":
        return jax.nn.gelu(h)
    if act in (None, "", "none", "linear"):
        return h
    raise ValueError(f"unsupported pipeline activation {act!r}")


def sequential_stack(w, b, x, act: str):
    """Single-device oracle/fallback: apply all L layers in order."""
    for i in range(w.shape[0]):
        x = _apply_act(x @ w[i] + b[i], act)
    return x
