"""Fault-tolerant dataset task dispatch — the go/master equivalent
(ref: go/master/service.go — chunk partition :106, GetTask :368,
TaskFinished :411, TaskFailed :455, timeout requeue :341 checkTimeoutFunc,
failure cap :313 processFailedTask, snapshot-to-store :207 / recover :166).

The reference's elastic-data-loading design: trainers are STATELESS
consumers of a task queue over dataset chunks; any trainer can die or join
mid-pass because unfinished tasks time out and requeue, and the master's
own state snapshots to etcd.  Here the dispatcher is an in-process (or
process-shared via a file snapshot) object the input pipeline consumes;
coordination-service membership is jax.distributed's job, data elasticity
is this one's.

Usage::

    m = TaskDispatcher(chunks, chunks_per_task=2, timeout=60., failure_max=3)
    while True:
        task = m.get_task()           # None => pass finished
        if task is None: break
        try:
            for chunk in task.chunks: consume(chunk)
            m.task_finished(task.task_id)
        except Exception:
            m.task_failed(task.task_id)
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["Task", "TaskDispatcher", "Backoff"]


@dataclass
class Backoff:
    """Deterministic exponential backoff with a cap — the retry pacing the
    reference's master/pserver clients use between reconnect attempts
    (ref go/master/client.go retry loop), shared by the elastic pod
    supervisor between generation restarts and the transient-I/O retry
    wrapper (``fluid.retry``).  ``delay(k)`` is the wait before attempt k
    (k=0 -> base).

    ``jitter`` (ISSUE 18 satellite) spreads a fleet-wide restart:
    ``delay(k)`` is multiplied by ``1 + jitter * u_k`` with ``u_k`` drawn
    from a private ``random.Random(seed)`` stream — after a fleet-wide
    kill, N supervisors re-registering on the bare exponential land on
    the coordinator in the same instant (the thundering herd); jittered,
    they smear across ``[d, d * (1 + jitter)]``.  ``seed=None`` keeps
    production entropy; a pinned seed makes the whole delay sequence
    reproducible (the unit-test contract)."""

    base: float = 1.0
    factor: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.0
    seed: Optional[int] = None

    def __post_init__(self):
        import random

        self._rng = random.Random(self.seed)

    def delay(self, attempt: int) -> float:
        d = self.base * (self.factor ** max(0, int(attempt)))
        d = min(d, self.max_delay)
        if self.jitter > 0.0:
            d *= 1.0 + self.jitter * self._rng.random()
        return d


@dataclass
class Task:
    task_id: int
    chunks: List  # opaque chunk descriptors (paths, index ranges, ...)
    epoch: int = 0
    num_failure: int = 0
    dispatched_at: float = field(default=0.0, compare=False)


class TaskDispatcher:
    """Single-master task queue with timeout requeue + failure caps.

    ``snapshot_path`` persists state after every transition (the etcd role,
    ref service.go:207); a restarted master resumes mid-pass (recover
    :166).  Pending tasks are reclaimed lazily: every get_task() first
    requeues pending tasks older than ``timeout`` (the reference arms a
    timer per dispatch — same observable behavior, no threads)."""

    def __init__(self, chunks: List, chunks_per_task: int = 1,
                 timeout: float = 60.0, failure_max: int = 3,
                 snapshot_path: Optional[str] = None):
        self.chunks_per_task = int(chunks_per_task)
        self.timeout = float(timeout)
        self.failure_max = int(failure_max)
        self.snapshot_path = snapshot_path
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()
            return
        self.cur_pass = 0
        self.todo: List[Task] = self._partition(chunks)
        self.pending: dict = {}
        self.done: List[Task] = []
        self.failed: List[Task] = []
        self._all_chunks = list(chunks)
        self._snapshot()

    # -- construction helpers --
    def _partition(self, chunks) -> List[Task]:
        n = self.chunks_per_task
        return [Task(task_id=i, chunks=list(chunks[i * n:(i + 1) * n]))
                for i in range((len(chunks) + n - 1) // n)]

    # -- the protocol --
    def get_task(self) -> Optional[Task]:
        """Next task, or None when nothing is dispatchable RIGHT NOW —
        distinguish "pass done" from "stragglers still pending" with
        ``pass_finished()``.  Reclaims timed-out pending tasks first
        (ref :341)."""
        self._reclaim_timeouts()
        if not self.todo:
            return None
        t = self.todo.pop(0)
        t.dispatched_at = time.time()
        self.pending[t.task_id] = t
        self._snapshot()
        return t

    def task_finished(self, task_id: int) -> None:
        t = self.pending.pop(task_id, None)
        if t is None:
            return  # late report after timeout-requeue (ref epoch check)
        self.done.append(t)
        self._snapshot()

    def task_failed(self, task_id: int) -> None:
        t = self.pending.pop(task_id, None)
        if t is None:
            return
        self._fail(t)
        self._snapshot()

    def pass_finished(self) -> bool:
        self._reclaim_timeouts()
        return not self.todo and not self.pending

    def start_new_pass(self) -> None:
        """Re-arm the queue with all chunks for the next pass (the
        reference flips CurPass when todo+pending drain, :411)."""
        self.cur_pass += 1
        self.todo = self._partition(self._all_chunks)
        self.pending = {}
        self.done = []
        self.failed = []
        self._snapshot()

    # -- internals --
    def _fail(self, t: Task) -> None:
        t.num_failure += 1
        if t.num_failure > self.failure_max:
            self.failed.append(t)  # discard (ref :330)
        else:
            self.todo.append(t)    # re-dispatch (ref :336)

    def _reclaim_timeouts(self) -> None:
        now = time.time()
        for tid in list(self.pending):
            t = self.pending[tid]
            if now - t.dispatched_at > self.timeout:
                del self.pending[tid]
                self._fail(t)
        # NOTE: the pass does NOT auto-flip when todo+pending drain; epoch
        # boundaries stay explicit via start_new_pass()

    # -- persistence (the etcd role) --
    def _snapshot(self) -> None:
        if not self.snapshot_path:
            return
        state = {
            "cur_pass": self.cur_pass,
            "todo": [self._ser(t) for t in self.todo],
            "pending": [self._ser(t) for t in self.pending.values()],
            "done": [self._ser(t) for t in self.done],
            "failed": [self._ser(t) for t in self.failed],
            "all_chunks": self._all_chunks,
            "chunks_per_task": self.chunks_per_task,
        }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.snapshot_path)  # atomic (crash-safe)

    def _recover(self) -> None:
        with open(self.snapshot_path) as f:
            state = json.load(f)
        self.cur_pass = state["cur_pass"]
        self.chunks_per_task = state["chunks_per_task"]
        self._all_chunks = state["all_chunks"]
        # pending tasks were in flight when the master died: requeue them
        # (their consumers cannot report back to a new master instance)
        self.todo = [self._de(t) for t in state["todo"]] + \
            [self._de(t) for t in state["pending"]]
        self.pending = {}
        self.done = [self._de(t) for t in state["done"]]
        self.failed = [self._de(t) for t in state["failed"]]

    @staticmethod
    def _ser(t: Task) -> dict:
        return {"task_id": t.task_id, "chunks": t.chunks, "epoch": t.epoch,
                "num_failure": t.num_failure}

    @staticmethod
    def _de(d: dict) -> Task:
        return Task(task_id=d["task_id"], chunks=d["chunks"],
                    epoch=d.get("epoch", 0),
                    num_failure=d.get("num_failure", 0))


def task_reader(dispatcher: TaskDispatcher, chunk_reader):
    """Adapter: a paddle reader that pulls tasks from the dispatcher and
    yields samples from each chunk via ``chunk_reader(chunk)`` — the shape
    of the v2 master-client reader (ref python/paddle/v2/master/client.py).
    Marks tasks finished only after ALL their samples were consumed."""

    def reader():
        while True:
            task = dispatcher.get_task()
            if task is None:
                if dispatcher.pass_finished():
                    return
                # stragglers still pending on another consumer: wait for
                # their timeout so a died consumer's chunks requeue to us
                # instead of being silently dropped
                time.sleep(min(max(dispatcher.timeout / 10.0, 0.01), 1.0))
                continue
            try:
                for chunk in task.chunks:
                    yield from chunk_reader(chunk)
            except Exception:
                dispatcher.task_failed(task.task_id)
                raise
            dispatcher.task_finished(task.task_id)

    return reader
