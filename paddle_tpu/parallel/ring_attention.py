"""Ring attention: sequence/context parallelism over the ICI ring.

A capability the reference does NOT have (SURVEY.md §5.7 / §2.6: SP/CP are
"Absent" — the reference scales sequence length only by LoD packing on one
device).  Here long sequences shard across the "sp" mesh axis: each device
holds a [T/S] slice of Q, K and V, and attention runs as S ring steps — the
local Q block attends to the resident K/V block while K/V rotate one
neighbor per step via ``lax.ppermute`` (pure ICI traffic, no all-gather).
Softmax is computed ONLINE (running max / denominator, the flash-attention
recurrence), so memory stays O(T/S * T/S) per step instead of O(T^2) and
the result is bit-for-bit equivalent to full softmax attention up to fp
reassociation.

Ref analogues for the mechanics it replaces: the pserver would ship whole
tensors (grpc_server.cc); GSPMD's default for sharded-sequence attention
would all-gather K/V.  The ring keeps peak memory flat and overlaps
transfer with compute — the standard TPU recipe (Liu et al., Ring
Attention; jax-ml scaling-book collectives chapter).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _axis_size(axis_name):
    """``lax.axis_size`` appeared in newer jax; ``psum(1, axis)`` of a
    static scalar is the version-stable spelling (evaluates statically)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 moved shard_map to the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def _amp_einsum(spec, a, b):
    """Contraction under the shared AMP recipe (fluid/amp.py einsum):
    bf16 operands on the MXU, fp32 activation contract restored."""
    from ..fluid import amp

    return amp.einsum(spec, a, b)


def _block_attend(q, k, v, q_off, k_off, scale, causal, m, l, o,
                  bias=None):
    """One online-softmax accumulation step of q against a (k, v) block.

    q: [B, H, Tq, D]; k/v: [B, H, Tk, D]; m/l/o are the running max,
    denominator and (unnormalized) output; bias, if given, is an additive
    [B, 1, 1, Tk] key-position bias (padding mask) for THIS k block."""
    s = _amp_einsum("bhqd,bhkd->bhqk", q, k) * scale  # [B,H,Tq,Tk]
    if bias is not None:
        s = s + bias
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        qpos = q_off + jnp.arange(tq)[:, None]
        kpos = k_off + jnp.arange(tk)[None, :]
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) -> use 0
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(s), s - safe_m, -jnp.inf))
    p = jnp.where(jnp.isfinite(p), p, 0.0)
    corr = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
    corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * corr + _amp_einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def _ring_body(q, k, v, bias, axis_name, causal, scale):
    """Runs inside shard_map: q/k/v are the LOCAL [B, H, T/S, D] blocks;
    bias (or None) is the LOCAL [B, 1, 1, T/S] key-bias block, which
    rotates around the ring together with its k/v block."""
    n_dev = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[2]
    q_off = my * t_local

    m = jnp.full(q.shape[:3] + (1,), -jnp.inf, q.dtype)
    l = jnp.zeros(q.shape[:3] + (1,), q.dtype)
    o = jnp.zeros_like(q)

    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    def step(i, carry):
        k_cur, v_cur, b_cur, m, l, o = carry
        src = (my - i) % n_dev  # whose K/V block we hold at step i
        m, l, o = _block_attend(q, k_cur, v_cur, q_off, src * t_local,
                                scale, causal, m, l, o, bias=b_cur)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        b_nxt = (lax.ppermute(b_cur, axis_name, perm)
                 if b_cur is not None else None)
        return k_nxt, v_nxt, b_nxt, m, l, o

    carry = (k, v, bias, m, l, o)
    # python loop: n_dev is static, XLA overlaps ppermute with the next
    # step's einsum (no scan-carried dynamic shapes)
    for i in range(n_dev):
        carry = step(i, carry)
    _, _, _, m, l, o = carry
    return o / jnp.maximum(l, jnp.finfo(l.dtype).tiny)


def ring_attention(q, k, v, mesh: Mesh, sp_axis: str = "sp",
                   causal: bool = False, scale=None, bias=None):
    """Sequence-parallel attention over ``mesh[sp_axis]``.

    q, k, v: [B, H, T, D] global arrays (T divisible by the sp size);
    returns [B, H, T, D] with the same sharding.  Batch may additionally be
    sharded on a "dp" axis — the spec below only constrains T.  bias, if
    given, is an additive [B, 1, 1, T] key-position bias (padding mask);
    it shards over sp on its key dim and rides the ring with k/v."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    # batch stays dp-sharded when the mesh has a dp axis — otherwise the
    # shard_map boundary would all-gather B across dp and every replica
    # would redo the full-batch attention
    b_axis = "dp" if "dp" in mesh.axis_names else None
    spec = P(b_axis, None, sp_axis, None)
    if bias is None:
        fn = _shard_map(
            partial(_ring_body, bias=None, axis_name=sp_axis, causal=causal,
                    scale=scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return fn(q, k, v)
    bspec = P(b_axis, None, None, sp_axis)
    fn = _shard_map(
        partial(_ring_body, axis_name=sp_axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec, bspec), out_specs=spec)
    return fn(q, k, v, bias)


def full_attention(q, k, v, causal: bool = False, scale=None, bias=None):
    """Single-device reference (used as the oracle and as the fallback when
    no sp mesh is active)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = _amp_einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return _amp_einsum("bhqk,bhkd->bhqd", p, v)
