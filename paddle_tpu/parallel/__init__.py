"""Parallelism: device meshes, SPMD sharding rules, collectives.

TPU-native replacement for the reference's distributed stack (SURVEY.md §2.5,
§2.6): NCCL context maps + gRPC parameter servers become a
``jax.sharding.Mesh`` with GSPMD-inserted collectives over ICI.
"""

from .local_sgd import AsyncLocalSGDTrainer
from .mesh import (make_mesh, make_mesh_nd, local_device_count,
                   mesh_from_spec, mesh_label, axes_of, axes_label,
                   parse_mesh_spec, env_mesh_spec, MESH_ENV)
from .reshard import ReshardError
from .spmd import (batch_spec, collective_stats, infer_param_specs,
                   shard_program_step, table_signature, ShardedTrainStep,
                   ShardedWindowRunner, SpecLayout)
from .master import Task, TaskDispatcher, task_reader

__all__ = ["make_mesh", "make_mesh_nd", "local_device_count",
           "mesh_from_spec", "mesh_label", "axes_of", "axes_label",
           "parse_mesh_spec", "env_mesh_spec", "MESH_ENV", "batch_spec",
           "collective_stats", "infer_param_specs", "shard_program_step",
           "table_signature", "ShardedTrainStep", "ShardedWindowRunner",
           "SpecLayout", "ReshardError", "Task", "TaskDispatcher",
           "task_reader", "AsyncLocalSGDTrainer"]
