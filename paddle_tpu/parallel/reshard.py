"""Elastic resharding: resume any sharded serial on any viable mesh.

Production preemption does not hand the same pod back: before this module
a dp4-sharded serial could only be loaded by a dp4 fleet, so losing a
host meant losing the run — the elastic supervisor just burned its
restart budget against a barrier timeout.  The pieces a mesh-changing
resume needs all exist (barrier-committed sharded serials, the canonical
``spmd.SpecLayout`` table, per-rank data cursors); this module is the
seam that composes them (ROADMAP item 4; ref lineage: ``go/master``'s
timeout-requeue — a dead trainer's work moves to the survivors instead
of wedging the job):

 1. **Assemble** the logical array view from a serial's per-rank shards
    (``multihost.load_sharded`` already rebuilds full host arrays from
    any shard layout — the serial records each shard's global index
    slices, so the logical view is mesh-independent by construction).
 2. **Re-lay out** every array under the NEW mesh's ``NamedSharding``s
    (the caller passes the PR 7 spec table for the live mesh;
    :func:`infer_state_specs` derives it for callers that only have the
    program).  Placement slices the assembled host array per device, so
    the resharded state is bit-exact against the logical view for every
    mesh pair — dp4→dp2, dp2→dp4, dp2tp2→dp4, rank permutations.
 3. **Remap the data cursors**: the dead fleet's per-rank pipeline
    cursor blobs merge/split deterministically onto the new fleet's
    shard layout (``data.sharding.merge_cursor_states`` — round-robin
    streams interleave in fixed order past the fleet's one committed
    cut; tp/fsdp peers collapse via the identical-data rule), so no
    sample is dropped or duplicated across the mesh change.

``multihost.load_sharded_latest`` consults :func:`needs_reshard`
whenever a serial's recorded topology (``meta["mesh_axes"]`` /
``meta["process_count"]``, stamped by ``save_sharded_serial``) differs
from the live one; loading under the SAME topology takes the existing
fast path untouched — no reshard code executes.  A mesh the serial
cannot viably land on raises :class:`ReshardError` by name instead of
falling back through older (equally unviable) serials.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .mesh import axes_label, axes_of, mesh_label

__all__ = [
    "ReshardError", "recorded_axes", "needs_reshard", "check_viable",
    "assemble_logical", "reshard_state", "remap_cursors",
    "load_resharded", "infer_state_specs",
]


class ReshardError(ValueError):
    """A serial cannot be resumed on the requested topology (shard
    streams don't tile, a cursor stream is missing or inconsistent, the
    pipeline shape forbids remapping).  Deliberately NOT an ``IOError``:
    the serial itself is healthy, so the serial-fallback loop in
    ``load_sharded_latest`` must not eat this and retry an older serial
    — every serial is equally unviable on a bad mesh."""


def _normalize(axes: Dict[str, int]) -> Tuple[Tuple[str, int], ...]:
    """Extent-1 axes shard nothing: ``dp4`` and ``dp4,tp1`` are the same
    topology for both state layout and data sharding."""
    return tuple((a, int(e)) for a, e in axes.items() if int(e) > 1)


def recorded_axes(meta: Optional[dict]) -> Optional[Dict[str, int]]:
    """The save-time topology from serial meta (``{axis: extent}``), or
    None for a legacy serial that recorded none."""
    if not isinstance(meta, dict):
        return None
    rec = meta.get("mesh_axes")
    if rec is None:
        return None
    return axes_of(rec)


def needs_reshard(meta: Optional[dict], mesh=None,
                  num_hosts: Optional[int] = None) -> bool:
    """True when the serial's recorded topology differs from the live
    one — by mesh shape (``mesh`` is a ``jax.sharding.Mesh``, a spec
    string, or None for the ``PADDLE_TPU_MESH`` env spec) or by process
    count.  A serial with no recorded topology never reshards (legacy
    fast path)."""
    if not isinstance(meta, dict):
        return False
    if num_hosts is None:
        from . import multihost

        num_hosts = multihost.process_count()
    rec_procs = meta.get("process_count")
    if rec_procs is not None and int(rec_procs) != int(num_hosts):
        return True
    rec = recorded_axes(meta)
    if rec is None:
        return False
    return _normalize(rec) != _normalize(axes_of(mesh))


def _old_layout(meta: dict) -> Optional[Dict[int, Tuple[int, int]]]:
    """The dead fleet's per-rank data-shard layout: the recorded
    ``meta["data_shards"]`` table when present, else re-derived from the
    recorded mesh + process count."""
    recorded = meta.get("data_shards")
    if isinstance(recorded, dict) and recorded:
        return {int(r): (int(p[0]), int(p[1]))
                for r, p in recorded.items()}
    rec = recorded_axes(meta)
    procs = meta.get("process_count")
    if procs is None:
        return None
    from ..data.sharding import shard_layout

    spec = ",".join(f"{a}{e}" for a, e in rec.items()) if rec else None
    try:
        return shard_layout(spec, int(procs))
    except ValueError as exc:
        raise ReshardError(
            f"reshard: cannot re-derive the saved fleet's shard layout "
            f"({exc})") from exc


def check_viable(meta: dict, mesh=None,
                 num_hosts: Optional[int] = None) -> Tuple[int, int]:
    """Prove the live topology can consume this serial's data plane;
    returns this fleet's ``(num_shards, shard_index)`` template for rank
    0.  Raises :class:`ReshardError` naming the first violated
    constraint: the new mesh/host pair must itself tile
    (``shard_spec``), and the old and new shard counts must tile with
    each other (round-robin streams merge or split only by integer
    factors)."""
    from ..data.sharding import shard_spec

    if num_hosts is None:
        from . import multihost

        num_hosts = multihost.process_count()
    try:
        new_n, new_i = shard_spec(mesh, host_rank=0, num_hosts=num_hosts)
    except ValueError as exc:
        raise ReshardError(
            f"reshard: target mesh is not viable — {exc}") from exc
    layout = _old_layout(meta)
    if layout:
        old_n = next(iter(layout.values()))[0]
        if old_n % new_n != 0 and new_n % old_n != 0:
            raise ReshardError(
                f"reshard: serial was saved with {old_n} data-shard "
                f"stream(s) but the target topology wants {new_n} — the "
                f"counts do not tile (need one to divide the other), so "
                f"the per-rank cursors cannot be remapped without "
                f"dropping or duplicating samples")
    return new_n, new_i


def assemble_logical(serial_dir: str) -> Dict[str, np.ndarray]:
    """The serial's full logical array view, assembled on host from every
    rank's shards + manifest (mesh-independent: each shard records its
    global index slices).  This is the reference every resharded layout
    must equal element-for-element."""
    from .multihost import load_sharded

    return load_sharded(serial_dir, None, {})


def reshard_state(logical: Dict[str, np.ndarray], mesh,
                  specs: Dict) -> Dict:
    """Lay the logical view out under the new mesh's ``NamedSharding``s
    (``specs`` is the PR 7 spec table for ``mesh``; absent names
    replicate).  Each device reads its slice of the host array, so the
    round trip is bitwise."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = {}
    for name, host in logical.items():
        spec = specs.get(name, P())
        sharding = NamedSharding(mesh, spec if spec is not None else P())
        out[name] = jax.make_array_from_callback(
            host.shape, sharding, lambda idx, h=host: h[idx])
    return out


def remap_cursors(serial_dir: str, meta: dict, mesh=None,
                  rank: Optional[int] = None,
                  num_hosts: Optional[int] = None) -> Optional[dict]:
    """This rank's data cursor under the NEW topology, merged/split from
    the serial's per-rank blobs.  None = the serial has no data plane
    (legacy resume); :class:`ReshardError` on any inconsistency."""
    from ..data.checkpoint import remap_data_state
    from ..data.sharding import shard_spec

    if num_hosts is None or rank is None:
        from . import multihost

        if num_hosts is None:
            num_hosts = multihost.process_count()
        if rank is None:
            rank = multihost.process_index()
    layout = _old_layout(meta)
    if layout is None:
        # a serial from before the meta enrichment: nothing to remap by;
        # treat any cursor it carries as unusable under a new topology
        from ..data.checkpoint import load_all_data_states

        if load_all_data_states(serial_dir):
            raise ReshardError(
                "reshard: serial carries data cursors but no recorded "
                "shard layout (pre-reshard save) — resuming them on a "
                "different topology would guess at sample positions")
        return None
    try:
        new_n, new_i = shard_spec(mesh, host_rank=rank, num_hosts=num_hosts)
        return remap_data_state(serial_dir, layout, new_n, new_i)
    except ReshardError:
        raise
    except ValueError as exc:
        raise ReshardError(f"reshard: {exc}") from exc


def load_resharded(serial_dir: str, meta: dict, mesh, specs: Dict,
                   rank: Optional[int] = None,
                   num_hosts: Optional[int] = None):
    """The reshard-on-load path: viability check, logical assembly,
    re-layout, cursor remap, and one ``reshard.load`` run event.

    Returns ``(state, data_state, info)`` where ``state`` is the model
    state under the new mesh (host numpy when ``mesh`` is None — the
    coordination-only fleets this container's CPU backend allows),
    ``data_state`` is this rank's remapped cursor (or None), and
    ``info`` is the jsonable transition record the caller folds into
    ``meta["resharded"]``."""
    if num_hosts is None:
        from . import multihost

        num_hosts = multihost.process_count()
    check_viable(meta, mesh, num_hosts=num_hosts)
    logical = assemble_logical(serial_dir)
    state = logical if mesh is None \
        else reshard_state(logical, mesh, specs or {})
    data_state = remap_cursors(serial_dir, meta, mesh, rank=rank,
                               num_hosts=num_hosts)
    from_label = axes_label(recorded_axes(meta) or {})
    to_label = mesh_label(mesh) if mesh is not None \
        else axes_label(axes_of(None))
    info = {"from_mesh": from_label, "to_mesh": to_label,
            "from_processes": meta.get("process_count"),
            "to_processes": int(num_hosts),
            "cursors_remapped": data_state is not None}
    try:
        from .. import observe

        observe.registry().inc("reshard.loads",
                               labels={"mesh": to_label or ""})
        observe.emit("reshard.load", path=serial_dir, **info)
    except Exception:
        pass  # accounting must never fail the resume it describes
    return state, data_state, info


def infer_state_specs(program, feed_names: List[str],
                      fetch_names: List[str], mesh,
                      tp_axis: Optional[str] = None,
                      zero1: bool = False) -> Dict:
    """The PR 7 spec table for ``program``'s state under ``mesh`` — the
    ``specs`` argument a mesh-changing resume passes to
    ``load_sharded_latest`` when it has no ``ShardedTrainStep`` in hand
    yet (the checkpoint must be laid out before the runner exists).
    Exactly the derivation ``ShardedTrainStep.__init__`` performs."""
    from ..fluid.executor import BlockPlan
    from .spmd import SpecLayout, infer_param_specs, resolve_tp_axis

    tp = resolve_tp_axis(mesh, tp_axis)
    layout = (SpecLayout(tp_axis=tp)
              if "tp" in mesh.axis_names or "fsdp" in mesh.axis_names
              else None)
    plan = BlockPlan(program, 0, list(feed_names), list(fetch_names))
    return infer_param_specs(program, plan, mesh, tp, zero1=zero1,
                             layout=layout)
