"""Stacked transformer layer blocks: scan-over-layers single-device, GPipe
pipeline over a "pp" mesh axis, Megatron-style tensor parallelism over "mp",
and ring-attention sequence parallelism over "sp" — composable on one mesh.

This is the TPU-first formulation of a transformer encoder/decoder stack
(used by models/transformer.py when cfg.pipeline_stages is set): every
layer's parameters are STACKED on a leading [L, ...] dim, so

 - single-device, the stack is a ``lax.scan`` over layers (one compiled
   layer body instead of L unrolled copies — faster compiles, same math);
 - with a "pp" mesh axis, layers shard over stages (dim 0) and microbatches
   flow through a GPipe ``ppermute`` schedule (parallel/pipeline.py design,
   generalized to a tree-valued carry so the encoder output / attention
   biases ride along with the activations);
 - with an "mp" axis, the per-layer matmuls run Megatron column/row
   parallel INSIDE the same shard_map body (q/k/v + ffn1 column-split,
   o + ffn2 row-split with one ``psum`` each);
 - with an "sp" axis, attention runs the ring schedule
   (parallel/ring_attention.py) over the sequence dim.

The reference has none of these (SURVEY.md §2.6: PP/SP/EP "Absent in
Fluid"); its transformer test model (python/paddle/fluid/tests/unittests/
transformer_model.py) is the functional contract for the per-layer math:
post-norm residual sublayers, scaled-dot-product attention with additive
biases, relu FFN.

Dropout matches fluid.layers.dropout's default ``downgrade_in_infer``
semantics and is applied to sublayer OUTPUTS (residual dropout).  Attention-
probability dropout is intentionally absent: under ring attention the
[T, T] probability matrix never materializes, so there is nothing to mask —
the residual dropout keeps the regularization story while staying identical
across every mesh layout.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _pvary(x, axis_names):
    """Newer jax tracks varying-manual-axes types inside shard_map and
    requires per-stage-written scan carries to be pcast to varying; older
    jax has no vma tracking (and no ``lax.pcast``) — identity there."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_names, to="varying")
    return x


def _axis_size(axis_name):
    """``lax.axis_size`` appeared in newer jax; ``psum(1, axis)`` of a
    static scalar is the version-stable spelling (evaluates statically)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)

from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 moved shard_map to the top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from . import ring_attention as ra

# slot -> (index of the dim sharded over "mp", or None).  Dim 0 is always
# the stacked layer dim (sharded over "pp" when present).  Column-parallel
# weights split their OUTPUT dim, row-parallel their INPUT dim (Megatron).
ENCODER_SLOTS = {
    "WQ": 2, "WK": 2, "WV": 2,          # [L, d, d]   column
    "WO": 1,                             # [L, d, d]   row
    "FFN1W": 2, "FFN1B": 1,              # [L, d, di] / [L, di] column
    "FFN2W": 1,                          # [L, di, d]  row
    "FFN2B": None,                       # [L, d]      replicated
    "LN1S": None, "LN1B": None, "LN2S": None, "LN2B": None,  # [L, d]
}
DECODER_SLOTS = dict(ENCODER_SLOTS)
DECODER_SLOTS.update({
    "CQ": 2, "CK": 2, "CV": 2, "CO": 1,  # cross-attention projections
    "LN3S": None, "LN3B": None,
})


def dist_spec_for(slot: str, ndim: int, decoder: bool) -> tuple:
    """Per-dim mesh-axis hints for a stacked param (consumed by
    spmd.infer_param_specs): dim 0 -> "pp", the Megatron dim -> "mp"."""
    table = DECODER_SLOTS if decoder else ENCODER_SLOTS
    mp_dim = table[slot]
    spec = ["pp"] + [None] * (ndim - 1)
    if mp_dim is not None:
        spec[mp_dim] = "mp"
    return tuple(spec)


def _layer_norm(x, scale, bias, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * scale + bias


def _dropout(x, key, rate, is_test):
    """fluid.layers.dropout default (downgrade_in_infer) semantics."""
    if not rate:
        return x
    if is_test:
        return x * (1.0 - rate)
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return x * keep.astype(x.dtype)


def _attend(q, k, v, bias, causal, local_heads, sp_axis, flash=False):
    """[b, tq, dh] x [b, tk, dh] -> [b, tq, dh] with dh split into
    ``local_heads`` heads; bias is [b, 1, 1, tk(-local)] or None.  Inside a
    shard_map with an sp axis the ring schedule runs over it; with
    flash=True the Pallas streamed kernel (fwd + bwd) runs instead of the
    XLA full-softmax; ``scale`` uses the GLOBAL head dim, which equals the
    local head dim (mp splits heads, not head size)."""
    b, tq, dh = q.shape
    tk = k.shape[1]
    dk = dh // local_heads
    q4 = q.reshape(b, tq, local_heads, dk).transpose(0, 2, 1, 3)
    k4 = k.reshape(b, tk, local_heads, dk).transpose(0, 2, 1, 3)
    v4 = v.reshape(b, tk, local_heads, dk).transpose(0, 2, 1, 3)
    scale = dk ** -0.5
    if sp_axis is not None:
        ctx = ra._ring_body(q4, k4, v4, bias, axis_name=sp_axis,
                            causal=causal, scale=scale)
    elif flash and _flash_bias_ok(bias, b, tk):
        from ..ops.pallas_flash import flash_attention

        ctx = flash_attention(q4, k4, v4, bias, scale, causal)
    else:
        ctx = ra.full_attention(q4, k4, v4, causal=causal, scale=scale,
                                bias=bias)
    return ctx.transpose(0, 2, 1, 3).reshape(b, tq, dh)


def _flash_bias_ok(bias, b, t_kv):
    from ..ops.pallas_flash import bias_supported

    return bias_supported(bias, b, t_kv)


def _attend_in_shard_map(local_heads, sp_axis, flash=False):
    """Attention callable for code already INSIDE a shard_map body."""
    def go(q, k, v, bias, causal):
        return _attend(q, k, v, bias, causal, local_heads, sp_axis,
                       flash=flash)

    return go


def _attend_gspmd_ring(n_head, mesh, sp_axis):
    """Attention callable for the scan path with an sp axis: the ring runs
    via the mesh-aware wrapper (its own shard_map); GSPMD owns the rest."""
    def go(q, k, v, bias, causal):
        b, tq, dh = q.shape
        tk = k.shape[1]
        dk = dh // n_head

        def to4(a, t):
            return a.reshape(b, t, n_head, dk).transpose(0, 2, 1, 3)

        ctx = ra.ring_attention(to4(q, tq), to4(k, tk), to4(v, tk), mesh,
                                sp_axis, causal=causal, bias=bias)
        return ctx.transpose(0, 2, 1, 3).reshape(b, tq, dh)

    return go


def _mm(a, b):
    """Matmul under the shared AMP recipe (fluid/amp.py matmul): bf16
    operands on the MXU, fp32 activation contract restored."""
    from ..fluid import amp

    return amp.matmul(a, b)


def _mha(p, prefix, x, kv, bias, causal, attend, mp_axis):
    """Projections + attention + output projection for one attention
    sublayer; prefix selects self ("W") or cross ("C") weights."""
    q = _mm(x, p[prefix + "Q"])
    k = _mm(kv, p[prefix + "K"])
    v = _mm(kv, p[prefix + "V"])
    out = _mm(attend(q, k, v, bias, causal), p[prefix + "O"])
    if mp_axis is not None:
        out = lax.psum(out, mp_axis)
    return out


def _ffn_sublayer(p, x, key, dropout, is_test, mp_axis, ln):
    h = jax.nn.relu(_mm(x, p["FFN1W"]) + p["FFN1B"])
    ff = _mm(h, p["FFN2W"])
    if mp_axis is not None:
        ff = lax.psum(ff, mp_axis)
    ff = ff + p["FFN2B"]
    return _layer_norm(x + _dropout(ff, key, dropout, is_test),
                       p[ln + "S"], p[ln + "B"])


def _encoder_layer(p: Dict[str, jnp.ndarray], x, bias, key, *, attend,
                   dropout, is_test, mp_axis):
    """One post-norm encoder layer.  p holds THIS layer's (possibly
    mp-local) param slices; x: [b, t, d]; bias: [b, 1, 1, t] or None.
    ``attend`` is the attention callable (full softmax / in-shard_map ring
    / GSPMD ring) — the single layer body serves every mesh layout."""
    k1, k2 = jax.random.split(key)
    attn = _mha(p, "W", x, x, bias, False, attend, mp_axis)
    x = _layer_norm(x + _dropout(attn, k1, dropout, is_test),
                    p["LN1S"], p["LN1B"])
    return _ffn_sublayer(p, x, k2, dropout, is_test, mp_axis, "LN2")


def _decoder_layer(p, x, enc, src_bias, key, *, attend, dropout, is_test,
                   mp_axis):
    """One post-norm decoder layer: causal self-attn, cross-attn, FFN."""
    k1, k2, k3 = jax.random.split(key, 3)
    sa = _mha(p, "W", x, x, None, True, attend, mp_axis)
    x = _layer_norm(x + _dropout(sa, k1, dropout, is_test),
                    p["LN1S"], p["LN1B"])
    ca = _mha(p, "C", x, enc, src_bias, False, attend, mp_axis)
    x = _layer_norm(x + _dropout(ca, k2, dropout, is_test),
                    p["LN2S"], p["LN2B"])
    return _ffn_sublayer(p, x, k3, dropout, is_test, mp_axis, "LN3")


def _scan_layers(layer_fn, params, carry_x, key, n_layer):
    """No-pp path: fold the stacked params with lax.scan (one compiled
    layer body).  GSPMD handles any mp/sp sharding of the scanned slices."""
    def body(x, inp):
        i, p = inp
        return layer_fn(p, x, jax.random.fold_in(key, i)), None

    x, _ = lax.scan(body, carry_x,
                    (jnp.arange(n_layer), params))
    return x


# ---------------------------------------------------------------------------
# GPipe schedule with a tree-valued carry
# ---------------------------------------------------------------------------


def _gpipe_tree_body(params, xs: Dict[str, jnp.ndarray], *, stage_fn,
                     pp_axis, n_micro, out_slot):
    """Runs inside shard_map.  xs: dict of LOCAL [n, ...] arrays that flow
    together through the pipeline (activations + context like enc_out /
    biases); stage_fn(params, tree, t) -> tree updates ``out_slot`` and
    passes the rest through.  Returns the final ``out_slot`` stream."""
    s_total = _axis_size(pp_axis)
    stage = lax.axis_index(pp_axis)
    n = next(iter(xs.values())).shape[0]
    if n % n_micro:
        raise ValueError(
            f"per-stage local batch {n} not divisible by n_micro {n_micro}")
    mb = n // n_micro
    xmb = {k: v.reshape((n_micro, mb) + v.shape[1:]) for k, v in xs.items()}
    perm = [(j, (j + 1) % s_total) for j in range(s_total)]

    def pick(t):
        return {k: lax.dynamic_index_in_dim(v, jnp.clip(t, 0, n_micro - 1),
                                            0, keepdims=False)
                for k, v in xmb.items()}

    def step(carry, t):
        cur, out_buf = carry
        recv = {k: lax.ppermute(v, pp_axis, perm) for k, v in cur.items()}
        mine = pick(t)
        my_in = {k: jnp.where(stage == 0, mine[k], recv[k]) for k in cur}
        out = stage_fn(params, my_in, t)
        o_idx = jnp.clip(t - (s_total - 1), 0, n_micro - 1)
        write = (stage == s_total - 1) & (t >= s_total - 1) \
            & (t - (s_total - 1) < n_micro)
        out_buf = jnp.where(
            write,
            lax.dynamic_update_index_in_dim(out_buf, out[out_slot], o_idx, 0),
            out_buf)
        return (out, out_buf), None

    cur0 = {k: _pvary(jnp.zeros_like(v[0]), (pp_axis,))
            for k, v in xmb.items()}
    buf0 = _pvary(jnp.zeros_like(xmb[out_slot]), (pp_axis,))
    (_, out_buf), _ = lax.scan(step, (cur0, buf0),
                               jnp.arange(n_micro + s_total - 1))
    out_buf = lax.psum(
        jnp.where(stage == s_total - 1, out_buf, jnp.zeros_like(out_buf)),
        pp_axis)
    return out_buf.reshape((n,) + xs[out_slot].shape[1:])


def _axis(mesh: Optional[Mesh], name: str) -> Optional[str]:
    if mesh is not None and name in mesh.axis_names \
            and mesh.shape[name] > 1:
        return name
    return None


def _xspec(mesh, dp, sp, ndim, seq_dim=1):
    dims = [dp] + [None] * (ndim - 1)
    dims[seq_dim] = sp
    return P(*dims)


def _pspecs(params, decoder, mesh, pp, mp):
    out = {}
    for slot, a in params.items():
        hint = dist_spec_for(slot, a.ndim, decoder)
        dims = []
        for d, ax in enumerate(hint):
            ok = (ax == "pp" and pp) or (ax == "mp" and mp)
            ok = ok and a.shape[d] % mesh.shape[ax] == 0
            dims.append(ax if ok else None)
        out[slot] = P(*dims)
    return out


def stack_apply(kind: str, x, enc, bias, params: Dict[str, jnp.ndarray],
                key, *, n_head: int, dropout: float, is_test: bool,
                n_micro: int, mesh: Optional[Mesh],
                recompute: bool = False, flash: bool = False):
    """Apply a stacked encoder ('enc') or decoder ('dec') to x.

    x: [N, T, D]; enc: [N, Ts, D] (decoder only); bias: [N, 1, 1, Tk] or
    None (encoder self / decoder cross key bias); params: stacked arrays
    keyed by ENCODER_SLOTS/DECODER_SLOTS; key: PRNG key (ignored when
    dropout=0 or is_test).

    recompute=True wraps each layer in ``jax.checkpoint``: the backward
    pass rematerializes activations layer by layer instead of saving them
    all, cutting peak memory from O(L*T*D) to O(T*D) + one extra forward —
    the standard long-sequence recipe (and exactly what the reference's
    memory_optimize pass tried to approximate with var reuse).
    """
    decoder = kind == "dec"
    n_layer = params["WQ"].shape[0]
    pp = _axis(mesh, "pp")
    mp = _axis(mesh, "mp")
    sp = _axis(mesh, "sp")
    dp = _axis(mesh, "dp")

    if pp is None:
        # scan path; mp (GSPMD) and sp (mesh-aware ring op) still apply
        attend = (_attend_in_shard_map(n_head, None, flash=flash)
                  if sp is None else _attend_gspmd_ring(n_head, mesh, sp))
        if decoder:
            def layer_fn(p, xx, kk):
                return _decoder_layer(p, xx, enc, bias, kk, attend=attend,
                                      dropout=dropout, is_test=is_test,
                                      mp_axis=None)
        else:
            def layer_fn(p, xx, kk):
                return _encoder_layer(p, xx, bias, kk, attend=attend,
                                      dropout=dropout, is_test=is_test,
                                      mp_axis=None)
        if recompute:
            layer_fn = jax.checkpoint(layer_fn)
        return _scan_layers(layer_fn, params, x, key, n_layer)

    # pp path: one shard_map over the whole mesh; stages hold L/S layers
    s = mesh.shape[pp]
    if n_layer % s != 0:
        raise ValueError(f"n_layer {n_layer} not divisible by pp size {s}")
    mp_size = mesh.shape[mp] if mp else 1
    if n_head % mp_size != 0:
        raise ValueError(f"n_head {n_head} not divisible by mp size {mp_size}")
    if mp_size > 1:
        # The pp layer body psums partial row-parallel outputs over mp, which
        # is only correct when every Megatron-sharded weight dim actually
        # splits mp_size ways; _pspecs degrading a dim to replicated here
        # would silently scale outputs by mp_size.
        table = DECODER_SLOTS if decoder else ENCODER_SLOTS
        for slot, mp_dim in table.items():
            if mp_dim is not None and params[slot].shape[mp_dim] % mp_size:
                raise ValueError(
                    f"param {slot} dim {mp_dim} (= "
                    f"{params[slot].shape[mp_dim]}) not divisible by mp size "
                    f"{mp_size}; d_model and d_inner must be divisible "
                    f"by mp")
    local_heads = n_head // mp_size

    xs = {"x": x}
    if decoder:
        xs["enc"] = enc
    if bias is not None:
        xs["bias"] = bias

    attend = _attend_in_shard_map(local_heads, sp, flash=flash)

    def one_layer(p_i, xx, tree, kk):
        if decoder:
            return _decoder_layer(
                p_i, xx, tree.get("enc"), tree.get("bias"), kk,
                attend=attend, dropout=dropout, is_test=is_test,
                mp_axis=mp)
        return _encoder_layer(
            p_i, xx, tree.get("bias"), kk, attend=attend,
            dropout=dropout, is_test=is_test, mp_axis=mp)

    if recompute:
        one_layer = jax.checkpoint(one_layer)

    def stage_fn(local_params, tree, t):
        # local_params leaves: [L/S, ...] (this stage's layers)
        xx = tree["x"]
        for i in range(n_layer // s):
            p_i = {k: v[i] for k, v in local_params.items()}
            kk = jax.random.fold_in(
                jax.random.fold_in(jax.random.fold_in(
                    key, lax.axis_index(pp)), t), i)
            if dp is not None:
                kk = jax.random.fold_in(kk, lax.axis_index(dp))
            xx = one_layer(p_i, xx, tree, kk)
        return {**tree, "x": xx}

    in_specs = (
        _pspecs(params, decoder, mesh, pp, mp),
        {k: (_xspec(mesh, dp, sp, v.ndim, seq_dim=3) if k == "bias"
             else _xspec(mesh, dp, sp, v.ndim)) for k, v in xs.items()},
    )
    out_spec = _xspec(mesh, dp, sp, x.ndim)
    fn = _shard_map(
        partial(_gpipe_tree_body, stage_fn=stage_fn, pp_axis=pp,
                n_micro=n_micro, out_slot="x"),
        mesh=mesh, in_specs=in_specs, out_specs=out_spec)
    return fn(params, xs)
