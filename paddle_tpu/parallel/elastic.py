"""Elastic multihost supervisor: launch the pod, watch it, restart it.

The reference stack survives trainer death because its Go master requeues
the dead trainer's tasks and its pserver checkpoints shards to etcd
(go/master/service.go:341 timeout requeue, go/pserver/service.go:346
checkpoint) — but nothing there *supervises* the processes themselves; k8s
does.  In the TPU build the pod is one gang-scheduled SPMD program: a
single dead or wedged worker stalls every collective, so the supervisor's
job is coarser and more total than the master's — detect the loss, tear
the WHOLE pod down, re-form `jax.distributed` and resume from the newest
complete sharded checkpoint (`multihost.save_sharded_serial`'s _SUCCESS
protocol).

Pieces:

 - heartbeat files: each worker writes ``<hb_dir>/hb_<rank>`` (atomic
   rename) from its training-step boundary — wired into ``Executor`` and
   ``multihost.heartbeat`` via the ``PADDLE_ELASTIC_HB_DIR`` env var this
   supervisor sets.  A worker that is alive-but-wedged (stalled
   collective) keeps its process but stops heartbeating, which is the only
   signal that distinguishes "slow" from "stuck".
 - :class:`ElasticSupervisor`: launches N local worker processes from a
   `tools.pod_launch.make_launch_plan` (same env contract as a real pod
   launch), polls exit codes + heartbeats, and on failure tears down,
   backs off (``master.Backoff``), and relaunches a fresh generation on a
   fresh coordinator port.  Restarts are bounded; every decision lands in
   a structured ``incidents.jsonl``.
 - fault handoff: ``PADDLE_FAULT_*`` flags (see ``fluid.fault``) are
   forwarded to generation 0 ONLY — a restarted generation must not
   replay the injected fault it just recovered from.
 - compile-cache handoff: every generation gets the same
   ``PADDLE_COMPILE_CACHE_DIR`` (``paddle_tpu.compile_cache``), so
   generation N+1 skips XLA compilation of the exact programs generation
   N was running when it died — restart latency drops from
   checkpoint-load + full-recompile to checkpoint-load alone.
 - observability handoff: every generation gets the same
   ``PADDLE_OBSERVE_DIR`` (``paddle_tpu.observe``), the supervisor's own
   decisions are mirrored into the same run-event stream, and at end of
   run the fleet aggregator writes ``<observe_dir>/fleet.json`` — one
   snapshot summing every worker's latest-generation counters.

CLI::

    python -m paddle_tpu.parallel.elastic --nproc 4 \
        --entry "python train.py" --workdir /tmp/run --max-restarts 3
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from .master import Backoff

__all__ = [
    "write_heartbeat", "read_heartbeat", "heartbeat_path",
    "host_loss_markers", "viable_mesh",
    "IncidentLog", "ElasticSupervisor",
]

#: marker files the permanent-host-loss fault drops into the heartbeat
#: dir (``host_lost_g<gen>_r<rank>``, written by ``fluid.fault`` at the
#: moment the doomed rank exits).  Unlike heartbeats they are never
#: cleaned between generations: each one is a host that will NOT come
#: back, and the supervisor's survivor census subtracts them all.
HOST_LOSS_PREFIX = "host_lost_"


def host_loss_markers(hb_dir: str) -> list:
    """All permanent-host-loss markers under ``hb_dir`` (sorted names)."""
    try:
        return sorted(n for n in os.listdir(hb_dir)
                      if n.startswith(HOST_LOSS_PREFIX))
    except OSError:
        return []


def viable_mesh(ladder: List[str], survivors: int,
                devices_per_host: int = 1) -> Optional[tuple]:
    """The largest ladder entry the surviving fleet can run: first spec
    (ladder order = preference order, largest first) whose device
    requirement fits on ``survivors`` hosts AND whose dp extent tiles
    with the process count it implies (``data.sharding.shard_spec`` —
    a mesh the data plane cannot feed is not viable).  Returns
    ``(spec, nproc)`` or ``None`` when nothing on the ladder fits."""
    from ..data.sharding import shard_spec
    from .mesh import parse_mesh_spec

    devices_per_host = max(1, int(devices_per_host))
    for spec in ladder:
        try:
            axes = parse_mesh_spec(spec)
        except ValueError:
            continue  # a typo'd rung must not wedge the downgrade
        need = 1
        for extent in axes.values():
            need *= int(extent)
        nproc = max(1, -(-need // devices_per_host))  # ceil division
        if nproc > max(0, int(survivors)):
            continue
        try:
            shard_spec(spec, host_rank=0, num_hosts=nproc)
        except ValueError:
            continue
        return spec, nproc
    return None


# ---------------------------------------------------------------------------
# Heartbeat file protocol (worker side)
# ---------------------------------------------------------------------------


def heartbeat_path(hb_dir: str, rank: int) -> str:
    return os.path.join(hb_dir, f"hb_{int(rank)}")


def write_heartbeat(hb_dir: str, step: Optional[int] = None,
                    rank: Optional[int] = None,
                    commit_step: Optional[int] = None) -> None:
    """Atomically publish this worker's liveness (tmp + rename, so the
    supervisor never reads a torn write).  Cheap enough for every step:
    one small file per rank, rewritten in place.

    ``commit_step`` is the last CHECKPOINT-COMMITTED step (defaults to
    the process-wide ``observe.note_commit_step`` context, stamped at
    every _SUCCESS write) — so the heartbeat a dead worker leaves behind
    prices the restart: ``step - commit_step`` is the work the fleet
    re-trains, and the supervisor copies both into the worker_exit /
    heartbeat_timeout incident (progress-at-death)."""
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if commit_step is None:
        try:
            from ..observe import current_commit_step

            commit_step = current_commit_step()
        except Exception:
            commit_step = None
    try:
        from ..fluid import fault as _fault
        from ..fluid.retry import retry_io

        os.makedirs(hb_dir, exist_ok=True)
        path = heartbeat_path(hb_dir, rank)
        tmp = f"{path}.tmp.{os.getpid()}"

        def _publish():
            _fault.io_error(path, "write")
            with open(tmp, "w") as f:
                json.dump({"ts": time.time(), "step": step,
                           "rank": int(rank), "pid": os.getpid(),
                           "commit_step": commit_step}, f)
            os.replace(tmp, path)

        # bounded retry first — a missed beat from a storage blip looks
        # exactly like a dead worker to the supervisor
        retry_io(_publish, what="census.heartbeat")
    except OSError:
        # liveness reporting must never kill the training it reports on
        pass


def read_heartbeat(hb_dir: str, rank: int) -> Optional[dict]:
    try:
        with open(heartbeat_path(hb_dir, rank)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Incident log
# ---------------------------------------------------------------------------


class IncidentLog:
    """Append-only JSON-lines incident record (the etcd-event analogue of
    the reference master's state transitions): one line per supervisor
    decision, machine-parseable for postmortems.

    Since ISSUE 5 this file is a *view* of the unified run-event stream:
    when a ``mirror`` (an :class:`paddle_tpu.observe.events.EventLog`) is
    attached, every incident also lands — fully stamped — in the observe
    dir, where ``python -m paddle_tpu.observe tail`` correlates it with
    guardian trips and compile-cache hits by (host, generation, step)."""

    def __init__(self, path: str, mirror=None):
        self.path = path
        self.mirror = mirror
        self.events: List[dict] = []
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def log(self, event: str, **fields) -> dict:
        rec = {"ts": time.time(), "event": event}
        rec.update(fields)
        self.events.append(rec)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        if self.mirror is not None:
            try:
                self.mirror.emit(event, **fields)
            except Exception:
                pass  # the mirror must never block the primary record
        return rec


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _tail(path: str, nbytes: int = 800) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - nbytes))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return ""


class ElasticSupervisor:
    """Supervise an N-process local pod with checkpoint auto-resume.

    ``entry`` is the per-worker command line; workers receive the standard
    PADDLE_* multihost env (fresh coordinator port per generation, so
    ``jax.distributed`` re-forms cleanly after a teardown) plus
    ``PADDLE_ELASTIC_HB_DIR`` / ``PADDLE_ELASTIC_GENERATION``.  Recovery
    itself is the WORKER's job on startup — restore from the newest
    complete sharded checkpoint (``multihost.load_sharded_latest``) and
    resume from its meta step; the supervisor only guarantees the pod gets
    that chance, boundedly many times.
    """

    def __init__(self, entry: str, nproc: int, workdir: str, *,
                 hb_timeout: float = 120.0, poll_interval: float = 0.25,
                 max_restarts: int = 3, backoff: Optional[Backoff] = None,
                 devices_per_host: Optional[int] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 fault_env: Optional[Dict[str, str]] = None,
                 deadline: Optional[float] = None,
                 compile_cache_dir: Optional[str] = None,
                 observe_dir: Optional[str] = None,
                 mesh_ladder: Optional[str] = None):
        if nproc < 1:
            raise ValueError("nproc must be >= 1")
        self.entry = entry
        self.nproc = int(nproc)
        self.initial_nproc = int(nproc)
        self.workdir = os.path.abspath(workdir)
        self.hb_timeout = float(hb_timeout)
        self.poll_interval = float(poll_interval)
        self.max_restarts = int(max_restarts)
        # jittered by default (ISSUE 18): after a fleet-wide kill every
        # pod's supervisor would otherwise re-register on the same
        # exponential instants — the thundering herd the jitter smears
        self.backoff = backoff or Backoff(base=0.5, factor=2.0,
                                          max_delay=30.0, jitter=0.25)
        self.devices_per_host = devices_per_host
        self.extra_env = dict(extra_env or {})
        self.fault_env = dict(fault_env or {})
        self.deadline = deadline
        self.hb_dir = os.path.join(self.workdir, "heartbeats")
        # persistent compile cache shared by ALL generations: priority is
        # explicit arg > inherited env > a per-run default under workdir
        self.compile_cache_dir = os.path.abspath(
            compile_cache_dir
            or os.environ.get("PADDLE_COMPILE_CACHE_DIR", "").strip()
            or os.path.join(self.workdir, "compile_cache"))
        # unified observability dir shared by every generation: workers
        # write per-(host, rank, gen) event logs + metric snapshots there,
        # and the supervisor's own decisions join the same stream (the
        # incidents.jsonl below stays as the legacy flat view)
        self.observe_dir = os.path.abspath(
            observe_dir
            or os.environ.get("PADDLE_OBSERVE_DIR", "").strip()
            or os.path.join(self.workdir, "observe"))
        from ..observe import trace as _trace
        from ..observe.events import EventLog, host_name

        os.makedirs(self.observe_dir, exist_ok=True)
        self._observe_log = EventLog(
            os.path.join(self.observe_dir,
                         f"events-{host_name()}-supervisor.jsonl"),
            source="supervisor")
        self.incidents = IncidentLog(
            os.path.join(self.workdir, "incidents.jsonl"),
            mirror=self._observe_log)
        # ONE trace id for the whole supervised run (adopted from an
        # inherited PADDLE_TRACEPARENT when this supervisor is itself a
        # child): each generation gets a span under it and workers
        # inherit `trace_id + generation span` via PADDLE_TRACEPARENT, so
        # kill-and-resume stitches into one cross-process trace tree
        self.trace_id = _trace.trace_context()[0]
        self._gen_span: Optional[dict] = None
        # in-flight straggler scan over the shared observe dir (ISSUE 13):
        # every scan interval the supervisor re-derives cross-rank step
        # skew from the workers' own window spans and emits one
        # straggler.detected incident per (generation, rank) — the
        # autoscaler-facing signal next to slo.breach in the same stream
        from ..fluid import envcontract as _ec

        self.goodput_scan_s = float(_ec.get("PADDLE_GOODPUT_SCAN_S"))
        self.straggler_factor = float(
            _ec.get("PADDLE_GOODPUT_STRAGGLER_FACTOR"))
        self.straggler_min_samples = int(
            _ec.get("PADDLE_GOODPUT_MIN_SAMPLES"))
        self._stragglers_flagged: set = set()
        self._last_scan = 0.0
        # mesh downgrade ladder (ISSUE 14): after a permanent host loss
        # the supervisor relaunches on the largest rung the survivor
        # census can run (smaller fleet + PADDLE_TPU_MESH rewritten for
        # every next-generation worker) instead of burning the restart
        # budget against a barrier the dead host will never reach.  The
        # reshard-on-load path (parallel.reshard) makes the downgraded
        # fleet able to CONSUME the bigger fleet's checkpoint.
        ladder_raw = (mesh_ladder
                      if mesh_ladder is not None
                      else _ec.get("PADDLE_TPU_MESH_LADDER")) or ""
        self.mesh_ladder = [s.strip() for s in ladder_raw.split(";")
                            if s.strip()]
        self.mesh_spec: Optional[str] = (
            self.extra_env.get("PADDLE_TPU_MESH")
            or _ec.get("PADDLE_TPU_MESH")
            or (self.mesh_ladder[0] if self.mesh_ladder else None))
        self._unviable = False

    # -- public --
    def run(self) -> dict:
        """Run to completion.  Returns a summary dict::

            {"status": "finished" | "failed", "generations": g,
             "incidents": [...], "incident_log": path}
        """
        start = time.time()
        generations = 0
        for gen in range(self.max_restarts + 1):
            if gen:
                delay = self.backoff.delay(gen - 1)
                self.incidents.log("backoff", generation=gen, delay_s=delay)
                time.sleep(delay)
            generations = gen + 1
            procs, logs = self._launch(gen)
            verdict = self._watch(procs, logs, gen, start)
            self._teardown(procs, gen)
            self._end_generation(gen, verdict)
            for lf in logs:
                lf.close()
            if verdict == "finished":
                self.incidents.log("finished", generation=gen)
                return self._summary("finished", generations)
            if verdict == "deadline":
                break  # no point relaunching into an expired budget
            if gen < self.max_restarts:
                self._maybe_downgrade(gen)
                if self._unviable:
                    break  # nothing on the ladder fits the survivors
        self.incidents.log("restart_budget_exhausted",
                           max_restarts=self.max_restarts)
        return self._summary("failed", generations)

    def _maybe_downgrade(self, gen: int) -> None:
        """Survivor census + mesh-ladder pick before relaunching.

        Heartbeat-dir ``host_lost_*`` markers (dropped by the
        PADDLE_FAULT_HOST_LOSS oracle; in production, by a node-death
        notifier) are hosts that will NOT rejoin.  With none, the
        relaunch keeps its size and mesh (the classic kill-and-resume
        path).  With losses and a ladder, the next generation runs the
        largest viable rung: fewer workers, ``PADDLE_TPU_MESH``
        rewritten, and one ``mesh.downgrade`` incident the goodput
        ledger prices the transition from.  No viable rung marks the
        run unviable (summary: failed) — restarting a fleet that cannot
        form is the exact budget-burn this exists to stop."""
        lost = host_loss_markers(self.hb_dir)
        if not lost:
            return
        survivors = max(0, self.initial_nproc - len(lost))
        if survivors >= self.nproc:
            return  # losses already absorbed by an earlier downgrade
        if not self.mesh_ladder:
            # no ladder: keep legacy behavior (same-size relaunch) but
            # leave the census in the incident trail for the postmortem
            self.incidents.log("host_loss", generation=gen,
                               survivors=survivors, lost=lost,
                               ladder=[])
            return
        pick = viable_mesh(self.mesh_ladder, survivors,
                           self.devices_per_host or 1)
        if pick is None:
            self._unviable = True
            self.incidents.log("mesh.unviable", generation=gen,
                               survivors=survivors, lost=lost,
                               ladder=self.mesh_ladder)
            return
        spec, nproc = pick
        if spec == self.mesh_spec and nproc == self.nproc:
            return
        self.incidents.log(
            "mesh.downgrade", generation=gen + 1,
            from_mesh=self.mesh_spec, to_mesh=spec,
            from_nproc=self.nproc, to_nproc=nproc,
            survivors=survivors, lost=lost)
        self.mesh_spec = spec
        self.nproc = nproc

    # -- internals --
    def _launch(self, gen: int):
        try:
            from tools.pod_launch import make_launch_plan
        except ImportError:  # repo checkout not on sys.path (installed pkg)
            repo = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            sys.path.insert(0, repo)
            from tools.pod_launch import make_launch_plan

        os.makedirs(self.hb_dir, exist_ok=True)
        # stale liveness must not mask death — clear up to the LARGEST
        # fleet this run ever launched (a downgraded generation must not
        # read a dead bigger fleet's heartbeats); host_lost_* markers
        # stay, they are the permanent-loss census
        for rank in range(self.initial_nproc):
            try:
                os.remove(heartbeat_path(self.hb_dir, rank))
            except OSError:
                pass
        os.makedirs(self.compile_cache_dir, exist_ok=True)
        from ..observe import trace as _trace

        # open this generation's span (closed by _end_generation with the
        # verdict); workers parent their root spans to it via the
        # traceparent handoff below
        self._gen_span = {"span_id": _trace.new_span_id(),
                          "t0": time.time(), "generation": gen}
        env = {"PADDLE_ELASTIC_HB_DIR": self.hb_dir,
               "PADDLE_ELASTIC_GENERATION": str(gen),
               # workers append their own decisions (guardian numerics
               # trips — fluid.guardian) next to the supervisor's: one
               # incident stream per pod, small O_APPEND json lines
               "PADDLE_ELASTIC_INCIDENTS": self.incidents.path,
               # generation N+1 reuses generation N's compiled programs
               "PADDLE_COMPILE_CACHE_DIR": self.compile_cache_dir,
               # every generation's events + metric snapshots land in one
               # shared observe dir (per-(host, rank, gen) files; the
               # fleet aggregator joins them at end of run)
               "PADDLE_OBSERVE_DIR": self.observe_dir,
               # trace stitching: every worker's spans join THIS run's
               # trace, parented to this generation's span
               "PADDLE_TRACEPARENT": _trace.format_traceparent(
                   self.trace_id, self._gen_span["span_id"])}
        env.update(self.extra_env)
        if self.mesh_spec:
            # the supervisor owns the topology per generation: a
            # downgraded fleet's workers see the LADDER-PICKED mesh, not
            # the one the launch env froze in
            env["PADDLE_TPU_MESH"] = self.mesh_spec
        if gen == 0:
            env.update(self.fault_env)
        port = _free_port()
        plan = make_launch_plan(["127.0.0.1"] * self.nproc, self.entry,
                                port=port,
                                devices_per_host=self.devices_per_host,
                                extra_env=env)
        procs, logs = [], []
        for p in plan:
            wenv = {k: v for k, v in os.environ.items()
                    if not (gen and k.startswith("PADDLE_FAULT_"))}
            wenv.update(p["env"])
            log_path = os.path.join(
                self.workdir, f"worker_g{gen}_r{p['trainer_id']}.log")
            lf = open(log_path, "ab")
            procs.append(subprocess.Popen(
                p["cmd"], env=wenv, stdout=lf, stderr=subprocess.STDOUT,
                cwd=self.workdir))
            logs.append(lf)
        self.incidents.log("generation_start", generation=gen, port=port,
                           nproc=self.nproc, mesh=self.mesh_spec,
                           compile_cache_dir=self.compile_cache_dir,
                           fault_env=sorted(self.fault_env) if gen == 0
                           else [])
        return procs, logs

    def _watch(self, procs, logs, gen: int, start: float) -> str:
        """Until success/failure: poll exits and heartbeats.
        Returns 'finished' | 'failed' | 'deadline'."""
        gen_start = time.time()
        while True:
            rcs = [p.poll() for p in procs]
            if all(rc == 0 for rc in rcs):
                return "finished"
            bad = [(r, rc) for r, rc in enumerate(rcs)
                   if rc is not None and rc != 0]
            if bad:
                rank, rc = bad[0]
                # progress-at-death from the rank's last heartbeat: the
                # step it reached vs the step its newest _SUCCESS covers —
                # what the restart re-trains (the goodput ledger prices
                # lost_steps from exactly this record)
                hb = read_heartbeat(self.hb_dir, rank) or {}
                self.incidents.log(
                    "worker_exit", generation=gen, rank=rank, exit_code=rc,
                    last_step=hb.get("step"),
                    commit_step=hb.get("commit_step"),
                    log_tail=_tail(logs[rank].name))
                return "failed"
            now = time.time()
            if self.deadline is not None and now - start > self.deadline:
                self.incidents.log("deadline_exceeded", generation=gen,
                                   deadline_s=self.deadline)
                return "deadline"
            for rank, rc in enumerate(rcs):
                if rc == 0:
                    continue  # exited clean; its silence is not a wedge
                hb = read_heartbeat(self.hb_dir, rank)
                last = hb["ts"] if hb else gen_start
                if now - last > self.hb_timeout:
                    self.incidents.log(
                        "heartbeat_timeout", generation=gen, rank=rank,
                        stale_s=round(now - last, 3),
                        last_step=hb.get("step") if hb else None,
                        commit_step=hb.get("commit_step") if hb else None,
                        log_tail=_tail(logs[rank].name))
                    return "failed"
            if self.goodput_scan_s > 0 \
                    and now - self._last_scan >= self.goodput_scan_s:
                self._last_scan = now
                self._scan_stragglers(gen)
            time.sleep(self.poll_interval)

    def _scan_stragglers(self, gen: int) -> None:
        """One skew pass over the fleet's window spans; each flagged rank
        gets ONE ``straggler.detected`` incident per generation (mirrored
        into the run-event stream next to the watchdog's slo.breach
        records).  Never fails the supervisor."""
        try:
            from ..observe.fleet import fleet_events, rank_skew

            skew = rank_skew(fleet_events(self.observe_dir),
                             factor=self.straggler_factor,
                             min_samples=self.straggler_min_samples,
                             gen=gen)
        except Exception:
            return
        for s in skew["stragglers"]:
            key = (gen, s["worker"])
            if key in self._stragglers_flagged:
                continue
            self._stragglers_flagged.add(key)
            self.incidents.log(
                "straggler.detected", generation=gen, rank=s["rank"],
                host=s["host"], median_step_s=s["median_step_s"],
                baseline_step_s=s["baseline_step_s"], ratio=s["ratio"],
                n=s["n"], factor=self.straggler_factor)

    def _end_generation(self, gen: int, verdict: str) -> None:
        """Close the generation span: one ``elastic.generation`` duration
        record per generation, all sharing the run trace id — the rows a
        merged trace view stitches worker spans under.  A final straggler
        scan runs first so a generation shorter than the scan interval
        still gets its skew verdict."""
        if self.goodput_scan_s > 0:
            self._scan_stragglers(gen)
        sp = self._gen_span
        if sp is None:
            return
        self._gen_span = None
        try:
            now = time.time()
            self._observe_log.emit(
                "elastic.generation", ts=now,
                dur_s=round(now - sp["t0"], 6),
                trace_id=self.trace_id, span_id=sp["span_id"],
                parent_span=None, tid=0, generation=gen, verdict=verdict)
        except Exception:
            pass  # span bookkeeping must never fail the supervisor

    def _teardown(self, procs, gen: int) -> None:
        """Kill the whole pod: one lost worker wedges every collective, so
        partial survival has no value — the generation is the failure
        domain (re-forming jax.distributed needs a full restart anyway)."""
        alive = [p for p in procs if p.poll() is None]
        for p in alive:
            p.terminate()
        grace_until = time.time() + 5.0
        for p in alive:
            try:
                p.wait(timeout=max(0.0, grace_until - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        for p in alive:
            try:
                p.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        if alive:
            self.incidents.log("teardown", generation=gen,
                               killed=len(alive))

    def _summary(self, status: str, generations: int) -> dict:
        from ..observe import fleet as _fleet

        # one aggregated view of every generation's metric snapshots
        # (<observe_dir>/fleet.json); never fails the summary
        try:
            fleet_path = _fleet.write_fleet(self.observe_dir)
        except Exception:
            fleet_path = None
        return {"status": status, "generations": generations,
                "incidents": list(self.incidents.events),
                "incident_log": self.incidents.path,
                "observe_dir": self.observe_dir,
                "fleet_snapshot": fleet_path,
                "trace_id": self.trace_id}


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Supervise an N-process multihost training pod with "
                    "heartbeat monitoring and checkpoint auto-resume.")
    ap.add_argument("--entry", required=True,
                    help="per-worker command line")
    ap.add_argument("--nproc", type=int, required=True)
    ap.add_argument("--workdir", required=True,
                    help="heartbeats, incidents.jsonl and worker logs")
    ap.add_argument("--hb-timeout", type=float, default=120.0)
    ap.add_argument("--poll-interval", type=float, default=0.25)
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--deadline", type=float, default=None,
                    help="overall wall-clock budget in seconds")
    ap.add_argument("--devices-per-host", type=int, default=None)
    ap.add_argument("--compile-cache-dir", default=None,
                    help="persistent compile cache shared by all "
                         "generations (default: <workdir>/compile_cache)")
    ap.add_argument("--observe-dir", default=None,
                    help="unified observability dir shared by all "
                         "generations (default: <workdir>/observe)")
    ap.add_argument("--mesh-ladder", default=None,
                    help="semicolon-ordered downgrade ladder, largest "
                         "first (e.g. 'dp4;dp2;dp1'); default "
                         "PADDLE_TPU_MESH_LADDER")
    ap.add_argument("--env", action="append", default=[], metavar="K=V")
    args = ap.parse_args(argv)
    extra = {}
    for kv in args.env:
        if "=" not in kv:
            ap.error(f"--env wants K=V, got {kv!r}")
        k, v = kv.split("=", 1)
        extra[k] = v
    sup = ElasticSupervisor(
        args.entry, args.nproc, args.workdir, hb_timeout=args.hb_timeout,
        poll_interval=args.poll_interval, max_restarts=args.max_restarts,
        deadline=args.deadline, devices_per_host=args.devices_per_host,
        extra_env=extra or None,
        compile_cache_dir=args.compile_cache_dir,
        observe_dir=args.observe_dir,
        mesh_ladder=args.mesh_ladder)
    result = sup.run()
    print(json.dumps(result))
    return 0 if result["status"] == "finished" else 1


if __name__ == "__main__":
    sys.exit(main())
