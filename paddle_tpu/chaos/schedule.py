"""Seeded multi-fault chaos schedules (ISSUE 18 tentpole).

One integer (``--seed`` / ``PADDLE_CHAOS_SEED``) deterministically expands
into a K-fault plan for one drill scenario: which faults from the catalog,
which knob values, which rank/step each fires at.  Replays are exact —
the same (scenario, seed, faults) triple always yields the byte-identical
canonical plan JSON, so a red drill from CI reproduces locally from the
one integer in its report.

The catalog is NOT a second fault list: every spec points at knobs
declared in :mod:`paddle_tpu.fluid.envcontract` (subsystem ``fault``),
and :func:`uncovered_knobs` computes the difference — a newly declared
fault knob that no :class:`FaultSpec` covers fails the chaos test suite
until it is either cataloged (samplable) or explicitly excluded with a
rationale (``scenarios=()``).  Auto-discovery keeps the chaos engine
honest as the fault family grows.

Trajectory-altering faults (NaN/grad-Inf/loss-spike injection, committed
checkpoint poisoning, permanent host loss) are cataloged but never
sampled: they change the converged state or the fleet shape BY DESIGN,
so the drill's strongest invariant — bitwise resume vs. an uninterrupted
reference — would be vacuously unfalsifiable with them armed.  They keep
their own dedicated oracles (guardian / canary / mesh-ladder tests).
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..fluid import envcontract as _ec

__all__ = [
    "FaultSpec", "CATALOG", "SCENARIOS", "EXEMPT_KNOBS",
    "ChaosSchedule", "canonical_json", "uncovered_knobs",
    "generate_fault_table",
]

#: the four drill scenarios the runner implements
SCENARIOS = ("train", "elastic", "serve", "fleet")

#: the drills' checkpoint cadence (the runner imports this): the sampler
#: needs it to keep composed plans RECOVERABLE — see the shard_corrupt
#: constraint in :meth:`ChaosSchedule.plan`
CKPT_STEP_INTERVAL = 3

#: declared PADDLE_FAULT_* names that are scoping/flavor, not faults:
#: RANK scopes other faults to one rank, MODE picks the crash flavor, and
#: the bare prefix entry covers dynamic suffixes for repo_lint
EXEMPT_KNOBS = frozenset({
    "PADDLE_FAULT_", "PADDLE_FAULT_RANK", "PADDLE_FAULT_MODE",
})


@dataclass(frozen=True)
class FaultSpec:
    """One samplable (or explicitly excluded) fault family.

    ``sample(rng, ctx)`` returns the env assignment for one drawn
    instance; ``ctx`` carries the drill shape (``nproc``, ``steps``).
    ``interrupting`` marks faults that end a generation (kill,
    checkpoint crash) — train/elastic plans guarantee at least one so
    every drill actually exercises restart+resume.  ``scenarios=()``
    with a ``rationale`` documents a deliberate exclusion."""

    key: str
    knobs: Tuple[str, ...]
    scenarios: Tuple[str, ...]
    interrupting: bool = False
    rationale: str = ""
    sample: Optional[Callable[[random.Random, dict], Dict[str, str]]] = \
        field(default=None, compare=False)


def _mid_third_step(rng: random.Random, ctx: dict) -> int:
    steps = max(3, int(ctx.get("steps", 12)))
    return rng.randrange(steps // 3, 2 * steps // 3 + 1)


CATALOG: List[FaultSpec] = [
    # -- interrupting: end generation 0, force a real resume -------------
    FaultSpec(
        "kill", ("PADDLE_FAULT_KILL_STEP",), ("train", "elastic"),
        interrupting=True,
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_KILL_STEP": str(_mid_third_step(rng, ctx))}),
    FaultSpec(
        "ckpt_crash", ("PADDLE_FAULT_CKPT_CRASH",), ("train", "elastic"),
        interrupting=True,
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_CKPT_CRASH": rng.choice(["before", "after"])}),
    # -- degradations that must NOT alter the committed trajectory ------
    FaultSpec(
        "io_delay", ("PADDLE_FAULT_IO_DELAY_MS",), ("train", "elastic"),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_IO_DELAY_MS": str(rng.choice([1, 2, 5]))}),
    FaultSpec(
        "io_error",
        ("PADDLE_FAULT_IO_ERROR_RATE", "PADDLE_FAULT_IO_ERROR_SEED"),
        ("train", "elastic", "serve", "fleet"),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_IO_ERROR_RATE":
                str(round(rng.uniform(0.4, 0.9), 3)),
            "PADDLE_FAULT_IO_ERROR_SEED":
                str(rng.randrange(1, 1 << 16))}),
    FaultSpec(
        "data_stall",
        ("PADDLE_FAULT_DATA_STALL_MS", "PADDLE_FAULT_DATA_STALL_AT"),
        ("train", "elastic"),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_DATA_STALL_MS": str(rng.choice([20, 40, 60])),
            "PADDLE_FAULT_DATA_STALL_AT":
                str(rng.randrange(0, max(1, int(ctx.get("steps", 12)))))}),
    FaultSpec(
        "cache_corrupt", ("PADDLE_FAULT_CACHE_CORRUPT",), ("train",),
        sample=lambda rng, ctx: {"PADDLE_FAULT_CACHE_CORRUPT": "1"}),
    FaultSpec(
        "shard_corrupt", ("PADDLE_FAULT_SHARD_CORRUPT",), ("elastic",),
        sample=lambda rng, ctx: {"PADDLE_FAULT_SHARD_CORRUPT": "1"}),
    FaultSpec(
        # kept well below the drill supervisor's heartbeat timeout: the
        # stall models a wedge the run RIDES OUT, not a restart trigger
        "barrier_stall", ("PADDLE_FAULT_BARRIER_STALL",), ("elastic",),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_BARRIER_STALL":
                str(round(rng.uniform(0.05, 0.2), 3))}),
    FaultSpec(
        "straggler",
        ("PADDLE_FAULT_STRAGGLER_RANK", "PADDLE_FAULT_STRAGGLER_MS"),
        ("elastic",),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_STRAGGLER_RANK":
                str(rng.randrange(max(1, int(ctx.get("nproc", 2))))),
            "PADDLE_FAULT_STRAGGLER_MS": str(rng.choice([5, 10, 15]))}),
    FaultSpec(
        "mem_pressure",
        ("PADDLE_FAULT_MEM_PRESSURE", "PADDLE_FAULT_MEM_PRESSURE_AT"),
        ("elastic",),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_MEM_PRESSURE": str(rng.choice([1, 2, 4])),
            "PADDLE_FAULT_MEM_PRESSURE_AT": str(rng.randrange(2, 6))}),
    # -- serving-path faults ---------------------------------------------
    FaultSpec(
        "serve_delay", ("PADDLE_FAULT_SERVE_DELAY_MS",), ("serve",),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_SERVE_DELAY_MS": str(rng.choice([1, 2, 5]))}),
    FaultSpec(
        "serve_fail", ("PADDLE_FAULT_SERVE_FAIL_EVERY",), ("serve",),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_SERVE_FAIL_EVERY": str(rng.randrange(3, 6))}),
    FaultSpec(
        "decode_stall", ("PADDLE_FAULT_DECODE_STALL_MS",), ("serve",),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_DECODE_STALL_MS": str(rng.choice([1, 2, 4]))}),
    FaultSpec(
        "replica_kill", ("PADDLE_FAULT_REPLICA_KILL_AFTER",), ("fleet",),
        sample=lambda rng, ctx: {
            "PADDLE_FAULT_REPLICA_KILL_AFTER": str(rng.randrange(2, 7))}),
    # -- cataloged but never sampled: each breaks an invariant BY DESIGN -
    FaultSpec(
        "nan", ("PADDLE_FAULT_NAN_VAR", "PADDLE_FAULT_NAN_STEP"), (),
        rationale="poisons the training state itself — bitwise-resume "
                  "vs. the clean reference is unfalsifiable (guardian "
                  "NaN-policy tests own this oracle)"),
    FaultSpec(
        "grad_inf",
        ("PADDLE_FAULT_GRAD_INF_STEP", "PADDLE_FAULT_GRAD_INF_VALUE"), (),
        rationale="alters the gradient trajectory in-graph; owned by the "
                  "guardian sentinel / loss-scaler overflow tests"),
    FaultSpec(
        "loss_spike",
        ("PADDLE_FAULT_LOSS_SPIKE_STEP", "PADDLE_FAULT_LOSS_SPIKE_FACTOR"),
        (),
        rationale="rewrites the observed loss; owned by the guardian "
                  "spike-detector tests"),
    FaultSpec(
        "ckpt_poison", ("PADDLE_FAULT_CKPT_POISON_SERIAL",), (),
        rationale="commits a structurally valid but NaN checkpoint — "
                  "resume from it CANNOT match the reference; owned by "
                  "the serving canary auto-rollback tests"),
    FaultSpec(
        "kv_page_leak", ("PADDLE_FAULT_KV_PAGE_LEAK",), (),
        rationale="skips page frees BY DESIGN, so the paged-serving "
                  "invariant the drills would judge (kvpool.pages_free "
                  "returns to its initial level after drain) is violated "
                  "on purpose; owned by the kvpool leak-oracle tests "
                  "(tests/test_kvpool.py)"),
    FaultSpec(
        "spec_draft_poison", ("PADDLE_FAULT_SPEC_DRAFT_POISON",), (),
        rationale="only meaningful with PADDLE_SERVE_SPEC=k>0 armed; the "
                  "drill scenarios run speculation off, so the knob "
                  "would be a silent no-op there — owned by the "
                  "acceptance-collapse oracle in tests/test_specdec.py "
                  "(fallback fires, output stays bitwise)"),
    FaultSpec(
        "host_loss",
        ("PADDLE_FAULT_HOST_LOSS_RANK", "PADDLE_FAULT_HOST_LOSS_AT_STEP"),
        (),
        rationale="permanently shrinks the fleet, so the resumed "
                  "generation runs a different data sharding than the "
                  "reference; owned by the mesh-ladder downgrade tests"),
]


def _catalog_by_key() -> Dict[str, FaultSpec]:
    return {s.key: s for s in CATALOG}


def uncovered_knobs() -> List[str]:
    """Declared fault knobs no catalog entry covers (must be empty —
    the auto-discovery contract enforced by tests/test_chaos.py)."""
    covered = set()
    for spec in CATALOG:
        covered.update(spec.knobs)
    return sorted(
        name for name, knob in _ec.REGISTRY.items()
        if knob.subsystem == "fault"
        and name not in EXEMPT_KNOBS
        and name not in covered)


def canonical_json(plan: dict) -> str:
    """The byte-stable rendering of a plan — what determinism is judged
    on (and what ``plan.json`` persists)."""
    return json.dumps(plan, sort_keys=True, separators=(",", ":"))


class ChaosSchedule:
    """Deterministic K-fault plan sampler for one scenario.

    The RNG is seeded from ``sha256(scenario | seed)`` (NOT python's
    randomized ``hash``), so the same integer replays the same plan in
    any process, any python version."""

    def __init__(self, scenario: str, seed: int, faults: int = 2,
                 nproc: int = 2, steps: int = 12):
        if scenario not in SCENARIOS:
            raise ValueError(
                f"scenario must be one of {SCENARIOS}, got {scenario!r}")
        if faults < 1:
            raise ValueError("faults must be >= 1")
        self.scenario = scenario
        self.seed = int(seed)
        self.faults = int(faults)
        self.nproc = int(nproc)
        self.steps = int(steps)
        digest = hashlib.sha256(
            f"{scenario}|{self.seed}".encode()).digest()
        self.stable_seed = int.from_bytes(digest[:8], "big")

    def plan(self) -> dict:
        rng = random.Random(self.stable_seed)
        ctx = {"nproc": self.nproc, "steps": self.steps}
        eligible = sorted((s for s in CATALOG
                           if self.scenario in s.scenarios),
                          key=lambda s: s.key)
        if not eligible:
            raise ValueError(f"no faults cataloged for {self.scenario!r}")
        k = min(self.faults, len(eligible))
        chosen: List[FaultSpec] = []
        if self.scenario in ("train", "elastic"):
            # a drill that never interrupts never exercises resume:
            # guarantee one generation-ending fault in every plan
            interrupting = [s for s in eligible if s.interrupting]
            chosen.append(rng.choice(interrupting))
            pool = [s for s in eligible if s.key != chosen[0].key]
            if chosen[0].key == "ckpt_crash":
                # shard_corrupt tears the FIRST serial's data_state blob
                # (committed with _SUCCESS when the crash is 'after'):
                # that serial would be the only complete one, restore
                # correctly refuses to train silently from scratch, and
                # the drill is unrecoverable BY DESIGN — never compose
                # the two
                pool = [s for s in pool if s.key != "shard_corrupt"]
            chosen.extend(rng.sample(pool, min(k - 1, len(pool))))
        else:
            chosen.extend(rng.sample(eligible, k))
        faults = []
        env: Dict[str, str] = {}
        for spec in sorted(chosen, key=lambda s: s.key):
            assignment = spec.sample(rng, ctx)
            faults.append({"key": spec.key, "env": assignment,
                           "interrupting": spec.interrupting})
            env.update(assignment)
        keys = {f["key"] for f in faults}
        if "shard_corrupt" in keys and "kill" in keys:
            # the torn data_state hits the FIRST checkpoint commit; the
            # kill must land after the SECOND clean serial commits, or
            # restore has nothing to fall back to and the pod dies loud
            # (the intended all-serials-corrupt behavior, but not a
            # drill that can ever pass)
            floor = 2 * CKPT_STEP_INTERVAL + 1
            if int(env["PADDLE_FAULT_KILL_STEP"]) < floor:
                step = rng.randrange(floor,
                                     max(floor + 1, self.steps - 1))
                env["PADDLE_FAULT_KILL_STEP"] = str(step)
                for f in faults:
                    if f["key"] == "kill":
                        f["env"]["PADDLE_FAULT_KILL_STEP"] = str(step)
        if self.scenario == "train":
            # the train drill is in-process: crashes must raise
            # InjectedFault, not os._exit the evaluating process
            env["PADDLE_FAULT_MODE"] = "raise"
        return {
            "version": 1,
            "scenario": self.scenario,
            "seed": self.seed,
            "stable_seed": self.stable_seed,
            "faults_requested": self.faults,
            "nproc": self.nproc,
            "steps": self.steps,
            "faults": faults,
            "env": env,
        }


# ---------------------------------------------------------------------------
# docs/FAULTS.md generation (mirrors envcontract.generate_markdown: the
# committed file is diffed against this generator by tools/repo_lint.py)
# ---------------------------------------------------------------------------

def generate_fault_table() -> str:
    lines = [
        "# Fault catalog",
        "",
        "<!-- GENERATED FILE - DO NOT EDIT BY HAND -->",
        "<!-- regenerate: python -m paddle_tpu.chaos faults --write -->",
        "",
        "Every deterministic fault the chaos engine can draw from, "
        "auto-discovered",
        "from the `fault` subsystem of `fluid.envcontract`.  "
        "`python -m paddle_tpu.chaos run`",
        "samples seeded K-fault plans over this catalog; "
        "`tests/test_chaos.py` fails",
        "when a newly declared fault knob is missing from it.",
        "",
        "## Declared fault knobs",
        "",
        "| Knob | Type | Default | Description |",
        "|---|---|---|---|",
    ]
    for knob in sorted((k for k in _ec.REGISTRY.values()
                        if k.subsystem == "fault" and k.type != "prefix"),
                       key=lambda k: k.name):
        default = "" if knob.default is None else repr(knob.default)
        help_text = " ".join(knob.help.split())
        lines.append(
            f"| `{knob.name}` | {knob.type} | `{default}` "
            f"| {help_text} |")
    lines += [
        "",
        "## Chaos catalog (samplable fault families)",
        "",
        "| Family | Knobs | Scenarios | Interrupting |",
        "|---|---|---|---|",
    ]
    for spec in sorted(CATALOG, key=lambda s: s.key):
        if not spec.scenarios:
            continue
        knobs = ", ".join(f"`{k}`" for k in spec.knobs)
        scen = ", ".join(spec.scenarios)
        lines.append(
            f"| `{spec.key}` | {knobs} | {scen} "
            f"| {'yes' if spec.interrupting else 'no'} |")
    lines += [
        "",
        "## Cataloged but never sampled",
        "",
        "These faults alter the committed trajectory or the fleet shape "
        "*by design*,",
        "so the drill invariants (bitwise resume, exactly-once coverage) "
        "cannot judge",
        "them; each keeps its own dedicated oracle.",
        "",
    ]
    for spec in sorted(CATALOG, key=lambda s: s.key):
        if spec.scenarios:
            continue
        knobs = ", ".join(f"`{k}`" for k in spec.knobs)
        lines.append(f"- **{spec.key}** ({knobs}): {spec.rationale}")
    lines += [
        "",
        "Scoping knobs (`PADDLE_FAULT_RANK`, `PADDLE_FAULT_MODE`) are "
        "composition",
        "modifiers, not faults, and are exempt from catalog coverage.",
        "",
    ]
    return "\n".join(lines)
