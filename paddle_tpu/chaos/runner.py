"""Chaos drill runner: execute a seeded fault plan, judge it from disk.

One drill is two strictly separated passes over one workdir:

1. **execute** — expand the seed into a fault plan (:mod:`.schedule`),
   arm it, and run the scenario end to end, persisting every piece of
   ground truth as it happens: the canonical ``plan.json``, per-rank
   per-generation batch-digest logs (fsync'd per record, so a kill mid-
   write leaves at worst one torn line), result blobs, the observe
   event/metric stream, census markers.
2. **evaluate** — :func:`paddle_tpu.chaos.invariants.evaluate` re-derives
   every verdict from those artifacts alone and the runner writes
   ``chaos_report.jsonl``.

The split is load-bearing: ``evaluate_and_report`` can re-judge an
existing workdir without re-running anything (how ``tools/chaos_smoke.py``
proves tampered artifacts flip verdicts to FAIL), and a drill that dies
mid-write is still judgeable from what it managed to persist.

Scenarios:

- ``train``   — in-process single-rank train/kill/resume (raise-mode
  crashes), the fast tier-1 drill;
- ``elastic`` — a real :class:`~paddle_tpu.parallel.elastic.
  ElasticSupervisor` pod of subprocess workers, killed and restarted;
- ``serve``   — a batching ServingEngine under per-request faults;
- ``fleet``   — a ServingFleet losing a replica and riding a load spike.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import sys
from typing import Dict, List, Optional

from . import invariants as _invariants
from .schedule import CKPT_STEP_INTERVAL, ChaosSchedule, canonical_json

__all__ = ["SCENARIO_SHAPE", "run_drill", "evaluate_and_report",
           "read_report", "tamper"]

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# -- shared drill shape (train/elastic data plane) --------------------------
N_SAMPLES = 96          # whole dataset; per-rank batches = 96/nproc/BATCH
BATCH = 4
SPD = 2                 # windowed loop: steps per dispatch
STEP_INTERVAL = CKPT_STEP_INTERVAL  # checkpoint cadence (schedule.py
                                    # samples kill steps against it)
DATA_SEED = 13

#: knobs forwarded to EVERY generation (via extra_env / the resume plan):
#: the transient-I/O oracle must also hit the RESUMED generation's loads,
#: mirroring how the supervisor strips fault_env after generation 0 but
#: extra_env persists
IO_KNOBS = ("PADDLE_FAULT_IO_ERROR_RATE", "PADDLE_FAULT_IO_ERROR_SEED")

#: (nproc, steps) per scenario — what the schedule samples step-indexed
#: faults against
SCENARIO_SHAPE = {
    "train": {"nproc": 1, "steps": N_SAMPLES // 1 // BATCH},
    "elastic": {"nproc": 2, "steps": N_SAMPLES // 2 // BATCH},
    "serve": {"nproc": 1, "steps": 12},
    "fleet": {"nproc": 1, "steps": 12},
}


# ---------------------------------------------------------------------------
# shared data plane (the worker script imports these back — one source of
# truth for the model/pipeline both the drill and its reference run)
# ---------------------------------------------------------------------------

def _sample_reader():
    import numpy as np

    for i in range(N_SAMPLES):
        x = np.full((4,), float(i), np.float32)
        yield (x, x[:1] * 0.5)


def _build_pipe(rank: int, nproc: int, record=None):
    from paddle_tpu import data

    pipe = (data.from_reader(_sample_reader)
                .shard_by_mesh("dp2", host_rank=rank, num_hosts=nproc)
                .shuffle(16, seed=DATA_SEED)
                .batch(BATCH))
    return pipe.map(record) if record is not None else pipe


def _digest(batch) -> str:
    import numpy as np

    h = hashlib.sha1()
    for sample in batch:
        for a in sample:
            h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def _train_func():
    import paddle_tpu.fluid as fluid

    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1, act=None)
    return fluid.layers.mean(
        fluid.layers.square_error_cost(input=pred, label=y))


def _optimizer_func():
    import paddle_tpu.fluid as fluid

    return fluid.optimizer.SGD(learning_rate=0.05)


def _train_once(workdir: str, ckpt_dir: str, rank: int, nproc: int,
                seq_path: Optional[str] = None) -> dict:
    """One full training pass (fresh framework session) over this rank's
    shard, checkpointing to ``ckpt_dir``; resumes from its newest
    complete serial when one exists.  Digests stream to ``seq_path``
    (fsync'd per record) so a raise-mode crash mid-pass still leaves the
    consumed prefix on disk."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework
    from paddle_tpu.fluid.executor import global_scope

    framework.fresh_session()
    fluid.default_main_program().random_seed = 7
    fluid.default_startup_program().random_seed = 7

    record = None
    if seq_path is not None:
        def record(batch):
            with open(seq_path, "a") as f:
                f.write(json.dumps({"digest": _digest(batch)}) + "\n")
                f.flush()
                os.fsync(f.fileno())
            return batch

    pipe = _build_pipe(rank, nproc, record=record)
    cfg = fluid.CheckpointConfig(ckpt_dir, step_interval=STEP_INTERVAL)
    trainer = fluid.Trainer(
        train_func=_train_func, optimizer_func=_optimizer_func,
        place=fluid.CPUPlace(), checkpoint_config=cfg)
    resume_step = cfg.step_id
    steps: List[int] = []

    def handler(ev):
        if isinstance(ev, fluid.EndStepEvent):
            steps.append(ev.step)

    trainer.train(num_epochs=1, event_handler=handler, reader=pipe,
                  feed_order=["x", "y"])
    w = np.asarray(global_scope().get("fc_0.w_0"))
    return {"resume_step": resume_step, "steps": steps,
            "exact": bool(trainer._data_exact_resume),
            "w_digest": hashlib.sha1(w.tobytes()).hexdigest()}


def _write_json(path: str, payload: dict) -> None:
    with open(path, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())


@contextlib.contextmanager
def _scoped_env(pairs: Dict[str, Optional[str]]):
    """Set/unset env vars for one drill phase, always restoring (the
    runner is also called in-process from tests)."""
    saved = {k: os.environ.get(k) for k in pairs}
    try:
        for k, v in pairs.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _flush_observe() -> None:
    from paddle_tpu import observe

    sink = observe.get_sink()
    if sink is not None:
        sink.flush()


def _resume_env(plan: dict) -> Dict[str, str]:
    """The fault env a post-crash generation sees: IO-oracle knobs only
    (the supervisor strips PADDLE_FAULT_* after generation 0; extra_env
    — where the runner routes the IO knobs — survives)."""
    return {k: plan["env"][k] for k in IO_KNOBS if k in plan["env"]}


# ---------------------------------------------------------------------------
# scenario: train (in-process, raise-mode — the tier-1 smoke drill)
# ---------------------------------------------------------------------------

def _execute_train(workdir: str, plan: dict) -> None:
    from paddle_tpu import observe
    from paddle_tpu.fluid import fault as _fault

    nproc = 1
    env = {
        "PADDLE_TPU_SPD": str(SPD),
        "PADDLE_IO_RETRY_BASE_S": "0.01",  # fast drill, same retry path
        "PADDLE_COMPILE_CACHE_DIR": os.path.join(workdir, "cache"),
        "PADDLE_ELASTIC_GENERATION": None,
    }
    with _scoped_env(env):
        try:
            # -- uninterrupted reference: clean faults, no observe ------
            _fault.install(None)
            observe.reset()
            ref_seq = os.path.join(workdir, "ref_r0.jsonl")
            with open(ref_seq, "w") as f:
                for batch in iter(_build_pipe(0, nproc)):
                    f.write(json.dumps({"digest": _digest(batch)}) + "\n")
            ref = _train_once(workdir, os.path.join(workdir, "refckpt_r0"),
                              0, nproc)
            _write_json(os.path.join(workdir, "ref_result_r0.json"), ref)

            # -- generation 0: full plan armed, crash expected ----------
            os.environ["PADDLE_ELASTIC_GENERATION"] = "0"
            observe.reset()
            observe.configure(os.path.join(workdir, "observe"))
            _fault.install(_fault.FaultPlan.from_env(plan["env"]))
            g0_blob: dict = {"interrupted": False}
            try:
                g0_blob.update(_train_once(
                    workdir, os.path.join(workdir, "ckpt_r0"), 0, nproc,
                    seq_path=os.path.join(workdir, "seq_r0_g0.jsonl")))
            except _fault.InjectedFault as exc:
                g0_blob = {"interrupted": True, "fault": str(exc)}
            _write_json(os.path.join(workdir, "result_r0_g0.json"),
                        g0_blob)
            _flush_observe()

            # -- generation 1: resume under the IO oracle only ----------
            if g0_blob.get("interrupted"):
                os.environ["PADDLE_ELASTIC_GENERATION"] = "1"
                observe.configure(os.path.join(workdir, "observe"))
                resume = _resume_env(plan)
                _fault.install(_fault.FaultPlan.from_env(resume)
                               if resume else None)
                g1 = _train_once(
                    workdir, os.path.join(workdir, "ckpt_r0"), 0, nproc,
                    seq_path=os.path.join(workdir, "seq_r0_g1.jsonl"))
                _write_json(os.path.join(workdir, "result_r0_g1.json"),
                            g1)
                _flush_observe()
        finally:
            _fault.clear()
            _flush_observe()
            observe.disable()


# ---------------------------------------------------------------------------
# scenario: elastic (a real supervised subprocess pod)
# ---------------------------------------------------------------------------

# self-contained worker: all drill parameters arrive via env, the data
# plane/model are imported back from THIS module so the reference run and
# the supervised workers cannot drift apart
_WORKER = '''
import os, sys, json, hashlib
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

# opt out of the supervisor's shared compile cache: this container's
# jaxlib CPU backend intermittently segfaults executing a deserialized
# cached executable for the windowed program in subprocess workers
# (pre-existing environment quirk; see tests/test_data_resume.py)
os.environ.pop("PADDLE_COMPILE_CACHE_DIR", None)

sys.path.insert(0, os.environ["CHAOS_REPO"])
rank = int(os.environ["PADDLE_TRAINER_ID"])
gen = int(os.environ.get("PADDLE_ELASTIC_GENERATION", "0"))
nproc = int(os.environ["CHAOS_NPROC"])
workdir = os.environ["CHAOS_WORKDIR"]

import paddle_tpu.fluid as fluid
from paddle_tpu.chaos import runner as spec

seq_log = os.path.join(workdir, "seq_r%d_g%d.jsonl" % (rank, gen))

def record(batch):
    with open(seq_log, "a") as f:
        f.write(json.dumps({"digest": spec._digest(batch)}) + "\\n")
        f.flush()
        os.fsync(f.fileno())
    return batch

fluid.default_main_program().random_seed = 7
fluid.default_startup_program().random_seed = 7
pipe = spec._build_pipe(rank, nproc, record=record)

cfg = fluid.CheckpointConfig(os.path.join(workdir, "ckpt_r%d" % rank),
                             step_interval=spec.STEP_INTERVAL)
trainer = fluid.Trainer(
    train_func=spec._train_func, optimizer_func=spec._optimizer_func,
    place=fluid.CPUPlace(), checkpoint_config=cfg)
resume_step = cfg.step_id
steps = []

def handler(ev):
    if isinstance(ev, fluid.EndStepEvent):
        steps.append(ev.step)

trainer.train(num_epochs=1, event_handler=handler, reader=pipe,
              feed_order=["x", "y"])

from paddle_tpu.fluid.executor import global_scope

w = np.asarray(global_scope().get("fc_0.w_0"))
with open(os.path.join(workdir, "result_r%d_g%d.json" % (rank, gen)),
          "w") as f:
    json.dump({"resume_step": resume_step, "steps": steps,
               "exact": bool(trainer._data_exact_resume),
               "w_digest": hashlib.sha1(w.tobytes()).hexdigest()}, f)
'''


def _execute_elastic(workdir: str, plan: dict) -> None:
    from paddle_tpu import observe
    from paddle_tpu.fluid import fault as _fault
    from paddle_tpu.parallel.elastic import ElasticSupervisor
    from paddle_tpu.parallel.master import Backoff

    nproc = int(plan["nproc"])
    with _scoped_env({"PADDLE_TPU_SPD": str(SPD),
                      "PADDLE_ELASTIC_GENERATION": None,
                      "PADDLE_COMPILE_CACHE_DIR": None}):
        # -- uninterrupted per-rank reference, in-process ----------------
        _fault.install(None)
        observe.reset()
        for rank in range(nproc):
            with open(os.path.join(workdir, f"ref_r{rank}.jsonl"),
                      "w") as f:
                for batch in iter(_build_pipe(rank, nproc)):
                    f.write(json.dumps({"digest": _digest(batch)}) + "\n")
            ref = _train_once(workdir,
                              os.path.join(workdir, f"refckpt_r{rank}"),
                              rank, nproc)
            _write_json(os.path.join(workdir, f"ref_result_r{rank}.json"),
                        ref)

    # -- the supervised drill ------------------------------------------
    worker_py = os.path.join(workdir, "worker.py")
    with open(worker_py, "w") as f:
        f.write(_WORKER)
    io_env = _resume_env(plan)
    fault_env = {k: v for k, v in plan["env"].items() if k not in io_env}
    extra_env = dict(io_env)
    extra_env.update({
        "CHAOS_REPO": _REPO,
        "CHAOS_WORKDIR": workdir,
        "CHAOS_NPROC": str(nproc),
        "PADDLE_TPU_SPD": str(SPD),
        "PADDLE_IO_RETRY_BASE_S": "0.01",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1 "
                     "--xla_cpu_enable_concurrency_optimized_scheduler"
                     "=false",
    })
    sup = ElasticSupervisor(
        f"{sys.executable} {worker_py}", nproc=nproc, workdir=workdir,
        hb_timeout=120.0, poll_interval=0.2, max_restarts=3,
        backoff=Backoff(base=0.2, factor=1.0), deadline=240.0,
        extra_env=extra_env, fault_env=fault_env,
        observe_dir=os.path.join(workdir, "observe"))
    result = sup.run()
    _write_json(os.path.join(workdir, "supervisor.json"),
                {"status": result["status"],
                 "generations": result["generations"],
                 "incidents": result["incidents"]})


# ---------------------------------------------------------------------------
# scenario: serve (batching engine under per-request faults)
# ---------------------------------------------------------------------------

def _execute_serve(workdir: str, plan: dict) -> None:
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import observe
    from paddle_tpu.fluid import fault as _fault
    from paddle_tpu.fluid import framework
    from paddle_tpu.inference import (AnalysisConfig, PaddleTensor,
                                      create_paddle_predictor)

    model_dir = os.path.join(workdir, "model")
    env = {
        "PADDLE_IO_RETRY_BASE_S": "0.01",
        "PADDLE_COMPILE_CACHE_DIR": os.path.join(workdir, "cache"),
        "PADDLE_ELASTIC_GENERATION": None,
    }
    eng = None
    with _scoped_env(env):
        try:
            observe.reset()
            observe.configure(os.path.join(workdir, "observe"))
            framework.fresh_session()
            fluid.default_main_program().random_seed = 11
            fluid.default_startup_program().random_seed = 11
            img = fluid.layers.data(name="img", shape=[784],
                                    dtype="float32")
            h = fluid.layers.fc(img, size=32, act="relu")
            pred_var = fluid.layers.fc(h, size=10, act="softmax")
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            fluid.io.save_inference_model(model_dir, ["img"], [pred_var],
                                          exe)
            framework.fresh_session()

            # warm up under the IO oracle alone: the manifest + compile
            # cache commits must recover through retries; the serving
            # faults stay disarmed until the reference outputs exist
            io_env = _resume_env(plan)
            _fault.install(_fault.FaultPlan.from_env(io_env)
                           if io_env else None)
            pred = create_paddle_predictor(AnalysisConfig(
                model_dir=model_dir, use_tpu=False, enable_serving=True,
                serving_max_batch_size=8, serving_max_wait_ms=30.0,
                serving_batch_invariant=True))
            eng = pred._engine
            eng.warmup()

            rng = np.random.RandomState(7)
            rows = [rng.normal(size=(1, 784)).astype(np.float32)
                    for _ in range(12)]
            ref = [pred.run([PaddleTensor(name="img", data=r)])[0].data
                   for r in rows]

            # full plan: per-request failures must stay isolated
            _fault.install(_fault.FaultPlan.from_env(plan["env"]))
            futs = [eng.submit([PaddleTensor(name="img", data=r)])
                    for r in rows]
            outcomes = []
            for i, f in enumerate(futs):
                try:
                    (out,) = f.result(timeout=60)
                    outcomes.append({
                        "ok": True,
                        "bitwise": bool(np.array_equal(out.data, ref[i])),
                    })
                except _fault.InjectedFault:
                    outcomes.append({"ok": False, "bitwise": False})
            _write_json(os.path.join(workdir, "serve_results.json"), {
                "outcomes": outcomes,
                "fail_every": int(plan["env"].get(
                    "PADDLE_FAULT_SERVE_FAIL_EVERY", 0) or 0),
            })
        finally:
            _fault.clear()
            if eng is not None:
                try:
                    eng.shutdown()
                except Exception:
                    pass
            _flush_observe()
            observe.disable()


# ---------------------------------------------------------------------------
# scenario: fleet (replica death + load spike under one router)
# ---------------------------------------------------------------------------

def _execute_fleet(workdir: str, plan: dict) -> None:
    import time

    import numpy as np

    from paddle_tpu import observe
    from paddle_tpu.fluid import fault as _fault
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import (AutoscalePolicy, DecodeEngine,
                                    RouterConfig, ServingFleet)

    def _wait(pred, timeout_s=60.0, tick=None):
        deadline = time.perf_counter() + timeout_s
        while not pred():
            if time.perf_counter() > deadline:
                return False
            if tick is not None:
                tick()
            time.sleep(0.01)
        return True

    env = {
        "PADDLE_IO_RETRY_BASE_S": "0.01",
        "PADDLE_COMPILE_CACHE_DIR": os.path.join(workdir, "cache"),
        "PADDLE_ELASTIC_GENERATION": None,
    }
    fleet = None
    with _scoped_env(env):
        try:
            observe.reset()
            observe.configure(os.path.join(workdir, "observe"))

            def make(labels):
                model = transformer.DecodeModel(
                    cfg=transformer.decode_lm_config(), max_slots=2,
                    max_len=32, prefill_buckets=[4], seed=5)
                return DecodeEngine(model, metrics_labels=labels)

            fleet = ServingFleet(
                {"chat": make}, replicas=2,
                hb_dir=os.path.join(workdir, "hb"),
                policy=AutoscalePolicy(min_replicas=2, max_replicas=3,
                                       cooldown_s=60.0, queue_high=6,
                                       hysteresis_ticks=2),
                router_config=RouterConfig(queue_hard=16), eval_s=30.0)
            fleet.start(wait_ready_s=90.0)
            ready = _wait(lambda: fleet.status()["models"]["chat"]
                          ["ready"] == 2)
            rng = np.random.RandomState(7)
            prompts = [[int(t) for t in rng.randint(2, 60, size=3)]
                       for _ in range(4)]
            base = [fleet.generate("chat", p, 6) for p in prompts]

            # arm the plan: replica_kill fires on a near-future request,
            # the io oracle rides along through respawn re-warm commits
            _fault.install(_fault.FaultPlan.from_env(plan["env"]))
            futs = [fleet.submit("chat", prompts[i % 4], 6)
                    for i in range(10)]
            got = [f.result(timeout=60) for f in futs]
            failover_ok = all(got[i] == base[i % 4] for i in range(10))
            respawned = _wait(
                lambda: fleet.status()["models"]["chat"]["ready"] >= 2,
                timeout_s=60.0, tick=fleet.poll_once)

            # load spike over the hard queue bound: the last-chance
            # scale-out must fire before any shed
            primers = [fleet.submit("chat", prompts[i % 4], 12)
                       for i in range(4)]
            spike = [fleet.submit("chat", prompts[i % 4], 4)
                     for i in range(48)]
            spike_ok = sum(1 for f in spike
                           if f.result(timeout=120) is not None)
            for f in primers:
                f.result(timeout=120)
            shed = fleet.status()["models"]["chat"]["shed"]
            _write_json(os.path.join(workdir, "fleet_results.json"), {
                "ready": ready, "failover_bitwise": failover_ok,
                "respawned": respawned, "spike_completed": spike_ok,
                "shed": shed,
            })
        finally:
            _fault.clear()
            if fleet is not None:
                try:
                    fleet.shutdown(timeout_s=15)
                except Exception:
                    pass
            _flush_observe()
            observe.disable()


_EXECUTORS = {
    "train": _execute_train,
    "elastic": _execute_elastic,
    "serve": _execute_serve,
    "fleet": _execute_fleet,
}


# ---------------------------------------------------------------------------
# report plumbing
# ---------------------------------------------------------------------------

def _write_report(workdir: str, plan: dict,
                  verdicts: List[dict]) -> dict:
    path = os.path.join(workdir, "chaos_report.jsonl")
    counts = {"PASS": 0, "FAIL": 0, "SKIP": 0}
    for v in verdicts:
        counts[v["status"]] = counts.get(v["status"], 0) + 1
    ok = counts["FAIL"] == 0
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "plan", "plan": plan},
                           sort_keys=True) + "\n")
        for v in verdicts:
            f.write(json.dumps({"kind": "verdict", **v},
                               sort_keys=True) + "\n")
        f.write(json.dumps({"kind": "summary", "ok": ok, **counts},
                           sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return {"ok": ok, "plan": plan, "verdicts": verdicts,
            "counts": counts, "report_path": path}


def read_report(path: str) -> dict:
    """Parse a chaos report, tolerating a torn final line (a drill may
    die mid-report; whatever verdicts landed are still returned)."""
    plan: Optional[dict] = None
    verdicts: List[dict] = []
    summary: Optional[dict] = None
    for rec in _invariants.read_jsonl_tolerant(path):
        kind = rec.get("kind")
        if kind == "plan":
            plan = rec.get("plan")
        elif kind == "verdict":
            verdicts.append(
                {k: v for k, v in rec.items() if k != "kind"})
        elif kind == "summary":
            summary = {k: v for k, v in rec.items() if k != "kind"}
    return {"plan": plan, "verdicts": verdicts, "summary": summary}


def evaluate_and_report(workdir: str) -> dict:
    """Judge an existing drill workdir from its persisted artifacts only
    and (re)write its ``chaos_report.jsonl``."""
    plan_path = os.path.join(workdir, "plan.json")
    try:
        with open(plan_path) as f:
            plan = json.load(f)
    except (OSError, ValueError):
        plan = None
    verdicts = _invariants.evaluate(workdir, plan)
    return _write_report(workdir, plan or {}, verdicts)


def tamper(workdir: str) -> str:
    """Corrupt one persisted-truth artifact so the next evaluate pass
    MUST flip a verdict to FAIL — the smoke tool's proof that the
    invariants actually consume the artifacts they claim to."""
    import glob
    import re

    seqs = sorted(glob.glob(os.path.join(workdir, "seq_r0_g*.jsonl")),
                  key=lambda p: int(re.search(r"_g(\d+)\.", p).group(1)))
    if seqs:
        target = seqs[-1]
        records = _invariants.read_jsonl_tolerant(target)
        if records:
            records[0]["digest"] = "0" * 40
            with open(target, "w") as f:
                for rec in records:
                    f.write(json.dumps(rec) + "\n")
            return target
    serve = os.path.join(workdir, "serve_results.json")
    if os.path.exists(serve):
        with open(serve) as f:
            payload = json.load(f)
        if payload.get("outcomes"):
            payload["outcomes"][0] = {"ok": True, "bitwise": False}
        with open(serve, "w") as f:
            json.dump(payload, f)
        return serve
    # fleet: fabricate a shed that predates every scale-out
    events = sorted(glob.glob(os.path.join(workdir, "observe",
                                           "events-*.jsonl")))
    if events:
        with open(events[0], "a") as f:
            f.write(json.dumps({"event": "fleet.shed", "ts": 0.0}) + "\n")
        return events[0]
    raise RuntimeError(f"nothing tamperable in {workdir}")


def run_drill(scenario: str, seed: int, faults: int, workdir: str,
              tamper_artifacts: bool = False) -> dict:
    """Execute one seeded drill end to end and judge it.  Returns the
    report dict (``ok`` / ``verdicts`` / ``plan`` / ``report_path``)."""
    if scenario not in _EXECUTORS:
        raise ValueError(f"unknown scenario {scenario!r} "
                         f"(have {sorted(_EXECUTORS)})")
    os.makedirs(workdir, exist_ok=True)
    shape = SCENARIO_SHAPE[scenario]
    plan = ChaosSchedule(scenario, seed, faults, **shape).plan()
    plan_path = os.path.join(workdir, "plan.json")
    with open(plan_path, "w") as f:
        f.write(canonical_json(plan) + "\n")
        f.flush()
        os.fsync(f.fileno())
    _EXECUTORS[scenario](workdir, plan)
    if tamper_artifacts:
        tamper(workdir)
    return evaluate_and_report(workdir)
