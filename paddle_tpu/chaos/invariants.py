"""Post-drill invariant verdicts over persisted truth (ISSUE 18).

Every checker here consumes ONLY what the drill left on disk — digest
logs, result JSON blobs, the observe event stream, metric snapshots,
census markers — never in-memory state from the run.  The split is the
point: a drill that dies mid-write must still be judgeable from its
artifacts (the runner's ``evaluate`` pass re-runs on an untouched
workdir), and a tampered artifact must flip a verdict to FAIL, which is
exactly how ``tools/chaos_smoke.py`` proves the invariants have teeth.

Verdict statuses:

- ``PASS`` — the invariant held (including vacuously: the drill never
  entered the state the invariant guards, e.g. no shed ever happened);
- ``FAIL`` — the artifacts contradict the invariant, or the artifacts
  the invariant NEEDS are missing/corrupt (a drill that cannot prove
  its safety property did not pass it);
- ``SKIP`` — the invariant does not apply to this scenario/plan (e.g.
  ``io_retries_observed`` when the plan never armed the I/O oracle).

Torn-tail tolerance: digest logs and the chaos report are JSONL streams
a crashing process may truncate mid-line; every reader here parses
line-by-line and drops the torn tail instead of raising (satellite 6).
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional

__all__ = ["evaluate", "read_jsonl_tolerant", "INVARIANTS"]


# ---------------------------------------------------------------------------
# tolerant artifact readers
# ---------------------------------------------------------------------------

def read_jsonl_tolerant(path: str) -> List[dict]:
    """Every parseable record of a JSONL file; a torn final line (the
    signature a killed writer leaves) is silently dropped, and a missing
    file is an empty stream — the caller decides whether empty is FAIL."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for ln in f:
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _digests(path: str) -> List[str]:
    return [r["digest"] for r in read_jsonl_tolerant(path)
            if "digest" in r]


def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def _drill_events(workdir: str) -> List[dict]:
    from ..observe.fleet import fleet_events

    root = os.path.join(workdir, "observe")
    if not os.path.isdir(root):
        return []
    return fleet_events(root)


def _ranks(plan: dict) -> List[int]:
    if plan.get("scenario") == "train":
        return [0]
    return list(range(int(plan.get("nproc", 2))))


def _gen_paths(workdir: str, rank: int) -> Dict[int, str]:
    """gen -> seq log path, discovered from what the drill persisted."""
    out: Dict[int, str] = {}
    for p in glob.glob(os.path.join(workdir, f"seq_r{rank}_g*.jsonl")):
        m = re.search(rf"seq_r{rank}_g(\d+)\.jsonl$", p)
        if m:
            out[int(m.group(1))] = p
    return out


def _is_slice(needle: List[str], hay: List[str]) -> bool:
    if not needle:
        return True
    n = len(needle)
    return any(hay[i:i + n] == needle
               for i in range(len(hay) - n + 1))


def _verdict(name: str, status: str, detail: str) -> dict:
    return {"invariant": name, "status": status, "detail": detail}


# ---------------------------------------------------------------------------
# the invariants
# ---------------------------------------------------------------------------

def exactly_once_coverage(workdir: str, plan: dict) -> dict:
    """Across all generations, each rank consumed the reference sample
    sequence exactly once: the final generation's digests are precisely
    the reference tail from its resume point, every earlier generation
    is a prefix/slice (prefetch lookahead staged past a kill is REPLAYED,
    never trained twice) — no skip, no double-consume."""
    name = "exactly_once_coverage"
    if plan.get("scenario") not in ("train", "elastic"):
        return _verdict(name, "SKIP", "data-plane drill only")
    for rank in _ranks(plan):
        ref = _digests(os.path.join(workdir, f"ref_r{rank}.jsonl"))
        gens = _gen_paths(workdir, rank)
        if not ref or not gens:
            return _verdict(
                name, "FAIL",
                f"rank {rank}: missing reference or generation digest "
                f"logs (ref={len(ref)} records, gens={sorted(gens)})")
        order = sorted(gens)
        last = _digests(gens[order[-1]])
        resume = len(ref) - len(last)
        if resume < 0 or last != ref[resume:]:
            return _verdict(
                name, "FAIL",
                f"rank {rank}: generation {order[-1]} is not the "
                f"reference tail (|ref|={len(ref)}, |last|={len(last)})")
        for g in order[:-1]:
            seq = _digests(gens[g])
            if g == order[0]:
                ok = seq == ref[:len(seq)] and len(seq) >= resume
            else:
                ok = _is_slice(seq, ref)
            if not ok:
                return _verdict(
                    name, "FAIL",
                    f"rank {rank}: generation {g} digests are not a "
                    f"reference prefix/slice covering the resume point "
                    f"{resume}")
    return _verdict(name, "PASS",
                    f"all ranks covered the reference sequence exactly "
                    f"once across {len(_gen_paths(workdir, 0))} "
                    f"generation(s)")


def bitwise_resume(workdir: str, plan: dict) -> dict:
    """The interrupted-and-resumed run's final parameters equal the
    uninterrupted reference's, bitwise, per rank."""
    name = "bitwise_resume"
    if plan.get("scenario") not in ("train", "elastic"):
        return _verdict(name, "SKIP", "data-plane drill only")
    for rank in _ranks(plan):
        ref = _load_json(os.path.join(workdir,
                                      f"ref_result_r{rank}.json"))
        gens = sorted(_gen_paths(workdir, rank))
        res = _load_json(os.path.join(
            workdir, f"result_r{rank}_g{gens[-1]}.json")) if gens else None
        if not ref or not res:
            return _verdict(name, "FAIL",
                            f"rank {rank}: missing final/reference "
                            f"result blob")
        if ref.get("w_digest") != res.get("w_digest"):
            return _verdict(
                name, "FAIL",
                f"rank {rank}: resumed weights "
                f"{res.get('w_digest', '?')[:12]} != reference "
                f"{ref.get('w_digest', '?')[:12]}")
    return _verdict(name, "PASS",
                    "resumed parameters bitwise-equal the uninterrupted "
                    "reference on every rank")


def ledger_wall_clock(workdir: str, plan: dict) -> dict:
    """The goodput ledger built from the drill's event stream accounts
    every rank's wall window: per-rank state seconds sum to its
    first-to-last-activity wall clock (coverage == 1 within 1e-3)."""
    name = "ledger_wall_clock"
    if plan.get("scenario") not in ("train", "elastic"):
        return _verdict(name, "SKIP", "data-plane drill only")
    from ..observe import goodput as _goodput

    records = _drill_events(workdir)
    if not records:
        return _verdict(name, "FAIL", "no drill events persisted")
    ledger = _goodput.build_ledger(records)
    ranks = ledger.get("ranks") or {}
    if not ranks:
        return _verdict(name, "FAIL",
                        "event stream yielded an empty ledger")
    for key, entry in sorted(ranks.items()):
        cov = float(entry.get("coverage", 0.0))
        if abs(cov - 1.0) > 1e-3:
            return _verdict(
                name, "FAIL",
                f"{key}: state seconds cover {cov:.4f} of the wall "
                f"window (must be 1.0 +/- 1e-3)")
    return _verdict(name, "PASS",
                    f"{len(ranks)} worker window(s) fully accounted")


def io_retries_observed(workdir: str, plan: dict) -> dict:
    """When the plan arms the transient-I/O oracle, the hardened call
    sites must have actually recovered through bounded retries — visible
    as ``io.retry`` events / nonzero ``io.retries`` counters in the
    observe stream (the acceptance oracle for the retry wrapper)."""
    name = "io_retries_observed"
    env = plan.get("env") or {}
    if not float(env.get("PADDLE_FAULT_IO_ERROR_RATE", 0) or 0):
        return _verdict(name, "SKIP", "io_error oracle not armed")
    events = [r for r in _drill_events(workdir)
              if r.get("event") == "io.retry"]
    if events:
        whats = sorted({e.get("what", "?") for e in events})
        return _verdict(name, "PASS",
                        f"{len(events)} transient retries recovered "
                        f"({', '.join(whats)})")
    from ..observe.fleet import fleet_snapshot

    root = os.path.join(workdir, "observe")
    counters = (fleet_snapshot(root).get("counters") or {}) \
        if os.path.isdir(root) else {}
    hits = {k: v for k, v in counters.items()
            if k.startswith("io.retries") and v > 0}
    if hits:
        return _verdict(name, "PASS",
                        f"retry counters nonzero: {sorted(hits)}")
    return _verdict(name, "FAIL",
                    "io_error armed but no io.retry event or nonzero "
                    "io.retries counter was persisted")


def scale_out_before_shed(workdir: str, plan: dict) -> dict:
    """Under load the fleet must scale out strictly before it sheds:
    the first ``fleet.shed`` (if any) is preceded by a
    ``fleet.scale_out``."""
    name = "scale_out_before_shed"
    if plan.get("scenario") != "fleet":
        return _verdict(name, "SKIP", "fleet drill only")
    records = _drill_events(workdir)
    sheds = [r for r in records if r.get("event") == "fleet.shed"]
    outs = [r for r in records if r.get("event") == "fleet.scale_out"]
    if not sheds:
        return _verdict(name, "PASS",
                        f"no shed ever happened "
                        f"({len(outs)} scale-out(s))")
    if not outs:
        return _verdict(name, "FAIL",
                        f"{len(sheds)} shed event(s) with no scale-out "
                        f"at all")
    if min(float(r.get("ts", 0)) for r in outs) < \
            min(float(r.get("ts", 0)) for r in sheds):
        return _verdict(name, "PASS",
                        "first scale-out precedes first shed")
    return _verdict(name, "FAIL", "shed before the first scale-out")


def veto_never_reserved(workdir: str, plan: dict) -> dict:
    """A checkpoint serial vetoed by canary rollback must never be
    served again: no later swap/promote/rollout event names it."""
    name = "veto_never_reserved"
    if plan.get("scenario") != "fleet":
        return _verdict(name, "SKIP", "fleet drill only")
    records = _drill_events(workdir)
    vetoed: Dict[int, float] = {}
    for r in records:
        if r.get("event") in ("model.rollback", "fleet.canary_rollback") \
                and r.get("serial") is not None:
            s = int(r["serial"])
            ts = float(r.get("ts", 0))
            vetoed[s] = min(vetoed.get(s, ts), ts)
    if not vetoed:
        return _verdict(name, "PASS", "no serial was ever vetoed")
    for r in records:
        if r.get("event") not in ("model.swap", "model.promote",
                                  "fleet.rollout"):
            continue
        s = r.get("serial")
        if s is None:
            continue
        s = int(s)
        if s in vetoed and float(r.get("ts", 0)) > vetoed[s]:
            return _verdict(
                name, "FAIL",
                f"vetoed serial {s} re-served via {r['event']}")
    return _verdict(name, "PASS",
                    f"{len(vetoed)} vetoed serial(s) never re-served")


def census_no_release(workdir: str, plan: dict) -> dict:
    """The census never hands lost capacity back.  Fleet: a device an
    unplanned replica death retired is never leased to a later
    spawn/respawn.  Elastic: a generation started after a host-loss
    marker landed cannot be larger than the surviving census."""
    name = "census_no_release"
    scenario = plan.get("scenario")
    if scenario == "fleet":
        records = _drill_events(workdir)
        lost: Dict[int, float] = {}
        for r in records:
            if r.get("event") == "fleet.replica_dead" \
                    and r.get("device") is not None:
                d = int(r["device"])
                ts = float(r.get("ts", 0))
                lost[d] = min(lost.get(d, ts), ts)
        if not lost:
            return _verdict(name, "PASS", "no device was ever lost")
        for r in records:
            if r.get("event") not in ("fleet.spawn", "fleet.respawn"):
                continue
            d = r.get("device")
            if d is None:
                continue
            d = int(d)
            if d in lost and float(r.get("ts", 0)) > lost[d]:
                return _verdict(
                    name, "FAIL",
                    f"lost device {d} re-leased by {r['event']} "
                    f"for {r.get('replica')}")
        return _verdict(name, "PASS",
                        f"{len(lost)} lost device(s) never re-leased")
    if scenario == "elastic":
        hb_dir = os.path.join(workdir, "heartbeats")
        markers = glob.glob(os.path.join(hb_dir, "host_lost_*")) \
            if os.path.isdir(hb_dir) else []
        if not markers:
            return _verdict(name, "PASS", "no host-loss marker dropped")
        records = _drill_events(workdir)
        gens = [r for r in records
                if r.get("event") == "generation_start"]
        if not gens:
            return _verdict(name, "FAIL",
                            "host lost but no generation_start events "
                            "persisted")
        initial = int(gens[0].get("nproc", plan.get("nproc", 2)))
        ceiling = initial - len(markers)
        for r in gens[1:]:
            if int(r.get("nproc", 0)) > ceiling:
                return _verdict(
                    name, "FAIL",
                    f"generation {r.get('generation')} started "
                    f"{r.get('nproc')} workers > surviving census "
                    f"{ceiling}")
        return _verdict(name, "PASS",
                        f"restarted generations respected the "
                        f"surviving census ({ceiling})")
    return _verdict(name, "SKIP", "fleet/elastic drill only")


def serve_isolation(workdir: str, plan: dict) -> dict:
    """Injected per-request serving failures stay isolated: exactly the
    targeted requests fail, every other response is bitwise-equal to
    the unfaulted reference predictor's."""
    name = "serve_isolation"
    if plan.get("scenario") != "serve":
        return _verdict(name, "SKIP", "serve drill only")
    res = _load_json(os.path.join(workdir, "serve_results.json"))
    if not res or not isinstance(res.get("outcomes"), list):
        return _verdict(name, "FAIL", "missing serve_results.json")
    outcomes = res["outcomes"]
    failed = [i for i, o in enumerate(outcomes) if not o.get("ok")]
    fail_every = int(res.get("fail_every") or 0)
    expected = len(outcomes) // fail_every if fail_every else 0
    if len(failed) != expected:
        return _verdict(
            name, "FAIL",
            f"{len(failed)} requests failed, expected {expected} "
            f"(fail_every={fail_every or 'unarmed'})")
    bad = [i for i, o in enumerate(outcomes)
           if o.get("ok") and not o.get("bitwise")]
    if bad:
        return _verdict(
            name, "FAIL",
            f"completed requests {bad} diverged from the reference "
            f"predictor")
    return _verdict(name, "PASS",
                    f"{len(outcomes) - len(failed)}/{len(outcomes)} "
                    f"requests bitwise-correct, {len(failed)} isolated "
                    f"injected failure(s)")


#: evaluation order — stable, so reports are diffable across runs
INVARIANTS = [
    exactly_once_coverage,
    bitwise_resume,
    ledger_wall_clock,
    io_retries_observed,
    scale_out_before_shed,
    veto_never_reserved,
    census_no_release,
    serve_isolation,
]


def evaluate(workdir: str, plan: Optional[dict] = None) -> List[dict]:
    """Run every invariant against a drill's persisted workdir.  Reads
    ``plan.json`` from the workdir when ``plan`` is not given; a checker
    that itself crashes yields a FAIL verdict (a judge that cannot run
    is not a pass)."""
    if plan is None:
        plan = _load_json(os.path.join(workdir, "plan.json"))
        if plan is None:
            return [_verdict("plan", "FAIL",
                             "plan.json missing or unparseable")]
    verdicts = []
    for check in INVARIANTS:
        try:
            verdicts.append(check(workdir, plan))
        except Exception as exc:
            verdicts.append(_verdict(
                check.__name__, "FAIL",
                f"checker crashed: {type(exc).__name__}: {exc}"))
    return verdicts
