"""Chaos engine: seeded multi-fault drills with invariant verdicts.

``python -m paddle_tpu.chaos run --scenario elastic --seed 7 --faults 3``
expands one integer into a deterministic multi-fault plan (sampled from
the envcontract fault registry), executes the scenario end to end, and
judges the wreckage purely from persisted artifacts — exactly-once data
coverage, bitwise resume, goodput-ledger accounting, autoscaler ordering,
checkpoint veto persistence, device-census hygiene.

The three layers are importable on their own:

- :mod:`.schedule` — seed -> replayable fault plan;
- :mod:`.runner`   — plan -> executed drill workdir + chaos report;
- :mod:`.invariants` — workdir -> verdicts (no live state consulted).
"""

from .invariants import INVARIANTS, evaluate, read_jsonl_tolerant
from .runner import (SCENARIO_SHAPE, evaluate_and_report, read_report,
                     run_drill, tamper)
from .schedule import (CATALOG, ChaosSchedule, canonical_json,
                       generate_fault_table, uncovered_knobs)

__all__ = [
    "CATALOG",
    "ChaosSchedule",
    "INVARIANTS",
    "SCENARIO_SHAPE",
    "canonical_json",
    "evaluate",
    "evaluate_and_report",
    "generate_fault_table",
    "read_jsonl_tolerant",
    "read_report",
    "run_drill",
    "tamper",
    "uncovered_knobs",
]
