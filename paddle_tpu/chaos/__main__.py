"""CLI for the chaos engine.

::

    python -m paddle_tpu.chaos run --scenario train|elastic|serve|fleet \
        --seed N [--faults K] [--workdir DIR] [--tamper]
    python -m paddle_tpu.chaos plan --scenario S --seed N [--faults K]
    python -m paddle_tpu.chaos faults [--write]

``run`` executes one seeded drill and prints the per-invariant verdicts;
exit status 0 iff no invariant FAILed.  ``plan`` prints the canonical
fault-plan JSON without executing anything (two invocations with the
same seed must be byte-identical — that IS the replayability contract).
``faults`` prints the auto-generated fault-injection table; ``--write``
refreshes ``docs/FAULTS.md`` in place.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# must precede any jax import (the executors import the framework)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    "--xla_force_host_platform_device_count=1 "
    "--xla_cpu_enable_concurrency_optimized_scheduler=false")

from .schedule import (ChaosSchedule, canonical_json,  # noqa: E402
                       generate_fault_table)

_FAULTS_DOC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "docs", "FAULTS.md")


def _cmd_plan(args) -> int:
    from .runner import SCENARIO_SHAPE

    shape = SCENARIO_SHAPE[args.scenario]
    plan = ChaosSchedule(args.scenario, args.seed, args.faults,
                         **shape).plan()
    print(canonical_json(plan))
    return 0


def _cmd_run(args) -> int:
    import tempfile

    from .runner import run_drill

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_")
    report = run_drill(args.scenario, args.seed, args.faults, workdir,
                       tamper_artifacts=args.tamper)
    plan = report["plan"]
    print(f"chaos drill: scenario={args.scenario} seed={args.seed} "
          f"faults={len(plan.get('faults', []))} workdir={workdir}")
    for f in plan.get("faults", []):
        knobs = " ".join(f"{k}={v}" for k, v in sorted(f["env"].items()))
        print(f"  fault {f['key']}: {knobs}")
    for v in report["verdicts"]:
        print(f"  [{v['status']:>4}] {v['invariant']}: {v['detail']}")
    counts = report["counts"]
    print(f"verdicts: {counts['PASS']} PASS, {counts['FAIL']} FAIL, "
          f"{counts['SKIP']} SKIP -> "
          f"{'OK' if report['ok'] else 'VIOLATED'}")
    print(f"report: {report['report_path']}")
    return 0 if report["ok"] else 1


def _cmd_faults(args) -> int:
    table = generate_fault_table()
    if args.write:
        os.makedirs(os.path.dirname(_FAULTS_DOC), exist_ok=True)
        with open(_FAULTS_DOC, "w") as f:
            f.write(table)
        print(f"wrote {_FAULTS_DOC}")
    else:
        print(table, end="")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.chaos",
                                 description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    run_p = sub.add_parser("run", help="execute one seeded drill")
    run_p.add_argument("--scenario", required=True,
                       choices=["train", "elastic", "serve", "fleet"])
    run_p.add_argument("--seed", type=int, required=True)
    run_p.add_argument("--faults", type=int, default=2)
    run_p.add_argument("--workdir", default=None,
                       help="drill workdir (default: fresh temp dir)")
    run_p.add_argument("--tamper", action="store_true",
                       help="corrupt one artifact before the verdict "
                            "pass (self-test: must FAIL)")
    run_p.set_defaults(fn=_cmd_run)

    plan_p = sub.add_parser("plan", help="print the canonical fault "
                                         "plan without executing")
    plan_p.add_argument("--scenario", required=True,
                        choices=["train", "elastic", "serve", "fleet"])
    plan_p.add_argument("--seed", type=int, required=True)
    plan_p.add_argument("--faults", type=int, default=2)
    plan_p.set_defaults(fn=_cmd_plan)

    faults_p = sub.add_parser("faults", help="print the fault table")
    faults_p.add_argument("--write", action="store_true",
                          help="refresh docs/FAULTS.md")
    faults_p.set_defaults(fn=_cmd_faults)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
