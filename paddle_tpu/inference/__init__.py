"""Inference API: the TPU-native equivalent of the reference's C++
predictor surface (ref: inference/api/paddle_inference_api.h —
PaddleTensor :67, PaddlePredictor :90, NativeConfig :119, AnalysisConfig
:156; impl api_impl.cc).

Redesign notes (SURVEY.md §2.9): the reference's analysis pipeline
(fluid→DFG→TensorRT-subgraph→fluid) exists to hand subgraphs to a separate
engine; under XLA the *whole* program is already one compiled engine, so
``AnalysisConfig`` maps to program-level rewrites that still pay off before
XLA sees the graph (is_test flips + conv+BN folding via
transpiler.InferenceTranspiler) and the jit cache plays the role of the
engine cache.  Each predictor owns a private Scope, so multiple predictors
coexist in one process exactly like the reference's independent predictors
(paddle_inference_api.h:90 contract: Run() is thread-compatible per clone).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np


@dataclass
class PaddleTensor:
    """Named ndarray crossing the predictor boundary
    (ref: paddle_inference_api.h:67 — name/shape/data/dtype/lod)."""
    name: str = ""
    data: Optional[np.ndarray] = None
    lod: Sequence[Sequence[int]] = field(default_factory=list)

    @property
    def shape(self):
        return tuple(self.data.shape) if self.data is not None else ()

    @property
    def dtype(self):
        return self.data.dtype if self.data is not None else None


@dataclass
class NativeConfig:
    """ref: paddle_inference_api.h:119 (model_dir or prog/param files,
    device selection).  use_tpu=False pins CPU like the reference's
    use_gpu=False."""
    model_dir: str = ""
    prog_file: str = ""
    param_file: str = ""
    use_tpu: bool = True
    device: int = 0


@dataclass
class AnalysisConfig(NativeConfig):
    """ref: paddle_inference_api.h:156.  enable_ir_optim runs the program
    rewrites that matter pre-XLA: is_test flips + conv+BN weight folding
    (transpiler.InferenceTranspiler ≈ the reference's analysis passes +
    inference_transpiler).  enable_int8 additionally rewrites matmul/conv
    weights to int8-in-HBM with per-channel scales, dequantized at the
    consuming op (transpiler.Int8WeightTranspiler ≈ the reference's int8
    analysis pass; weight-only, so accuracy loss stays <1%)."""
    enable_ir_optim: bool = True
    enable_int8: bool = False
    # engine-backed mode (paddle_tpu.serving): Run() routes through a
    # shared dynamic-batching ServingEngine, so concurrent callers get
    # batched dispatches and bucketed compiles for free.  The serving_*
    # knobs seed the engine's ServingConfig; serving_warmup AOT-precompiles
    # every batch bucket at predictor construction (docs/SERVING.md).
    enable_serving: bool = False
    serving_max_batch_size: int = 32
    serving_max_wait_ms: float = 5.0
    serving_max_queue_depth: int = 256
    serving_warmup: bool = False
    serving_batch_invariant: bool = False
    # bucket-manifest destination for warmup() (atomic write; lets a
    # restarted predictor re-warm the same bucket set — empty means "under
    # the persistent compile cache when enabled, else nowhere")
    serving_manifest_path: str = ""
    # localhost /metrics + /healthz port (paddle_tpu.observe; 0 picks an
    # ephemeral port, negative means disabled)
    serving_metrics_port: int = -1


class PaddlePredictor:
    """ref: paddle_inference_api.h:90 / api_impl.cc NativePaddlePredictor.

    Loads the saved inference model into a private scope; Run() feeds
    PaddleTensors, executes the (jit-cached) program, returns fetches.
    """

    def __init__(self, config: NativeConfig):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid.executor import Scope

        self._config = config
        self._scope = Scope()
        place = fluid.TPUPlace(config.device) if config.use_tpu \
            else fluid.CPUPlace()
        self._exe = fluid.Executor(place)
        dirname = config.model_dir
        model_filename = os.path.basename(config.prog_file) or None
        params_filename = os.path.basename(config.param_file) or None
        if not dirname and config.prog_file:
            dirname = os.path.dirname(config.prog_file)
        self._program, self._feed_names, self._fetch_vars = \
            fluid.io.load_inference_model(dirname, self._exe,
                                          model_filename=model_filename,
                                          params_filename=params_filename,
                                          scope=self._scope)
        if isinstance(config, AnalysisConfig) and config.enable_ir_optim:
            from paddle_tpu.fluid.transpiler import InferenceTranspiler

            # install the RETURNED program: the transpile contract is
            # "returns the fused program", not "mutates in place"
            self._program = InferenceTranspiler().transpile(
                self._program, place, scope=self._scope)
        if isinstance(config, AnalysisConfig) and config.enable_int8:
            from paddle_tpu.fluid.transpiler import Int8WeightTranspiler

            # NOTE: returns the quantized weight NAMES, not a program —
            # the int8 rewrite is in-place
            Int8WeightTranspiler().transpile(self._program, place,
                                             scope=self._scope)
        self._engine = None
        if isinstance(config, AnalysisConfig) and config.enable_serving:
            from paddle_tpu.serving import ServingConfig, ServingEngine

            self._engine = ServingEngine(self, ServingConfig(
                max_batch_size=config.serving_max_batch_size,
                max_wait_ms=config.serving_max_wait_ms,
                max_queue_depth=config.serving_max_queue_depth,
                batch_invariant=config.serving_batch_invariant,
                manifest_path=config.serving_manifest_path or None,
                metrics_port=(config.serving_metrics_port
                              if config.serving_metrics_port >= 0
                              else None)))
            if config.serving_warmup:
                self._engine.warmup()

    def close(self) -> None:
        """Drain and stop the serving engine (engine-backed mode only);
        a predictor without an engine has nothing to release."""
        if self._engine is not None:
            self._engine.shutdown()
            self._engine = None

    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return [v.name for v in self._fetch_vars]

    def run(self, inputs: List[PaddleTensor],
            batch_size: int = -1) -> List[PaddleTensor]:
        if self._engine is not None:
            # engine-backed mode: Run() becomes a blocking submit to the
            # shared dynamic batcher — concurrent callers coalesce into
            # bucketed batch dispatches (docs/SERVING.md)
            return self._engine.infer(inputs)
        return self._run_direct(inputs)

    def _run_direct(self, inputs: List[PaddleTensor]) -> List[PaddleTensor]:
        """The un-batched executor path (also the serving engine's
        backend — the engine calls this to avoid re-entering itself)."""
        from paddle_tpu.fluid.lod_tensor import LoDTensor

        # positional fallback is only well-defined when the FULL feed list
        # arrives in declaration order; a partial unnamed feed would bind
        # self._feed_names[i] to the wrong tensor silently
        if any(not t.name for t in inputs) \
                and len(inputs) != len(self._feed_names):
            raise ValueError(
                f"unnamed PaddleTensors are fed positionally, which "
                f"requires exactly the full feed list "
                f"{self._feed_names} in declaration order; got "
                f"{len(inputs)} tensors. Name the tensors to feed a "
                f"subset.")
        feed = {}
        for i, t in enumerate(inputs):
            name = t.name or self._feed_names[i]
            # the reference's PaddleTensor carries LoD alongside data
            # (paddle_inference_api.h:67); a sequence model fed flat data
            # without its LoD would silently see one giant sequence
            if t.lod:
                # offsets-form sanity: every level starts at 0 and is
                # non-decreasing; the FINEST level ends at the row count,
                # and each coarser level indexes into the next level's
                # sequence count (standard nested-LoD invariants —
                # lengths-form input would fail these loudly instead of
                # silently mis-slicing)
                for li, level in enumerate(t.lod):
                    ok = (len(level) >= 2 and level[0] == 0
                          and all(a <= b for a, b in zip(level, level[1:])))
                    if ok:
                        end = (int(t.data.shape[0]) if li == len(t.lod) - 1
                               else len(t.lod[li + 1]) - 1)
                        ok = int(level[-1]) == end
                    if not ok:
                        raise ValueError(
                            f"PaddleTensor '{name}' lod must be offsets "
                            f"form (e.g. [[0, 2, 5]] for lengths [2, 3]); "
                            f"level {li} of {t.lod} is inconsistent with "
                            f"{t.data.shape[0]} rows")
                feed[name] = LoDTensor(t.data, t.lod)
            else:
                feed[name] = t.data
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=[v.name for v in self._fetch_vars],
                             scope=self._scope, return_numpy=False)
        result = []
        for v, o in zip(self._fetch_vars, outs):
            lod = ()
            if isinstance(o, LoDTensor):
                lod = o.lod()
            result.append(PaddleTensor(name=v.name, data=np.asarray(o),
                                       lod=lod))
        return result

    # the reference's C++ clone shares weights via the scope; here a clone
    # shares the scope (arrays are immutable jax values, so concurrent
    # Run()s never alias mutable state)
    def clone(self) -> "PaddlePredictor":
        c = object.__new__(PaddlePredictor)
        c._config = self._config
        c._scope = self._scope
        c._exe = self._exe
        c._program = self._program
        c._feed_names = list(self._feed_names)
        c._fetch_vars = list(self._fetch_vars)
        # clones share the batcher: N cloned front ends all coalesce into
        # the one engine, which is the point of engine-backed mode
        c._engine = self._engine
        return c


def create_paddle_predictor(config: NativeConfig) -> PaddlePredictor:
    """ref: paddle_inference_api.h:179 CreatePaddlePredictor."""
    return PaddlePredictor(config)
