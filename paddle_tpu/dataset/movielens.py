"""MovieLens-1M reader (ref: python/paddle/dataset/movielens.py — yields
[user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
score]; max_user_id :154, max_movie_id :149, max_job_id :159).

Synthetic fallback: deterministic preference structure (users like genres
by id parity) so recommender models actually fit."""

from __future__ import annotations

import numpy as np

# the REAL ml-1m cardinalities (ref movielens meta), so scripts that
# hardcode demo ids (book recommender infer: movie 783, titles ~4k)
# stay in range of the synthetic tables; row COUNTS remain synthetic
N_USERS = 6040
N_MOVIES = 3952
N_JOBS = 20
N_AGES = 7
N_CATEGORIES = 18
TITLE_VOCAB = 5177
N_TRAIN = 6000
N_TEST = 600


def max_user_id():
    return N_USERS


def max_movie_id():
    return N_MOVIES


def max_job_id():
    return N_JOBS


# module-level LIST like the reference (movielens.py:42) — scripts do
# len(paddle.dataset.movielens.age_table)
age_table = [1, 18, 25, 35, 45, 50, 56]


def categories():
    return ["c%d" % i for i in range(N_CATEGORIES)]


def movie_categories():
    """ref movielens.py:225 — the category vocabulary."""
    return categories()


def get_movie_title_dict():
    """ref movielens.py:178 — word -> id over the title vocabulary."""
    return {("w%d" % i): i for i in range(TITLE_VOCAB)}


def _rows(n, seed):
    rng = np.random.RandomState(seed)
    user_genre = rng.randint(0, N_CATEGORIES, size=N_USERS + 1)
    movie_genre = rng.randint(0, N_CATEGORIES, size=N_MOVIES + 1)
    for _ in range(n):
        u = int(rng.randint(1, N_USERS + 1))
        m = int(rng.randint(1, N_MOVIES + 1))
        gender = int(u % 2)
        age = int(u % N_AGES)
        job = int(u % N_JOBS)
        cats = [int(movie_genre[m]),
                int((movie_genre[m] + 1) % N_CATEGORIES)]
        title = [int(x) for x in
                 rng.randint(0, TITLE_VOCAB, size=int(rng.randint(1, 5)))]
        # structured score: genre match -> high rating (+noise)
        base = 4.5 if user_genre[u] == movie_genre[m] else 2.5
        score = float(np.clip(base + rng.normal(0, 0.5), 1.0, 5.0))
        yield [u, gender, age, job, m, cats, title, score]


def train():
    def reader():
        yield from _rows(N_TRAIN, 7)

    return reader


def test():
    def reader():
        yield from _rows(N_TEST, 8)

    return reader
