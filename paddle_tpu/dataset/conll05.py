"""CoNLL-2005 SRL reader (ref: python/paddle/dataset/conll05.py — test()
yields 9-slot samples: word_ids, 5 context windows, predicate id, mark,
IOB label ids; get_dict :184, get_embedding :235).

Synthetic fallback: deterministic predicate/argument structure (words near
the predicate are labeled as its arguments) so SRL models can learn."""

from __future__ import annotations

import numpy as np

WORD_VOCAB = 300
VERB_VOCAB = 30
# IOB labels over 2 chunk types + O: B-A0 I-A0 B-A1 I-A1 O
LABELS = ["B-A0", "I-A0", "B-A1", "I-A1", "O"]
N_TEST = 300


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_VOCAB)}
    verb_dict = {f"v{i}": i for i in range(VERB_VOCAB)}
    label_dict = {l: i for i, l in enumerate(LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """ref conll05.py get_embedding: returns the PATH of the downloaded
    binary fp32 emb file (consumers np.fromfile it, e.g. the book SRL
    chapter's load_parameter).  Synthetic here, cached on disk once."""
    import os
    import tempfile

    from .common import cached_path, must_mkdirs

    path = cached_path("conll05", f"emb_{WORD_VOCAB}x32.bin")
    if not os.path.exists(path):
        must_mkdirs(os.path.dirname(path))
        rng = np.random.RandomState(5)
        arr = rng.normal(scale=0.1,
                         size=(WORD_VOCAB, 32)).astype(np.float32)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        with os.fdopen(fd, "wb") as f:
            f.write(b"\x00" * 16)  # the reference file's 16-byte header
            arr.tofile(f)
        os.replace(tmp, path)  # atomic publish; racers write their own tmp
    return path


def _samples(n, seed):
    rng = np.random.RandomState(seed)
    o = LABELS.index("O")
    for _ in range(n):
        ln = int(rng.randint(5, 12))
        words = rng.randint(0, WORD_VOCAB, size=ln).astype(np.int64)
        vpos = int(rng.randint(ln))
        verb = int(words[vpos]) % VERB_VOCAB
        mark = np.zeros(ln, np.int64)
        mark[vpos] = 1
        labels = np.full(ln, o, np.int64)
        if vpos > 0:
            labels[vpos - 1] = LABELS.index("B-A0")
        if vpos + 1 < ln:
            labels[vpos + 1] = LABELS.index("B-A1")
        if vpos + 2 < ln:
            labels[vpos + 2] = LABELS.index("I-A1")

        def ctx(off):
            idx = np.clip(np.arange(ln) + off, 0, ln - 1)
            return words[idx]

        yield (list(words), list(ctx(-2)), list(ctx(-1)), list(ctx(0)),
               list(ctx(1)), list(ctx(2)),
               [verb] * ln, list(mark), list(labels))


def test():
    def reader():
        yield from _samples(N_TEST, 41)

    return reader
