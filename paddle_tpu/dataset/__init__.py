"""Datasets (ref: python/paddle/dataset/ — mnist, cifar, uci_housing, ...).

The reference auto-downloads into ~/.cache/paddle.  This environment has no
network egress, so each dataset falls back to a deterministic synthetic
generator with the real shapes/dtypes/cardinalities when the cached copy is
absent — enough for the train-loop, checkpoint, and benchmark harnesses.
"""

from . import mnist, cifar, uci_housing, imdb, common

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "common"]
