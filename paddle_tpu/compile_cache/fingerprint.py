"""Canonical program fingerprinting for the persistent compile cache.

The in-process jit cache keys on ``Program._cache_token`` — a per-object
identity that dies with the process.  Cross-process reuse needs a *content*
identity: two processes that built the same model must produce the same
key even though every ``unique_name`` counter, ``id()``, and variable name
suffix differs between them ("fc_0.w_0" in one build is "fc_3.w_0" in the
next when layers were built in a different order).

The fingerprint therefore hashes a CANONICALIZED form of the ProgramDesc:

 - variable names are replaced by dense indices in deterministic
   first-use order (blocks in index order, ops in program order, slots
   sorted, inputs before outputs) — pure rename noise cancels out;
 - ops contribute (type, slot->canonical-name lists, canonicalized attrs);
   attr STRINGS that exactly match a var name are canonicalized too
   (``op_role_var`` carries param/grad names);
 - every referenced var contributes its shape/dtype/persistable/lod_level/
   is_data metadata, keyed by canonical name — an attr- or shape-level
   change MUST change the hash;
 - the jit configuration rides along: feed signature (shapes/dtypes),
   fetch names (canonicalized), and an ``extra`` dict for everything else
   the compiled artifact depends on (platform, amp mode, donation, scan
   length, serving bucket, mesh spec, ...);
 - jax/jaxlib versions are folded in, so a toolchain upgrade naturally
   invalidates every entry instead of resurrecting stale executables.

Anything un-canonicalizable (exotic attr object) degrades to ``repr`` —
deterministic within a build, possibly process-unique, which turns a cache
hit into a miss but never a wrong hit.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["program_fingerprint", "program_signature"]


def _canon_attr(v, rename: Dict[str, str]):
    """Deterministic, rename-aware encoding of one attr value."""
    if isinstance(v, (list, tuple)):
        return [_canon_attr(x, rename) for x in v]
    if isinstance(v, dict):
        return {str(k): _canon_attr(v[k], rename)
                for k in sorted(v, key=str)}
    if isinstance(v, str):
        return rename.get(v, v)
    if isinstance(v, bool) or v is None:
        return v
    if isinstance(v, (int, float)):
        return v
    try:
        import numpy as np

        if isinstance(v, np.ndarray):
            return ["ndarray", list(v.shape), str(v.dtype),
                    hashlib.sha256(v.tobytes()).hexdigest()[:16]]
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, (np.floating, np.bool_)):
            return float(v)
    except Exception:
        pass
    return repr(v)


def program_signature(program) -> Tuple[list, Dict[str, str]]:
    """Canonical structural signature of a Program.

    Returns ``(signature, rename)`` where ``rename`` maps every var name
    referenced by an op to its canonical dense name — callers reuse it to
    canonicalize feed/fetch names so the jit config is rename-invariant
    too.
    """
    rename: Dict[str, str] = {}

    def cname(n: str) -> str:
        if n not in rename:
            rename[n] = f"v{len(rename)}"
        return rename[n]

    # pass 1: structure + name discovery (attrs wait for the full map)
    skeleton = []
    for b in program.blocks:
        ops = []
        for op in b.ops:
            ins = [[slot, [cname(n) if n else "" for n in names]]
                   for slot, names in sorted(op.inputs.items())]
            outs = [[slot, [cname(n) if n else "" for n in names]]
                    for slot, names in sorted(op.outputs.items())]
            ops.append([op.type, ins, outs, op.attrs])
        skeleton.append([b.idx, b.parent_idx, b.forward_block_idx, ops])

    # pass 2: attrs (with the complete rename map) + var metadata
    sig_blocks = []
    for b_idx, parent, fwd, ops in skeleton:
        sig_ops = [[t, i, o,
                    {str(k): _canon_attr(a[k], rename)
                     for k in sorted(a, key=str)}]
                   for t, i, o, a in ops]
        sig_blocks.append([b_idx, parent, fwd, sig_ops])
    var_meta = []
    gb = program.global_block()
    for name in rename:
        try:
            v = gb._var_recursive(name)
        except ValueError:
            v = None
            for b in program.blocks:
                if b._has_var_recursive(name):
                    v = b._var_recursive(name)
                    break
        if v is None:
            var_meta.append([rename[name], None])
            continue
        var_meta.append([rename[name],
                         [list(v.shape) if v.shape is not None else None,
                          str(v.dtype), bool(v.persistable),
                          int(v.lod_level), bool(v.is_data),
                          str(getattr(v, "type", ""))]])
    var_meta.sort()
    return [sig_blocks, var_meta], rename


def program_fingerprint(program,
                        feeds: Optional[Iterable[tuple]] = None,
                        fetches: Optional[Sequence[str]] = None,
                        extra: Optional[dict] = None,
                        spec_table: Optional[Iterable[list]] = None,
                        include_versions: bool = True) -> str:
    """Stable content hash of (program, jit configuration, toolchain).

    ``feeds``      iterable of ``(name, shape, dtype)`` — the concrete feed
                   signature the executable is specialized on;
    ``fetches``    fetch var names (canonicalized through the program's
                   rename map, so noise-renamed fetch temporaries still hit);
    ``extra``      any further jsonable config the artifact depends on
                   (platform, amp, donation set, n_steps, bucket, mesh...);
    ``spec_table`` iterable of ``[var_name, spec]`` sharding-table entries
                   (``parallel.spmd.table_signature``) — var names are
                   canonicalized through the rename map and the table is
                   sorted AFTER renaming, so the fingerprint is
                   rename-invariant yet changes whenever the mesh layout
                   assigns any var a different PartitionSpec.
    """
    sig, rename = program_signature(program)
    feed_sig: List[list] = []
    for name, shape, dtype in (feeds or []):
        feed_sig.append([rename.get(str(name), str(name)),
                         [int(d) for d in shape], str(dtype)])
    feed_sig.sort()
    payload = {
        "program": sig,
        "feeds": feed_sig,
        "fetches": [rename.get(str(n), str(n)) for n in (fetches or [])],
        "extra": _canon_attr(dict(extra or {}), rename),
    }
    if spec_table is not None:
        payload["spec_table"] = sorted(
            [rename.get(str(name), str(name)), _canon_attr(spec, rename)]
            for name, spec in spec_table)
    if include_versions:
        import jax
        import jaxlib

        payload["versions"] = [jax.__version__, jaxlib.__version__]
    blob = json.dumps(payload, sort_keys=True, default=repr,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:32]
