"""On-disk program artifact store: the persistent half of the compile cache.

Layout (everything under one root, shareable between processes on a host —
or between hosts only when platform/toolchain match, see
docs/PERFORMANCE.md)::

    <root>/entries/<fingerprint>/
        manifest.json   fingerprint, jit config summary, compile_seconds,
                        program_sha256, created timestamp
        program.bin     the serialized Program (Program.serialize_to_string)
        _SUCCESS        commit marker, written LAST — the same durability
                        convention as the checkpoint subsystem
                        (trainer.save_checkpoint / multihost serials)
    <root>/xla/         jax's persistent compilation cache (the backend
                        XLA executables), wired via
                        jax_compilation_cache_dir
    <root>/serving/     bucket manifests written by ServingEngine.warmup
    <root>/tmp/         staging dirs for atomic commits

Durability rules, mirrored from the checkpoint subsystem:

 - commit is staged-dir -> rename -> ``_SUCCESS`` last: a crash mid-write
   leaves an unmarked dir that loads ignore and ``prune`` deletes;
 - loads are corruption-TOLERANT: any failure (missing marker, unreadable
   manifest, payload checksum mismatch, or an armed
   ``PADDLE_FAULT_CACHE_CORRUPT`` injection) quarantines the entry and
   returns a miss — a broken cache must never fail the run, only slow it;
 - a size budget (``PADDLE_COMPILE_CACHE_BUDGET_MB``) is enforced by LRU
   eviction over entries AND backend xla files, keyed on last-use mtime
   (hits ``touch`` their entry).

Telemetry flows through ``fluid.profiler.record_counter`` (always-on):
``compile_cache.hit`` / ``.miss`` / ``.put`` / ``.evict`` /
``.corrupt_fallback`` / ``.error`` and the accumulated
``compile_cache.compile_seconds``.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Dict, List, Optional

__all__ = ["CompileCacheStore", "SUCCESS_MARK"]

SUCCESS_MARK = "_SUCCESS"
ENTRIES_DIR = "entries"
XLA_DIR = "xla"
SERVING_DIR = "serving"
TMP_DIR = "tmp"
MANIFEST_FILE = "manifest.json"
PROGRAM_FILE = "program.bin"


def _counter(name: str, inc=1, value=None) -> None:
    from ..fluid import profiler as _prof

    _prof.record_counter(f"compile_cache.{name}", inc=inc, value=value)


def _tree_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, f))
            except OSError:
                pass
    return total


class CompileCacheStore:
    """One cache root; safe for concurrent use by many processes (atomic
    rename commits; last-writer-wins on identical fingerprints)."""

    def __init__(self, root: str, budget_mb: Optional[float] = None):
        self.root = os.path.abspath(root)
        self.budget_bytes = (None if not budget_mb
                             else int(float(budget_mb) * (1 << 20)))
        for d in (ENTRIES_DIR, XLA_DIR, SERVING_DIR, TMP_DIR):
            os.makedirs(os.path.join(self.root, d), exist_ok=True)

    # -- paths --
    def entry_dir(self, fp: str) -> str:
        return os.path.join(self.root, ENTRIES_DIR, str(fp))

    @property
    def xla_dir(self) -> str:
        return os.path.join(self.root, XLA_DIR)

    def serving_manifest_path(self, key: str) -> str:
        return os.path.join(self.root, SERVING_DIR, f"{key}.json")

    # -- backend wiring --
    def enable_backend_cache(self) -> None:
        """Point jax's persistent compilation cache into this store so the
        XLA executable itself round-trips across processes (our entries
        layer carries the program/manifest above it).  Best-effort: some
        backends/versions don't support it, and the framework-level cache
        still works without."""
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", self.xla_dir)
            # test-scale programs compile in <1s; without this the backend
            # would skip persisting exactly the entries we want warm
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
        except Exception:
            pass

    # -- read path --
    def complete(self, fp: str) -> bool:
        return os.path.exists(os.path.join(self.entry_dir(fp), SUCCESS_MARK))

    def get(self, fp: str, count: bool = True) -> Optional[dict]:
        """Manifest of a complete, uncorrupted entry, else None (miss).

        Any load failure — including the deterministic
        ``PADDLE_FAULT_CACHE_CORRUPT`` injection — quarantines the entry
        and reports a miss: the caller compiles fresh and re-``put``s.
        """
        d = self.entry_dir(fp)
        marker = os.path.join(d, SUCCESS_MARK)
        if not os.path.exists(marker):
            if count:
                _counter("miss")
            return None
        from ..fluid import fault as _fault

        try:
            if _fault.cache_corrupt():
                raise IOError("injected cache corruption "
                              "(PADDLE_FAULT_CACHE_CORRUPT)")
            with open(os.path.join(d, MANIFEST_FILE)) as f:
                manifest = json.load(f)
            with open(os.path.join(d, PROGRAM_FILE), "rb") as f:
                blob = f.read()
            if hashlib.sha256(blob).hexdigest() \
                    != manifest.get("program_sha256"):
                raise IOError("payload checksum mismatch")
        except Exception:
            # corrupt-tolerant fallback: drop the entry, report a miss —
            # the run recompiles and rewrites it; never raise
            shutil.rmtree(d, ignore_errors=True)
            if count:
                _counter("corrupt_fallback")
                _counter("miss")
            return None
        if count:
            _counter("hit")
        try:
            os.utime(marker)  # LRU recency
        except OSError:
            pass
        return manifest

    def program_blob(self, fp: str) -> Optional[bytes]:
        """Raw serialized Program of a complete entry (cache_ctl / debug)."""
        try:
            with open(os.path.join(self.entry_dir(fp), PROGRAM_FILE),
                      "rb") as f:
                return f.read()
        except OSError:
            return None

    # -- write path --
    def put(self, fp: str, program_blob: bytes,
            meta: Optional[dict] = None) -> bool:
        """Commit one entry atomically; True when this call created it.
        Existing complete entries are only touched (freshened for LRU)."""
        d = self.entry_dir(fp)
        if self.complete(fp):
            try:
                os.utime(os.path.join(d, SUCCESS_MARK))
            except OSError:
                pass
            return False
        manifest = dict(meta or {})
        manifest.update({
            "fingerprint": str(fp),
            "program_sha256": hashlib.sha256(program_blob).hexdigest(),
            "program_bytes": len(program_blob),
            "created": time.time(),
        })
        tmp = os.path.join(self.root, TMP_DIR,
                           f"{fp}.{os.getpid()}.{time.monotonic_ns()}")
        from ..fluid import fault as _fault
        from ..fluid.retry import retry_io

        try:
            os.makedirs(tmp)

            # staged writes + _SUCCESS get bounded transient retry (keyed
            # on the DESTINATION dir — the tmp name is unique per call);
            # the rename race below stays unretried: contention is a
            # protocol outcome, not a storage blip
            def _stage():
                _fault.io_error(os.path.join(d, PROGRAM_FILE), "write")
                with open(os.path.join(tmp, PROGRAM_FILE), "wb") as f:
                    f.write(program_blob)
                with open(os.path.join(tmp, MANIFEST_FILE), "w") as f:
                    json.dump(manifest, f)

            retry_io(_stage, what="cache.stage")
            try:
                os.rename(tmp, d)
            except OSError:
                # racer committed first, or a stale partial dir squats the
                # name: clear an UNMARKED corpse once, else concede
                if self.complete(fp):
                    shutil.rmtree(tmp, ignore_errors=True)
                    return False
                shutil.rmtree(d, ignore_errors=True)
                os.rename(tmp, d)

            # _SUCCESS last: the commit point (checkpoint convention)
            def _commit():
                _fault.io_error(os.path.join(d, SUCCESS_MARK), "write")
                with open(os.path.join(d, SUCCESS_MARK), "w") as f:
                    f.write(str(fp))

            retry_io(_commit, what="cache.success")
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            _counter("error")
            return False
        _counter("put")
        self.evict_to_budget(protect=fp)
        return True

    # -- eviction / maintenance --
    def _lru_items(self) -> List[tuple]:
        """(mtime, kind, path, bytes) for every evictable unit: one entry
        dir or one backend xla file."""
        items = []
        ed = os.path.join(self.root, ENTRIES_DIR)
        for name in os.listdir(ed):
            d = os.path.join(ed, name)
            marker = os.path.join(d, SUCCESS_MARK)
            try:
                mtime = os.path.getmtime(
                    marker if os.path.exists(marker) else d)
            except OSError:
                continue
            items.append((mtime, "entry", d, _tree_bytes(d)))
        for dirpath, _dirs, files in os.walk(self.xla_dir):
            for f in files:
                p = os.path.join(dirpath, f)
                try:
                    items.append((os.path.getmtime(p), "xla", p,
                                  os.path.getsize(p)))
                except OSError:
                    pass
        items.sort()
        return items

    def evict_to_budget(self, budget_bytes: Optional[int] = None,
                        protect: Optional[str] = None) -> int:
        """LRU-evict until total bytes fit the budget; returns evictions.
        ``protect`` pins one fingerprint (the entry just written) so a
        budget smaller than a single entry cannot evict its own write."""
        budget = self.budget_bytes if budget_bytes is None else budget_bytes
        if budget is None:
            return 0
        items = self._lru_items()
        total = sum(sz for _, _, _, sz in items)
        evicted = 0
        for _mtime, kind, path, sz in items:
            if total <= budget:
                break
            if protect and kind == "entry" \
                    and os.path.basename(path) == str(protect):
                continue
            if kind == "entry":
                shutil.rmtree(path, ignore_errors=True)
            else:
                try:
                    os.remove(path)
                except OSError:
                    continue
            total -= sz
            evicted += 1
            _counter("evict")
        return evicted

    def entries(self) -> List[dict]:
        """One summary dict per entry (cache_ctl ls/verify)."""
        out = []
        ed = os.path.join(self.root, ENTRIES_DIR)
        for name in sorted(os.listdir(ed)):
            d = os.path.join(ed, name)
            rec = {"fingerprint": name, "dir": d,
                   "complete": os.path.exists(os.path.join(d, SUCCESS_MARK)),
                   "bytes": _tree_bytes(d)}
            try:
                with open(os.path.join(d, MANIFEST_FILE)) as f:
                    rec["manifest"] = json.load(f)
            except (OSError, ValueError):
                rec["manifest"] = None
            out.append(rec)
        return out

    def verify_entry(self, fp: str) -> str:
        """'ok' | 'incomplete' | 'corrupt:<why>' — read-only integrity
        check (no quarantine, no counters; ``get`` does those)."""
        d = self.entry_dir(fp)
        if not os.path.exists(os.path.join(d, SUCCESS_MARK)):
            return "incomplete"
        try:
            with open(os.path.join(d, MANIFEST_FILE)) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as exc:
            return f"corrupt:manifest ({exc})"
        try:
            with open(os.path.join(d, PROGRAM_FILE), "rb") as f:
                blob = f.read()
        except OSError as exc:
            return f"corrupt:payload ({exc})"
        if hashlib.sha256(blob).hexdigest() != manifest.get("program_sha256"):
            return "corrupt:checksum mismatch"
        return "ok"

    def prune(self, budget_bytes: Optional[int] = None) -> dict:
        """Drop incomplete/corrupt entries and stale tmp dirs, then evict
        to budget.  Returns a report dict."""
        removed = []
        for rec in self.entries():
            status = self.verify_entry(rec["fingerprint"])
            if status != "ok":
                shutil.rmtree(rec["dir"], ignore_errors=True)
                removed.append({"fingerprint": rec["fingerprint"],
                                "status": status})
        tmp_root = os.path.join(self.root, TMP_DIR)
        for name in os.listdir(tmp_root):
            shutil.rmtree(os.path.join(tmp_root, name), ignore_errors=True)
        evicted = self.evict_to_budget(budget_bytes)
        return {"removed": removed, "evicted": evicted,
                "stats": self.stats()}

    def clear(self) -> None:
        for d in (ENTRIES_DIR, XLA_DIR, SERVING_DIR, TMP_DIR):
            p = os.path.join(self.root, d)
            shutil.rmtree(p, ignore_errors=True)
            os.makedirs(p, exist_ok=True)

    def stats(self) -> Dict[str, object]:
        recs = self.entries()
        return {
            "root": self.root,
            "budget_mb": (None if self.budget_bytes is None
                          else round(self.budget_bytes / (1 << 20), 3)),
            "entries": len(recs),
            "complete": sum(1 for r in recs if r["complete"]),
            "entry_bytes": sum(r["bytes"] for r in recs),
            "xla_bytes": _tree_bytes(self.xla_dir),
            "serving_manifests": len(os.listdir(
                os.path.join(self.root, SERVING_DIR))),
        }
