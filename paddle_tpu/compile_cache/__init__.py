"""paddle_tpu.compile_cache — persistent, cross-process compilation cache.

Every process today pays full XLA compilation from zero: ``bench.py``'s
~10x compile overhead before steady state, every elastic-supervisor
generation recompiling the exact program the dead generation ran, every
serving restart re-AOT-compiling its whole bucket set.  This package makes
compiled programs a durable artifact:

 - :mod:`fingerprint` — a stable content hash over the ProgramDesc + jit
   configuration + toolchain, invariant to variable-name noise;
 - :mod:`store` — an on-disk artifact store (atomic ``_SUCCESS`` commits,
   LRU size budget, corruption-tolerant loads) that also hosts jax's
   persistent compilation cache for the backend executables;
 - this module — process-level wiring: the env-driven singleton and the
   Executor-facing probe API.

Env contract::

    PADDLE_COMPILE_CACHE_DIR        enable, rooted here
    PADDLE_COMPILE_CACHE_BUDGET_MB  optional LRU size budget

Operate it with ``tools/cache_ctl.py`` (ls/stats/verify/prune/clear).
"""

from __future__ import annotations

import os
from typing import Optional

from .fingerprint import program_fingerprint, program_signature
from .store import CompileCacheStore

__all__ = [
    "program_fingerprint", "program_signature", "CompileCacheStore",
    "get_store", "configure", "disable", "reset", "executor_probe",
]

ENV_DIR = "PADDLE_COMPILE_CACHE_DIR"
ENV_BUDGET = "PADDLE_COMPILE_CACHE_BUDGET_MB"

# _UNSET = env not yet consulted (same late-binding contract as
# fluid.fault: a subprocess that sets PADDLE_COMPILE_CACHE_DIR before
# first executor use is honored without import-order dependencies)
_UNSET = object()
_store = _UNSET


def get_store() -> Optional[CompileCacheStore]:
    """The process-wide store, built lazily from the env; None = disabled."""
    global _store
    if _store is _UNSET:
        d = os.environ.get(ENV_DIR, "").strip()
        if not d:
            _store = None
        else:
            budget = os.environ.get(ENV_BUDGET, "").strip() or None
            try:
                _store = CompileCacheStore(d, budget)
                _store.enable_backend_cache()
            except Exception:
                _store = None  # an unusable cache dir must not fail runs
    return _store


def configure(root: str,
              budget_mb: Optional[float] = None) -> CompileCacheStore:
    """Enable programmatically (overrides the env)."""
    global _store
    _store = CompileCacheStore(root, budget_mb)
    _store.enable_backend_cache()
    return _store


def disable() -> None:
    global _store
    _store = None


def reset() -> None:
    """Back to the unconsulted state (env honored on next use) and detach
    the backend cache dir.  Test-harness hook."""
    global _store
    if _store not in (None, _UNSET):
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:
            pass
    _store = _UNSET


# ---------------------------------------------------------------------------
# Executor-facing probe
# ---------------------------------------------------------------------------


class _Probe:
    """One pending compile: created at store-lookup time (before tracing),
    finished after the first dispatch (which is where jax actually traces
    AND compiles).  ``finish`` is idempotent and never raises — cache
    bookkeeping must not fail the run it measures."""

    __slots__ = ("store", "fp", "hit", "done", "manifest")

    def __init__(self, store: CompileCacheStore, fp: str, hit: bool,
                 manifest: Optional[dict] = None):
        self.store = store
        self.fp = fp
        self.hit = hit
        self.manifest = manifest
        self.done = False

    def finish(self, seconds: float, program=None,
               meta: Optional[dict] = None) -> None:
        if self.done:
            return
        self.done = True
        try:
            from ..fluid import profiler as _prof
            from .. import observe

            _prof.record_counter("compile_cache.compile_seconds",
                                 inc=round(float(seconds), 6))
            # warm starts and cold compiles belong in the run-event stream
            # next to guardian trips and generation restarts — a restarted
            # generation's cache hits are the proof its recovery was cheap
            observe.emit("compile_cache.hit" if self.hit
                         else "compile_cache.miss",
                         fingerprint=self.fp[:12],
                         first_dispatch_s=round(float(seconds), 6),
                         kind=(meta or {}).get("kind"))
            if self.hit and isinstance(self.manifest, dict) \
                    and isinstance(self.manifest.get("memory"), dict):
                # the per-executable memory table persisted at compile
                # time: a warm start republishes the memory.peak_bytes
                # gauge family WITHOUT re-lowering anything
                from ..observe import memory as _obsmem

                _obsmem.note_compiled_memory(
                    self.manifest["memory"],
                    mesh=self.manifest.get("mesh"),
                    kind=self.manifest.get("kind"),
                    n_steps=self.manifest.get("n_steps"), cached=True)
            if not self.hit and program is not None:
                m = dict(meta or {})
                m["compile_seconds"] = round(float(seconds), 6)
                self.store.put(self.fp, program.serialize_to_string(), m)
        except Exception:
            try:
                from ..fluid import profiler as _prof

                _prof.record_counter("compile_cache.error")
            except Exception:
                pass


def executor_probe(program, feed_arrays=None, fetch_names=None,
                   extra=None, spec_table=None) -> Optional[_Probe]:
    """Consult the store for an executor-shaped program specialization.

    Called by ``Executor.run``/``run_steps`` (and the SPMD step/window
    runners, which also pass their mesh-derived ``spec_table``) right
    before building a fresh jit entry (i.e. on every in-process cache
    miss).  Returns None when the cache is disabled or fingerprinting
    fails; otherwise a :class:`_Probe` whose hit/miss was already
    counted."""
    store = get_store()
    if store is None:
        return None
    try:
        feeds = [(k, tuple(v.shape), str(v.dtype))
                 for k, v in sorted((feed_arrays or {}).items())]
        fp = program_fingerprint(program, feeds=feeds,
                                 fetches=list(fetch_names or []),
                                 extra=extra, spec_table=spec_table)
        manifest = store.get(fp)
        from .. import observe

        # every event the run emits from here on correlates to this program
        observe.note_program(fp[:12])
        return _Probe(store, fp, manifest is not None, manifest)
    except Exception:
        try:
            from ..fluid import profiler as _prof

            _prof.record_counter("compile_cache.error")
        except Exception:
            pass
        return None
