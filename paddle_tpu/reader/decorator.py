"""Reader decorators (ref: python/paddle/reader/decorator.py:36-443)."""

from __future__ import annotations

import itertools
import random
from queue import Empty, Full, Queue
from threading import Event, Thread

__all__ = ["PipeReader", "map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "cache", "device_buffered"]


class _WorkerError:
    """Exception captured in a reader worker thread, queued so the CONSUMER
    re-raises it.  Without this, a raising worker dies before posting the
    end sentinel and the consumer deadlocks on q.get() forever."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size, seed=None):
    """Buffered shuffle.  ``seed`` pins the permutation to a private
    ``random.Random`` (NOT the global module state some other library may
    have reseeded), so data order is reproducible — and therefore
    recordable/replayable by the guardian's flight recorder.

    Each call of the returned reader is one EPOCH, and epoch ``e``'s RNG
    is derived from ``(seed, e)`` — not one stream threaded across
    epochs — so epoch N's order is reproducible directly: a restarted
    run calls ``data_reader.set_epoch(N)`` and gets epoch N's exact
    permutation without replaying epochs ``0..N-1`` (the resumable-
    shuffle contract ``paddle_tpu.data`` builds on; one shared stream
    silently drifts the order on every restart).  A fresh decorator
    starts at epoch 0, so same-seed decorators still agree.  String
    seeding hashes via sha512, so the order also reproduces across
    processes.  ``seed=None`` keeps independent randomness."""
    epoch_box = [0]

    def set_epoch(epoch):
        epoch_box[0] = int(epoch)

    def data_reader():
        epoch = epoch_box[0]
        epoch_box[0] = epoch + 1
        rng = random.Random(None if seed is None else f"{seed}|{epoch}")
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            rng.shuffle(buf)
            for b in buf:
                yield b

    data_reader.set_epoch = set_epoch
    return data_reader


def chain(*readers):
    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in zip_longest_check(*rs):
                yield sum(list(map(make_tuple, outputs)), ())

    def zip_longest_check(*iters):
        sentinel = object()
        for row in itertools.zip_longest(*iters, fillvalue=sentinel):
            if sentinel in row:
                raise ComposeNotAligned("readers have different lengths")
            yield row

    return reader


def buffered(reader, size):
    class EndSignal:
        pass

    end = EndSignal()

    def read_worker(r, q):
        try:
            for d in r:
                q.put(d)
        except BaseException as exc:
            # surface the failure to the consumer instead of dying
            # silently (which would hang the consumer's q.get() forever)
            q.put(_WorkerError(exc))
        else:
            q.put(end)

    def data_reader():
        r = reader()
        q = Queue(maxsize=size)
        t = Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            if isinstance(e, _WorkerError):
                raise e.exc
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel-map a reader with worker threads (ref: decorator.py:243).

    A raising ``mapper`` (or source reader) propagates to the consumer
    instead of silently killing its thread — which would leave ``end``
    unposted and the consumer blocked on ``out_q.get()`` forever.  On
    error the consumer flips an abort event; feeder and workers use
    timeout-puts so a full queue can never wedge the drain."""
    end = object()

    def data_reader():
        in_q = Queue(buffer_size)
        out_q = Queue(buffer_size)
        abort = Event()

        def _put(q, item) -> bool:
            while not abort.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except Full:
                    continue
            return False

        def feed():
            try:
                for sample in reader():
                    if not _put(in_q, sample):
                        return
            except BaseException as exc:
                _put(out_q, _WorkerError(exc))
                return
            for _ in range(process_num):
                if not _put(in_q, end):
                    return

        def work():
            while True:
                try:
                    sample = in_q.get(timeout=0.05)
                except Empty:
                    if abort.is_set():
                        return
                    continue
                if sample is end:
                    _put(out_q, end)
                    return
                try:
                    result = mapper(sample)
                except BaseException as exc:
                    _put(out_q, _WorkerError(exc))
                    return
                if not _put(out_q, result):
                    return

        feeder = Thread(target=feed)
        feeder.daemon = True
        feeder.start()
        workers = []
        for _ in range(process_num):
            w = Thread(target=work)
            w.daemon = True
            w.start()
            workers.append(w)
        finished = 0
        try:
            while finished < process_num:
                sample = out_q.get()
                if isinstance(sample, _WorkerError):
                    raise sample.exc
                if sample is end:
                    finished += 1
                else:
                    yield sample
        finally:
            # stops on error AND on an early-exiting consumer (firstn):
            # the remaining threads drain via their timeout loops instead
            # of blocking forever on a queue nobody reads
            abort.set()

    return data_reader


def device_buffered(reader, size=None, place=None):
    """Like :func:`buffered`, but the worker thread also issues the
    host→device transfer for every array in the sample, so samples arrive
    at the consumer already device-resident — the H2D copy overlaps the
    consumer's compute instead of serializing with it (the Executor passes
    pre-placed jax arrays straight through, ``Executor._coerce_feed``).

    ``size`` bounds the number of in-flight staged samples (default
    ``PADDLE_TPU_PREFETCH_DEPTH``); worker exceptions propagate to the
    consumer and an early-exiting consumer never wedges the worker — the
    same contract as :func:`buffered`/:func:`xmap_readers`.  For staging
    whole ``run_steps`` windows, use
    :class:`paddle_tpu.fluid.prefetch.DevicePrefetcher`, which this
    delegates to."""

    def data_reader():
        from ..fluid.prefetch import iter_device_samples

        yield from iter_device_samples(reader, depth=size, place=place)

    return data_reader


def cache(reader):
    all_data = []

    def cache_reader():
        if not all_data:
            all_data.extend(reader())
        for d in all_data:
            yield d

    return cache_reader


class PipeReader:
    """Stream records from a shell command's stdout (ref:
    python/paddle/reader/decorator.py:438 — used to read sharded datasets
    from `hadoop fs -cat` style pipes).  ``get_line`` yields decoded lines
    split on ``line_break``; callers parse each into a sample."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        if not isinstance(command, str):
            raise TypeError("PipeReader command must be a string")
        import subprocess

        self.command = command
        self.bufsize = bufsize
        self.file_type = file_type
        self.process = subprocess.Popen(
            command.split(" "), bufsize=bufsize, stdout=subprocess.PIPE)
        if file_type == "gzip":
            import zlib

            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        elif file_type != "plain":
            raise TypeError(f"file_type {file_type} is not allowed")

    def close(self):
        if self.process.poll() is None:
            self.process.terminate()
        if self.process.stdout and not self.process.stdout.closed:
            self.process.stdout.close()
        self.process.wait()

    def get_line(self, cut_lines=True, line_break="\n"):
        import codecs
        import zlib

        # incremental decoder: a multibyte UTF-8 char split across the
        # bufsize boundary must not be dropped
        decoder = codecs.getincrementaldecoder("utf-8")("ignore")
        remained = ""
        try:
            while True:
                buff = self.process.stdout.read(self.bufsize)
                if not buff:
                    break
                if self.file_type == "gzip":
                    out = [self.dec.decompress(buff)]
                    # concatenated members (one per shard in `cat *.gz`
                    # pipes): restart the decompressor on leftover bytes —
                    # but only when they start a real member; gzip(1)
                    # tolerates trailing garbage (block padding) and so
                    # must we
                    while self.dec.eof and \
                            self.dec.unused_data.startswith(b"\x1f\x8b"):
                        rest = self.dec.unused_data
                        self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
                        out.append(self.dec.decompress(rest))
                    buff = b"".join(out)
                decomp_buff = decoder.decode(buff)
                if not cut_lines:
                    yield decomp_buff
                    continue
                lines = (remained + decomp_buff).split(line_break)
                remained = lines.pop(-1)
                for line in lines:
                    yield line
            remained += decoder.decode(b"", final=True)
            if remained:
                yield remained
        finally:
            # consumers that stop early (firstn) must not leak the child
            self.close()
