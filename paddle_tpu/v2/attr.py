"""v2 attr namespace (ref: python/paddle/v2/attr.py — Param/Extra/Hook
aliases over trainer_config_helpers.attrs)."""

from ..trainer_config_helpers.attrs import (ExtraAttr,  # noqa: F401
                                            ExtraLayerAttribute,
                                            ParameterAttribute, ParamAttr)

Param = ParamAttr
Extra = ExtraAttr


class Hook:
    """ref attrs.py HookAttribute (pruning hooks) — accepted for config
    compatibility; the Fluid substrate has no parameter-hook stage."""

    def __init__(self, type=None, **kwargs):  # noqa: A002
        self.type = type


HookAttribute = Hook

__all__ = ["Param", "Extra", "Hook", "ParamAttr", "ParameterAttribute",
           "ExtraAttr", "ExtraLayerAttribute", "HookAttribute"]
