"""v2 master client facade (ref: python/paddle/v2/master/client.py — a
ctypes shim over the Go master's C library: set_dataset partitions
recordio chunks on an etcd-backed task queue, next_record streams records
with fault-tolerant task accounting).

Here the fault-tolerant task queue is the in-process TaskDispatcher
(parallel/master.py — timeout requeue, failure caps, snapshot/recover:
the go/master service redesigned for the jax.distributed world, where
coordination rides the distributed runtime rather than etcd).  The
client keeps the reference's call surface so v2 reader loops run
unchanged: set_dataset -> paddle_start_get_records(pass) ->
next_record until (None, -1).
"""

from __future__ import annotations

import threading

from ...native import RecordIOScanner
from ...parallel.master import TaskDispatcher

__all__ = ["client"]


class client:
    def __init__(self, etcd_endpoints=None, timeout_sec=None, buf_size=0,
                 chunks_per_task=1, snapshot_path=None):
        self._dispatcher = None
        self._chunks_per_task = int(chunks_per_task)
        self._snapshot_path = snapshot_path
        self._task = None
        self._scanner = None
        self._chunk_idx = 0
        # save-model arbitration window (per client ≡ per master, the
        # reference's scope — one Go master per job)
        self._save_lock = threading.Lock()
        self._save_until = 0.0

    def _drop_cursor(self):
        """Abandon any in-flight task/scanner (dataset or pass changed
        mid-stream; the old cursor must not leak records into the new
        configuration)."""
        if self._scanner is not None:
            self._scanner.close()
            self._scanner = None
        self._task = None
        self._chunk_idx = 0

    def set_dataset(self, paths):
        """Partition recordio files into dispatcher tasks (ref
        paddle_set_dataset; the Go master splits by chunk — files here,
        the dispatcher's own unit)."""
        self._drop_cursor()
        self._dispatcher = TaskDispatcher(
            list(paths), chunks_per_task=self._chunks_per_task,
            snapshot_path=self._snapshot_path)

    def paddle_start_get_records(self, pass_id):
        if self._dispatcher is None:
            raise ValueError("set_dataset must be called first")
        self._drop_cursor()
        if pass_id > 0:
            self._dispatcher.start_new_pass()

    def next_record(self):
        """(record_bytes, 0) per record; ("", 0) for an empty record;
        (None, -1) once the pass is drained (the reference's
        end-of-pass error code)."""
        if self._dispatcher is None:
            raise ValueError("set_dataset must be called first")
        while True:
            if self._scanner is not None:
                try:
                    rec = next(self._scanner)
                    return (rec, 0)
                except StopIteration:
                    self._scanner.close()
                    self._scanner = None
                    self._chunk_idx += 1
                except Exception:
                    # corrupt chunk: report the task failed so the
                    # dispatcher's failure-cap machinery engages (requeue
                    # up to failure_max, then discard) instead of
                    # wedging this client on the same broken scanner
                    self._scanner.close()
                    self._scanner = None
                    if self._task is not None:
                        self._dispatcher.task_failed(self._task.task_id)
                        self._task = None
                        self._chunk_idx = 0
                    continue
            if self._task is not None:
                if self._chunk_idx < len(self._task.chunks):
                    try:
                        self._scanner = iter(RecordIOScanner(
                            self._task.chunks[self._chunk_idx]))
                    except Exception:
                        # unreadable chunk: same failure path as a
                        # corrupt record mid-scan
                        self._dispatcher.task_failed(self._task.task_id)
                        self._task = None
                        self._chunk_idx = 0
                    continue
                self._dispatcher.task_finished(self._task.task_id)
                self._task = None
            t = self._dispatcher.get_task()
            if t is None:
                return (None, -1)
            self._task = t
            self._chunk_idx = 0

    def request_save_model(self, trainer_id, block_ms):
        """First caller in a block window saves; others are told no (ref
        paddle_request_save_model semantics, single-process scope)."""
        import time

        with self._save_lock:
            now = time.time()
            if now >= self._save_until:
                self._save_until = now + float(block_ms) / 1000.0
                return 1
            return 0

    def release(self):
        self._drop_cursor()
        self._dispatcher = None
        self._save_until = 0.0
