from .client import client  # noqa: F401

__all__ = ["client"]
