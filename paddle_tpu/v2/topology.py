"""v2 Topology (ref: python/paddle/v2/topology.py — wraps the parsed
ModelConfig proto: serialize for the trainer, enumerate data layers for
feeding).  The Fluid Program IS the model config on this substrate, so
Topology wraps the output layers' program and answers the same
questions: proto() -> the serialized program, data_layers() ->
name-ordered feed layers, get_layer_proto(name) -> the op/var desc."""

from __future__ import annotations

__all__ = ["Topology"]


class Topology:
    def __init__(self, layers, extra_layers=None):
        if not isinstance(layers, (list, tuple)):
            layers = [layers]
        self.layers = list(layers)
        self.extra_layers = list(extra_layers or [])
        programs = {v.block.program for v in
                    self.layers + self.extra_layers}
        if len(programs) != 1:
            raise ValueError("all topology layers must come from one "
                             "program")
        self._program = programs.pop()

    @property
    def program(self):
        return self._program

    def proto(self):
        """The serialized model config (the reference returns the
        ModelConfig proto bytes; here the program desc)."""
        return self._program.to_string()

    def data_layers(self):
        """name -> data Variable, in declaration order (ref returns the
        input layer configs used to build the DataFeeder)."""
        gb = self._program.global_block()
        return {v.name: v for v in gb.vars.values()
                if getattr(v, "is_data", False)}

    def data_type(self):
        """[(name, dtype)] for the feed layers."""
        return [(name, str(v.dtype))
                for name, v in self.data_layers().items()]

    def get_layer_proto(self, name):
        gb = self._program.global_block()
        v = gb.vars.get(name)
        return v
