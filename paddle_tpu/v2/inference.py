"""v2 inference (ref: python/paddle/v2/inference.py — paddle.infer runs a
topology's output layer over input batches with trained parameters).

Two modes, like the reference:
 - plain output layers: feed the input batch, fetch the outputs
   (field="id" returns per-row argmax ids like the reference);
 - a trainer_config_helpers GenerationResult (from beam_search):
   auto-feed the bos-seeded init tensors and return the decoded
   hypotheses as (nested ids per source, scores), honoring
   num_results_per_sample.
"""

from __future__ import annotations

import threading

import numpy as np

from .. import fluid
from ..trainer_config_helpers import GenerationResult
from ._feeding import accel as _accel
from ._feeding import build_feed

__all__ = ["infer", "Inference"]


class Inference:
    def __init__(self, output_layer, parameters=None):
        outs = output_layer if isinstance(output_layer, (list, tuple)) \
            else [output_layer]
        self._gen = outs[0] if isinstance(outs[0], GenerationResult) \
            else None
        self._outputs = list(outs)
        first = self._gen.ids if self._gen is not None else outs[0]
        self._program = first.block.program
        self._place = fluid.CPUPlace() if not _accel() else fluid.TPUPlace()
        self._exe = fluid.Executor(self._place)
        self._parameters = parameters
        self._install(parameters)

    @staticmethod
    def _install(parameters):
        """Copy an explicit Parameters/from_tar mapping into the scope.
        A live Parameters is a view over the scope, so installing it once
        suffices; a DETACHED mapping (from_tar) carries its own values and
        is re-installed on every run() (like the reference, which owns a
        GradientMachine initialized from the parameters) so training in
        between cannot silently change what infer uses."""
        if parameters is not None and hasattr(parameters, "names"):
            from ..fluid.executor import global_scope

            scope = global_scope()
            for n in parameters.names():
                scope.set(n, np.asarray(parameters.get(n)))

    def _feed(self, input, feeding):
        skip = ()
        if self._gen is not None:
            skip = (self._gen.init_ids_name, self._gen.init_scores_name)
        return build_feed(self._program, input, feeding, skip=skip)

    def run(self, input, feeding=None, field="value"):
        from .parameters import _LoadedParameters

        if isinstance(self._parameters, _LoadedParameters):
            # detached values: the scope may have been retrained since the
            # last call — every run must infer with the tar's weights
            self._install(self._parameters)
        feed = self._feed(input, feeding)
        if self._gen is not None:
            feed.update(self._gen.init_feeds(len(input)))
            ids_t, scores_t = self._exe.run(
                self._program, feed=feed,
                fetch_list=[self._gen.ids, self._gen.scores],
                return_numpy=False)
            seq_lens = ids_t.recursive_sequence_lengths()
            src_counts, hyp_lens = seq_lens[0], seq_lens[-1]
            flat = np.asarray(ids_t).ravel().tolist()
            sflat = np.asarray(scores_t).ravel().tolist()
            hyps, scores, off = [], [], 0
            for ln in hyp_lens:
                hyps.append(flat[off:off + ln])
                scores.append(sflat[off + ln - 1] if ln else 0.0)
                off += ln
            # group hypotheses per source by the decode LoD's own counts
            grouped, gscores, h = [], [], 0
            keep = self._gen.n_results or None
            for cnt in src_counts:
                grouped.append(hyps[h:h + cnt][:keep])
                gscores.append(scores[h:h + cnt][:keep])
                h += cnt
            return (grouped, gscores) if field != "id" else grouped
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=self._outputs)
        if field == "id":
            return [np.argmax(np.asarray(o), axis=-1) for o in outs] \
                if len(outs) > 1 else np.argmax(np.asarray(outs[0]),
                                                axis=-1)
        return outs[0] if len(outs) == 1 else outs


_INFER_CACHE = {}
_INFER_LOCK = threading.Lock()


def infer(output_layer, parameters=None, input=None, feeding=None,
          field="value"):
    """ref v2/inference.py infer().  Repeated calls with the same output
    layer(s) reuse one Inference — the executor's jit cache is
    per-instance, so a fresh instance per batch would retrace and
    recompile the whole program every call.  Parameters are re-installed
    into the scope on every call (the cache key holds the output vars
    alive, so their ids cannot be recycled)."""
    outs = output_layer if isinstance(output_layer, (list, tuple)) \
        else [output_layer]
    key = tuple(id(o) for o in outs)
    with _INFER_LOCK:
        inf = _INFER_CACHE.get(key)
        if inf is None:
            if len(_INFER_CACHE) > 8:
                _INFER_CACHE.clear()
            inf = _INFER_CACHE[key] = Inference(output_layer, parameters)
            inf._last_params = parameters
    if parameters is not inf._last_params:
        # a DIFFERENT parameters object: install it.  (A live Parameters
        # is a view over the scope — re-installing the same object is a
        # no-op; only a detached from_tar mapping carries new values.)
        Inference._install(parameters)
        inf._last_params = parameters
        inf._parameters = parameters  # run() re-installs detached mappings
    return inf.run(input, feeding=feeding, field=field)
