"""v2 image utilities (ref: python/paddle/v2/image.py — load / resize /
crop / flip / simple_transform over HWC ndarrays; the reference backs
them with cv2, here PIL handles decode+resize and numpy does the rest,
so the no-cv2 environment keeps the same surface)."""

from __future__ import annotations

import io
import tarfile

import numpy as np

__all__ = [
    "batch_images_from_tar", "load_image_bytes", "load_image",
    "resize_short", "to_chw", "center_crop", "random_crop",
    "left_right_flip", "simple_transform", "load_and_transform",
]


def _pil():
    from PIL import Image

    return Image


def load_image_bytes(bytes, is_color=True):  # noqa: A002 - v2 API name
    im = _pil().open(io.BytesIO(bytes))
    im = im.convert("RGB" if is_color else "L")
    arr = np.asarray(im)
    return arr


def load_image(file, is_color=True):  # noqa: A002 - v2 API name
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color=is_color)


def resize_short(im, size):
    """Resize so the SHORTER edge is ``size`` (HWC, bicubic like the
    reference's INTER_CUBIC)."""
    h, w = im.shape[:2]
    if h > w:
        h_new, w_new = int(size * h / w), int(size)
    else:
        h_new, w_new = int(size), int(size * w / h)
    pim = _pil().fromarray(np.ascontiguousarray(im))
    pim = pim.resize((w_new, h_new), _pil().BICUBIC)
    return np.asarray(pim)


def to_chw(im, order=(2, 0, 1)):
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1, :] if len(im.shape) == 3 else im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short -> (random crop + coin-flip mirror | center crop) ->
    CHW float32, optionally mean-subtracted (per-channel or
    elementwise)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """ref image.py batch_images_from_tar: read images out of a tar,
    pickle (image-bytes, label) batches next to it, return the meta
    file path."""
    import pickle
    import os

    out_path = f"{data_file}_{dataset_name}_batch"
    meta = os.path.join(out_path, "batch_meta")
    if os.path.exists(meta):
        return meta
    os.makedirs(out_path, exist_ok=True)
    data, labels, file_id, names = [], [], 0, []
    with tarfile.open(data_file) as tf:
        for mem in tf.getmembers():
            if mem.name not in img2label:
                continue
            data.append(tf.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                batch_name = os.path.join(out_path, f"batch_{file_id}")
                with open(batch_name, "wb") as f:
                    pickle.dump({"data": data, "label": labels}, f)
                names.append(batch_name)
                file_id += 1
                data, labels = [], []
    if data:
        batch_name = os.path.join(out_path, f"batch_{file_id}")
        with open(batch_name, "wb") as f:
            pickle.dump({"data": data, "label": labels}, f)
        names.append(batch_name)
    with open(meta, "w") as f:
        f.write("\n".join(names))
    return meta
