"""v2 op namespace (ref: python/paddle/v2/op.py — elementwise math over
layer outputs; the reference registers unary math ops and patches
arithmetic onto LayerOutput).  Layer outputs here are fluid Variables,
whose arithmetic is already patched (fluid math_op_patch); the unary
functions delegate to the fluid activation layers."""

from ..fluid import layers as _fl

__all__ = ["exp", "log", "abs", "sigmoid", "tanh", "square", "relu",
           "sqrt", "ceil", "floor", "reciprocal", "softmax"]


def _unary(name):
    fn = getattr(_fl, name)

    def op(x):
        return fn(x)

    op.__name__ = name
    op.__doc__ = f"Elementwise {name} over a layer output (ref v2/op.py)."
    return op


exp = _unary("exp")
log = _unary("log")
abs = _unary("abs")  # noqa: A001 - v2 API name
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
square = _unary("square")
relu = _unary("relu")
sqrt = _unary("sqrt")
ceil = _unary("ceil")
floor = _unary("floor")
reciprocal = _unary("reciprocal")
softmax = _unary("softmax")
