"""v2 Parameters (ref: python/paddle/v2/parameters.py — a name->ndarray
dict view over the GradientMachine's parameters; here a view over the
fluid global scope, where Fluid keeps the same state)."""

from __future__ import annotations

import numpy as np

__all__ = ["Parameters", "create"]


class Parameters:
    def __init__(self, program, scope=None):
        from ..fluid.executor import global_scope

        self._program = program
        self._scope = scope or global_scope()

    def names(self):
        return [p.name for p in
                self._program.global_block().all_parameters()]

    def keys(self):
        return self.names()

    def has_key(self, key):
        return key in self.names()

    def __contains__(self, key):
        return self.has_key(key)

    def get(self, key):
        return np.asarray(self._scope.get(key))

    def __getitem__(self, key):
        return self.get(key)

    def set(self, key, value):
        self._scope.set(key, np.asarray(value))

    def __setitem__(self, key, value):
        self.set(key, value)

    def to_tar(self, f):
        """ref parameters.py to_tar — the v2 checkpoint container.  The
        substrate's native format is one .npz; keep the method name so v2
        scripts save/restore unchanged."""
        np.savez(f, **{n: self.get(n) for n in self.names()})

    @staticmethod
    def from_tar(f):
        data = np.load(f)
        loaded = _LoadedParameters({n: data[n] for n in data.files})
        return loaded

    def init_from_tar(self, f):
        data = np.load(f)
        for n in data.files:
            if self.has_key(n):
                self.set(n, data[n])


class _LoadedParameters(dict):
    """from_tar result: a plain name->ndarray mapping that also answers
    the Parameters surface (names/get) so infer(parameters=...) installs
    it into the scope like a live Parameters object."""

    def get(self, key):  # noqa: A003 - v2 API name
        return self[key]

    def names(self):
        return list(self.keys())

    def has_key(self, key):
        return key in self


def create(cost):
    """ref parameters.py create(topology): parameters of cost's program."""
    return Parameters(cost.block.program)
