"""v2 evaluator namespace (ref: python/paddle/v2/evaluator.py — wraps each
trainer_config_helpers evaluator, dropping the ``_evaluator`` suffix:
``paddle.v2.evaluator.classification_error(input=.., label=..)``).

Calling one inside a topology registers a metric variable the v2 trainer
fetches each batch; values arrive on ``event.metrics``.
"""

from __future__ import annotations

from ..trainer_config_helpers import evaluators as _evs

__all__ = []


def _initialize():
    for ev_name in [n for n in _evs.__all__ if n.endswith("_evaluator")]:
        new_name = ev_name[: -len("_evaluator")]
        fn = getattr(_evs, ev_name)
        globals()[new_name] = fn
        __all__.append(new_name)


_initialize()
