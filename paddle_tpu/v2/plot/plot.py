"""v2 training-curve plotter (ref: python/paddle/v2/plot/plot.py —
Ploter collects (step, value) series per title and renders via
matplotlib/IPython in notebooks; DISABLE_PLOT=True keeps headless test
runs import-safe).  Same surface; matplotlib is imported lazily and the
class degrades to a data collector when it (or a display) is missing."""

from __future__ import annotations

import os

__all__ = ["PlotData", "Ploter"]


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {title: PlotData() for title in args}
        self.plt = None
        if not self.__plot_is_disabled__():
            try:
                import matplotlib

                # headless-safe WITHOUT hijacking an interactive
                # session's backend: only switch to Agg when the current
                # backend needs a display that is not there (a notebook's
                # inline backend has no DISPLAY either and must be kept)
                bk = matplotlib.get_backend().lower()
                # macosx uses Cocoa (no X11), qt/gtk may ride Wayland
                needs_x11 = any(k in bk for k in ("tk", "qt", "gtk", "wx"))
                headless = not os.environ.get("DISPLAY") and \
                    not os.environ.get("WAYLAND_DISPLAY")
                if needs_x11 and headless:
                    matplotlib.use("Agg")
                import matplotlib.pyplot as plt

                self.plt = plt
            except Exception:
                self.plt = None  # collector-only mode

    def __plot_is_disabled__(self):
        return os.environ.get("DISABLE_PLOT") == "True"

    def append(self, title, step, value):
        data = self.__plot_data__[title]
        data.append(step, value)

    def plot(self, path=None):
        if self.plt is None:
            return
        titles = []
        for title in self.__args__:
            data = self.__plot_data__[title]
            if len(data.step) > 0:
                titles.append(title)
                self.plt.plot(data.step, data.value)
        self.plt.legend(titles, loc="upper left")
        if path is not None:
            self.plt.savefig(path)
        self.plt.gcf().clear()

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
