from .plot import PlotData, Ploter  # noqa: F401

__all__ = ["PlotData", "Ploter"]
