"""paddle.v2 compatibility facade (ref: python/paddle/v2/__init__.py).

The v2 generation drove a C++ GradientMachine via swig
(ref: v2/trainer.py:37 SGD, legacy/gserver/gradientmachines/
GradientMachine.h:75); every capability it exposed — layer config, SGD
event loop, parameters dict — is a subset of the Fluid surface, so this
facade lowers the v2 API onto Fluid programs and the TPU executor.
A v2-era training script (init / layer graph / parameters.create /
trainer.SGD(...).train(reader, event_handler)) runs unchanged.
"""

from __future__ import annotations

from .. import batch, reader  # reader composition is shared with v2
from ..trainer_config_helpers import (AdamOptimizer, AvgPooling,
                                      LinearActivation, MaxPooling,
                                      MomentumOptimizer, ReluActivation,
                                      SigmoidActivation, SoftmaxActivation,
                                      TanhActivation)
from . import activation, attr, data_type, evaluator, event, image, \
    inference, layer, master, op, optimizer, parameters, plot, pooling, \
    topology, trainer
from .inference import infer
from .topology import Topology

__all__ = ["init", "batch", "reader", "layer", "activation", "pooling",
           "data_type", "evaluator", "event", "optimizer", "parameters",
           "trainer", "inference", "infer", "master", "plot", "topology",
           "Topology", "image", "attr", "op"]


def init(use_gpu=False, trainer_count=1, **kwargs):
    """ref v2/__init__.py init(): swig_paddle.initPaddle arg marshalling.
    Device selection is the executor's Place on this substrate; accepted
    for script compatibility."""
    return None
