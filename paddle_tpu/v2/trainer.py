"""v2 SGD trainer + event loop (ref: python/paddle/v2/trainer.py:37 SGD,
:137 train — reader loop around a swig GradientMachine's
forwardBackward + ParameterUpdater).  Here the cost's Fluid program is the
topology, Optimizer.build().minimize is the update equation, and the Fluid
Executor runs the jitted step; the v2 event protocol (BeginPass /
BeginIteration / EndIteration / EndPass, trainer.test -> TestResult) is
preserved verbatim so v2 scripts' monitoring loops run unchanged."""

from __future__ import annotations

import numpy as np

from .. import fluid
from ._feeding import accel as _accel
from . import event as v2_event
from . import optimizer as v2_optimizer
from .parameters import Parameters

__all__ = ["SGD"]


class SGD:
    def __init__(self, cost, parameters, update_equation, extra_layers=None,
                 is_local=True, **kwargs):
        if not isinstance(parameters, Parameters):
            raise TypeError("parameters should be a Parameters object "
                            "(paddle.parameters.create(cost))")
        if not isinstance(update_equation, v2_optimizer.Optimizer):
            raise TypeError("update_equation must be a v2 Optimizer")
        self._cost = cost
        self._parameters = parameters
        self._program = cost.block.program
        self._startup = fluid.default_startup_program()
        update_equation.build().minimize(cost)
        self._extra = list(extra_layers or [])
        self._place = fluid.CPUPlace() if not _accel() else fluid.TPUPlace()
        self._exe = fluid.Executor(self._place)
        self._exe.run(self._startup)
        self._test_program = None

    def _feed(self, data_batch, feeding):
        """feeding: {data_layer_name: column index} (ref trainer.py:137
        DataFeeder contract).  Without it, columns map to the program's
        data layers in declaration order."""
        from ._feeding import build_feed

        return build_feed(self._program, data_batch, feeding)

    def _evaluator_fetches(self):
        """Evaluator entries registered in THIS program's topology
        (trainer_config_helpers.evaluators registry; stale entries from
        other sessions' programs are ignored)."""
        from ..trainer_config_helpers.evaluators import get_evaluators

        return [(n, v, cum) for n, v, cum in get_evaluators()
                if v.block.program is self._program]

    @staticmethod
    def _metric_value(out):
        """Scalar metrics report as float; vector metrics (column sums)
        keep their full value."""
        arr = np.asarray(out).reshape(-1)
        return float(arr[0]) if arr.size == 1 else arr

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        """ref trainer.py:137: for each pass, for each batch: feed,
        one train step, fire events.  Evaluators declared in the topology
        are fetched alongside the cost; batch values ride
        EndIteration.metrics, pass values ride EndPass.metrics (the
        reference's batch_evaluator / pass_evaluator pair: per-batch
        metrics average over the pass, cumulative ones report their final
        accumulated value)."""
        event_handler = event_handler or (lambda e: None)
        evals = self._evaluator_fetches()
        fetch = [self._cost] + [v for _, v, _ in evals]
        cumulative = {n for n, _, cum in evals if cum}
        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            pass_sums, pass_n = {}, 0
            for batch_id, data_batch in enumerate(reader()):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                outs = self._exe.run(
                    self._program, feed=self._feed(data_batch, feeding),
                    fetch_list=fetch)
                metrics = {n: self._metric_value(o)
                           for (n, _, _), o in zip(evals, outs[1:])}
                pass_n += 1
                for n, val in metrics.items():
                    pass_sums[n] = (val if n in cumulative
                                    else pass_sums.get(n, 0.0) + val)
                event_handler(v2_event.EndIteration(
                    pass_id, batch_id,
                    float(np.asarray(outs[0]).reshape(-1)[0]),
                    metrics=metrics))
            event_handler(v2_event.EndPass(
                pass_id, metrics={
                    n: (s if n in cumulative else s / max(pass_n, 1))
                    for n, s in pass_sums.items()}))

    def test(self, reader, feeding=None):
        """ref trainer.py:216: forward-only pass over the reader; returns
        the average cost plus declared evaluators' values as a
        TestResult (the reference evaluates them during the test pass)."""
        if self._test_program is None:
            self._test_program = self._program.clone(for_test=True)
        evals = self._evaluator_fetches()
        fetch = [self._cost] + [v for _, v, _ in evals]
        cumulative = {n for n, _, cum in evals if cum}
        costs, n = [], 0
        sums, batches = {}, 0
        for data_batch in reader():
            outs = self._exe.run(
                self._test_program, feed=self._feed(data_batch, feeding),
                fetch_list=fetch)
            costs.append(float(np.asarray(outs[0]).reshape(-1)[0])
                         * len(data_batch))
            n += len(data_batch)
            batches += 1
            for (name, _, _), o in zip(evals, outs[1:]):
                val = self._metric_value(o)
                sums[name] = (val if name in cumulative
                              else sums.get(name, 0.0) + val)
        metrics = {name: (s if name in cumulative else s / max(batches, 1))
                   for name, s in sums.items()}
        return v2_event.TestResult(cost=sum(costs) / max(n, 1),
                                   metrics=metrics)

    def save_parameter_to_tar(self, f):
        self._parameters.to_tar(f)


