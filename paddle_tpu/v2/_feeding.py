"""Shared v2 feeding/device helpers (Trainer and Inference build feeds
from reader rows with the same DataFeeder contract — ref trainer.py:137,
inference.py)."""

from __future__ import annotations

import numpy as np


def accel() -> bool:
    from ..fluid import core

    return core.is_compiled_with_tpu()


def build_feed(program, data_batch, feeding, skip=()):
    """feeding: {data_layer_name: column index}.  Without it, columns map
    to the program's data layers in declaration order.  lod_level>0 data
    layers take ragged rows (variable-length 1-D arrays) and are packed
    into a LoDTensor; dense layers stack to [N, -1]."""
    from ..fluid import create_lod_tensor

    gb = program.global_block()
    data_vars = [v for v in gb.vars.values()
                 if getattr(v, "is_data", False) and v.name not in skip]
    if feeding is None:
        feeding = {v.name: i for i, v in enumerate(data_vars)}
    feed = {}
    for v in data_vars:
        col = feeding.get(v.name)
        if col is None:
            continue
        is_int = v.dtype is not None and "int" in str(v.dtype)
        if getattr(v, "lod_level", 0):
            rows = [np.atleast_1d(np.asarray(r[col])) for r in data_batch]
            lens = [len(r) for r in rows]
            flat = np.concatenate(rows)
            flat = flat.astype(np.int64).reshape(-1, 1) if is_int \
                else flat.astype(np.float32).reshape(-1, int(v.shape[-1]))
            feed[v.name] = create_lod_tensor(flat, [lens])
        else:
            vals = [np.asarray(row[col]) for row in data_batch]
            arr = np.stack(vals)
            # scalar class labels become [N, 1]; integer SEQUENCES
            # (n-gram windows etc.) keep all their columns
            arr = arr.astype(np.int64 if is_int else np.float32) \
                .reshape(len(vals), -1)
            feed[v.name] = arr
    return feed
