"""High-level Trainer with event callbacks and checkpoint/resume.

ref: python/paddle/fluid/trainer.py — ``Trainer`` (:169) builds the programs
from a ``train_func``, runs an event-driven epoch/step loop (:379), and with
a ``CheckpointConfig`` (:100) periodically saves serial-numbered checkpoint
directories with a ``_SUCCESS`` marker (:663, :1212), restores the newest
complete one on init (:763), keeps at most N via scroll-delete (:1190), and
persists trainer args (epoch/step) so resume continues mid-epoch (:1060).

This is also the TPU build's preemption-safety story (SURVEY.md §5.3): a
preempted worker restarts, finds the newest ``_SUCCESS``-marked serial dir,
and resumes the identical trajectory.  For multihost SPMD runs each process
saves only its addressable shards (see parallel.multihost.save_sharded /
load_sharded) under the same serial-dir protocol.
"""

from __future__ import annotations

import json
import threading
import os
import shutil

import numpy as np

from . import core, io, unique_name
from .data_feeder import DataFeeder
from .executor import Executor, Scope, global_scope, scope_guard
from .framework import Program, program_guard

__all__ = [
    "BeginEpochEvent", "EndEpochEvent", "BeginStepEvent", "EndStepEvent",
    "CheckpointConfig", "Trainer", "Inferencer",
]


# ---------------------------------------------------------------------------
# Events (ref: trainer.py:46-97)
# ---------------------------------------------------------------------------


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        #: set False in the handler to skip this step's fetch
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


# ---------------------------------------------------------------------------
# CheckpointConfig (ref: trainer.py:100)
# ---------------------------------------------------------------------------

CKPT_PREFIX = "checkpoint"
SUCCESS_MARK = "_SUCCESS"
TRAINER_ARGS_FILE = "trainer_args.json"


class CheckpointConfig:
    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10, async_save=False):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            os.getcwd(), "checkpoint")
        self.max_num_checkpoints = int(max_num_checkpoints)
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        # async_save: snapshot device state synchronously (cheap D2H),
        # write files in a background thread so the train loop never
        # blocks on checkpoint IO — the orbax-style async checkpoint,
        # and the TPU answer to the reference pserver's background
        # checkpoint thread (ref go/pserver/service.go:346)
        self.async_save = bool(async_save)
        # filled on restore
        self.epoch_id = 0
        self.step_id = 0


def _serial_dirs(root):
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if name.startswith(CKPT_PREFIX + "_"):
            try:
                out.append((int(name.rsplit("_", 1)[1]), name))
            except ValueError:
                continue
    return sorted(out)


def _latest_complete_serial(root):
    """Newest serial whose _SUCCESS marker exists (a kill mid-save leaves an
    incomplete dir that must be ignored — ref trainer.py:763 checks the
    success file before trusting a checkpoint)."""
    for serial, name in reversed(_serial_dirs(root)):
        if os.path.exists(os.path.join(root, name, SUCCESS_MARK)):
            return serial
    return -1


_ckpt_lock = threading.Lock()
_ckpt_state = {}  # ckpt root -> {"threads": [...], "errors": [...]}
_ckpt_reserved = {}  # checkpoint_dir -> highest serial handed out


def _state_for(root):
    return _ckpt_state.setdefault(root, {"threads": [], "errors": []})


def wait_for_checkpoints(checkpoint_dir=None):
    """Barrier for async saves (call before process exit / evaluation that
    reads checkpoint files).  Re-raises the first background write error —
    a failed checkpoint must not pass silently (the sync path raises).
    State is scoped per checkpoint dir, so two Trainers in one process
    never join or misattribute each other's writers; no dir = all dirs."""
    roots = ([os.path.abspath(checkpoint_dir)] if checkpoint_dir
             else None)
    with _ckpt_lock:
        if roots is None:
            roots = list(_ckpt_state)
        pending = [t for r in roots for t in
                   _ckpt_state.get(r, {}).get("threads", [])]
    for t in pending:
        t.join()
    with _ckpt_lock:
        for r in roots:
            st = _ckpt_state.get(r)
            if st is None:
                continue
            st["threads"][:] = [t for t in st["threads"] if t.is_alive()]
            if st["errors"]:
                exc = st["errors"][0]
                st["errors"].clear()
                raise IOError(
                    f"async checkpoint write failed ({r}): "
                    f"{exc!r}") from exc


def save_checkpoint(executor, checkpoint_dir, main_program,
                    trainer_args=None, max_num_checkpoints=3,
                    background=False, data_state=None):
    """Write serial dir -> persistables -> trainer args -> data state ->
    _SUCCESS, then scroll-delete old serials (ref: trainer.py:663,1190).

    ``data_state`` (a ``paddle_tpu.data`` iterator-state blob) commits
    under the SAME _SUCCESS marker as the model state — either both
    survive a kill or neither does, so resume can restart the input
    pipeline exactly at the first un-committed sample.

    background=True snapshots the persistables to host memory NOW (one
    D2H sync) and does the file IO in a daemon thread; _SUCCESS is still
    written last, so a crash mid-write leaves an ignorable incomplete
    dir.  wait_for_checkpoints() joins outstanding writers and re-raises
    their errors."""
    root = os.path.abspath(checkpoint_dir)
    os.makedirs(checkpoint_dir, exist_ok=True)
    with _ckpt_lock:
        # an in-flight async serial has no _SUCCESS yet, so
        # _latest_complete_serial cannot see it; the serial is reserved ON
        # DISK (exclusive mkdir, atomic at the filesystem level) so two
        # processes — or a restarted run racing an orphaned async writer —
        # can never pick the same directory.  The in-process map remains as
        # a fast-path floor.
        serial = max(_latest_complete_serial(checkpoint_dir),
                     _ckpt_reserved.get(root, -1)) + 1
        while True:
            cur = os.path.join(checkpoint_dir, f"{CKPT_PREFIX}_{serial}")
            try:
                os.makedirs(cur, exist_ok=False)
                break
            except FileExistsError:
                serial += 1
        _ckpt_reserved[root] = serial
    if not background:
        import time as _t

        from ..observe import goodput as _goodput

        t0 = _t.perf_counter()
        io.save_persistables(executor, cur, main_program)
        _finish_checkpoint(checkpoint_dir, cur, trainer_args,
                           max_num_checkpoints, data_state=data_state)
        dur = _t.perf_counter() - t0
        # synchronous save blocks the training loop: checkpoint-state
        # wall-clock in the goodput ledger, one span in the event stream
        _goodput.note("checkpoint", dur)
        from .. import observe as _observe

        _observe.emit("checkpoint.save", serial=int(serial),
                      dur_s=round(dur, 6))
        return serial
    from .executor import global_scope
    from .io import _resolve_vars, is_persistable, snapshot_vars

    snapshot = snapshot_vars(
        global_scope(), _resolve_vars(main_program, is_persistable, None))

    def write():
        try:
            import time as _t

            t0 = _t.perf_counter()
            io.write_var_files(cur, snapshot)
            # data_state is a small host dict snapshotted by the caller,
            # so the background writer commits the same cursor the train
            # loop saw at the checkpoint boundary
            _finish_checkpoint(checkpoint_dir, cur, trainer_args,
                               max_num_checkpoints, data_state=data_state)
            from .. import observe as _observe

            # background IO overlaps training, so it is NOT goodput
            # checkpoint-state time — the span is still recorded (the
            # ledger's device-over-checkpoint priority keeps overlapped
            # windows productive)
            _observe.emit("checkpoint.save", serial=int(serial),
                          dur_s=round(_t.perf_counter() - t0, 6),
                          background=True)
        except BaseException as exc:  # surfaced by wait_for_checkpoints
            # a half-written serial is junk forever (it never gets
            # _SUCCESS and the pruner skips incomplete dirs) — remove it
            shutil.rmtree(cur, ignore_errors=True)
            with _ckpt_lock:
                _state_for(root)["errors"].append(exc)

    t = threading.Thread(target=write, daemon=True)
    with _ckpt_lock:
        st = _state_for(root)
        # prune finished writers so long runs don't accumulate threads
        st["threads"][:] = [x for x in st["threads"] if x.is_alive()]
        st["threads"].append(t)
    t.start()
    return serial


def _finish_checkpoint(checkpoint_dir, cur, trainer_args,
                       max_num_checkpoints, data_state=None):
    from . import fault as _fault
    from .retry import retry_io

    if trainer_args is not None:
        args_path = os.path.join(cur, TRAINER_ARGS_FILE)

        def _write_args():
            _fault.io_error(args_path, "write")
            with open(args_path, "w") as f:
                json.dump(trainer_args, f)

        retry_io(_write_args, what="ckpt.trainer_args")
    if data_state is not None:
        from ..data.checkpoint import save_data_state

        save_data_state(cur, data_state,
                        rank=int(os.environ.get("PADDLE_TRAINER_ID",
                                                "0") or 0))
    # fault hooks bracket the commit point: a crash 'before' leaves an
    # unmarked dir restore must skip; 'after' leaves a complete serial a
    # crash cannot un-commit; the poison hook rewrites this serial's
    # weights as NaN and then lets the commit proceed — a structurally
    # valid checkpoint only the serving canary can catch
    try:
        _fault.ckpt_poison(int(os.path.basename(cur).rsplit("_", 1)[1]),
                           cur)
    except (ValueError, IndexError):
        pass  # non-serial dirname: nothing to key the poison on
    _fault.ckpt_crash_point("before")
    success_path = os.path.join(cur, SUCCESS_MARK)

    def _write_success():
        # the commit point itself: a transient blip here must not turn a
        # fully-written serial into an ignored corpse — retry, bounded
        _fault.io_error(success_path, "write")
        with open(success_path, "w") as f:
            f.write("")

    retry_io(_write_success, what="ckpt.success")
    _fault.ckpt_crash_point("after")
    try:
        from .. import observe as _observe

        # the single-process commit point, twin of multihost's: the
        # committed step feeds heartbeat progress-at-death and the
        # goodput ledger's lost-work pricing
        step = (trainer_args or {}).get("step_id")
        if not isinstance(step, int) or step < 0:
            step = _observe.current_step()
        _observe.note_commit_step(step)
        # mesh-labeled like multihost's commit (ISSUE 14): the env spec
        # covers workers whose topology never dispatched a sharded
        # runner in this process (note_mesh context unset)
        commit_fields = {"path": cur, "step": step}
        if _observe.current_mesh() is None:
            from ..parallel.mesh import axes_label, axes_of

            tag = axes_label(axes_of(None))
            if tag is not None:
                commit_fields["mesh"] = tag
        _observe.emit("checkpoint.commit", **commit_fields)
    except Exception:
        pass  # telemetry must never fail the commit it describes
    # scroll-delete: keep newest max_num_checkpoints complete serials,
    # only ever deleting COMPLETE ones older than the newest keepers (an
    # in-flight async serial has no _SUCCESS yet and must survive)
    with _ckpt_lock:
        serials = [(n, name) for n, name in _serial_dirs(checkpoint_dir)
                   if os.path.exists(os.path.join(
                       checkpoint_dir, name, SUCCESS_MARK))]
        for _, name in serials[:max(0, len(serials) - max_num_checkpoints)]:
            shutil.rmtree(os.path.join(checkpoint_dir, name),
                          ignore_errors=True)


def load_checkpoint(executor, checkpoint_dir, main_program):
    """Restore the newest complete checkpoint; returns its trainer args
    (or None when no checkpoint exists).  When the serial carries a
    ``data_state`` blob for this rank, it is returned under the
    ``"data_state"`` key so the Trainer can restart the input pipeline
    exactly where the commit left it.

    Corruption fallback: a serial can carry _SUCCESS yet still be
    unreadable (bit rot / truncation AFTER the marker was committed) —
    and that includes the data_state blob: a garbage cursor silently
    resuming at the wrong sample is as bad as garbage weights.  Rather
    than killing the restore, fall back serial-by-serial to the newest
    complete checkpoint that actually loads — losing a few steps beats
    losing the run.  Only if EVERY complete serial is unreadable does
    the error surface (silently training from scratch would be worse)."""
    complete = [s for s, name in _serial_dirs(checkpoint_dir)
                if os.path.exists(os.path.join(
                    checkpoint_dir, name, SUCCESS_MARK))]
    last_exc = None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    for serial in reversed(complete):
        cur = os.path.join(checkpoint_dir, f"{CKPT_PREFIX}_{serial}")
        try:
            io.load_persistables(executor, cur, main_program)
            from ..data.checkpoint import load_data_state

            data_state = load_data_state(cur, rank=rank)
        except Exception as exc:
            from .log import LOG

            LOG(f"checkpoint {cur} is unreadable ({exc!r}); falling back "
                f"to the previous complete serial")
            last_exc = exc
            continue
        args = {}
        args_path = os.path.join(cur, TRAINER_ARGS_FILE)
        if os.path.exists(args_path):
            from . import fault as _fault
            from .retry import retry_io

            def _read_args():
                _fault.io_error(args_path, "read")
                with open(args_path) as f:
                    return f.read()

            try:
                args = json.loads(retry_io(_read_args,
                                           what="ckpt.trainer_args"))
            except (OSError, ValueError) as exc:
                # same condemnation contract as the weights: a serial
                # whose args cannot be read (after transient retries)
                # falls back to the previous complete one
                from .log import LOG

                LOG(f"checkpoint {cur} trainer args unreadable "
                    f"({exc!r}); falling back to the previous serial")
                last_exc = exc
                continue
        if data_state is not None:
            args["data_state"] = data_state
        return args
    if last_exc is not None:
        raise IOError(
            f"no loadable checkpoint under {checkpoint_dir}: every "
            f"complete serial failed to read") from last_exc
    return None


def clean_checkpoint(checkpoint_dir, delete_dir=False):
    for _, name in _serial_dirs(checkpoint_dir):
        shutil.rmtree(os.path.join(checkpoint_dir, name), ignore_errors=True)
    if delete_dir and os.path.isdir(checkpoint_dir):
        shutil.rmtree(checkpoint_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Trainer (ref: trainer.py:169)
# ---------------------------------------------------------------------------


class Trainer:
    """``train_func() -> loss`` (or [loss, ...]) builds the model;
    ``optimizer_func() -> Optimizer`` attaches the backward + update.

    ``parallel=True`` dispatches training through the SPMD path: a named
    mesh from ``PADDLE_TPU_MESH`` (e.g. ``dp4,tp2``, docs/SPMD.md) or the
    all-devices dp mesh, per step via ``ParallelExecutor.run`` or — under
    ``PADDLE_TPU_SPD=K`` — as K-step fused windows whose input the
    prefetcher stages already dp-sharded."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        if checkpoint_config is not None and \
                not isinstance(checkpoint_config, CheckpointConfig):
            raise TypeError("checkpoint_config must be a CheckpointConfig")
        self.checkpoint_cfg = checkpoint_config
        self.place = place if place is not None else core.CPUPlace()
        self.parallel = parallel
        self.stop_flag = False

        self.train_program = Program()
        self.startup_program = Program()
        # fresh name counters: an Inferencer rebuilding the topology under
        # its own guard must produce the SAME parameter names
        with program_guard(self.train_program, self.startup_program), \
                unique_name.guard():
            outs = train_func()
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            self.train_func_outputs = list(outs)
            self.loss = outs[0]
            optimizer = optimizer_func()
            optimizer.minimize(self.loss, self.startup_program)

        self.exe = Executor(self.place)
        self.exe.run(self.startup_program)

        # parallel=True: train dispatches go through the SPMD path — a
        # named mesh from PADDLE_TPU_MESH (e.g. "dp4,tp2") or the
        # degenerate all-devices dp mesh, windows via the sharded
        # run_steps when PADDLE_TPU_SPD>1.  Built AFTER startup so the
        # scope state it places is initialized.
        self.parallel_exe = None
        if parallel:
            from .parallel_executor import ParallelExecutor

            self.parallel_exe = ParallelExecutor(
                loss_name=self.loss.name, main_program=self.train_program)

        # data-plane exact resume (paddle_tpu.data): the restored serial's
        # iterator-state blob, handed to a checkpointable reader in train()
        self._restored_data_state = None
        self._data_exact_resume = False
        self._ckpt_reader = None
        if self.checkpoint_cfg:
            args = load_checkpoint(self.exe, self.checkpoint_cfg.checkpoint_dir,
                                   self.train_program)
            if args is not None:
                self.checkpoint_cfg.epoch_id = int(args.get("epoch_id", 0))
                # step_id records the last COMPLETED step; absent (a
                # checkpoint saved outside the Trainer loop) means none
                self.checkpoint_cfg.step_id = int(args.get("step_id", -1)) + 1
                self._restored_data_state = args.get("data_state")
        elif param_path:
            io.load_persistables(self.exe, param_path, self.train_program)

    def stop(self):
        self.stop_flag = True

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        """Epoch/step loop with events; resumes from a restored epoch/step
        (skipping already-consumed steps of the restored epoch, ref
        trainer.py:1060 trainer args).

        ``PADDLE_TPU_SPD=K`` (steps per dispatch, K>1) switches to the
        windowed production loop: K steps fuse into one ``run_steps``
        dispatch (guardian sentinel and dynamic fp16 loss scale included —
        they ride the scan carry) while a
        :class:`~paddle_tpu.fluid.prefetch.DevicePrefetcher` stages the
        NEXT window's batches onto the device concurrently
        (``PADDLE_TPU_PREFETCH_DEPTH``).  Step events then fire once per
        window and checkpoint step cadence is preserved at window
        granularity; LoD (variable-length) feeds need the per-step loop.
        """
        start_epoch = self.checkpoint_cfg.epoch_id if self.checkpoint_cfg else 0
        feeder = DataFeeder(feed_list=feed_order, place=self.place,
                            program=self.train_program)
        from . import envcontract

        # checkpointable readers (paddle_tpu.data pipelines) get EXACT
        # resume: the restored state blob repositions the pipeline at the
        # first un-committed sample, so the loops below renumber instead
        # of replaying (skip_until) — and every checkpoint from here on
        # commits the reader's cursor next to the model state
        self._ckpt_reader = None
        self._data_exact_resume = False
        from ..data import is_checkpointable

        if reader is not None and is_checkpointable(reader) \
                and envcontract.get("PADDLE_DATA_CKPT"):
            self._ckpt_reader = reader
            if self._restored_data_state is not None:
                reader.restore(self._restored_data_state)
                self._data_exact_resume = True

        spd = int(envcontract.get("PADDLE_TPU_SPD") or 0)
        try:
            if spd > 1:
                self._train_loop_windowed(start_epoch, num_epochs,
                                          event_handler, reader, feeder, spd)
            else:
                self._train_loop(start_epoch, num_epochs, event_handler,
                                 reader, feeder)
        except BaseException:
            if self.checkpoint_cfg and self.checkpoint_cfg.async_save:
                # drain writes so the newest checkpoint lands, but never
                # let a checkpoint error mask the primary training failure
                try:
                    wait_for_checkpoints(self.checkpoint_cfg.checkpoint_dir)
                except Exception as ckpt_exc:
                    # secondary failure: keep the signal without masking
                    # the primary training exception
                    from .log import LOG

                    LOG(f"async checkpoint failed during training "
                        f"teardown: {ckpt_exc!r}")
            raise
        else:
            if self.checkpoint_cfg and self.checkpoint_cfg.async_save:
                wait_for_checkpoints(self.checkpoint_cfg.checkpoint_dir)

    def _train_loop(self, start_epoch, num_epochs, event_handler, reader,
                    feeder):
        last_epoch_saved = None
        for epoch_id in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch_id))
            skip_until = (self.checkpoint_cfg.step_id
                          if self.checkpoint_cfg and
                          epoch_id == self.checkpoint_cfg.epoch_id else 0)
            start_step = 0
            if skip_until and self._data_exact_resume:
                # the restored pipeline already points at the first
                # un-committed sample: renumber the enumeration instead
                # of consuming skip_until replayed batches
                start_step, skip_until = skip_until, 0
            data_iter = reader()
            if self._ckpt_reader is not None:
                from .. import data as _data

                data_iter = _data.timed(data_iter, epoch=epoch_id)
            for step_id, data in enumerate(data_iter, start=start_step):
                if self.stop_flag:
                    return
                if step_id < skip_until:
                    continue
                begin = BeginStepEvent(epoch_id, step_id)
                event_handler(begin)
                fetch = self.train_func_outputs if begin.fetch_metrics else []
                if self.parallel_exe is not None:
                    metrics = self.parallel_exe.run(
                        fetch, feed=feeder.feed(data))
                else:
                    metrics = self.exe.run(self.train_program,
                                           feed=feeder.feed(data),
                                           fetch_list=fetch)
                event_handler(EndStepEvent(epoch_id, step_id, metrics))
                if self.checkpoint_cfg and \
                        (step_id + 1) % self.checkpoint_cfg.step_interval == 0:
                    self._save_checkpoint(epoch_id, step_id,
                                          data_state=self._data_state())
            if self.checkpoint_cfg and \
                    (epoch_id + 1) % self.checkpoint_cfg.epoch_interval == 0:
                self._save_checkpoint(epoch_id, -1, end_of_epoch=True,
                                      data_state=self._data_state())
                last_epoch_saved = epoch_id
            event_handler(EndEpochEvent(epoch_id))
        # the guardian's sentinel observes each step one boundary late;
        # flush here so a trip on the LAST step still raises/dumps instead
        # of dying silently with the loop
        from . import guardian as _guardian
        from ..observe import goodput as _goodput

        _guardian.flush()
        _goodput.report(force=True)
        if self.checkpoint_cfg and last_epoch_saved != num_epochs - 1:
            # final state is always captured so resume never replays work
            # (skipped when the in-loop epoch save already wrote it)
            self._save_checkpoint(num_epochs - 1, -1, end_of_epoch=True,
                                  data_state=self._data_state())

    def _train_loop_windowed(self, start_epoch, num_epochs, event_handler,
                             reader, feeder, n_steps):
        """The fused-window loop: the prefetcher stages window k+1 while
        the device runs window k, and each window is one ``run_steps``
        dispatch.  A checkpoint fires whenever the window crossed a
        ``step_interval`` boundary, stamped with the window's last step —
        so resume lands on the same steps the per-step loop would have
        saved."""
        import itertools
        import time as _time

        from .prefetch import DevicePrefetcher
        from ..observe import trace as _trace
        from ..observe import watchdog as _watchdog

        last_epoch_saved = None
        iv = self.checkpoint_cfg.step_interval if self.checkpoint_cfg else 0
        for epoch_id in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch_id))
            skip_until = (self.checkpoint_cfg.step_id
                          if self.checkpoint_cfg and
                          epoch_id == self.checkpoint_cfg.epoch_id else 0)
            feeds = (feeder.feed(data) for data in reader())
            if skip_until and not self._data_exact_resume:
                feeds = itertools.islice(feeds, skip_until, None)
            # exact resume: the restored pipeline already points at the
            # first un-committed sample, so nothing is sliced off — the
            # step numbering below still starts at the resume step
            step_id = skip_until
            # sharded runs stage windows with the batch axis ALREADY
            # dp-sharded (stage_window), so the prefetch thread's H2D
            # overlap covers the mesh placement too
            stage_fn = (self.parallel_exe.stage_window
                        if self.parallel_exe is not None else None)
            if self._ckpt_reader is not None:
                from ..data import CheckpointablePrefetcher

                # snapshots iterator state per staged window so the
                # checkpoint below commits the WINDOW boundary it refers
                # to, not the prefetch head (lookahead is replayed)
                prefetcher = CheckpointablePrefetcher(
                    feeds, self._ckpt_reader, n_steps=n_steps,
                    place=self.place, stage_fn=stage_fn)
            else:
                prefetcher = DevicePrefetcher(feeds, n_steps=n_steps,
                                              place=self.place,
                                              stage_fn=stage_fn)
            with prefetcher as pf:
                t_prev = _time.perf_counter()
                for feed_dev, count in pf:
                    if self.stop_flag:
                        return
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    fetch = (self.train_func_outputs
                             if begin.fetch_metrics else [])
                    # the train.window span carries the prefetch link
                    # (staged_span = the worker-thread span that staged
                    # THIS window's input) so the trace view stitches the
                    # async hand-off; the executor's window span nests
                    # inside it automatically
                    with _trace.span("train.window", epoch=epoch_id,
                                     step=step_id,
                                     staged_span=pf.last_stage_span):
                        if self.parallel_exe is not None:
                            metrics = self.parallel_exe.run_steps(
                                fetch, feed=feed_dev, n_steps=count,
                                feed_per_step=True)
                        else:
                            metrics = self.exe.run_steps(
                                self.train_program, feed=feed_dev,
                                fetch_list=fetch, n_steps=count,
                                feed_per_step=True)
                        t_now = _time.perf_counter()
                        # SLO watchdog on window-to-window wall time:
                        # unlike the executor's metric this INCLUDES
                        # input-feed stalls (a slow reader / injected IO
                        # delay regresses it even though dispatch time is
                        # flat).  Fed inside the span so a breach record
                        # carries this window's span id.
                        _watchdog.observe_value(
                            "train.step_time_s",
                            (t_now - t_prev) / max(1, count),
                            step=step_id + count - 1, epoch=epoch_id)
                    t_prev = t_now
                    last_step = step_id + count - 1
                    event_handler(EndStepEvent(epoch_id, last_step, metrics))
                    if self.checkpoint_cfg and \
                            (last_step + 1) // iv > step_id // iv:
                        self._save_checkpoint(
                            epoch_id, last_step,
                            data_state=(pf.last_state
                                        if self._ckpt_reader is not None
                                        else None))
                    step_id += count
            if self.checkpoint_cfg and \
                    (epoch_id + 1) % self.checkpoint_cfg.epoch_interval == 0:
                self._save_checkpoint(epoch_id, -1, end_of_epoch=True,
                                      data_state=self._data_state())
                last_epoch_saved = epoch_id
            event_handler(EndEpochEvent(epoch_id))
        # same teardown as the per-step loop: surface a last-window trip,
        # capture final state, flush a final goodput report
        from . import guardian as _guardian
        from ..observe import goodput as _goodput

        _guardian.flush()
        _goodput.report(force=True)
        if self.checkpoint_cfg and last_epoch_saved != num_epochs - 1:
            self._save_checkpoint(num_epochs - 1, -1, end_of_epoch=True,
                                  data_state=self._data_state())

    def test(self, reader, feed_order):
        feeder = DataFeeder(feed_list=feed_order, place=self.place,
                            program=self.train_program)
        test_prog = self.train_program.clone(for_test=True)
        totals = None
        count = 0
        for data in reader():
            outs = self.exe.run(test_prog, feed=feeder.feed(data),
                                fetch_list=self.train_func_outputs)
            vals = [float(np.asarray(o).reshape(-1)[0]) for o in outs]
            totals = vals if totals is None else \
                [a + b for a, b in zip(totals, vals)]
            count += 1
        return [t / max(count, 1) for t in (totals or [])]

    def save_params(self, param_path):
        io.save_persistables(self.exe, param_path, self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        io.save_inference_model(
            param_path, feeded_var_names,
            [self.train_func_outputs[i] for i in target_var_indexes],
            self.exe, self.train_program)

    # -- internal --
    def _data_state(self):
        """The active checkpointable reader's cursor (None otherwise) —
        taken at the loop's commit boundary, i.e. pointing at the first
        sample no completed step has consumed."""
        if self._ckpt_reader is None:
            return None
        return self._ckpt_reader.state()

    def _save_checkpoint(self, epoch_id, step_id, end_of_epoch=False,
                         data_state=None):
        args = {"epoch_id": epoch_id + 1 if end_of_epoch else epoch_id,
                "step_id": -1 if end_of_epoch else step_id}
        save_checkpoint(self.exe, self.checkpoint_cfg.checkpoint_dir,
                        self.train_program, trainer_args=args,
                        max_num_checkpoints=self.checkpoint_cfg.max_num_checkpoints,
                        background=self.checkpoint_cfg.async_save,
                        data_state=data_state)


class Inferencer:
    """High-level inference API (ref: python/paddle/fluid/inferencer.py):
    rebuild the inference topology with FRESH unique-name counters (so
    parameter names align with a Trainer-built model saved via
    save_params), load the params into a private scope, and answer
    feed-dict queries."""

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        self.param_path = param_path
        self.scope = Scope()
        self.place = place if place is not None else core.CPUPlace()
        build = Program()
        startup = Program()
        with program_guard(build, startup):
            with unique_name.guard():
                self.predict_var = infer_func()
        # test-mode semantics for dropout/batch-norm (the reference
        # inferencer clones for_test the same way)
        self.inference_program = build.clone(for_test=True)
        self.exe = Executor(self.place)
        with scope_guard(self.scope):
            self.exe.run(startup)
            # save_params writes PERSISTABLES (bn moving stats included);
            # read them all back, not just Parameters
            io.load_persistables(self.exe, param_path,
                                 self.inference_program)

    def infer(self, inputs, return_numpy=True):
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        with scope_guard(self.scope):
            return self.exe.run(self.inference_program, feed=inputs,
                                fetch_list=[self.predict_var],
                                return_numpy=return_numpy)
