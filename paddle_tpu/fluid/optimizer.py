"""Optimizers (ref: python/paddle/fluid/optimizer.py — Optimizer base :38,
minimize :253 = append_backward + clip + regularization + per-param update ops).

The update ops land in the Program with OpRole.Optimize, so the whole train
step (fwd + bwd + update) traces into ONE XLA program — params update in-HBM
with donated buffers instead of the reference's per-op optimizer kernels.
"""

from __future__ import annotations

from collections import defaultdict

from . import unique_name
from .backward import append_backward
from .clip import append_gradient_clip_ops, error_clip_callback
from .framework import OpRole, Program, Variable, default_main_program, \
    default_startup_program, program_guard
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops

__all__ = ["SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
           "Adadelta", "RMSProp", "Ftrl", "SGDOptimizer", "MomentumOptimizer",
           "AdagradOptimizer", "AdamOptimizer", "AdamaxOptimizer",
           "DecayedAdagradOptimizer", "AdadeltaOptimizer", "RMSPropOptimizer",
           "FtrlOptimizer", "Optimizer",
    "ProximalGDOptimizer", "ProximalAdagradOptimizer", "ProximalGD",
    "ProximalAdagrad", "ModelAverage",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None,
                 LARS_weight_decay=0.0):
        self._name = name
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = defaultdict(dict)
        self.helper = None
        self._LARS_weight_decay = float(LARS_weight_decay)

    # -- learning rate plumbing --
    def _create_global_learning_rate(self):
        program = default_main_program()
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        from .layers import tensor as _tensor

        self._learning_rate_map[program] = _tensor.create_global_var(
            name=unique_name.generate("learning_rate"), shape=[1],
            value=float(self._learning_rate), dtype="float32",
            persistable=True)

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = (param.optimize_attr or {}).get("learning_rate", 1.0)
        if not isinstance(param_lr, (int, float)):
            # a Variable: append_LARS already folded the global lr in
            return param_lr
        base = self._global_learning_rate()
        if param_lr == 1.0:
            return base
        from .layers import nn as _nn

        return _nn.scale(base, scale=float(param_lr))

    # -- accumulators --
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        if shape is None:
            shape = list(param.shape)
        var = self.helper.create_global_variable(
            name=unique_name.generate(name + "_" + param.name),
            persistable=True, dtype=dtype or param.dtype, shape=shape)
        self.helper.set_variable_initializer(
            var, ConstantInitializer(float(fill_value)))
        self._accumulators[name][param.name] = var
        # explicit accumulator->param registry on the Program, consumed by
        # parallel.spmd.infer_param_specs so sharding specs follow ownership
        # instead of name heuristics (ref: the C++ side records this pairing
        # via the optimize-op's OpRoleVar attr, op_proto_maker.h)
        prog = var.block.program
        if not hasattr(prog, "_accumulator_owner"):
            prog._accumulator_owner = {}
        prog._accumulator_owner[var.name] = param.name
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block):
        pass

    # -- the pass --
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        program = loss.block.program
        with program_guard(program, startup_program or
                           default_startup_program()):
            self.helper = LayerHelper(self.__class__.__name__)
            self._create_global_learning_rate()
            if self._LARS_weight_decay > 0.0:
                from .layers.learning_rate_scheduler import append_LARS

                append_LARS(parameters_and_grads,
                            self._global_learning_rate(),
                            self._LARS_weight_decay)
            self._create_accumulators(
                program.global_block(),
                [p for p, g in parameters_and_grads if g is not None])
            optimize_ops = []
            for param_and_grad in parameters_and_grads:
                if param_and_grad[1] is None:
                    continue
                if getattr(param_and_grad[0], "trainable", True):
                    op = self._append_optimize_op(program.global_block(),
                                                  param_and_grad)
                    op.attrs[OpRole.KEY] = OpRole.Optimize
                    op.attrs[OpRole.VAR_KEY] = [param_and_grad[0].name,
                                                param_and_grad[1].name]
                    optimize_ops.append(op)
            self._finish_update(program.global_block())
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from . import amp as _amp

        # fp16 dynamic loss scaling is a build-time transform: a
        # persistable scale var seeds the backward (run_op folds it into
        # the __loss_seed__ op) and the raw grads are unscaled here,
        # BEFORE clip/regularization/update ever see them
        scale_var = None
        if _amp.dynamic_scaling_active():
            scale_var = _amp.create_loss_scaling_vars(
                loss.block.program,
                startup_program or default_startup_program())
        params_grads = append_backward(loss, parameter_list, no_grad_set,
                                       [error_clip_callback])
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        if scale_var is not None:
            from .clip import append_unscale_ops

            params_grads = append_unscale_ops(params_grads, scale_var)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss,
                                                      startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]]})


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator(self._velocity_acc_str,
                                         param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"
    _beta1_pow_acc_str = "beta1_pow_acc"
    _beta2_pow_acc_str = "beta2_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p, shape=[1],
                                  fill_value=self._beta1)
            self._add_accumulator(self._beta2_pow_acc_str, p, shape=[1],
                                  fill_value=self._beta2)

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        m1 = self._get_accumulator(self._moment1_acc_str, p)
        m2 = self._get_accumulator(self._moment2_acc_str, p)
        b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
        b2p = self._get_accumulator(self._beta2_pow_acc_str, p)
        return block.append_op(
            type=self.type,
            inputs={"Param": [p], "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)],
                    "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p]},
            outputs={"ParamOut": [p], "Moment1Out": [m1], "Moment2Out": [m2],
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"
    _beta1_pow_acc_str = "beta1_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p, shape=[1],
                                  fill_value=self._beta1)

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        moment = self._get_accumulator(self._moment_acc_str, p)
        inf_norm = self._get_accumulator(self._inf_norm_acc_str, p)
        b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
        op = block.append_op(
            type=self.type,
            inputs={"Param": [p], "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)],
                    "Moment": [moment], "InfNorm": [inf_norm],
                    "Beta1Pow": [b1p]},
            outputs={"ParamOut": [p], "MomentOut": [moment],
                     "InfNormOut": [inf_norm]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})
        return op

    def _finish_update(self, block):
        """Update beta1 power accumulators after all param updates."""
        for p_name, b1p in self._accumulators[self._beta1_pow_acc_str].items():
            block.append_op(type="scale", inputs={"X": [b1p]},
                            outputs={"Out": [b1p]},
                            attrs={"scale": self._beta1,
                                   OpRole.KEY: OpRole.Optimize})


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        asg = self._get_accumulator(self._avg_squared_grad_acc_str,
                                    param_and_grad[0])
        asu = self._get_accumulator(self._avg_squared_update_acc_str,
                                    param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "AvgSquaredGrad": [asg], "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "AvgSquaredGradOut": [asg], "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator(self._momentum_acc_str,
                                             param_and_grad[0])
        mean_square_acc = self._get_accumulator(self._mean_square_acc_str,
                                                param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "Moment": [momentum_acc], "MeanSquare": [mean_square_acc],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "MomentOut": [momentum_acc],
                     "MeanSquareOut": [mean_square_acc]},
            attrs={"epsilon": self._epsilon, "decay": self._rho,
                   "momentum": self._momentum})


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        sq = self._get_accumulator(self._squared_acc_str, param_and_grad[0])
        lin = self._get_accumulator(self._linear_acc_str, param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]], "Grad": [param_and_grad[1]],
                    "SquaredAccumulator": [sq], "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


class ProximalGDOptimizer(Optimizer):
    """ref: optimizer.py ProximalGDOptimizer / proximal_gd_op.*"""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "proximal_gd"
        self._l1 = l1
        self._l2 = l2

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]]},
            attrs={"l1": self._l1, "l2": self._l2})


class ProximalAdagradOptimizer(Optimizer):
    """ref: optimizer.py ProximalAdagradOptimizer / proximal_adagrad_op.*"""
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "proximal_adagrad"
        self._l1 = l1
        self._l2 = l2

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        m = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]], "Moment": [m],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [m]},
            attrs={"l1": self._l1, "l2": self._l2})


class ModelAverage(Optimizer):
    """Running parameter averages for evaluation (ref: optimizer.py:1145
    ModelAverage + average_accumulates_op.*).  Construct AFTER the real
    optimizer's minimize(); it appends an average_accumulates op per
    trainable param to the main program, so every train step accumulates.
    ``apply()`` is a context manager that swaps averaged values into the
    scope for evaluation; ``restore()`` puts the trained values back."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super().__init__(0.0, **kwargs)
        self.type = "average_accumulates"
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        from .framework import Parameter, default_main_program

        # accumulators are created at construction (no minimize() call)
        self.helper = LayerHelper(self.__class__.__name__)
        block = default_main_program().global_block()
        self.params_grads = [(p, None) for p in block.vars.values()
                             if isinstance(p, Parameter) and p.trainable]
        for p, _ in self.params_grads:
            self._add_accumulator("sum_1", p)
            self._add_accumulator("sum_2", p)
            self._add_accumulator("sum_3", p)
            self._add_accumulator("num_accumulates", p, dtype="int64",
                                  shape=[1])
            self._add_accumulator("old_num_accumulates", p, dtype="int64",
                                  shape=[1])
            self._add_accumulator("num_updates", p, dtype="int64", shape=[1])
            self._append_average_accumulate_op(block, p)

    def _append_average_accumulate_op(self, block, param):
        accs = {n: self._get_accumulator(n, param)
                for n in ("sum_1", "sum_2", "sum_3", "num_accumulates",
                          "old_num_accumulates", "num_updates")}
        block.append_op(
            type="average_accumulates",
            inputs={"param": [param], "in_sum_1": [accs["sum_1"]],
                    "in_sum_2": [accs["sum_2"]], "in_sum_3": [accs["sum_3"]],
                    "in_num_accumulates": [accs["num_accumulates"]],
                    "in_old_num_accumulates": [accs["old_num_accumulates"]],
                    "in_num_updates": [accs["num_updates"]]},
            outputs={"out_sum_1": [accs["sum_1"]],
                     "out_sum_2": [accs["sum_2"]],
                     "out_sum_3": [accs["sum_3"]],
                     "out_num_accumulates": [accs["num_accumulates"]],
                     "out_old_num_accumulates":
                         [accs["old_num_accumulates"]],
                     "out_num_updates": [accs["num_updates"]]},
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window,
                   OpRole.KEY: OpRole.Optimize})

    def apply(self, executor=None, need_restore=True):
        """Context manager: parameters hold their AVERAGED values inside
        the with-block (ref :1204)."""
        import contextlib

        import numpy as np

        from .executor import global_scope

        @contextlib.contextmanager
        def _ctx():
            scope = global_scope()
            self._backup = {}
            for p, _ in self.params_grads:
                s1 = np.asarray(scope.get(
                    self._get_accumulator("sum_1", p).name))
                s2 = np.asarray(scope.get(
                    self._get_accumulator("sum_2", p).name))
                s3 = np.asarray(scope.get(
                    self._get_accumulator("sum_3", p).name))
                na = float(np.asarray(scope.get(self._get_accumulator(
                    "num_accumulates", p).name)).reshape(-1)[0])
                ona = float(np.asarray(scope.get(self._get_accumulator(
                    "old_num_accumulates", p).name)).reshape(-1)[0])
                total = na + ona
                if total <= 0:
                    continue
                self._backup[p.name] = np.asarray(scope.get(p.name))
                avg = (s1 + s2 + s3) / total
                scope.set(p.name, avg.astype(self._backup[p.name].dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)

        return _ctx()

    def restore(self, executor=None):
        from .executor import global_scope

        scope = global_scope()
        for name, val in getattr(self, "_backup", {}).items():
            scope.set(name, val)
        self._backup = {}


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer
