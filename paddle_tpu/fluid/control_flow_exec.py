"""Trace-time execution of IR control-flow ops (while / conditional_block).

ref: paddle/fluid/operators/while_op.cc:36 (grad :101),
conditional_block_op.cc.  The reference interprets the sub-block per
iteration in a kid scope.  Here the sub-block is *unrolled into the trace*:
the loop condition must be concrete at trace time (a counter chain rooted in
fill_constant / static lod — the DynamicRNN & StaticRNN pattern), each
iteration's ops are traced into the same XLA program, and XLA schedules the
unrolled graph.  Data-dependent conditions require eager mode (see
executor.BlockPlan.needs_eager), where every value is concrete and the same
unrolling works unchanged.

while_grad is jax.vjp over a replay of the unrolled loop from the stashed
pre-loop state — the trace-time analogue of the reference's reversed
sub-block execution with saved step scopes.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

WHILE_STASH = "@WHILE_STASH@"
MAX_WHILE_ITERS = 100_000


def _concrete_scalar(v, what):
    if v is None:
        raise RuntimeError(f"{what}: condition variable is undefined")
    if isinstance(v, jax.core.Tracer):
        raise NotImplementedError(
            f"{what}: the loop/branch condition is a traced (data-dependent) "
            f"value.  Supported conditions are counter/lod-derived and "
            f"concrete at trace time; for data-dependent control flow run "
            f"the program in eager mode (it contains no such ops, so the "
            f"executor chose jit — restructure the condition or fetch "
            f"through an eager op)")
    return bool(np.asarray(v).reshape(-1)[0])


def _snap(v):
    """Snapshot a value for replay: TensorArrays are mutable, so clone."""
    from ..ops.array_ops import TensorArray

    if isinstance(v, TensorArray):
        return v.clone()
    return v


def run_while(op, env: Dict[str, object], rng_box, run_op):
    body = op.block.program.block(op.attr("sub_block"))
    cond_name = op.inputs["Condition"][0]
    # stash pre-loop values of X for while_grad's replay
    stash = env.setdefault(WHILE_STASH, {})
    stash[op.attr("sub_block")] = {
        n: _snap(env.get(n)) for n in op.inputs.get("X", []) if n}
    it = 0
    while _concrete_scalar(env.get(cond_name), "while"):
        for bop in body.ops:
            run_op(bop, env, rng_box)
        it += 1
        if it > MAX_WHILE_ITERS:
            raise RuntimeError("while: exceeded max iterations "
                               f"({MAX_WHILE_ITERS}); non-terminating loop?")


def _is_float_array(v):
    return hasattr(v, "dtype") and jnp.issubdtype(jnp.asarray(v).dtype,
                                                  jnp.inexact)


def _is_float_tarray(v):
    from ..ops.array_ops import TensorArray

    return isinstance(v, TensorArray) and v.vals and \
        all(_is_float_array(x) for x in v.vals)


def _to_tree(v):
    """Differentiable pytree view: TensorArray -> list of its arrays."""
    from ..ops.array_ops import TensorArray

    return list(v.vals) if isinstance(v, TensorArray) else v


def _from_tree(orig, tree):
    from ..ops.array_ops import TensorArray

    if isinstance(orig, TensorArray):
        return TensorArray(vals=list(tree), lods=list(orig.lods))
    return tree


def run_while_grad(op, env: Dict[str, object], rng_box, run_op):
    """Replay the loop from stashed pre-loop state under jax.vjp.

    Gradients flow through plain arrays AND TensorArray contents (a tensor
    array's grad is a tensor array — matching ref while_grad semantics where
    step-scope arrays get grad arrays)."""
    sub_idx = op.attr("sub_block")
    body = op.block.program.block(sub_idx)
    pre = env.get(WHILE_STASH, {}).get(sub_idx)
    if pre is None:
        raise RuntimeError("while_grad: forward while was never executed")

    from ..ops import registry as _reg
    from ..ops.array_ops import TensorArray

    for bop in body.ops:
        d = _reg.REGISTRY.get(bop.type)
        if d is not None and d.stateful:
            raise NotImplementedError(
                f"while_grad: stateful op '{bop.type}' inside the loop body "
                f"cannot be replayed for gradients (rng would diverge); "
                f"move it outside the loop")

    x_names = [n for n in op.inputs.get("X", []) if n]
    xg_names = op.outputs.get("X@GRAD", [])
    want = {x: g for x, g in zip(x_names, xg_names) if g}
    out_names = [n for n in op.inputs.get("Out", []) if n]
    og_names = op.inputs.get("Out@GRAD", [])
    out_grads = {}
    for i, n in enumerate(op.inputs.get("Out", [])):
        if n and i < len(og_names) and og_names[i]:
            g = env.get(og_names[i])
            if g is not None:
                out_grads[n] = g

    diff = {}
    for n in want:
        v = pre.get(n)
        if _is_float_array(v) or _is_float_tarray(v):
            diff[n] = _to_tree(v)
    if not diff:
        return
    cond_name = op.inputs["Condition"][0]

    def f(xtrees):
        env2 = {k: _snap(v) for k, v in env.items() if k != WHILE_STASH}
        env2.update({k: _snap(v) for k, v in pre.items()})  # rewind
        for k, t in xtrees.items():
            env2[k] = _from_tree(pre[k], t)
        it = 0
        while _concrete_scalar(env2.get(cond_name), "while_grad replay"):
            for bop in body.ops:
                run_op(bop, env2, None)
            it += 1
            if it > MAX_WHILE_ITERS:
                raise RuntimeError("while_grad: runaway replay")
        outs = {}
        for n in out_names:
            v = env2.get(n)
            if n in out_grads and (_is_float_array(v) or
                                   _is_float_tarray(v)):
                outs[n] = _to_tree(v)
        return outs

    primals, vjp_fn = jax.vjp(f, diff)
    cots = {}
    for n, p in primals.items():
        g = out_grads[n]
        if isinstance(p, list):
            gvals = list(g.vals) if isinstance(g, TensorArray) else []
            cots[n] = [
                jnp.asarray(gvals[i], p[i].dtype) if i < len(gvals)
                and gvals[i] is not None else jnp.zeros_like(p[i])
                for i in range(len(p))]
        else:
            cots[n] = jnp.asarray(g, p.dtype)
    (grads,) = vjp_fn(cots)
    for x, gname in want.items():
        g = grads.get(x)
        if g is None:
            continue
        g = _from_tree(pre[x], g) if isinstance(g, list) else g
        prev = env.get(gname)
        if prev is None or isinstance(g, TensorArray):
            env[gname] = g
        else:
            env[gname] = prev + g


def run_conditional_block(op, env: Dict[str, object], rng_box, run_op):
    body = op.block.program.block(op.attr("sub_block"))
    cond_vals = [env.get(n) for n in op.inputs.get("Cond", []) if n]
    if bool(ctx_all(cond_vals, op)):
        stash = env.setdefault(WHILE_STASH, {})
        stash[op.attr("sub_block")] = {
            n: env.get(n) for n in op.inputs.get("Input", []) if n}
        stash[("taken", op.attr("sub_block"))] = True
        for bop in body.ops:
            run_op(bop, env, rng_box)
    else:
        env.setdefault(WHILE_STASH, {})[("taken", op.attr("sub_block"))] = \
            False


def ctx_all(cond_vals, op):
    if not cond_vals:
        raise RuntimeError("conditional_block: missing Cond input")
    if bool(op.attr("is_scalar_condition", False)):
        return _concrete_scalar(cond_vals[0], "conditional_block")
    vals = []
    for v in cond_vals:
        if isinstance(v, jax.core.Tracer):
            _concrete_scalar(v, "conditional_block")  # raises with guidance
        vals.append(bool(np.asarray(v).all()))
    return all(vals)


def run_conditional_block_grad(op, env, rng_box, run_op):
    sub_idx = op.attr("sub_block")
    taken = env.get(WHILE_STASH, {}).get(("taken", sub_idx))
    in_names = [n for n in op.inputs.get("Input", []) if n]
    ig_names = op.outputs.get("Input@GRAD", [])
    want = {x: g for x, g in zip(in_names, ig_names) if g}
    if not taken:
        for x, gname in want.items():
            v = env.get(x)
            if v is not None and _is_float_array(v):
                env[gname] = jnp.zeros_like(jnp.asarray(v))
        return
    body = op.block.program.block(sub_idx)
    pre = env.get(WHILE_STASH, {}).get(sub_idx, {})
    out_names = [n for n in op.inputs.get("Out", []) if n]
    og_names = op.inputs.get("Out@GRAD", [])
    out_grads = {}
    for i, n in enumerate(op.inputs.get("Out", [])):
        if n and i < len(og_names) and og_names[i]:
            g = env.get(og_names[i])
            if g is not None:
                out_grads[n] = g
    diff = {n: pre[n] for n in want if n in pre and _is_float_array(pre[n])}
    if not diff:
        return

    def f(xvals):
        env2 = {k: v for k, v in env.items() if k != WHILE_STASH}
        env2.update(pre)
        env2.update(xvals)
        for bop in body.ops:
            run_op(bop, env2, None)
        return {n: env2[n] for n in out_names
                if n in out_grads and _is_float_array(env2.get(n))}

    primals, vjp_fn = jax.vjp(f, diff)
    cots = {n: jnp.asarray(out_grads[n], primals[n].dtype) for n in primals}
    (grads,) = vjp_fn(cots)
    for x, gname in want.items():
        g = grads.get(x)
        if g is not None:
            env[gname] = g


def run_jit_beam_search(op, env: Dict[str, object], rng_box, run_op):
    """Whole-loop beam search as ONE traced lax.while_loop (VERDICT r4
    missing #1; contrast run_while, which unrolls a concrete-condition
    loop).  The step sub-block is traced symbolically inside the loop body,
    so the entire generation — embedding, cell update, vocab projection,
    top-k expansion — compiles into a single XLA program with static
    [batch, beam] shapes; see ops/beam_search_jit.py for the engine."""
    from ..ops import beam_search_jit as bsj

    body = op.block.program.block(op.attr("sub_block"))
    id_feed = op.attr("id_feed")
    state_feeds = list(op.attr("state_feeds") or [])
    state_outs = list(op.attr("state_outs") or [])
    ctx_feeds = list(op.attr("ctx_feeds") or [])
    prob_var = op.attr("prob_var")
    beam_size = int(op.attr("beam_size"))
    max_len = int(op.attr("max_len"))
    end_id = int(op.attr("end_id"))
    vocab = int(op.attr("vocab_size"))

    init_name = op.inputs["InitIds"][0]
    init_ids = jnp.asarray(env[init_name])
    init_lod = env.get(init_name + "@LOD")
    if init_lod:
        lvl0 = list(init_lod[0]) if isinstance(init_lod, (list, tuple)) \
            and init_lod[0] else []
        if lvl0 and lvl0 != list(range(len(lvl0))):
            raise ValueError(
                "jit_beam_search: init_ids must carry exactly one init "
                f"hypothesis per source (lod level 0 {lvl0}); multi-"
                "hypothesis warm starts need the eager BeamSearchDecoder")
    init_scores = jnp.asarray(env[op.inputs["InitScores"][0]])
    init_states = [jnp.asarray(env[n])
                   for n in op.inputs.get("StateInit", []) if n]
    ctx_tiled = [jnp.repeat(jnp.asarray(env[n]), beam_size, axis=0)
                 for n in op.inputs.get("Context", []) if n]
    missing = [n for n in op.inputs.get("X", []) if n and n not in env]
    if missing:
        raise RuntimeError(
            f"jit_beam_search: loop-invariant inputs {missing} are not in "
            f"scope — was the startup program run, and are all captured "
            f"vars produced before this op?")
    base = {n: env[n] for n in op.inputs.get("X", []) if n}

    def step_fn(states, tokens):
        env2 = dict(base)
        env2[id_feed] = tokens
        for ph, v in zip(state_feeds, states):
            env2[ph] = v
        for ph, v in zip(ctx_feeds, ctx_tiled):
            env2[ph] = v
        for bop in body.ops:
            run_op(bop, env2, rng_box)
        return env2[prob_var], [env2[n] for n in state_outs]

    h_ids, h_par, h_sc, n_steps = bsj.beam_search_loop(
        step_fn, init_states, init_ids, init_scores,
        beam_size=beam_size, vocab_size=vocab, max_len=max_len,
        end_id=end_id)
    env[op.outputs["HistIds"][0]] = h_ids
    env[op.outputs["HistParents"][0]] = h_par
    env[op.outputs["HistScores"][0]] = h_sc
    env[op.outputs["NumSteps"][0]] = n_steps


HANDLERS = {
    "while": run_while,
    "while_grad": run_while_grad,
    "conditional_block": run_conditional_block,
    "conditional_block_grad": run_conditional_block_grad,
    "jit_beam_search": run_jit_beam_search,
}
