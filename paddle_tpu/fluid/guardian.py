"""Training guardian: async numerics sentinel, flight recorder, step replay.

The elastic supervisor (``parallel.elastic``) answers *structural* failure —
a dead rank restarts the pod from a checkpoint.  The failures that actually
burn pod-hours are in-band: a NaN/Inf that silently poisons the weights
thousands of steps before anyone reads the loss curve, an fp16 overflow, a
loss spike from one corrupt batch.  The guardian detects these the step
they happen and reacts by policy, without adding a per-step host sync:

 - **sentinel**: the Executor folds a device-side health reduction (loss,
   global grad-norm, an ``isfinite`` all-reduce over the raw grads) into
   the jitted train step.  The host fetches the tiny health scalars with a
   ONE-STEP LAG — by the next step boundary the previous dispatch has long
   retired, so materializing them costs nothing on the hot path.
 - **device-side commit gate**: the step's state update is committed with
   ``jnp.where(ok, new, old)`` *inside* the same XLA program, where ``ok``
   is "all grads and the loss are finite AND the loss is under the spike
   cap".  A bad step therefore never touches parameters or optimizer
   state — ``skip`` costs zero host round-trips and leaves the state
   bit-identical to the previous step.
 - **policy** per trip: ``skip`` (log + keep going), ``halt`` (raise
   :class:`NumericsTripped`), ``dump_and_halt`` (write a replay bundle,
   then raise).  A trip under an elastic supervisor also lands one line in
   its ``incidents.jsonl`` (``PADDLE_ELASTIC_INCIDENTS``).
 - **flight recorder**: a bounded ring of the last K steps' health records
   (loss, grad-norm, loss scale, wall time).  On ``dump_and_halt`` it
   writes a replay bundle: the bad step's feeds, pre-step state snapshot
   (parameters, optimizer accumulators, RNG key), the pickled Program,
   the sentinel inputs of that step, and the ring itself.
 - **replay CLI**: ``python -m paddle_tpu.fluid.guardian replay <bundle>``
   re-executes the recorded step on CPU (``JAX_PLATFORMS=cpu``), checks
   the recomputed loss reproduces the recorded value bit-for-bit, then
   walks the block op-by-op eagerly to bisect which variable first goes
   non-finite.

Enable programmatically (``guardian.enable(policy="skip")``) or via env::

    PADDLE_TPU_GUARDIAN=skip|halt|dump_and_halt   arm the sentinel
    PADDLE_TPU_GUARDIAN_SPIKE=f      loss-spike factor (0 disables; a step
                                     whose loss exceeds f x the median of
                                     the recent window trips)
    PADDLE_TPU_GUARDIAN_WINDOW=w     spike window (default 32 steps)
    PADDLE_TPU_GUARDIAN_RING=k       flight-recorder depth (default 128)
    PADDLE_TPU_GUARDIAN_DIR=path     replay-bundle directory
                                     (default ./guardian_dumps)

The deterministic oracles live in ``fluid.fault``:
``PADDLE_FAULT_GRAD_INF_STEP`` poisons the backward seed at a step (a real
in-graph Inf that flows through every grad) and
``PADDLE_FAULT_LOSS_SPIKE_STEP`` multiplies the observed loss.
"""

from __future__ import annotations

import json
import math
import os
import statistics
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "NumericsTripped", "GuardianConfig", "Guardian", "HealthRecord",
    "FlightRecorder", "enable", "disable", "install", "current",
    "for_program", "metrics", "flush", "replay",
]

POLICIES = ("skip", "halt", "dump_and_halt")

#: reserved env name the guarded step uses to scale the backward seed
#: (dynamic loss scale x fault injection); consumed by run_op on the op
#: tagged ``__loss_seed__`` by append_backward
LOSS_SEED_MUL = "@LOSS_SEED_MUL@"

BUNDLE_META = "meta.json"
BUNDLE_PROGRAM = "program.pkl"
BUNDLE_FEEDS = "feeds.npz"
BUNDLE_STATE = "state.npz"
BUNDLE_RECORDS = "records.json"


class NumericsTripped(RuntimeError):
    """Raised by the ``halt``/``dump_and_halt`` policies.  Carries the
    offending :class:`HealthRecord` and, when dumped, the bundle path."""

    def __init__(self, record: "HealthRecord", bundle: Optional[str] = None):
        self.record = record
        self.bundle = bundle
        msg = (f"numerics sentinel tripped at step {record.step}: "
               f"loss={record.loss!r} grad_norm={record.grad_norm!r} "
               f"finite={record.finite} spike={record.spike}")
        if bundle:
            msg += f" (replay bundle: {bundle})"
        super().__init__(msg)


class GuardianConfig:
    def __init__(self, policy: str = "skip", spike_factor: float = 0.0,
                 spike_window: int = 32, ring_size: int = 128,
                 bundle_dir: Optional[str] = None):
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, got {policy!r}")
        self.policy = policy
        self.spike_factor = float(spike_factor)
        self.spike_window = max(2, int(spike_window))
        self.ring_size = max(2, int(ring_size))
        self.bundle_dir = bundle_dir or os.path.join(os.getcwd(),
                                                     "guardian_dumps")

    @classmethod
    def from_env(cls, env=None) -> Optional["GuardianConfig"]:
        env = os.environ if env is None else env
        policy = env.get("PADDLE_TPU_GUARDIAN", "").strip().lower()
        if not policy or policy in ("0", "off", "false"):
            return None
        if policy in ("1", "true", "on"):
            policy = "skip"
        return cls(
            policy=policy,
            spike_factor=float(env.get("PADDLE_TPU_GUARDIAN_SPIKE", "").strip()
                               or 0.0),
            spike_window=int(env.get("PADDLE_TPU_GUARDIAN_WINDOW", "").strip()
                             or 32),
            ring_size=int(env.get("PADDLE_TPU_GUARDIAN_RING", "").strip()
                          or 128),
            bundle_dir=env.get("PADDLE_TPU_GUARDIAN_DIR", "").strip() or None,
        )


class HealthRecord:
    """One step's health, as observed (one step late) by the host."""

    __slots__ = ("step", "loss", "grad_norm", "scale", "finite", "ok",
                 "spike", "duration_s")

    def __init__(self, step, loss, grad_norm, scale, finite, ok, spike,
                 duration_s=0.0):
        self.step = int(step)
        self.loss = float(loss)
        self.grad_norm = float(grad_norm)
        self.scale = float(scale)
        self.finite = bool(finite)
        self.ok = bool(ok)
        self.spike = bool(spike)
        self.duration_s = float(duration_s)

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class FlightRecorder:
    """Bounded ring of the last K health records + the spike statistics."""

    def __init__(self, size: int, spike_window: int):
        self.ring: deque = deque(maxlen=size)
        self._recent_losses: deque = deque(maxlen=spike_window)

    def append(self, rec: HealthRecord) -> None:
        self.ring.append(rec)
        if rec.ok and math.isfinite(rec.loss):
            self._recent_losses.append(rec.loss)

    def records(self) -> List[HealthRecord]:
        return list(self.ring)

    def loss_cap(self, spike_factor: float) -> float:
        """Host-computed spike threshold fed INTO the next jitted step (so
        the device commit gate can reject a spiked step without any host
        sync).  inf until enough clean history exists."""
        if spike_factor <= 0 or len(self._recent_losses) < 4:
            return float("inf")
        med = statistics.median(self._recent_losses)
        if med <= 0 or not math.isfinite(med):
            return float("inf")
        return spike_factor * med


# ---------------------------------------------------------------------------
# Per-program guard spec (what the Executor folds into the jitted step)
# ---------------------------------------------------------------------------


class GuardSpec:
    """Static description of how to guard one training Program."""

    def __init__(self, loss_name: str, grad_names: List[str],
                 scale_vars, growth_interval: int):
        self.loss_name = loss_name
        self.grad_names = list(grad_names)
        self.scale_vars = tuple(scale_vars) if scale_vars else None
        self.growth_interval = int(growth_interval)

    def extra_fetch_names(self) -> List[str]:
        return [self.loss_name] + self.grad_names

    def cache_token(self):
        """Part of the Executor's compile-cache key: anything that changes
        the *compiled* guarded function (policy does not — it is host-side)."""
        return ("guard", self.loss_name, tuple(self.grad_names),
                self.scale_vars, self.growth_interval)


def for_program(program) -> Optional[GuardSpec]:
    """GuardSpec when this program should run guarded: it is a training
    program (has params/grads + a recorded loss) AND either the guardian is
    armed or the program was built with dynamic loss scaling."""
    if getattr(program, "_params_grads", None) is None:
        return None
    loss_name = getattr(program, "_loss_name", None)
    if not loss_name:
        return None
    scale_vars = getattr(program, "_loss_scale_vars", None)
    if current() is None and scale_vars is None:
        return None
    grad_names = [g.name for _, g in program._params_grads if g is not None]
    if not grad_names:
        return None
    return GuardSpec(loss_name, grad_names, scale_vars,
                     getattr(program, "_loss_scale_growth", 1000))


# ---------------------------------------------------------------------------
# Device-side health fold (runs inside the Executor's jitted step)
# ---------------------------------------------------------------------------


def seed_multiplier(spec: GuardSpec, state: Dict, sentinel: Dict):
    """The traced scalar the backward seed is multiplied by: dynamic loss
    scale (when built in) x fault grad-Inf injection (normally 1.0)."""
    import jax.numpy as jnp

    mul = jnp.asarray(sentinel["seed_mul"], jnp.float32)
    if spec.scale_vars is not None:
        mul = mul * state[spec.scale_vars[0]].reshape(()).astype(jnp.float32)
    return mul


def fold_health(spec: GuardSpec, extra_fetches, new_state: Dict,
                mut_state: Dict, state: Dict, sentinel: Dict):
    """Pure-JAX health reduction + conditional commit + loss-scale update.

    Called inside the Executor's jitted train step.  Returns
    ``(new_state, health)`` where health is a dict of device scalars the
    host will materialize one step later.
    """
    import jax.numpy as jnp

    from .framework import RNG_STATE_VAR

    f32 = jnp.float32
    loss_raw = extra_fetches[0]
    grads = extra_fetches[1:]

    loss_scalar = jnp.asarray(loss_raw, f32).reshape(-1)[0]
    # injected loss spike (fault oracle for the spike detector)
    health_loss = loss_scalar * jnp.asarray(sentinel["loss_mul"], f32)

    finite = jnp.isfinite(health_loss)
    gn_sq = jnp.zeros((), f32)
    for g in grads:
        gf = g.astype(f32)
        finite = finite & jnp.all(jnp.isfinite(gf))
        gn_sq = gn_sq + jnp.sum(gf * gf)
    grad_norm = jnp.sqrt(gn_sq)

    if spec.scale_vars is not None:
        scale_name, good_name = spec.scale_vars
        scale = state[scale_name].reshape(()).astype(f32)
        # raw grads carry the loss scale; report the true norm
        grad_norm = grad_norm / scale
    else:
        scale = jnp.ones((), f32)

    # commit gate: NaN loss compares False against any cap, so this single
    # predicate covers both non-finite and spike trips
    ok = finite & (health_loss <= jnp.asarray(sentinel["loss_cap"], f32))

    skip_revert = {RNG_STATE_VAR}
    if spec.scale_vars is not None:
        skip_revert.update(spec.scale_vars)
    committed = {}
    for name, val in new_state.items():
        old = mut_state.get(name)
        if old is None or name in skip_revert:
            # write-only vars (freshly derived, e.g. a decayed lr), the RNG
            # key (always advances — replaying a mask is worse than losing
            # one draw) and the scaler state (updated below) keep the new
            # value; everything read-write reverts when the step is bad
            committed[name] = val
        else:
            committed[name] = jnp.where(ok, val, old)

    if spec.scale_vars is not None:
        good = state[good_name].reshape(()).astype(jnp.int32)
        new_good = jnp.where(finite, good + 1, 0)
        grow = new_good >= spec.growth_interval
        new_scale = jnp.where(
            finite,
            jnp.where(grow, scale * 2.0, scale),
            jnp.maximum(scale * 0.5, 1.0))
        new_good = jnp.where(grow, 0, new_good)
        committed[scale_name] = new_scale.reshape(
            state[scale_name].shape).astype(state[scale_name].dtype)
        committed[good_name] = new_good.reshape(
            state[good_name].shape).astype(state[good_name].dtype)
        scale = new_scale

    health = {"loss": health_loss, "grad_norm": grad_norm,
              "finite": finite, "ok": ok, "scale": scale}
    return committed, health


def window_health_init(n_steps: int):
    """Initial aggregated-health carry for a fused ``run_steps`` window.

    The scan cannot ship one health record per step back to the host
    without stacking ``n_steps`` buffers; instead the carry reduces the
    window to the record the host actually acts on: the FIRST tripped
    step (index + its health values — the trip the policy attributes),
    the worst values seen anywhere in the window, and the trip count.
    ``trip_idx == n_steps`` is the no-trip sentinel."""
    import jax.numpy as jnp

    f32 = jnp.float32
    return {
        "trip_idx": jnp.full((), n_steps, jnp.int32),
        "trip_loss": jnp.zeros((), f32),
        "trip_grad_norm": jnp.zeros((), f32),
        "trip_finite": jnp.asarray(True),
        "bad_steps": jnp.zeros((), jnp.int32),
        "worst_loss": jnp.full((), -jnp.inf, f32),
        "worst_grad_norm": jnp.zeros((), f32),
        "all_finite": jnp.asarray(True),
        "scale": jnp.ones((), f32),
    }


def window_health_update(agg, health, step_i, n_steps: int):
    """Fold one scanned step's health into the window aggregate (pure JAX,
    runs inside the scan body)."""
    import jax.numpy as jnp

    first = (agg["trip_idx"] == n_steps) & ~health["ok"]
    return {
        "trip_idx": jnp.where(first, step_i.astype(jnp.int32),
                              agg["trip_idx"]),
        "trip_loss": jnp.where(first, health["loss"], agg["trip_loss"]),
        "trip_grad_norm": jnp.where(first, health["grad_norm"],
                                    agg["trip_grad_norm"]),
        "trip_finite": jnp.where(first, health["finite"],
                                 agg["trip_finite"]),
        "bad_steps": agg["bad_steps"] + (~health["ok"]).astype(jnp.int32),
        # maximum propagates NaN, so a NaN loss also poisons worst_loss —
        # exactly what "worst" should report
        "worst_loss": jnp.maximum(agg["worst_loss"], health["loss"]),
        "worst_grad_norm": jnp.maximum(agg["worst_grad_norm"],
                                       health["grad_norm"]),
        "all_finite": agg["all_finite"] & health["finite"],
        "scale": health["scale"],
    }


# ---------------------------------------------------------------------------
# Host-side guardian (module singleton, env-armed like fluid.fault)
# ---------------------------------------------------------------------------


_UNSET = object()
_guardian = _UNSET


class Guardian:
    def __init__(self, config: GuardianConfig):
        self.config = config
        self.recorder = FlightRecorder(config.ring_size, config.spike_window)
        self.counters = {"steps": 0, "trips": 0, "skips": 0, "halts": 0,
                         "spikes": 0, "nonfinite": 0}
        self._pending = None  # (spec, step, health, ctx)
        self.last_scale = 1.0

    # -- step plumbing (called by the Executor) --
    def loss_cap(self) -> float:
        return self.recorder.loss_cap(self.config.spike_factor)

    def on_boundary(self) -> None:
        """Step boundary: observe the PREVIOUS step's health (its dispatch
        has retired; the scalars are free to read) and apply policy before
        the next step runs."""
        self._check_pending()

    def defer(self, spec, step, health, ctx) -> None:
        """Queue a dispatch's health for observation at the next boundary.
        ``step`` is the dispatch's first absolute step; a fused window
        (``ctx["window"]``) carries the AGGREGATED health of all its steps
        (see :func:`window_health_init`)."""
        self._pending = (spec, step, health, ctx)
        self.counters["steps"] += (ctx.get("window") or {}).get("n_steps", 1)

    def flush(self) -> None:
        """Force-check the deferred health record (call after the last
        training step; the Trainer does this automatically)."""
        self._check_pending()

    # -- observation + policy --
    def _check_pending(self) -> None:
        if self._pending is None:
            return
        import numpy as np

        spec, step, health, ctx = self._pending
        self._pending = None
        win = ctx.get("window")
        if win is not None:
            # fused window: materialize the aggregate (the dispatch has
            # retired; these are a handful of scalars) and attribute the
            # record to the FIRST tripped step's absolute index — or, on a
            # clean window, to its last step with the worst values seen
            n = int(win["n_steps"])
            trip_idx = int(np.asarray(health["trip_idx"]))
            tripped = trip_idx < n
            win["trip_offset"] = trip_idx if tripped else None
            win["bad_steps"] = int(np.asarray(health["bad_steps"]))
            rec = HealthRecord(
                step=step + (trip_idx if tripped else n - 1),
                loss=float(np.asarray(
                    health["trip_loss" if tripped else "worst_loss"])),
                grad_norm=float(np.asarray(
                    health["trip_grad_norm" if tripped
                           else "worst_grad_norm"])),
                scale=float(np.asarray(health["scale"])),
                finite=bool(np.asarray(
                    health["trip_finite" if tripped else "all_finite"])),
                ok=not tripped,
                spike=False,
                duration_s=ctx.get("duration_s", 0.0),
            )
        else:
            rec = HealthRecord(
                step=step,
                loss=float(np.asarray(health["loss"])),
                grad_norm=float(np.asarray(health["grad_norm"])),
                scale=float(np.asarray(health["scale"])),
                finite=bool(np.asarray(health["finite"])),
                ok=bool(np.asarray(health["ok"])),
                spike=False,
                duration_s=ctx.get("duration_s", 0.0),
            )
        rec.spike = rec.finite and not rec.ok
        self.recorder.append(rec)
        self.last_scale = rec.scale
        from . import profiler as _prof

        _prof.record_counter("guardian_steps")
        _prof.record_counter("guardian_loss_scale", value=rec.scale)
        if rec.ok:
            return
        self._trip(rec, spec, ctx)

    def _trip(self, rec: HealthRecord, spec: GuardSpec, ctx: dict) -> None:
        from .log import LOG
        from . import profiler as _prof

        self.counters["trips"] += 1
        self.counters["nonfinite" if not rec.finite else "spikes"] += 1
        _prof.record_counter("guardian_trips")
        policy = self.config.policy
        bundle = None
        if policy == "dump_and_halt":
            try:
                bundle = self.dump_bundle(rec, spec, ctx)
            except Exception as exc:
                LOG(f"guardian: replay-bundle dump failed: {exc!r}")
        self._incident(rec, policy, bundle, window=ctx.get("window"))
        if policy == "skip":
            self.counters["skips"] += 1
            _prof.record_counter("guardian_skips")
            LOG(f"guardian: step {rec.step} tripped "
                f"(loss={rec.loss!r}, grad_norm={rec.grad_norm!r}) — "
                f"update dropped, training continues")
            return
        self.counters["halts"] += 1
        _prof.record_counter("guardian_halts")
        raise NumericsTripped(rec, bundle)

    def _incident(self, rec: HealthRecord, policy: str,
                  bundle: Optional[str], window: Optional[dict] = None) -> None:
        """A guardian trip must be a recorded *decision*, not just a dead
        process: one stamped record in the run-event stream (where it
        correlates with the supervisor's generation restarts and the next
        generation's cache hits by (host, gen, step)), plus — under an
        elastic supervisor — one line in the legacy incidents.jsonl view.
        A trip inside a fused window additionally records the window's
        extent and trip count — the granularity the policy acted at."""
        from .. import observe

        extra = {}
        if window is not None:
            extra = {"window_start": window["start"],
                     "window_steps": window["n_steps"],
                     "window_bad_steps": window.get("bad_steps")}
        observe.emit("guardian_trip", step=rec.step, policy=policy,
                     loss=rec.loss, grad_norm=rec.grad_norm, scale=rec.scale,
                     finite=rec.finite, spike=rec.spike, bundle=bundle,
                     **extra)
        path = os.environ.get("PADDLE_ELASTIC_INCIDENTS")
        if not path:
            return
        try:
            from ..parallel.elastic import IncidentLog

            IncidentLog(path).log(
                "guardian_trip", step=rec.step, policy=policy,
                loss=rec.loss, grad_norm=rec.grad_norm, scale=rec.scale,
                finite=rec.finite, spike=rec.spike, bundle=bundle,
                rank=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
        except Exception:
            # incident reporting must never mask the trip itself
            pass

    # -- flight-recorder dump --
    def dump_bundle(self, rec: HealthRecord, spec: GuardSpec,
                    ctx: dict) -> str:
        import numpy as np

        root = self.config.bundle_dir
        os.makedirs(root, exist_ok=True)
        bdir = os.path.join(root, f"step_{rec.step}")
        n = 1
        while os.path.exists(bdir):
            bdir = os.path.join(root, f"step_{rec.step}.{n}")
            n += 1
        os.makedirs(bdir)

        program = ctx["program"]
        with open(os.path.join(bdir, BUNDLE_PROGRAM), "wb") as f:
            f.write(program.serialize_to_string())
        np.savez(os.path.join(bdir, BUNDLE_FEEDS),
                 **{k: np.asarray(v) for k, v in ctx["feeds"].items()})
        np.savez(os.path.join(bdir, BUNDLE_STATE),
                 **{k: np.asarray(v) for k, v in ctx["state"].items()})
        loss32 = np.float32(rec.loss)

        def _sent_json(v):
            # per-step injection multipliers are (n_steps,) arrays in a
            # fused-window bundle, scalars in a per-step one
            a = np.asarray(v, np.float32)
            return a.tolist() if a.ndim else float(a)

        meta = {
            "step": rec.step,
            "loss": rec.loss,
            "loss_bits": loss32.tobytes().hex(),
            "grad_norm": rec.grad_norm,
            "scale": rec.scale,
            "finite": rec.finite,
            "spike": rec.spike,
            "fetch_names": list(ctx.get("fetch_names", [])),
            "extra_fetch_names": spec.extra_fetch_names(),
            "scale_vars": list(spec.scale_vars) if spec.scale_vars else None,
            "growth_interval": spec.growth_interval,
            "sentinel": {k: _sent_json(v)
                         for k, v in ctx["sentinel"].items()},
            "feed_lods": {k: [list(map(int, lv)) for lv in lod]
                          for k, lod in (ctx.get("feed_lods") or {}).items()},
            "program_cache_token": getattr(program, "_cache_token", None),
        }
        win = ctx.get("window")
        if win is not None:
            # the bundle's state/feeds are PRE-WINDOW; replay advances
            # trip_offset steps to reproduce the trip bit-for-bit
            meta["window"] = {
                "start": int(win["start"]),
                "n_steps": int(win["n_steps"]),
                "feed_per_step": bool(win.get("feed_per_step", False)),
                "trip_offset": int(rec.step - win["start"]),
            }
        with open(os.path.join(bdir, BUNDLE_META), "w") as f:
            json.dump(meta, f, indent=1)
        with open(os.path.join(bdir, BUNDLE_RECORDS), "w") as f:
            json.dump([r.to_dict() for r in self.recorder.records()], f)
        return bdir

    def metrics(self) -> dict:
        """ServingMetrics-style counter snapshot."""
        out = dict(self.counters)
        out["loss_scale"] = self.last_scale
        out["ring_depth"] = len(self.recorder.ring)
        return out


# -- module-level management --


def install(config: Optional[GuardianConfig]) -> Optional[Guardian]:
    """Arm (or with None, disarm) the guardian programmatically — this
    overrides the PADDLE_TPU_GUARDIAN env contract."""
    global _guardian
    _guardian = Guardian(config) if config is not None else None
    return _guardian


def enable(policy: str = "skip", **kwargs) -> Guardian:
    return install(GuardianConfig(policy=policy, **kwargs))


def disable() -> None:
    install(None)


def current() -> Optional[Guardian]:
    global _guardian
    if _guardian is _UNSET:
        cfg = GuardianConfig.from_env()
        _guardian = Guardian(cfg) if cfg is not None else None
    return _guardian


def metrics() -> dict:
    g = current()
    return g.metrics() if g is not None else {}


def flush() -> None:
    g = current()
    if g is not None:
        g.flush()


# ---------------------------------------------------------------------------
# Replay: re-execute a dumped step on CPU and bisect the first bad var
# ---------------------------------------------------------------------------


def replay(bundle_dir: str, verbose: bool = False) -> dict:
    """Re-execute a replay bundle's recorded step.

    Two passes:

    1. **jit pass** — rebuild the exact guarded step (same plan, same extra
       fetches, same sentinel inputs) and execute it once; the recomputed
       loss must reproduce the recorded value bit-for-bit (same XLA
       program, same inputs, one machine).
    2. **eager bisect** — walk the block op-by-op with concrete values and
       report the FIRST variable that goes non-finite, i.e. the op that
       manufactured the NaN/Inf.

    Returns a report dict (also printed as JSON by the CLI)."""
    import numpy as np

    try:  # force CPU when the backend is not yet initialized
        import jax

        jax.config.update("jax_platforms", "cpu")
    except (ImportError, RuntimeError):
        pass
    import jax
    import jax.numpy as jnp

    from .executor import LOD_SUFFIX, BlockPlan, run_op, trace_block
    from .framework import Program, RNG_STATE_VAR

    with open(os.path.join(bundle_dir, BUNDLE_META)) as f:
        meta = json.load(f)
    with open(os.path.join(bundle_dir, BUNDLE_PROGRAM), "rb") as f:
        program = Program.parse_from_string(f.read())
    feeds = dict(np.load(os.path.join(bundle_dir, BUNDLE_FEEDS)))
    state_np = dict(np.load(os.path.join(bundle_dir, BUNDLE_STATE)))

    user_fetches = meta["fetch_names"]
    extra = meta["extra_fetch_names"]
    spec = GuardSpec(extra[0], extra[1:],
                     meta.get("scale_vars"), meta.get("growth_interval", 1000))

    # window bundles store PRE-WINDOW state + the whole window's feeds and
    # per-step injection arrays; a per-step bundle is the degenerate
    # 1-step window with trip_offset 0, so one loop replays both
    win = meta.get("window") or {"n_steps": 1, "trip_offset": 0,
                                 "feed_per_step": False}
    trip_offset = int(win["trip_offset"])
    feed_per_step = bool(win["feed_per_step"])
    sent_meta = meta["sentinel"]
    loss_cap = np.float32(sent_meta.get("loss_cap", np.inf))
    seed_muls = np.asarray(sent_meta.get("seed_mul", 1.0),
                           np.float32).reshape(-1)
    loss_muls = np.asarray(sent_meta.get("loss_mul", 1.0),
                           np.float32).reshape(-1)

    def _step_feed(arrs, i):
        return {k: v[i] for k, v in arrs.items()} if feed_per_step else arrs

    def _step_sent(i):
        return {"loss_cap": loss_cap,
                "seed_mul": seed_muls[min(i, len(seed_muls) - 1)],
                "loss_mul": loss_muls[min(i, len(loss_muls) - 1)]}

    feed_keys = list(_step_feed(feeds, 0))
    plan = BlockPlan(program, 0, feed_keys, user_fetches + extra)
    static_env = {k + LOD_SUFFIX: tuple(tuple(lv) for lv in lod)
                  for k, lod in (meta.get("feed_lods") or {}).items()}
    # the bundle's state IS the window's exact input set (including the
    # scaler vars the executor force-gathers outside plan.state_in)
    state = {k: jnp.asarray(v) for k, v in state_np.items()}

    n_user = len(user_fetches)

    def step(feed_vals, state_vals, sent):
        env_state = dict(state_vals)
        feed_vals = dict(feed_vals)
        feed_vals[LOSS_SEED_MUL] = seed_multiplier(spec, env_state, sent)
        fetches, new_state = trace_block(program, 0, plan, feed_vals,
                                         env_state, static_env=static_env)
        mut = {k: v for k, v in new_state.items() if k in env_state}
        committed, health = fold_health(spec, fetches[n_user:], new_state,
                                        mut, env_state, sent)
        return fetches, health, committed

    feeds_j = {k: jnp.asarray(v) for k, v in feeds.items()}
    jstep = jax.jit(step)
    # committed-state walk up to the trip step (clean prefix steps commit
    # exactly like the scanned window did)
    for i in range(trip_offset):
        _, _, committed = jstep(_step_feed(feeds_j, i), state, _step_sent(i))
        state = {**state, **committed}
    pre_trip_state = dict(state)
    trip_feed = _step_feed(feeds_j, trip_offset)
    trip_sent = _step_sent(trip_offset)
    fetches, health, _ = jstep(trip_feed, state, trip_sent)
    replayed_loss = np.float32(np.asarray(health["loss"]))
    recorded_bits = meta["loss_bits"]
    replayed_bits = replayed_loss.tobytes().hex()
    # NaNs never compare equal; the BIT pattern is the reproduction check
    bitwise_match = replayed_bits == recorded_bits

    # eager bisect of the TRIP step: concrete op-by-op walk from the
    # committed pre-trip state, first non-finite var wins
    env: Dict[str, object] = {}
    env.update(static_env)
    env.update(pre_trip_state)
    env.update(trip_feed)
    env[LOSS_SEED_MUL] = seed_multiplier(spec, env, trip_sent)
    rng_box = [env[RNG_STATE_VAR]] if plan.needs_rng else None
    first_bad = None
    trail = []
    for idx, op in enumerate(plan.ops):
        run_op(op, env, rng_box)
        if first_bad is not None:
            continue
        for name in op.output_arg_names:
            val = env.get(name)
            if val is None or not hasattr(val, "dtype"):
                continue
            if not jnp.issubdtype(val.dtype, jnp.floating):
                continue
            arr = np.asarray(val)
            if not np.isfinite(arr).all():
                kinds = []
                if np.isnan(arr).any():
                    kinds.append("nan")
                if np.isinf(arr).any():
                    kinds.append("inf")
                first_bad = {"op_index": idx, "op_type": op.type,
                             "var": name, "kind": "+".join(kinds),
                             "bad_count": int((~np.isfinite(arr)).sum()),
                             "size": int(arr.size)}
                break
        if verbose:
            trail.append({"op_index": idx, "op_type": op.type})

    report = {
        "bundle": os.path.abspath(bundle_dir),
        "step": meta["step"],
        "recorded_loss": meta["loss"],
        "replayed_loss": float(replayed_loss),
        "recorded_loss_bits": recorded_bits,
        "replayed_loss_bits": replayed_bits,
        "bitwise_match": bitwise_match,
        "first_nonfinite": first_bad,
        "n_ops": len(plan.ops),
        "window": meta.get("window"),
    }
    if verbose:
        report["trail"] = trail
    return report


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.fluid.guardian",
        description="Guardian flight-recorder tools.")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("replay",
                        help="re-execute a replay bundle on CPU and bisect "
                             "the first non-finite variable")
    rp.add_argument("bundle", help="replay-bundle directory")
    rp.add_argument("--verbose", action="store_true",
                    help="include the full op trail in the report")
    args = ap.parse_args(argv)
    if args.cmd == "replay":
        report = replay(args.bundle, verbose=args.verbose)
        json.dump(report, sys.stdout, indent=1)
        sys.stdout.write("\n")
        if report["first_nonfinite"] is None and not report["bitwise_match"]:
            return 1  # neither reproduced the bad value nor found one
        return 0
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
