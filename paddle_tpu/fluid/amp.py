"""Mixed-precision compute mode: bf16 matmuls/convs with fp32 master weights.

TPU-native equivalent of the reference's float16 transpiler
(ref: paddle/contrib/float16/float16_transpiler.py, which rewrites a program
so inference runs in fp16).  The reference rewrites the *program* because its
kernels are dtype-monomorphic; here the op library itself is polymorphic, so
mixed precision is an execution mode: when enabled, the matmul-class ops
(mul/matmul/fc, conv2d/3d and friends) cast fp32 operands to the compute
dtype and accumulate in fp32 via ``preferred_element_type``.

This is exactly the TPU-idiomatic recipe: parameters, optimizer state,
normalizations and reductions stay fp32 (master weights), while the
MXU-bound contractions run in the low dtype.  The contraction itself
executes entirely in that dtype (the MXU accumulates bf16 products in fp32
*in hardware*; there is no explicit preferred_element_type — its vjp rules
reject mixed cotangent/operand dtypes for convs).  Consequences:

 - "bfloat16" (recommended, the default): same exponent range as fp32, no
   loss scaling needed; hardware fp32 accumulation makes operand rounding
   the only precision loss.
 - "float16": the contraction accumulates in fp16 with fp16's narrow
   exponent range and NO loss scaling — experimental, can overflow on
   real models.  The reference's fp16 transpiler targets *inference*
   (float16_benchmark.md) for the same reason.

Enable programmatically::

    import paddle_tpu.fluid as fluid
    fluid.amp.enable("bfloat16")          # or fluid.amp.amp_guard(...)

or via the environment: ``PADDLE_TPU_AMP=bfloat16``.
"""

from __future__ import annotations

import contextlib
import os

_SUPPORTED = ("bfloat16", "float16")

_state = {"dtype": None}


def enable(dtype: str = "bfloat16") -> None:
    if dtype not in _SUPPORTED:
        raise ValueError(f"amp dtype must be one of {_SUPPORTED}, got {dtype!r}")
    _state["dtype"] = dtype


def disable() -> None:
    _state["dtype"] = None


def is_enabled() -> bool:
    return _state["dtype"] is not None


def compute_dtype():
    """The active low-precision compute dtype name, or None."""
    return _state["dtype"]


@contextlib.contextmanager
def amp_guard(dtype: str = "bfloat16"):
    prev = _state["dtype"]
    enable(dtype)
    try:
        yield
    finally:
        _state["dtype"] = prev


def matmul(a, b):
    """``a @ b`` in the AMP compute dtype with the result restored to the
    fp32 activation contract; identity when AMP is off.  The shared helper
    for code that contracts OUTSIDE the op library (stacked transformer,
    ring attention) — one policy, every path."""
    a2, b2, back = cast_operands(a, b)
    return restore_astype(a2 @ b2, back)


def einsum(spec, a, b):
    """Two-operand einsum under the same AMP recipe as :func:`matmul`."""
    import jax.numpy as jnp

    a2, b2, back = cast_operands(a, b)
    return restore_astype(jnp.einsum(spec, a2, b2), back)


def cast_operands(*arrays):
    """Cast fp32 contraction operands to the AMP dtype.

    Returns ``(arrays..., restore_dtype)``.  When AMP is off (or any operand
    is not fp32) the operands pass through unchanged and restore_dtype is
    None.  Otherwise the caller computes the contraction in the low dtype
    and casts its result back with ``restore_astype`` — NOT via
    ``preferred_element_type``, whose vjp rules reject mixed
    cotangent/operand dtypes for convs.  On the MXU this costs nothing:
    bf16 matmuls accumulate in fp32 internally; the explicit cast just
    restores the fp32 activation contract for the rest of the graph.
    """
    import jax.numpy as jnp

    d = _state["dtype"]
    if d is None or any(a is None or a.dtype != jnp.float32 for a in arrays):
        return (*arrays, None)
    cd = jnp.bfloat16 if d == "bfloat16" else jnp.float16
    return (*(a.astype(cd) for a in arrays), jnp.float32)


def restore_astype(out, restore_dtype):
    """Cast a contraction result back to the pre-AMP dtype (no-op when
    cast_operands passed through)."""
    return out if restore_dtype is None else out.astype(restore_dtype)


# environment bridge (ref: python/paddle/fluid/__init__.py:121-140 reads
# FLAGS from env at import time)
_env = os.environ.get("PADDLE_TPU_AMP", "").strip().lower()
if _env in ("bf16", "bfloat16", "1", "true"):
    enable("bfloat16")
elif _env in ("fp16", "float16"):
    enable("float16")
