"""Mixed-precision compute mode: bf16 matmuls/convs with fp32 master weights.

TPU-native equivalent of the reference's float16 transpiler
(ref: paddle/contrib/float16/float16_transpiler.py, which rewrites a program
so inference runs in fp16).  The reference rewrites the *program* because its
kernels are dtype-monomorphic; here the op library itself is polymorphic, so
mixed precision is an execution mode: when enabled, the matmul-class ops
(mul/matmul/fc, conv2d/3d and friends) cast fp32 operands to the compute
dtype and accumulate in fp32 via ``preferred_element_type``.

This is exactly the TPU-idiomatic recipe: parameters, optimizer state,
normalizations and reductions stay fp32 (master weights), while the
MXU-bound contractions run in the low dtype.  The contraction itself
executes entirely in that dtype (the MXU accumulates bf16 products in fp32
*in hardware*; there is no explicit preferred_element_type — its vjp rules
reject mixed cotangent/operand dtypes for convs).  Consequences:

 - "bfloat16" (recommended, the default): same exponent range as fp32, no
   loss scaling needed; hardware fp32 accumulation makes operand rounding
   the only precision loss.
 - "float16": the contraction accumulates in fp16 with fp16's narrow
   exponent range and NO loss scaling — experimental, can overflow on
   real models.  The reference's fp16 transpiler targets *inference*
   (float16_benchmark.md) for the same reason.

Enable programmatically::

    import paddle_tpu.fluid as fluid
    fluid.amp.enable("bfloat16")          # or fluid.amp.amp_guard(...)

or via the environment: ``PADDLE_TPU_AMP=bfloat16``.
"""

from __future__ import annotations

import contextlib
import os

_SUPPORTED = ("bfloat16", "float16")

_state = {"dtype": None, "keep": False}


def enable(dtype: str = "bfloat16", keep_activations=None) -> None:
    """Enable mixed precision.

    ``keep_activations=True`` selects the pure-low-precision activation
    regime: contraction outputs STAY in the compute dtype instead of being
    cast back to fp32, so inter-layer activations (the dominant HBM
    traffic of conv nets at scale) move at half the bytes.  Numerics keep
    the master-fp32 discipline everywhere it matters: parameters,
    optimizer state and gradients stay fp32 (the cast's transpose upcasts
    cotangents), batch_norm/layer_norm compute statistics in fp32, and
    softmax/cross-entropy upcast at the loss boundary.  This is the
    standard production-TPU training recipe (measured on the round-5
    tunnel: ~2x ResNet-50 step throughput — docs/PERF.md).
    Default: the PADDLE_TPU_AMP_KEEP env var, else False.
    """
    if dtype not in _SUPPORTED:
        raise ValueError(f"amp dtype must be one of {_SUPPORTED}, got {dtype!r}")
    _state["dtype"] = dtype
    if keep_activations is None:
        keep_activations = os.environ.get(
            "PADDLE_TPU_AMP_KEEP", "").strip().lower() in ("1", "true")
    _state["keep"] = bool(keep_activations)


def disable() -> None:
    _state["dtype"] = None
    _state["keep"] = False


def is_enabled() -> bool:
    return _state["dtype"] is not None


def compute_dtype():
    """The active low-precision compute dtype name, or None."""
    return _state["dtype"]


def keep_low_activations() -> bool:
    """True when AMP is on in the pure-low-activation regime."""
    return _state["dtype"] is not None and _state["keep"]


def is_low_float(dtype) -> bool:
    """True for sub-32-bit float dtypes (bf16/fp16) — THE predicate ops use
    to decide 'compute this norm/loss internally in fp32'.  Centralized so
    the regime's dtype policy has one definition."""
    import jax.numpy as jnp

    return jnp.issubdtype(dtype, jnp.floating) and jnp.finfo(dtype).bits < 32


@contextlib.contextmanager
def amp_guard(dtype: str = "bfloat16", keep_activations=None):
    prev = dict(_state)
    enable(dtype, keep_activations=keep_activations)
    try:
        yield
    finally:
        _state.update(prev)


def matmul(a, b):
    """``a @ b`` in the AMP compute dtype; identity when AMP is off.  The
    result is restored to fp32 in the default regime, or LEFT in the
    compute dtype under keep_activations.  The shared helper for code that
    contracts OUTSIDE the op library (stacked transformer, ring
    attention) — one policy, every path."""
    a2, b2, back = cast_operands(a, b)
    return restore_astype(a2 @ b2, back)


def einsum(spec, a, b):
    """Two-operand einsum under the same AMP recipe (and keep_activations
    behavior) as :func:`matmul`."""
    import jax.numpy as jnp

    a2, b2, back = cast_operands(a, b)
    return restore_astype(jnp.einsum(spec, a2, b2), back)


def cast_operands(*arrays):
    """Cast fp32 contraction operands to the AMP dtype.

    Returns ``(arrays..., restore_dtype)``.  Default regime: when AMP is
    off (or any operand is not fp32) the operands pass through unchanged
    and restore_dtype is None; otherwise the caller computes the
    contraction in the low dtype and casts its result back with
    ``restore_astype`` — NOT via ``preferred_element_type``, whose vjp
    rules reject mixed cotangent/operand dtypes for convs.  On the MXU
    this costs nothing: bf16 matmuls accumulate in fp32 internally.

    keep_activations regime: operands may arrive fp32 (params/feeds) or
    already in the compute dtype (upstream activations); fp32 ones are
    cast down, restore_dtype is None, and the result STAYS low — the
    whole point of the regime (half the inter-layer HBM bytes).
    """
    import jax.numpy as jnp

    d = _state["dtype"]
    if d is None:
        return (*arrays, None)
    cd = jnp.bfloat16 if d == "bfloat16" else jnp.float16
    if _state["keep"]:
        # pure-low-activation regime: operands may arrive fp32 (params,
        # feeds) or already in the compute dtype (upstream activations);
        # cast the fp32 ones down and DON'T restore — the contraction
        # result stays low so downstream layers read half the bytes.
        if any(a is None or a.dtype not in (jnp.float32, cd)
               for a in arrays):
            return (*arrays, None)
        return (*(a.astype(cd) if a.dtype == jnp.float32 else a
                  for a in arrays), None)
    if any(a is None or a.dtype != jnp.float32 for a in arrays):
        return (*arrays, None)
    return (*(a.astype(cd) for a in arrays), jnp.float32)


def restore_astype(out, restore_dtype):
    """Cast a contraction result back to the pre-AMP dtype (no-op when
    cast_operands passed through)."""
    return out if restore_dtype is None else out.astype(restore_dtype)


# environment bridge (ref: python/paddle/fluid/__init__.py:121-140 reads
# FLAGS from env at import time)
_env = os.environ.get("PADDLE_TPU_AMP", "").strip().lower()
if _env in ("bf16", "bfloat16", "1", "true"):
    enable("bfloat16")
elif _env in ("fp16", "float16"):
    enable("float16")
